"""Configuration dataclasses.

The reference has *no* config system at all — no flags, no env vars; its only
runtime configuration is the ``topology`` message (reference main.go:132-149).
The new framework makes every implicit constant explicit and sweepable:
cluster size N, fanout, protocol mode, topology family, mesh shape, backend.

All configs are frozen (hashable) so they can be closed over by jitted
functions or used as static arguments.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

# Protocol modes.
PUSH = "push"            # infected nodes push the rumor to sampled peers
PULL = "pull"            # all nodes pull from sampled peers
PUSH_PULL = "pushpull"   # both directions in one round
FLOOD = "flood"          # push to ALL neighbors every round (Go-parity mode:
                         # the reference relays to its full neighbor list,
                         # main.go:72-75; coverage(t) == BFS ball of radius t)
ANTI_ENTROPY = "antientropy"  # periodic bidirectional digest reconciliation
SWIM = "swim"            # SWIM-style suspect/confirm failure detection
RUMOR = "rumor"          # SIR rumor mongering: infective nodes push until
                         # they lose interest (counter death, models/rumor.py)

MODES = (PUSH, PULL, PUSH_PULL, FLOOD, ANTI_ENTROPY, SWIM, RUMOR)

RUMOR_VARIANTS = ("feedback", "blind")

# Topology families.
COMPLETE = "complete"    # implicit: uniform random peer, no neighbor table
RING = "ring"
GRID = "grid"
ERDOS_RENYI = "erdos_renyi"
WATTS_STROGATZ = "watts_strogatz"
POWER_LAW = "power_law"  # Barabasi-Albert preferential attachment

FAMILIES = (COMPLETE, RING, GRID, ERDOS_RENYI, WATTS_STROGATZ, POWER_LAW)


@dataclasses.dataclass(frozen=True)
class TopologyConfig:
    """Which graph the rumor spreads on.

    The reference receives its topology at runtime as a ``node -> [neighbors]``
    JSON map (main.go:132-149).  Here topologies are generated up front as
    static padded neighbor tables (``int32[N, D]`` with out-of-range sentinel
    padding) so shapes stay static for XLA; the ``complete`` family is
    *implicit* (uniform sampling, no table) so it scales to 10M+ nodes with
    zero adjacency memory.
    """

    family: str = COMPLETE
    n: int = 1024
    # family-specific parameters:
    k: int = 4            # ring/WS: neighbors per side*2; BA: edges per new node
    p: float = 0.01       # ER edge probability / WS rewire probability
    degree_cap: Optional[int] = None  # cap padded table width (power-law tails)
    seed: int = 0

    def __post_init__(self):
        if self.family not in FAMILIES:
            raise ValueError(f"unknown topology family {self.family!r}")
        if self.n < 2:
            raise ValueError("need at least 2 nodes")


@dataclasses.dataclass(frozen=True)
class ProtocolConfig:
    """Gossip protocol semantics for one simulation.

    ``fanout`` generalizes the reference's fixed "all neighbors" fan-out
    (main.go:72): sampled-peer protocols contact ``fanout`` random peers per
    round; ``flood`` ignores it and contacts every neighbor, which is the
    faithful Go-parity behavior.
    """

    mode: str = PUSH
    fanout: int = 1
    rumors: int = 1          # R: number of concurrent rumors (multi-rumor broadcast)
    exclude_self: bool = True
    # anti-entropy: run a bidirectional digest reconciliation every
    # `period` rounds (both partners merge; off-rounds are quiescent).
    period: int = 1
    # SWIM parameters (see models/swim.py):
    swim_proxies: int = 3        # indirect-probe proxies (the "k" of SWIM)
    swim_suspect_rounds: int = 4 # rounds a suspect waits before confirm-dead
    swim_subjects: int = 8       # tracked subjects (window width when rotating)
    # Full-membership mode: the S-subject window rotates over ALL n nodes,
    # advancing by S every `swim_epoch_rounds` rounds (0 = auto: long enough
    # for detect + disseminate + confirm).  Every node is eventually watched
    # without an [N, N] view table (models/swim.py module doc).
    swim_rotate: bool = False
    swim_epoch_rounds: int = 0
    # Dissemination scatter implementation (models/swim.disseminate_max):
    # 'scatter' = direct duplicate-index scatter-max; 'sort' = sort pushes
    # by receiver then a sorted segment-max; 'pack' = the sort lowering
    # with the row gather done on 8/16-bit packed transport codes (an
    # order isomorphism on the wires a round-bounded run can produce) —
    # all bitwise-identical results (max is order-independent),
    # different TPU lowerings.  Hardware arbitrated
    # (artifacts/swim_ab_r04.json, 1M-node BASELINE shape): sort is
    # 2.2x faster steady-state AND 1.5x faster to compile than scatter,
    # so it is the default; 'scatter' stays selectable as the control;
    # 'pack' needs the driver's max_rounds to prove its lane bound and
    # falls back to 'sort' where that is unknown.
    swim_diss: str = "sort"
    # Per-round randomness lowering (models/swim.packed_round_draws):
    # 'split' = the original contract — an independent fold_in+draw
    # chain per random quantity (subject, proxies, peers, drop coins),
    # ~5 threefry streams per node per round; 'packed' = ONE per-node
    # key chain and ONE multi-word draw per round, bit-fields split
    # into the same quantities.  Packed is an OPT-IN statistical
    # contract change, not a relowering: trajectories differ from
    # 'split' (different streams), per-draw marginals are uniform up to
    # a documented modulo bias <= m/2^32 (m = the draw's range), and
    # mesh-invariance (draws keyed by global node id) is preserved —
    # the same contract class as the fused SI kernels vs the threefry
    # path.  Motivation: PERF.md names the per-node threefry chains as
    # a steady-state suspect at 1M nodes (VERDICT r4 task 4).
    swim_rng: str = "split"
    # Rumor mongering (mode='rumor', models/rumor.py): an infective
    # (node, rumor) stops spreading — becomes removed, SIR — once its
    # unnecessary-contact counter reaches `rumor_k` (Demers et al. §1.4
    # counter death).  'feedback' counts only pushes whose recipient
    # already knew the rumor; 'blind' counts every push.
    rumor_k: int = 2
    rumor_variant: str = "feedback"

    def __post_init__(self):
        if self.mode not in MODES:
            raise ValueError(f"unknown protocol mode {self.mode!r}")
        if self.fanout < 1:
            raise ValueError("fanout must be >= 1")
        if self.rumors < 1:
            raise ValueError("rumors must be >= 1")
        if self.swim_subjects < 1:
            raise ValueError("swim_subjects must be >= 1")
        if self.swim_epoch_rounds < 0:
            raise ValueError("swim_epoch_rounds must be >= 0 (0 = auto)")
        if self.swim_diss not in ("scatter", "sort", "pack"):
            raise ValueError(f"unknown swim_diss {self.swim_diss!r}; "
                             "choose 'scatter', 'sort', or 'pack'")
        if self.swim_rng not in ("split", "packed"):
            raise ValueError(f"unknown swim_rng {self.swim_rng!r}; "
                             "choose 'split' or 'packed'")
        if self.rumor_k < 1:
            raise ValueError("rumor_k must be >= 1")
        if self.rumor_variant not in RUMOR_VARIANTS:
            raise ValueError(f"unknown rumor_variant "
                             f"{self.rumor_variant!r}; choose from "
                             f"{RUMOR_VARIANTS}")


# Ceiling on the schedule horizon (ops/nemesis table length T): the
# lowering materializes [T]-sized device tables plus host lists per
# trace, so an absurd partition/ramp end must error loudly instead of
# hanging and OOMing.  100k rounds x 4 bytes = 400 KB per table —
# orders of magnitude past any real run ("partitioned forever" just
# needs end >= the run's max_rounds: beyond the horizon the schedule
# holds its final row, i.e. partitions closed, drop at the ramp's
# final value).
MAX_CHURN_HORIZON = 100_000


@dataclasses.dataclass(frozen=True)
class ChurnConfig:
    """A fault *program over rounds* — the compiled nemesis schedule.

    Maelstrom's nemesis partitions the network MID-RUN and heals it
    (reference main.go:77-87 survives via at-least-once retry); the
    static masks of :class:`FaultConfig` cannot express that.  This
    config scripts time-varying faults, lowered by
    :mod:`gossip_tpu.ops.nemesis` into small round-indexed schedule
    tables consumed INSIDE the compiled round loops:

    * ``events`` — crash/recover churn: ``(node, die_round,
      recover_round)`` triples.  The node is down for rounds
      ``die_round <= r < recover_round`` (it neither sends, responds,
      nor receives); ``recover_round < 0`` means it never comes back.
      Scripted events override nothing else — they stack on top of the
      static ``node_death_rate`` mask (and a scripted death of the
      rumor origin is honored: explicit scripts are the user's call,
      unlike the random mask, which pins the origin alive).
    * ``partitions`` — network partition windows: ``(start, end,
      cut)``.  For rounds ``start <= r < end`` every message crossing
      the node-id cut (one side is ``id < cut``, the other
      ``id >= cut``) is lost; both sides keep gossiping internally.
      Applied to the dense, sparse, and halo exchanges (the
      plane-sharded fused engine has no per-pair messages to cut —
      it rejects partition windows rather than silently ignoring
      them).  A cut at a multiple of ``n_pad / n_devices`` is
      shard-group aligned (no shard straddles the cut).  Windows must
      not overlap.
    * ``ramp`` — a drop-rate ramp ``(start, end, from_p, to_p)``:
      ``drop_prob`` is ``FaultConfig.drop_prob`` before ``start``,
      moves linearly from ``from_p`` to ``to_p`` over
      ``[start, end)``, and holds ``to_p`` after.

    All fields JSON-friendly (the RPC ``fault.churn`` object delivers
    lists, coerced here).  An all-default ChurnConfig is normalized to
    ``None`` by :class:`FaultConfig` so the fault-free/static-only hot
    paths stay untouched.
    """

    events: Tuple[Tuple[int, int, int], ...] = ()
    partitions: Tuple[Tuple[int, int, int], ...] = ()
    ramp: Optional[Tuple[int, int, float, float]] = None

    def __post_init__(self):
        object.__setattr__(self, "events", tuple(
            tuple(int(x) for x in e) for e in self.events))
        object.__setattr__(self, "partitions", tuple(
            tuple(int(x) for x in w) for w in self.partitions))
        if self.ramp is not None:
            r = tuple(self.ramp)
            if len(r) != 4:
                raise ValueError(f"drop ramp {r} must be "
                                 "(start, end, from_p, to_p)")
            object.__setattr__(
                self, "ramp",
                (int(r[0]), int(r[1]), float(r[2]), float(r[3])))
        for e in self.events:
            if len(e) != 3:
                raise ValueError(f"churn event {e} must be "
                                 "(node, die_round, recover_round)")
            node, die, rec = e
            if node < 0:
                raise ValueError(f"churn event node {node} must be >= 0")
            if die < 0:
                raise ValueError(f"churn event die_round {die} must be "
                                 ">= 0")
            if rec >= 0 and rec <= die:
                raise ValueError(
                    f"churn event {e}: recover_round must be > die_round "
                    "(or < 0 for a permanent crash)")
            if die > MAX_CHURN_HORIZON or rec > MAX_CHURN_HORIZON:
                raise ValueError(
                    f"churn event {e}: rounds exceed the schedule "
                    f"horizon cap {MAX_CHURN_HORIZON} (rec < 0 already "
                    "means 'down forever'; larger values would collide "
                    "with the kernels' int32 NEVER sentinel)")
        nodes = [e[0] for e in self.events]
        if len(set(nodes)) != len(nodes):
            raise ValueError("churn events must script each node at most "
                             "once (one die/recover pair per node)")
        spans = []
        for w in self.partitions:
            if len(w) != 3:
                raise ValueError(f"partition window {w} must be "
                                 "(start, end, cut)")
            start, end, cut = w
            if start < 0 or end <= start:
                raise ValueError(f"partition window {w}: need "
                                 "0 <= start < end")
            if cut <= 0:
                raise ValueError(f"partition window {w}: cut must be a "
                                 "positive node id (both sides non-empty)")
            if end > MAX_CHURN_HORIZON:
                raise ValueError(
                    f"partition window {w}: end {end} exceeds the "
                    f"schedule horizon cap {MAX_CHURN_HORIZON} (any end "
                    ">= the run's max_rounds already means 'open for "
                    "the whole run' — the lowered tables are sized by "
                    "the largest end)")
            spans.append((start, end))
        spans.sort()
        for (s0, e0), (s1, _) in zip(spans, spans[1:]):
            if s1 < e0:
                raise ValueError("partition windows overlap: "
                                 f"[{s0}, {e0}) and [{s1}, ...)")
        if self.ramp is not None:
            start, end, p0, p1 = self.ramp
            if start < 0 or end <= start:
                raise ValueError(f"drop ramp {self.ramp}: need "
                                 "0 <= start < end")
            if end > MAX_CHURN_HORIZON:
                raise ValueError(
                    f"drop ramp {self.ramp}: end {end} exceeds the "
                    f"schedule horizon cap {MAX_CHURN_HORIZON} (the "
                    "ramp holds its final value beyond end, so a "
                    "shorter ramp expresses the same steady state)")
            for p in (p0, p1):
                if not 0.0 <= p <= 1.0:
                    raise ValueError(
                        f"drop ramp probability {p} outside [0, 1]")

    @property
    def empty(self) -> bool:
        return not (self.events or self.partitions or self.ramp)

    def horizon(self) -> int:
        """Rounds after which the schedule is constant: the table
        length T of the ops/nemesis lowering.  Beyond it, partitions
        are closed and the drop rate holds its final value."""
        ends = [1]
        ends += [end for _, end, _ in self.partitions]
        if self.ramp is not None:
            ends.append(self.ramp[1])
        return max(ends) + 1


# Byzantine liar actions (ops/nemesis byz lowering).  Every kind is a
# SERVE-side transform of the state row a liar hands to pulling peers:
BYZ_CORRUPT = "corrupt"        # flip payload words it forwards (xor arg)
BYZ_REPLAY = "replay"          # serve a stale snapshot of its own planes
BYZ_EQUIVOCATE = "equivocate"  # different state per partner (keyed by id)
BYZ_INFLATE = "inflate"        # write columns/keys it does not own

BYZ_KINDS = (BYZ_CORRUPT, BYZ_REPLAY, BYZ_EQUIVOCATE, BYZ_INFLATE)


@dataclasses.dataclass(frozen=True)
class ByzConfig:
    """A scripted *byzantine program* — nodes that LIE (ROADMAP item 4),
    the adversarial half of the nemesis subsystem.

    Where :class:`ChurnConfig` scripts fail-stop faults (a down node is
    silent), this scripts liars: ``liars`` are ``(node, round, kind,
    arg)`` quadruples — from ``round`` onward, ``node`` serves every
    pull with a transformed state row (:data:`BYZ_KINDS` catalog;
    docs/ROBUSTNESS.md "Byzantine adversaries").  The program lowers to
    padded runtime operands on the step's ``tables`` tail exactly like
    the churn schedule (ops/nemesis.byz_args — compiled loops carry
    shapes, never liar content), and the transforms render RECEIVER
    side, so a liar's own durable state stays honest: the lie is on the
    wire, which is the BFT model (a faulty replica can say anything but
    cannot rewrite history it already gossiped).

    A liar corrupts only components it does NOT own — its own
    column/element/key writes are its own to make and are
    indistinguishable from honest writes (the standard BFT limitation;
    the ``byz_conv`` metric judges convergence on HONEST-owned
    components for exactly this reason).

    ``quorum`` is the echo-sampling threshold q of the defended packed
    set kernels: a broadcast bit not served by its owner directly is
    admitted only when seen from >= q distinct partners in one round.
    It lowers as a TRACED scalar operand, and bounds the non-colluding
    liar tolerance at f < q (q identically-scripted colluders can meet
    their own quorum — docs/ROBUSTNESS.md).

    One action per node (the ChurnConfig one-event rule); an empty
    program is normalized to ``None`` by :class:`FaultConfig`.
    """

    liars: Tuple[Tuple[int, int, str, int], ...] = ()
    quorum: int = 2

    def __post_init__(self):
        object.__setattr__(self, "liars", tuple(
            (int(a[0]), int(a[1]), str(a[2]), int(a[3]) if len(a) > 3
             else 0)
            for a in (tuple(x) for x in self.liars)))
        for a in self.liars:
            if len(a) != 4:
                raise ValueError(f"byz liar {a} must be "
                                 "(node, round, kind[, arg])")
            node, rnd, kind, arg = a
            if node < 0:
                raise ValueError(f"byz liar node {node} must be >= 0")
            if rnd < 0 or rnd > MAX_CHURN_HORIZON:
                raise ValueError(
                    f"byz liar round {rnd} outside "
                    f"[0, {MAX_CHURN_HORIZON}] (the schedule horizon "
                    "cap, shared with ChurnConfig)")
            if kind not in BYZ_KINDS:
                raise ValueError(f"unknown byz kind {kind!r}; choose "
                                 f"from {BYZ_KINDS}")
            if arg < 0:
                raise ValueError(f"byz liar {a}: arg must be >= 0 (an "
                                 "xor/inflation pattern, not a sign)")
        nodes = [a[0] for a in self.liars]
        if len(set(nodes)) != len(nodes):
            raise ValueError("byz program must script each node at "
                             "most once (one standing lie per node — "
                             "the ChurnConfig one-event rule)")
        if not 1 <= self.quorum <= 3:
            raise ValueError(
                f"quorum={self.quorum} outside [1, 3]: the defended "
                "set kernels count echoes with a carry-save chain of "
                "depth 3 (ops/crdt.pull_merge_crdt_byz); a larger "
                "quorum needs a deeper chain, added when an engine "
                "needs it")

    @property
    def empty(self) -> bool:
        return not self.liars


# CRDT payload kinds (ops/crdt.py).  The Gossip Glomers sibling
# workloads of the reference's broadcast: same epidemic exchange, a
# commutative-merge payload instead of the infected bit.
GCOUNTER = "gcounter"      # grow-only counter: per-node shards, merge=max
PNCOUNTER = "pncounter"    # inc/dec counter: P and N shard planes
GSET = "gset"              # grow-only set: packed add bit-planes, merge=OR
ORSET = "orset"            # add/remove set: add + tombstone planes, merge=OR
VCLOCK = "vclock"          # per-node vector clocks, merge=elementwise max

CRDT_KINDS = (GCOUNTER, PNCOUNTER, GSET, ORSET, VCLOCK)
CRDT_COUNTER_KINDS = (GCOUNTER, PNCOUNTER)
CRDT_SET_KINDS = (GSET, ORSET)


@dataclasses.dataclass(frozen=True)
class CrdtConfig:
    """A commutative-merge payload workload (ops/crdt.py, models/crdt.py).

    The injections are a *program over rounds*, exactly like the nemesis
    schedule: counter ``adds`` are ``(node, round, amount)`` triples
    (node adds ``amount`` to its own shard at ``round``; for
    ``pncounter`` a negative amount lands in the N plane, for
    ``gcounter`` amounts must be positive), set ``set_adds`` /
    ``set_removes`` are ``(element, round)`` pairs injected at the
    element's owner node ``(origin + element) % n`` (the rumor-origin
    convention).  Empty ``adds`` on a counter kind means the default
    program: node ``j`` adds ``1 + j % 7`` at round 0 (closed form, so
    no O(N) config is ever materialized); empty ``set_adds`` means
    every element is added at round 0 at its owner.

    Ground truth is the merge of all *applied* injections — an
    injection is applied iff its owner is alive at the injection round
    AND eventually alive under the fault program (ops/crdt.ground
    truth doc: the batched analog of the Maelstrom counter checker
    counting only ACKED adds — a node destined for permanent death
    contributes nothing, which is what makes exact value convergence
    on the eventual-alive set a guaranteed invariant).
    """

    kind: str = GCOUNTER
    adds: Tuple[Tuple[int, int, int], ...] = ()
    set_adds: Tuple[Tuple[int, int], ...] = ()
    set_removes: Tuple[Tuple[int, int], ...] = ()
    elements: int = 64          # set element universe E (W = ceil(E/32))

    def __post_init__(self):
        object.__setattr__(self, "adds", tuple(
            tuple(int(x) for x in a) for a in self.adds))
        object.__setattr__(self, "set_adds", tuple(
            tuple(int(x) for x in a) for a in self.set_adds))
        object.__setattr__(self, "set_removes", tuple(
            tuple(int(x) for x in a) for a in self.set_removes))
        if self.kind not in CRDT_KINDS:
            raise ValueError(f"unknown CRDT kind {self.kind!r}; choose "
                             f"from {CRDT_KINDS}")
        if self.elements < 1:
            raise ValueError("elements must be >= 1")
        if self.kind in CRDT_SET_KINDS:
            if self.adds:
                raise ValueError(f"{self.kind} takes set_adds/"
                                 "set_removes, not counter adds")
        else:
            if self.set_adds or self.set_removes:
                raise ValueError(f"{self.kind} takes counter adds, not "
                                 "set_adds/set_removes")
        if self.kind == VCLOCK and self.adds:
            # vclock carries no injection program at all (owner ticks
            # only) — silently dropping a scripted one would violate
            # the reject-loudly policy every other kind mismatch obeys
            raise ValueError("vclock takes no injection program (the "
                             "owner tick is the only local event); "
                             "drop the adds")
        if self.kind == GSET and self.set_removes:
            raise ValueError("gset is grow-only; removes need kind="
                             "'orset'")
        for a in self.adds:
            if len(a) != 3:
                raise ValueError(f"counter add {a} must be "
                                 "(node, round, amount)")
            node, rnd, amt = a
            if node < 0:
                raise ValueError(f"add node {node} must be >= 0")
            if rnd < 0 or rnd > MAX_CHURN_HORIZON:
                raise ValueError(
                    f"add round {rnd} outside [0, {MAX_CHURN_HORIZON}] "
                    "(the schedule horizon cap, shared with ChurnConfig)")
            if self.kind == GCOUNTER and amt <= 0:
                raise ValueError(
                    f"gcounter add {a}: amounts must be positive "
                    "(grow-only; use pncounter for decrements)")
            if self.kind == PNCOUNTER and amt == 0:
                raise ValueError(f"pncounter add {a}: amount must be "
                                 "nonzero")
        for name, pairs in (("set_add", self.set_adds),
                            ("set_remove", self.set_removes)):
            for p in pairs:
                if len(p) != 2:
                    raise ValueError(f"{name} {p} must be "
                                     "(element, round)")
                elem, rnd = p
                if not 0 <= elem < self.elements:
                    raise ValueError(
                        f"{name} element {elem} outside the universe "
                        f"[0, {self.elements})")
                if rnd < 0 or rnd > MAX_CHURN_HORIZON:
                    raise ValueError(
                        f"{name} round {rnd} outside "
                        f"[0, {MAX_CHURN_HORIZON}]")
        seen_elems = [e for e, _ in self.set_adds]
        if len(set(seen_elems)) != len(seen_elems):
            raise ValueError("set_adds must script each element at most "
                             "once (the packed-plane OR-set models one "
                             "unique add tag per element — "
                             "docs/WORKLOADS.md)")
        seen_rems = [e for e, _ in self.set_removes]
        if len(set(seen_rems)) != len(seen_rems):
            raise ValueError("set_removes must script each element at "
                             "most once")
        # A remove at-or-before its element's add would make the
        # packed tombstone plane remove-wins where the documented
        # contract is add-wins == 2P (the remove must happen-after the
        # observed add tag) — reject the silent semantic fork.  An
        # unscripted add means the default program's round 0; a remove
        # of a never-added element is a harmless no-op and allowed.
        add_round = {e: r for e, r in self.set_adds}
        for e, rr in self.set_removes:
            ra = add_round.get(e, 0 if not self.set_adds else None)
            if ra is not None and rr <= ra:
                raise ValueError(
                    f"set_remove ({e}, {rr}) fires at or before the "
                    f"element's add (round {ra}): a remove must "
                    "happen-after the add it tombstones, or add-wins "
                    "and 2P semantics diverge (docs/WORKLOADS.md)")

    def horizon(self) -> int:
        """Rounds after which no further injection fires (the zero-row
        steady state of the lowered injection tables)."""
        rounds = [0]
        rounds += [r for _, r, _ in self.adds]
        rounds += [r for _, r in self.set_adds]
        rounds += [r for _, r in self.set_removes]
        return max(rounds) + 1


@dataclasses.dataclass(frozen=True)
class LogConfig:
    """A replicated kafka-style log workload (ops/logs.py,
    models/log.py) — the last Gossip Glomers sibling of the
    reference's broadcast: ordered per-key offset payloads with
    committed offsets, gossiped as fixed-capacity ring buffers whose
    merge is elementwise max over owner-indexed slot planes.

    ``sends`` are ``(node, key, round, value)`` — node appends
    ``value`` to key's log at ``round``; ``commits`` are ``(node, key,
    round, upto)`` — node commits key's offsets below
    ``min(upto, acked_len(key))`` at ``round``.  Both are *programs
    over rounds* lowered to runtime operands (the nemesis/CRDT
    pattern); empty means the closed-form default programs
    (ops/logs.log_sends / log_commits — no O(K) config object).

    Contracts the validation enforces loudly:

    * values >= 1 (0 is the empty-slot sentinel — a 0 value would be
      invisible to the merge);
    * at most ``capacity`` sends per key (the ring position is
      ``offset % capacity``; more sends would wrap onto an unconsumed
      slot and silently alias two offsets);
    * per-key script order is round-nondecreasing (offsets are
      assigned in script order — ops/logs.send_offsets — so this is
      what makes offset order equal time order, the ORDERED half of
      the kafka invariants);
    * commit ``upto`` >= 1 (committing nothing is the default state).
    """

    keys: int = 4               # K: number of per-key logs
    capacity: int = 16          # C: ring slots per key
    sends: Tuple[Tuple[int, int, int, int], ...] = ()
    commits: Tuple[Tuple[int, int, int, int], ...] = ()

    def __post_init__(self):
        object.__setattr__(self, "sends", tuple(
            tuple(int(x) for x in s) for s in self.sends))
        object.__setattr__(self, "commits", tuple(
            tuple(int(x) for x in c) for c in self.commits))
        if self.keys < 1:
            raise ValueError("keys must be >= 1")
        if self.capacity < 1:
            raise ValueError("capacity must be >= 1")
        per_key_rounds: dict = {}
        for s in self.sends:
            if len(s) != 4:
                raise ValueError(f"log send {s} must be "
                                 "(node, key, round, value)")
            node, key, rnd, val = s
            if node < 0:
                raise ValueError(f"send node {node} must be >= 0")
            if not 0 <= key < self.keys:
                raise ValueError(f"send key {key} outside "
                                 f"[0, {self.keys})")
            if rnd < 0 or rnd > MAX_CHURN_HORIZON:
                raise ValueError(
                    f"send round {rnd} outside [0, {MAX_CHURN_HORIZON}]"
                    " (the schedule horizon cap, shared with "
                    "ChurnConfig)")
            if val < 1:
                raise ValueError(
                    f"send {s}: values must be >= 1 (0 is the "
                    "empty-slot sentinel the merge identity rides)")
            rounds = per_key_rounds.setdefault(key, [])
            if rounds and rnd < rounds[-1]:
                raise ValueError(
                    f"send {s}: key {key}'s sends must be scripted in "
                    "round-nondecreasing order — offsets are assigned "
                    "in script order, so out-of-order rounds would "
                    "break offset-order == time-order (the kafka "
                    "ordered-append contract, ops/logs module doc)")
            rounds.append(rnd)
        for key, rounds in per_key_rounds.items():
            if len(rounds) > self.capacity:
                raise ValueError(
                    f"key {key} scripts {len(rounds)} sends but "
                    f"capacity is {self.capacity}: the ring would wrap "
                    "onto an unconsumed slot and alias two offsets — "
                    "raise capacity or split the program")
        # the DEFAULT send program appends 4 entries per key
        # (ops/logs.log_sends) — it must obey the same no-wrap
        # contract, or an unscripted tiny-capacity config would alias
        # slots silently where a scripted one errors loudly
        if not self.sends and self.capacity < 4:
            raise ValueError(
                f"capacity={self.capacity} cannot hold the default "
                "send program (4 sends per key — ops/logs.log_sends): "
                "the ring would wrap and alias offsets; raise "
                "capacity to >= 4 or script the sends")
        for c in self.commits:
            if len(c) != 4:
                raise ValueError(f"log commit {c} must be "
                                 "(node, key, round, upto)")
            node, key, rnd, upto = c
            if node < 0:
                raise ValueError(f"commit node {node} must be >= 0")
            if not 0 <= key < self.keys:
                raise ValueError(f"commit key {key} outside "
                                 f"[0, {self.keys})")
            if rnd < 0 or rnd > MAX_CHURN_HORIZON:
                raise ValueError(
                    f"commit round {rnd} outside "
                    f"[0, {MAX_CHURN_HORIZON}]")
            if upto < 1:
                raise ValueError(f"commit {c}: upto must be >= 1 "
                                 "(nothing-committed is the default "
                                 "state, not a scripted op)")

    def horizon(self) -> int:
        """Rounds after which no further send/commit fires (the
        zero-row steady state of the lowered injection tables).  The
        DEFAULT programs end at rounds 3 (sends) / 4 (commits —
        ops/logs.log_sends / log_commits), so an empty config still
        needs max_rounds > 4."""
        rounds = [3 if not self.sends else 0,
                  4 if not self.commits else 0]
        rounds += [r for _, _, r, _ in self.sends]
        rounds += [r for _, _, r, _ in self.commits]
        return max(rounds) + 1


# Txn traffic load shapes (ops/registers.txn_writes): how the default
# skewed write program spreads over rounds.
TXN_LOADS = ("uniform", "diurnal")


@dataclasses.dataclass(frozen=True)
class TxnConfig:
    """A totally-available transaction workload over last-writer-wins
    registers (ops/registers.py, models/register.py) — the Maelstrom
    ``txn-rw-register`` shape batched: K per-key LWW registers gossiped
    on the pull fabric, each a ``(value, timestamp)`` pair whose
    timestamp is the lexicographic ``(round, owner)`` key packed into
    one int32 plane, so the merge is the exact lattice join (the
    PR 8/10 column discipline extended to a two-plane key).

    ``writes`` script the transactions' write micro-ops as a *program
    over rounds* — ``(node, key, round, value)`` quadruples, lowered to
    runtime operands exactly like the nemesis schedule and the CRDT/log
    injections (compiled loops carry shapes, never content).  Empty
    means the **skewed default program** (ops/registers.txn_writes): a
    closed-form generator — no RNG, no O(T) config object — of
    ``txns`` writes whose key popularity is zipfian(``zipf_alpha``)
    over ``keys``, optionally concentrated onto key 0 with probability
    ``hot_key`` during the middle third of the program (a hot-key
    storm), spread over ``spread_rounds`` rounds by the ``load`` curve
    (``uniform``, or ``diurnal``: density ``1 + sin`` peaking
    mid-window).  Because the builders are closed forms over the
    config scalars, a scenario sweep across skews stays one
    executable.

    Contracts validated loudly:

    * values >= 1 (0 is the never-written sentinel of the value
      plane);
    * at most one write per ``(key, round, node)`` — the packed
      timestamp is what makes LWW deterministic, and two writes
      sharing one timestamp would fork the winner silently (the
      CrdtConfig one-add-tag convention; the default program is
      collision-free by construction, re-checked at lowering);
    * zipf_alpha > 0, 0 <= hot_key <= 1, spread_rounds >= 1.

    Ground truth is LWW over the *applied* writes — a write applies
    iff its owner is alive at the write round AND eventually alive
    under the fault program (the acked-adds rule shared with ops/crdt
    and ops/logs) — computed in-trace from the same operands and
    liveness predicate as the in-loop injection.
    """

    keys: int = 8               # K: register universe
    txns: int = 16              # T: default-program write count
    zipf_alpha: float = 1.1     # key-popularity skew (> 0)
    hot_key: float = 0.0        # storm mass onto key 0, middle third
    load: str = "uniform"       # writes-over-rounds shape (TXN_LOADS)
    spread_rounds: int = 8      # rounds the default program spans
    writes: Tuple[Tuple[int, int, int, int], ...] = ()

    def __post_init__(self):
        object.__setattr__(self, "writes", tuple(
            tuple(int(x) for x in w) for w in self.writes))
        if self.keys < 1:
            raise ValueError("keys must be >= 1")
        if self.txns < 1:
            raise ValueError("txns must be >= 1")
        if self.zipf_alpha <= 0:
            raise ValueError(
                f"zipf_alpha={self.zipf_alpha} must be > 0 (1.0 is "
                "classic zipf; larger is more skewed)")
        if not 0.0 <= self.hot_key <= 1.0:
            raise ValueError(
                f"hot_key={self.hot_key} outside [0, 1] (the storm "
                "probability mass redirected onto key 0)")
        if self.load not in TXN_LOADS:
            raise ValueError(f"unknown load {self.load!r}; choose "
                             f"from {TXN_LOADS}")
        if self.spread_rounds < 1:
            raise ValueError("spread_rounds must be >= 1")
        seen = set()
        for w in self.writes:
            if len(w) != 4:
                raise ValueError(f"txn write {w} must be "
                                 "(node, key, round, value)")
            node, key, rnd, val = w
            if node < 0:
                raise ValueError(f"write node {node} must be >= 0")
            if not 0 <= key < self.keys:
                raise ValueError(f"write key {key} outside "
                                 f"[0, {self.keys})")
            if rnd < 0 or rnd > MAX_CHURN_HORIZON:
                raise ValueError(
                    f"write round {rnd} outside [0, {MAX_CHURN_HORIZON}]"
                    " (the schedule horizon cap, shared with "
                    "ChurnConfig)")
            if val < 1:
                raise ValueError(
                    f"write {w}: values must be >= 1 (0 is the "
                    "never-written sentinel of the value plane)")
            trip = (key, rnd, node)
            if trip in seen:
                raise ValueError(
                    f"write {w}: duplicate (key, round, node) — the "
                    "(round, owner) timestamp is what makes LWW "
                    "deterministic, and two writes sharing one "
                    "timestamp would fork the winner silently "
                    "(docs/WORKLOADS.md \"Transactions\")")
            seen.add(trip)

    def horizon(self) -> int:
        """Rounds after which no further write fires (the zero-row
        steady state of the lowered write tables).  The DEFAULT
        program spans ``spread_rounds`` rounds."""
        if self.writes:
            return max(r for _, _, r, _ in self.writes) + 1
        return self.spread_rounds


@dataclasses.dataclass(frozen=True)
class FaultConfig:
    """In-kernel fault injection.

    The reference never injects faults itself — Maelstrom partitions the
    network externally, and the node survives via an unbounded retry loop
    (main.go:77-87).  In the batched simulator faults are masks applied inside
    the round kernel: a dead node neither sends nor receives; a dropped edge
    loses this round's message (retried implicitly next round, which mirrors
    at-least-once delivery + idempotent receipt, main.go:80-87 + 113).

    ``churn`` scripts TIME-VARYING faults (crash/recover churn,
    partition windows, drop-rate ramps — :class:`ChurnConfig`), lowered
    into round-indexed schedule tables by :mod:`gossip_tpu.ops.nemesis`
    and consumed inside the compiled round loops; ``None`` (or an
    all-default ChurnConfig, normalized to None here) keeps every
    kernel on its static-fault path, bitwise unchanged.
    """

    node_death_rate: float = 0.0   # fraction of nodes dead (static mask)
    drop_prob: float = 0.0         # per-message drop probability per round
    seed: int = 0
    # Explicit failure scenario (SWIM kernels): exactly these node ids fail
    # permanently at round `fail_round`.  Complements the random static mask
    # above; empty = no scripted deaths.  Reachable from the CLI
    # (--dead-nodes/--fail-round) and the RPC `fault` object.
    dead_nodes: Tuple[int, ...] = ()
    fail_round: int = 0
    # Time-varying fault schedule (CLI --churn-event/--partition/
    # --drop-ramp, RPC fault.churn object).
    churn: Optional["ChurnConfig"] = None
    # Scripted byzantine liars (CLI --byz NODE:ROUND:KIND[:ARG], RPC
    # fault.byz object) — ByzConfig; None keeps every kernel on its
    # honest-exchange path, bitwise unchanged.
    byz: Optional["ByzConfig"] = None

    def __post_init__(self):
        # JSON/RPC delivers lists; coerce so the config stays hashable.
        if not isinstance(self.dead_nodes, tuple):
            object.__setattr__(self, "dead_nodes", tuple(self.dead_nodes))
        if any(d < 0 for d in self.dead_nodes):
            raise ValueError("dead_nodes must be non-negative node ids")
        if self.fail_round < 0:
            raise ValueError("fail_round must be >= 0")
        # probabilities are probabilities: an out-of-range rate would
        # silently skew the bernoulli mask draws instead of failing
        if not 0.0 <= self.node_death_rate <= 1.0:
            raise ValueError(
                f"node_death_rate={self.node_death_rate} outside [0, 1]")
        if not 0.0 <= self.drop_prob <= 1.0:
            raise ValueError(
                f"drop_prob={self.drop_prob} outside [0, 1]")
        if isinstance(self.churn, dict):      # RPC: nested JSON object
            object.__setattr__(self, "churn", ChurnConfig(**self.churn))
        if self.churn is not None and self.churn.empty:
            # all-default schedule == no schedule: keep the static hot
            # path (and its bitwise pins) for configs that carry a
            # vacuous churn object
            object.__setattr__(self, "churn", None)
        if isinstance(self.byz, dict):        # RPC: nested JSON object
            object.__setattr__(self, "byz", ByzConfig(**self.byz))
        if self.byz is not None and self.byz.empty:
            # no liars == no byzantine program: keep the honest
            # exchange path (the churn normalization rule)
            object.__setattr__(self, "byz", None)


ENGINES = ("auto", "fused", "xla", "native")


@dataclasses.dataclass(frozen=True)
class RunConfig:
    """Simulation driver parameters.

    ``engine`` selects the round implementation:

    * ``auto``  — the best eligible engine.  On a TPU, single-device
      pull runs on the implicit complete topology (<= 32 rumors) route
      to the fused Pallas kernel automatically (meta records
      ``engine_auto``) — since round 4 that includes static-fault and
      --curve runs (in-kernel masks; fixed-length scan twins); other
      pull / anti-entropy runs take the bit-packed XLA fast path
      (models/si_packed.py); everything else the bool kernels
      (models/si.py).  Works on any backend, any mode.
    * ``xla``   — force the XLA kernels even where the fused engine is
      eligible (pull/anti-entropy bit-packed, bool otherwise) — the
      opt-out for cross-validating against the sharded paths, whose
      threefry partner streams match the single-device XLA engine
      bitwise but not the fused kernel's hardware-PRNG stream.
    * ``fused`` — force the fused Pallas kernels (ops/pallas_round.py):
      hardware-PRNG partner sampling + in-row gather + OR-merge in one
      ``pallas_call`` (tables past the VMEM envelope use the staged
      big-table path).  TPU only (the hardware PRNG has no CPU
      equivalent); pull mode on the implicit complete topology; static
      fault masks (node_death_rate / drop_prob) in-kernel on every
      layout, scripted dead_nodes rejected.  Single device: <= 32
      rumors packed in one word per node.  Multi-device: rumor planes
      of 32 sharded across the mesh (parallel/sharded_fused.py), zero
      per-round ICI.  Ineligible configs raise rather than silently
      substituting another engine.
    * ``native`` — go-native backend only: force the C++ event core
      (native/eventsim.cpp, 20-100x the Python engine) and raise the
      node cap to 1M, making large-N parity spot checks CLI-reachable
      (VERDICT r2 item 8).  Raises if no C++ compiler is available —
      never a silent Python fallback.  The jax-tpu backend rejects it.
    """

    target_coverage: float = 0.99
    max_rounds: int = 256
    seed: int = 0
    origin: int = 0          # node where rumor 0 starts (rumor r starts at origin+r)
    engine: str = "auto"

    def __post_init__(self):
        if not 0.0 < self.target_coverage <= 1.0:
            raise ValueError("target_coverage must be in (0, 1]")
        if self.engine not in ENGINES:
            raise ValueError(f"unknown engine {self.engine!r}; "
                             f"choose from {ENGINES}")


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Admission-batching knobs for the gRPC sidecar (rpc/batcher).

    The serving layer coalesces in-flight ``Run``/``Ensemble`` requests
    into one device-resident megabatch per collector tick
    (parallel/sweep.request_sweep_curves); these are the queue-shape
    parameters — everything about WHICH requests may share an
    executable lives in the batch key (rpc/batcher.batch_key,
    docs/SERVING.md memo-key vs operand table), not here.

    * ``tick_ms`` — the collector cadence: every tick the queue drains
      and each batch-key group runs as one megabatch.  Smaller ticks
      trade batch size for admission latency.
    * ``max_batch`` — per-tick per-key cap on coalesced requests
      (ensemble members count individually); the rest stay queued for
      the next tick.
    * ``max_queue`` — the backpressure cap: an admission past this
      depth is rejected with RESOURCE_EXHAUSTED instead of growing the
      queue without bound (the reply tells the client to back off —
      SidecarClient's retry policy treats it as a well-formed error,
      never a transport failure).
    * ``devices`` — megabatch mesh width: the batcher shards the
      request axis of each tick's megabatch over the first ``devices``
      JAX devices (a 1-D ``Mesh`` on the ``"request"`` axis —
      parallel/sweep.request_sweep_curves).  Must be a power of two so
      every pow2 lane bucket divides the mesh and dispatch never
      fragments the executable cache; 1 (default) is the solo
      single-device path, bit-identical everywhere.  The batcher
      REFUSES at construction when the process has fewer devices than
      requested — a mesh must never silently degrade.
    * ``coordinator`` / ``num_processes`` / ``process_id`` — the
      jax.distributed topology for one logical replica spanning
      processes (``jax.distributed.initialize`` in rpc/sidecar.serve);
      ``num_processes == 1`` (default) is the degenerate single-process
      case that skips initialization entirely and runs everywhere.
    """

    tick_ms: float = 20.0
    max_batch: int = 64
    max_queue: int = 256
    devices: int = 1
    coordinator: Optional[str] = None
    num_processes: int = 1
    process_id: int = 0

    def __post_init__(self):
        if self.tick_ms <= 0:
            raise ValueError("tick_ms must be > 0")
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        if self.devices < 1 or (self.devices & (self.devices - 1)):
            raise ValueError(
                "devices must be a power of two >= 1 (pow2 lane "
                "buckets must divide the mesh so dispatch never "
                f"fragments the executable cache), got {self.devices}")
        if self.num_processes < 1:
            raise ValueError("num_processes must be >= 1")
        if not 0 <= self.process_id < self.num_processes:
            raise ValueError("process_id must be in [0, num_processes)")
        if self.num_processes > 1 and not self.coordinator:
            raise ValueError(
                "a multi-process replica (num_processes > 1) needs a "
                "coordinator address (host:port) for "
                "jax.distributed.initialize")


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Replicated-serving knobs for the fronting router (rpc/router).

    The router fronts N sidecar replicas, health-probes each on the
    ``SidecarClient.health`` path, dispatches ``Run``/``Ensemble`` to
    healthy replicas, and on a replica transport failure re-dispatches
    the in-flight request to a survivor (safe: requests are
    deterministic pure functions of their payload — docs/SERVING.md
    "Fleet").  These are the fleet-shape parameters:

    * ``replicas`` — replica count for spawned fleets (``Fleet`` /
      CLI ``route``); a router built over explicit addresses takes its
      count from the address list.
    * ``probe_interval_ms`` / ``probe_timeout_s`` — health-probe
      cadence and per-probe deadline; a probe past the deadline counts
      as a failure.
    * ``down_after`` — consecutive probe failures before a replica
      leaves rotation (a dispatch-path transport failure marks it down
      immediately: a dead connection is definitive).
    * ``up_after`` — consecutive healthy probes before a DOWNED
      replica re-enters rotation — the flap hysteresis: a replica
      alternating healthy/unhealthy can never oscillate back in
      faster than this (test-pinned).  First-time admission at startup
      needs only one healthy probe (nothing was lost yet).
    * ``max_inflight`` — per-replica in-flight dispatch cap; when every
      healthy replica is at its cap the router SHEDS with
      RESOURCE_EXHAUSTED + a ``shed`` ledger event (it never queues
      unboundedly, never silently drops).
    * ``control_capacity`` — ring capacity of each replica's
      control-plane log key (ops/logs; committed offset = config
      epoch); a fleet exceeding it in one run errors loudly rather
      than alias epochs on a ring wrap.
    * ``devices_per_replica`` — megabatch mesh width each spawned
      replica must serve with (ServingConfig.devices in the child).
      The fleet threads the host-device-count env to children
      (``XLA_FLAGS=--xla_force_host_platform_device_count=K`` via
      router.fleet_env) and REFUSES loudly after spawn when a child's
      health probe reports fewer serving devices than requested — the
      child pins ``JAX_PLATFORMS=cpu``, so without the env the mesh
      would silently degrade to 1 device.  Power of two, like
      ServingConfig.devices.
    """

    replicas: int = 2
    probe_interval_ms: float = 250.0
    probe_timeout_s: float = 2.0
    down_after: int = 2
    up_after: int = 3
    max_inflight: int = 8
    control_capacity: int = 64
    devices_per_replica: int = 1

    def __post_init__(self):
        if self.replicas < 1:
            raise ValueError("replicas must be >= 1")
        if (self.devices_per_replica < 1
                or (self.devices_per_replica
                    & (self.devices_per_replica - 1))):
            raise ValueError(
                "devices_per_replica must be a power of two >= 1, "
                f"got {self.devices_per_replica}")
        if self.probe_interval_ms <= 0:
            raise ValueError("probe_interval_ms must be > 0")
        if self.probe_timeout_s <= 0:
            raise ValueError("probe_timeout_s must be > 0")
        if self.down_after < 1:
            raise ValueError("down_after must be >= 1")
        if self.up_after < 1:
            raise ValueError("up_after must be >= 1")
        if self.max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        if self.control_capacity < 4:
            # the LogConfig floor: an unscripted log config must hold
            # the default send program's ring
            raise ValueError("control_capacity must be >= 4")


EXCHANGES = ("dense", "sparse", "halo")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device mesh for node-dimension sharding (the SP/CP analog: the scaled
    long dimension here is *nodes*, not tokens — see SURVEY.md §5).

    ``exchange`` picks the cross-shard communication pattern:

    * ``dense``  — all_gather / psum_scatter of full digest tables (any
      topology, any mode; O(N) ICI bytes per round);
    * ``sparse`` — stratified all_to_all request/response (implicit
      complete topology, pull/anti-entropy; O(messages) bytes —
      parallel/sharded_sparse.py);
    * ``halo``   — ppermute halo exchange (band-limited explicit
      topologies, flood/pull/push/pushpull; O(band) bytes —
      parallel/halo.py).
    """

    n_devices: int = 1
    axis_name: str = "nodes"
    exchange: str = "dense"

    def __post_init__(self):
        if self.exchange not in EXCHANGES:
            raise ValueError(f"unknown exchange {self.exchange!r}; "
                             f"choose from {EXCHANGES}")
