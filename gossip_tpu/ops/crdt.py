"""CRDT merge kernels: commutative-merge payloads on the gossip fabric.

PAPER.md's reference solves exactly one Maelstrom / Gossip Glomers
workload — broadcast with a dedup set — but the sibling challenges
(grow-only / PN counters, OR-sets) are the *same* epidemic exchange
with a different payload: instead of an infected bit that merges by
OR, each node carries a state whose merge is commutative, associative,
and idempotent (a join-semilattice), so gossip order, duplication, and
loss never corrupt the value — partitions are exactly what CRDTs exist
for (Shapiro et al., "Conflict-free Replicated Data Types", SSS 2011).

Array forms (one row per node, the ``seen[N, R]`` convention):

  * **G-Counter / PN-Counter** — per-node counter shards
    ``int32[N, S]``: column ``j`` is node-``owner(j)``'s contribution;
    only the owner increments its own column, everyone else learns it
    by gossip, and merge is **elementwise max per shard column** (the
    owner's value is monotone, so max is exact).  ``gcounter``: S = n,
    owner(j) = j; ``pncounter``: S = 2n — columns 0..n-1 are the P
    (increment) plane, n..2n-1 the N (decrement) plane, both grow-only,
    value = sum(P) - sum(N).
  * **G-Set / OR-Set** — packed bit-planes ``uint32[N, 2W]``
    (ops/bitpack layout, 32 elements per word): columns 0..W-1 are the
    add plane, W..2W-1 the tombstone plane, merge is **bitwise OR** on
    both, membership = add & ~tombstone.  This is the array form of an
    OR-set where each element carries one unique add tag per run
    (CrdtConfig enforces at-most-one scripted add per element), so
    add-wins and 2P semantics coincide — documented in
    docs/WORKLOADS.md.
  * **Vector clocks** — ``int32[N, n]``: node i's causal clock; the
    owner ticks its own entry per local event, merge is elementwise
    max (the classic vector-clock join).

Injections are runtime OPERANDS, like the nemesis schedule tables
(ops/nemesis module doc): :func:`inject_args` lowers a CrdtConfig to a
tiny tuple of padded arrays the step factories append to their
``tables`` tuple, so the compiled loops carry injection SHAPES but no
CONTENT — two add programs of the same padded arity re-enter one
executable.

Ground truth and the value-convergence metric
---------------------------------------------
An injection is **applied** iff its owner is alive at the injection
round AND eventually alive under the fault program — the batched
analog of the Maelstrom counter checker counting only ACKED adds: a
node destined for permanent death contributes nothing, which is what
makes exact convergence on the eventual-alive set a guaranteed
invariant (every applied contribution's owner eventually recovers and
re-disseminates its full shard).  :func:`ground_truth` computes the
merged truth row from the same operands IN-TRACE (integer-exact — no
float readout anywhere), and :func:`converged_count` counts alive
nodes whose full state row equals it bitwise.  The drivers divide the
integer count by the eventual-alive total ONCE on the host
(value_conv), the repo's bitwise-curve convention.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from gossip_tpu.config import (CRDT_COUNTER_KINDS, CRDT_SET_KINDS,
                               GCOUNTER, GSET, ORSET, PNCOUNTER, VCLOCK,
                               CrdtConfig, FaultConfig)
from gossip_tpu.ops.bitpack import n_words, pack

# How many trailing step arguments an injection program occupies on a
# factory's ``tables`` tuple (inject_args / split_inject): counters
# lower to (col, round, amount), sets to (add_elem, add_round,
# rem_elem, rem_round).  Vector clocks inject nothing (self-tick).
N_INJECT_OPERANDS = {GCOUNTER: 3, PNCOUNTER: 3, GSET: 4, ORSET: 4,
                     VCLOCK: 0}

# Minimum padded injection-list length: like the nemesis tables'
# SCHED_T_MIN, a power-of-two bucket makes same-bucket programs
# shape-identical so they share one compiled loop.
INJECT_A_MIN = 8

# Sentinel round for "no injection" on padding rows — far beyond any
# real round (the ChurnConfig horizon cap is 100k), so the in-loop
# ``round == r`` compare never fires for them.
NO_ROUND = 1 << 29


def shard_columns(kind: str, n: int) -> int:
    """S: the state's column count for ``n`` nodes (module doc)."""
    if kind == GCOUNTER or kind == VCLOCK:
        return n
    if kind == PNCOUNTER:
        return 2 * n
    raise ValueError(f"{kind!r} is not a counter-shard kind")


def set_words(cfg: CrdtConfig) -> int:
    """2W: the packed set state's word count (add + tombstone planes)."""
    return 2 * n_words(cfg.elements)


def state_width(cfg: CrdtConfig, n: int) -> int:
    """Columns of the ``val`` row for this config (counter shards or
    packed set words)."""
    if cfg.kind in CRDT_SET_KINDS:
        return set_words(cfg)
    return shard_columns(cfg.kind, n)


def state_dtype(cfg: CrdtConfig):
    return jnp.uint32 if cfg.kind in CRDT_SET_KINDS else jnp.int32


# -- merge kernels (the join-semilattice operations) -------------------

def merge_max(a: jax.Array, b: jax.Array) -> jax.Array:
    """Counter-shard / vector-clock join: elementwise max.  Exact
    because each column is written only by its monotone owner."""
    return jnp.maximum(a, b)


def merge_or(a: jax.Array, b: jax.Array) -> jax.Array:
    """Packed-set join: bitwise OR on the add + tombstone planes."""
    return a | b


def merge(kind: str, a: jax.Array, b: jax.Array) -> jax.Array:
    """The ONE kind dispatcher — every exchange path and every
    algebraic pin goes through it, so a driver can never ship a merge
    the tests did not pin."""
    if kind in CRDT_SET_KINDS:
        return merge_or(a, b)
    return merge_max(a, b)


def pull_merge_crdt(kind: str, rows_all: jax.Array, partners: jax.Array,
                    sentinel: int) -> jax.Array:
    """Merge of k sampled peers' state rows -> ``[N_local, S]``.

    The CRDT twin of ops/propagate.pull_merge / si_packed
    .pull_merge_packed: gather k rows, mask invalid partners to the
    merge identity (0 — the identity of both OR and max-on-nonnegative,
    which the counter planes are by construction), reduce with
    :func:`merge`.  One uint32/int32 gather moves 32 set elements or
    one counter shard per lane.
    """
    valid = partners < sentinel
    safe = jnp.minimum(partners, sentinel - 1)
    got = rows_all[safe]                              # [Nl, k, S]
    got = jnp.where(valid[:, :, None], got,
                    jnp.zeros((), rows_all.dtype))
    out = got[:, 0, :]
    for j in range(1, got.shape[1]):
        out = merge(kind, out, got[:, j, :])
    return out


# -- byzantine exchange: liar transforms + array-form defenses ---------
#
# The byz half of the nemesis subsystem (ops/nemesis ByzSchedule;
# docs/ROBUSTNESS.md "Byzantine adversaries").  Both halves are
# RECEIVER-side renders of the gathered rows, all jnp.where on the byz
# tables, so the compiled loop carries liar SHAPES but never content:
#
#   * :func:`_byz_serve_counter` / :func:`_byz_serve_set` transform the
#     row an ACTIVE liar partner serves — corrupt (xor), replay (the
#     genesis snapshot: all zeros, maximal staleness), equivocate (a
#     receiver-id-keyed pattern), inflate (raise columns / set bits it
#     does not own).  Every transform touches only NON-OWN components:
#     a liar's own column/element is its own to write (the standard
#     BFT limitation — an own-component lie is indistinguishable from
#     a legitimate write), which is exactly what makes the defended
#     admission below provably reject ALL dishonest content.
#   * with ``defend=True`` the admission is a one-line lattice check
#     per payload: counters admit only the partner's OWN column (the
#     owner-column write guard; the max join is itself the per-column
#     monotonicity clamp), packed sets admit a bit served directly by
#     its owner OR echoed by >= quorum distinct partners this round
#     (the quorum scalar is a traced operand).  Defended exchanges
#     propagate owner-direct (slower — coupon-collector rounds — but
#     EXACT on honest-owned components under any f liars scripted
#     here; quorum additionally tolerates f < q non-colluding forgers
#     on the broadcast planes).

def set_owner_words(elements: int, n: int, origin: int) -> jax.Array:
    """uint32[n, 2W]: the packed element bits node i OWNS (element e's
    owner is ``(origin + e) % n`` — the inject_rows convention), tiled
    over both planes (an add bit and its tombstone share an owner).
    Content-static (iota + pack), shared by the liar transforms, the
    defended admission, and the honest-component convergence mask so
    the three can never disagree on ownership."""
    owners = (origin + jnp.arange(elements, dtype=jnp.int32)) % n
    own = owners[None, :] == jnp.arange(n, dtype=jnp.int32)[:, None]
    w = pack(own)                                      # [n, W]
    return jnp.concatenate([w, w], axis=1)             # [n, 2W]


def _set_universe(elements: int, words2: int) -> jax.Array:
    """uint32[2W]: the element-universe bits of both planes — keeps
    every wire transform off the padding bits past ``elements`` (the
    ops/bitpack never-set contract; a forged padding bit would leak
    into popcount observability)."""
    ones = jnp.ones((1, elements), jnp.bool_)
    w = pack(ones)[0]
    return jnp.concatenate([w, w])[:words2]


def _byz_serve_counter(got, safe, active, gids, byz, n: int):
    """Render what liar partners SERVE (counter shards [Nl, k, S]) —
    module comment catalog; non-own columns only."""
    from gossip_tpu.ops import nemesis as NE
    kindp = byz.kind[safe][:, :, None]                 # [Nl, k, 1]
    argp = byz.arg[safe][:, :, None]
    s = got.shape[-1]
    col_owner = jnp.arange(s, dtype=jnp.int32) % n
    nonown = col_owner[None, None, :] != safe[:, :, None]
    corrupt = jnp.where(nonown, got ^ argp, got)
    inflate = jnp.where(nonown, got + argp, got)
    equiv = jnp.where(nonown,
                      got + argp * (1 + gids[:, None, None]), got)
    out = jnp.where(kindp == NE.BYZ_CODES["corrupt"], corrupt, got)
    out = jnp.where(kindp == NE.BYZ_CODES["replay"],
                    jnp.zeros_like(got), out)
    out = jnp.where(kindp == NE.BYZ_CODES["equivocate"], equiv, out)
    out = jnp.where(kindp == NE.BYZ_CODES["inflate"], inflate, out)
    return jnp.where(active[:, :, None], out, got)


def _byz_serve_set(got, safe, active, gids, byz, own_words, universe):
    """Render what liar partners SERVE (packed set planes
    [Nl, k, 2W]) — non-own bits only, inside the element universe."""
    from gossip_tpu.ops import nemesis as NE
    kindp = byz.kind[safe][:, :, None]
    argp = byz.arg[safe].astype(jnp.uint32)[:, :, None]
    foreign = ~own_words[safe] & universe              # [Nl, k, 2W]
    corrupt = got ^ (argp & foreign)
    inflate = got | foreign
    epat = argp ^ (gids.astype(jnp.uint32)
                   * jnp.uint32(2654435761))[:, None, None]
    equiv = got ^ (epat & foreign)
    out = jnp.where(kindp == NE.BYZ_CODES["corrupt"], corrupt, got)
    out = jnp.where(kindp == NE.BYZ_CODES["replay"],
                    jnp.zeros_like(got), out)
    out = jnp.where(kindp == NE.BYZ_CODES["equivocate"], equiv, out)
    out = jnp.where(kindp == NE.BYZ_CODES["inflate"], inflate, out)
    return jnp.where(active[:, :, None], out, got)


def _unique_valid(safe, valid):
    """bool[Nl, k]: first occurrence of each distinct valid partner —
    the quorum dedupe (a partner sampled twice is ONE independent
    witness, never two)."""
    k = safe.shape[1]
    if k == 1:
        return valid
    eq = safe[:, :, None] == safe[:, None, :]          # [Nl, j, i]
    earlier = jnp.tril(jnp.ones((k, k), jnp.bool_), -1)[None]
    dup = jnp.any(eq & valid[:, None, :] & earlier, axis=2)
    return valid & ~dup


def pull_merge_crdt_byz(cfg: CrdtConfig, rows_all: jax.Array,
                        partners: jax.Array, sentinel: int, *,
                        byz, round_, gids: jax.Array, n: int,
                        origin: int, alive_fn, defend: bool
                        ) -> jax.Array:
    """:func:`pull_merge_crdt` under a byzantine program: gather, mask
    invalid partners to the merge identity, render what each ACTIVE
    liar partner serves (module comment), then either the honest merge
    (``defend=False`` — the control arm, provably divergent under
    forging liars: a forged value above truth sticks under max/OR
    forever) or the defended admission (owner-column guard for counter
    shards; owner-direct OR quorum-echo for packed set bits).  A
    churn-down liar serves nothing — its row is already zeroed by the
    visibility mask and ``alive_fn`` gates the transform too."""
    kind = cfg.kind
    valid = partners < sentinel
    safe = jnp.minimum(partners, sentinel - 1)
    got = rows_all[safe]                               # [Nl, k, S]
    got = jnp.where(valid[:, :, None], got,
                    jnp.zeros((), rows_all.dtype))
    from gossip_tpu.ops import nemesis as NE
    active = (valid & NE.byz_active(byz, safe, round_)
              & alive_fn(safe, round_))
    if kind in CRDT_SET_KINDS:
        own_words = set_owner_words(cfg.elements, n, origin)
        universe = _set_universe(cfg.elements, rows_all.shape[-1])
        got = _byz_serve_set(got, safe, active, gids, byz, own_words,
                             universe)
        if not defend:
            out = got[:, 0, :]
            for j in range(1, got.shape[1]):
                out = merge_or(out, got[:, j, :])
            return out
        # defended: owner-direct bits, plus bits echoed by >= quorum
        # distinct partners (carry-save counting chain, depth 3 —
        # ByzConfig caps quorum at 3)
        uniq = _unique_valid(safe, valid)
        once = jnp.zeros_like(got[:, 0, :])
        twice = jnp.zeros_like(once)
        thrice = jnp.zeros_like(once)
        direct = jnp.zeros_like(once)
        for j in range(got.shape[1]):
            b = jnp.where(uniq[:, j, None], got[:, j, :],
                          jnp.zeros((), got.dtype))
            thrice = thrice | (twice & b)
            twice = twice | (once & b)
            once = once | b
            direct = direct | (got[:, j, :] & own_words[safe[:, j]])
        q = byz.quorum
        echoed = jnp.where(q <= 1, once,
                           jnp.where(q == 2, twice, thrice))
        return direct | echoed
    # counter shards / vector clocks
    got = _byz_serve_counter(got, safe, active, gids, byz, n)
    if defend:
        # owner-column write guard: from partner p admit only column
        # p's plane entries (pncounter: both its P and N columns fold
        # through col % n); max IS the monotonicity clamp
        s = got.shape[-1]
        col_owner = jnp.arange(s, dtype=jnp.int32) % n
        admit = ((col_owner[None, None, :] == safe[:, :, None])
                 & valid[:, :, None])
        got = jnp.where(admit, got, jnp.zeros((), got.dtype))
    out = got[:, 0, :]
    for j in range(1, got.shape[1]):
        out = merge_max(out, got[:, j, :])
    return out


# -- honest-component convergence (the byz_conv metric) ----------------

def honest_component_mask(cfg: CrdtConfig, n: int, origin: int,
                          honest: jax.Array):
    """The honest-OWNED components of a state row: bool[S] column mask
    for counter shards, uint32[2W] bit mask for packed sets.  A
    liar-owned component is excluded from the ``byz_conv`` equality —
    a liar may withhold (replay) or self-write arbitrarily, both
    undetectable by construction, so honest convergence is only ever
    claimable on honest-owned state (docs/ROBUSTNESS.md)."""
    if cfg.kind in CRDT_SET_KINDS:
        owners = (origin + jnp.arange(cfg.elements,
                                      dtype=jnp.int32)) % n
        w = pack(honest[owners][None, :])[0]
        return jnp.concatenate([w, w])
    s = state_width(cfg, n)
    col_owner = jnp.arange(s, dtype=jnp.int32) % n
    return honest[col_owner]


def byz_converged_count(cfg: CrdtConfig, rows: jax.Array,
                        truth: jax.Array, alive_honest: jax.Array,
                        comp_mask) -> jax.Array:
    """int32 count of honest eventually-alive nodes whose HONEST-owned
    components equal the ground truth bitwise — the ``byz_conv``
    numerator (:func:`converged_count` restricted by
    :func:`honest_component_mask`; divide by the honest eventual-alive
    total once on the host, the bitwise-curve convention)."""
    if cfg.kind in CRDT_SET_KINDS:
        eq = jnp.all((rows & comp_mask[None, :])
                     == (truth & comp_mask)[None, :], axis=-1)
    else:
        eq = jnp.all(jnp.where(comp_mask[None, :],
                               rows == truth[None, :], True), axis=-1)
    return jnp.sum(eq & alive_honest, dtype=jnp.int32)


def byz_conv_frac(cfg: CrdtConfig, rows: jax.Array, truth: jax.Array,
                  alive_honest: jax.Array, comp_mask) -> jax.Array:
    """f32 in-trace byz_conv fraction — RoundMetrics column only (the
    value_conv_frac rule: pinned readouts use the integer count)."""
    c = byz_converged_count(cfg, rows, truth, alive_honest,
                            comp_mask).astype(jnp.float32)
    return c / jnp.maximum(jnp.sum(alive_honest, dtype=jnp.float32),
                           1.0)


# -- injection lowering (runtime operands, the nemesis pattern) --------

def _pad_pow2(length: int) -> int:
    return max(INJECT_A_MIN, 1 << max(0, (length - 1).bit_length()))


def counter_adds(cfg: CrdtConfig, n: int):
    """The effective add list ``[(node, round, amount), ...]`` —
    scripted, or the default program's closed form: node j adds
    ``1 + j % 7`` at round 0, pncounter alternating sign by parity
    (odd nodes decrement).  A formula, not a config table, so no O(N)
    config object is ever materialized (CrdtConfig doc); this is the
    ONE definition of the defaults, shared by the lowering and ground
    truth through :func:`inject_args`."""
    if cfg.adds:
        return list(cfg.adds)
    sign = -1 if cfg.kind == PNCOUNTER else 1
    return [(j, 0, int(1 + j % 7) * (sign if j % 2 else 1))
            for j in range(n)]


def inject_args(cfg: CrdtConfig, n: int) -> tuple:
    """Lower the injection program to its operand tuple (module doc):
    counters -> ``(col int32[A], round int32[A], amount int32[A])``
    with the pncounter N-plane offset already folded into ``col``;
    sets -> ``(add_elem, add_round, rem_elem, rem_round)`` (int32,
    padded with NO_ROUND sentinels).  Padded to a power-of-two bucket
    so same-arity programs are shape-identical."""
    kind = cfg.kind
    if kind == VCLOCK:
        return ()
    if kind in CRDT_COUNTER_KINDS:
        adds = counter_adds(cfg, n)
        bad = [a for a in adds if a[0] >= n]
        if bad:
            raise ValueError(f"counter adds reference node ids >= "
                             f"n={n}: {bad}")
        a_pad = _pad_pow2(len(adds))
        col = [(node if amt >= 0 else n + node)
               if kind == PNCOUNTER else node
               for node, _, amt in adds]
        col += [0] * (a_pad - len(adds))
        rnd = [r for _, r, _ in adds] + [NO_ROUND] * (a_pad - len(adds))
        amt = [abs(a) for _, _, a in adds] + [0] * (a_pad - len(adds))
        return (jnp.asarray(col, jnp.int32), jnp.asarray(rnd, jnp.int32),
                jnp.asarray(amt, jnp.int32))
    # sets: default add program = every element at round 0
    set_adds = (list(cfg.set_adds) if cfg.set_adds
                else [(e, 0) for e in range(cfg.elements)])

    def elem_rounds(pairs):
        a_pad = _pad_pow2(len(pairs)) if pairs else INJECT_A_MIN
        elem = [e for e, _ in pairs] + [0] * (a_pad - len(pairs))
        rnd = ([r for _, r in pairs]
               + [NO_ROUND] * (a_pad - len(pairs)))
        return (jnp.asarray(elem, jnp.int32),
                jnp.asarray(rnd, jnp.int32))

    return elem_rounds(set_adds) + elem_rounds(list(cfg.set_removes))


def split_inject(cfg: CrdtConfig, tbl: tuple):
    """(head_tables, inject_operands): peel the injection operands
    :func:`inject_args` appended back off a step's ``*tables`` tail —
    the ONE inverse (the nemesis split_tables discipline)."""
    k = N_INJECT_OPERANDS[cfg.kind]
    if k == 0:
        return tbl, ()
    return tbl[:-k], tbl[-k:]


def _applied_mask(rounds: jax.Array, owners: jax.Array,
                  alive_at_fn, eventual: jax.Array) -> jax.Array:
    """bool[A]: which injections are APPLIED under the fault program —
    owner alive at the injection round and eventually alive (module
    doc: the acked-adds semantics).  ``alive_at_fn(node, round) ->
    bool`` broadcasts; padding rows carry NO_ROUND and an in-range
    dummy owner, and die out on the alive_at compare below only if the
    schedule said so — so they are excluded explicitly here."""
    real = rounds < NO_ROUND
    return real & alive_at_fn(owners, rounds) & eventual[owners]


def alive_at_fn(fault: Optional[FaultConfig], n: int, origin: int):
    """``(nodes int32[...], rounds int32[...]) -> bool[...]`` liveness
    of ``nodes`` at ``rounds`` under the static mask + churn windows —
    in-trace safe, shared by the step's apply mask and ground truth so
    the two can never disagree on which injections fired."""
    from gossip_tpu.ops import nemesis as NE
    base = NE.base_alive_or_ones(fault, n, origin) \
        if fault is not None else jnp.ones((n,), jnp.bool_)
    ch = NE.get(fault)
    if ch is not None:
        sched_die, sched_rec = NE._event_tables(ch, n)
    else:
        sched_die = jnp.full((n,), NE.NEVER, jnp.int32)
        sched_rec = jnp.full((n,), NE.NEVER, jnp.int32)

    def fn(nodes, rounds):
        nodes = jnp.asarray(nodes, jnp.int32)
        rounds = jnp.asarray(rounds, jnp.int32)
        down = (sched_die[nodes] <= rounds) & (rounds < sched_rec[nodes])
        return base[nodes] & ~down

    return fn


def eventual_alive_crdt(fault: Optional[FaultConfig], n: int,
                        origin: int) -> jax.Array:
    """bool[n] eventual-alive set as a real array (the CRDT value-
    convergence denominator; ops/nemesis.eventual_alive, None-free)."""
    from gossip_tpu.ops import nemesis as NE
    if fault is None:
        return jnp.ones((n,), jnp.bool_)
    return NE.eventual_alive(fault, n, origin)


def inject_rows(cfg: CrdtConfig, inj: tuple, gids: jax.Array, round_,
                n: int, origin: int, alive_fn, eventual: jax.Array
                ) -> jax.Array:
    """The rows each node merges into its OWN state at ``round_`` —
    ``[len(gids), S]`` in the state dtype, zero except where this
    round's applied injections land on a ``gids`` row.  In-trace; the
    injections arrive as the :func:`inject_args` operands."""
    r = jnp.asarray(round_, jnp.int32)
    kind = cfg.kind
    if kind == VCLOCK:
        raise ValueError("vclock rows tick via vclock_tick, not "
                         "injections")
    if kind in CRDT_COUNTER_KINDS:
        col, rnd, amt = inj
        owner = col % n                                   # N-plane folds
        fire = (rnd == r) & _applied_mask(rnd, owner, alive_fn,
                                          eventual)
        s = shard_columns(kind, n)
        row = jnp.zeros((s,), jnp.int32).at[col].add(
            jnp.where(fire, amt, 0), mode="drop")
        col_owner = jnp.arange(s, dtype=jnp.int32) % n
        own = col_owner[None, :] == gids[:, None]         # [Nl, S]
        return jnp.where(own, row[None, :], 0)
    add_elem, add_rnd, rem_elem, rem_rnd = inj
    owners = (origin + jnp.arange(cfg.elements, dtype=jnp.int32)) % n

    def plane(elem, rnd):
        fire = (rnd == r) & _applied_mask(rnd, owners[elem], alive_fn,
                                          eventual)
        bits = jnp.zeros((cfg.elements,), jnp.bool_).at[elem].max(
            fire, mode="drop")
        # element e lands on its owner's row only
        mine = owners[None, :] == gids[:, None]           # [Nl, E]
        return pack(bits[None, :] & mine)                 # [Nl, W]

    return jnp.concatenate([plane(add_elem, add_rnd),
                            plane(rem_elem, rem_rnd)], axis=1)


def vclock_tick(vc: jax.Array, gids: jax.Array, alive: jax.Array,
                n: int) -> jax.Array:
    """One local event per alive node: owner entries increment
    (``vc[i, gids[i]] += alive[i]``) — the classic tick, the only
    write a non-owner never makes."""
    rows = jnp.arange(vc.shape[0], dtype=jnp.int32)
    return vc.at[rows, gids].add(
        jnp.where(alive, 1, 0).astype(vc.dtype), mode="drop")


# -- ground truth + value convergence (integer-exact) ------------------

def ground_truth(cfg: CrdtConfig, inj: tuple, fault, n: int,
                 origin: int) -> jax.Array:
    """The merged row ``[S]`` every eventually-alive node must reach:
    the merge of all APPLIED injections (module doc).  Built from the
    SAME operands and liveness predicate as the in-loop injection, so
    the target and the trajectory cannot drift.  In-trace safe and
    integer-exact."""
    alive_fn = alive_at_fn(fault, n, origin)
    eventual = eventual_alive_crdt(fault, n, origin)
    kind = cfg.kind
    if kind in CRDT_COUNTER_KINDS:
        col, rnd, amt = inj
        fire = _applied_mask(rnd, col % n, alive_fn, eventual)
        s = shard_columns(kind, n)
        return jnp.zeros((s,), jnp.int32).at[col].add(
            jnp.where(fire, amt, 0), mode="drop")
    add_elem, add_rnd, rem_elem, rem_rnd = inj
    owners = (origin + jnp.arange(cfg.elements, dtype=jnp.int32)) % n

    def plane(elem, rnd):
        fire = _applied_mask(rnd, owners[elem], alive_fn, eventual)
        bits = jnp.zeros((cfg.elements,), jnp.bool_).at[elem].max(
            fire, mode="drop")
        return pack(bits[None, :])[0]                     # [W]

    return jnp.concatenate([plane(add_elem, add_rnd),
                            plane(rem_elem, rem_rnd)])


def counter_value(kind: str, rows: jax.Array, n: int) -> jax.Array:
    """int32[...]: the merged counter value of each state row — sum of
    shards (gcounter), sum(P) - sum(N) (pncounter).  Integer-exact."""
    if kind == GCOUNTER:
        return jnp.sum(rows, axis=-1, dtype=jnp.int32)
    if kind == PNCOUNTER:
        return (jnp.sum(rows[..., :n], axis=-1, dtype=jnp.int32)
                - jnp.sum(rows[..., n:], axis=-1, dtype=jnp.int32))
    raise ValueError(f"{kind!r} has no scalar counter value")


def set_members(rows: jax.Array) -> jax.Array:
    """Membership planes of a packed set state: add & ~tombstone
    (``[..., W]`` from the ``[..., 2W]`` planes)."""
    w = rows.shape[-1] // 2
    return rows[..., :w] & ~rows[..., w:]


def converged_count(rows: jax.Array, truth: jax.Array,
                    alive: jax.Array) -> jax.Array:
    """int32 count of alive nodes whose state row equals the ground
    truth BITWISE (full-row equality: for sets that is both planes, so
    a node holding the member set but missing a tombstone has not
    converged — it could still un-remove on a later merge).  Divide by
    the eventual-alive total ONCE on the host for value_conv (module
    doc: integer counts cross the device boundary, never fractions)."""
    eq = jnp.all(rows == truth[None, :], axis=-1)
    return jnp.sum(eq & alive, dtype=jnp.int32)


def value_conv_frac(rows: jax.Array, truth: jax.Array,
                    alive: jax.Array) -> jax.Array:
    """f32 in-trace convergence fraction — for the RoundMetrics
    ``value_conv`` column and while_loop conds ONLY (observability and
    control flow); every pinned readout uses :func:`converged_count`
    and divides on the host."""
    c = converged_count(rows, truth, alive).astype(jnp.float32)
    return c / jnp.maximum(jnp.sum(alive, dtype=jnp.float32), 1.0)


def payload_count(cfg: CrdtConfig, rows: jax.Array,
                  alive: jax.Array) -> jax.Array:
    """f32 total payload mass over alive rows — counter mass (shard
    sums) or set bit count — the CRDT ``newly`` counter's integrand
    (ops/round_metrics: ``newly`` = per-round delta of this, exact
    because both mass measures are monotone under merge)."""
    if cfg.kind in CRDT_SET_KINDS:
        pc = jnp.where(alive[:, None],
                       jax.lax.population_count(rows), 0)
        return jnp.sum(pc, dtype=jnp.float32)
    return jnp.sum(jnp.where(alive[:, None], rows, 0),
                   dtype=jnp.float32)
