"""Random peer sampling — the batched analog of "pick a neighbor to gossip to".

The reference contacts *all* neighbors sequentially (main.go:72-75).  Sampled
protocols (push/pull/push-pull with fanout k) instead draw k random peers per
node per round.  Everything here is shaped ``[N_local, k]`` with **static**
shapes: we sample for every node every round and mask by activity afterwards —
wasted lanes are far cheaper on TPU than ragged shapes (SURVEY.md §7 "Static
shapes for sparse fanout").

Reproducibility / mesh independence: peer choice for global node ``i`` in
round ``t`` depends only on ``(base_key, t, i)`` — per-node keys are derived
with ``fold_in(round_key, global_id)`` — so results are bitwise identical
regardless of how the node axis is sharded (SURVEY.md §7 "Cross-shard
randomness").
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from gossip_tpu.topology.generators import Topology


def node_keys(round_key: jax.Array, global_ids: jax.Array) -> jax.Array:
    """Per-node PRNG keys: fold the global node id into the round key."""
    return jax.vmap(jax.random.fold_in, in_axes=(None, 0))(round_key, global_ids)


def drop_mask(round_key: jax.Array, tag: int, global_ids: jax.Array,
              width: int, drop_prob: float) -> jax.Array:
    """Per-edge-use drop mask ``bool[len(ids), width]`` keyed by *global* node
    id, so lossy-link draws are bitwise independent of how the node axis is
    sharded (same contract as peer sampling above)."""
    keys = node_keys(jax.random.fold_in(round_key, tag), global_ids)
    return jax.vmap(
        lambda k: jax.random.bernoulli(k, drop_prob, (width,)))(keys)


def apply_drop(round_key: jax.Array, tag: int, global_ids: jax.Array,
               targets: jax.Array, drop_prob: float,
               sentinel: int, force: bool = False) -> jax.Array:
    """Lossy links: turn dropped targets into the sentinel (scatter-dropped,
    gather-masked).  A dropped push/pull is simply retried in a later round —
    the batched analog of at-least-once delivery (reference main.go:80-87).

    ``force=True`` skips the static zero-rate early-out so ``drop_prob``
    may be a TRACED per-round scalar (the ops/nemesis drop-ramp path —
    bernoulli takes a traced p; a p=0 round draws an all-False mask,
    bitwise a no-op on the trajectory)."""
    if not force and drop_prob <= 0.0:
        return targets
    dropped = drop_mask(round_key, tag, global_ids, targets.shape[1],
                        drop_prob)
    return jnp.where(dropped, jnp.int32(sentinel), targets)


def shift_excluding_self(r: jax.Array, gid) -> jax.Array:
    """The complete-graph self-exclusion shift trick, ONE definition:
    ``r`` uniform on [0, n-1) becomes uniform on [0, n) \\ {gid} by
    bumping every draw >= gid.  Shape-polymorphic (broadcasts ``gid``
    against ``r``) — shared by the per-key sampler below and the SWIM
    packed-word lowering (models/swim.packed_round_draws)."""
    return r + (r >= gid).astype(jnp.int32)


def table_lookup_or_sentinel(idx: jax.Array, rows: jax.Array,
                             deg: jax.Array, sentinel: int) -> jax.Array:
    """Neighbor-table peer resolution, ONE definition: gather ``idx``
    along each row; degree-0 rows emit the sentinel (dropped by
    scatters, masked by gathers).  ``deg`` broadcasts against ``idx``
    (scalar per row under vmap, or [N, 1] batched)."""
    t = jnp.take_along_axis(rows, idx, axis=-1)
    return jnp.where(deg > 0, t, jnp.int32(sentinel))


def sample_peers_complete(round_key: jax.Array, global_ids: jax.Array,
                          n_total, k: int,
                          exclude_self: bool = True) -> jax.Array:
    """Uniform peers on the implicit complete graph -> int32[len(ids), k].

    Self-exclusion uses the shift trick (draw from n-1, bump >= self) so no
    rejection loop is needed.  ``n_total`` may be a TRACED scalar (the
    mixed-n config sweep passes each point's own n as an operand, one
    program for all sizes); ``jax.random.randint`` takes traced bounds
    and its draw depends only on the bound's VALUE, so a traced bound
    reproduces the static-bound solo trajectory bitwise.  Traced bounds
    require n_total >= 2 when excluding self (the static path keeps the
    n==1 degenerate-case guard).
    """
    keys = node_keys(round_key, global_ids)
    # value check for ANY static integer (python or numpy scalar);
    # only a traced bound skips it (callers guarantee n >= 2 there).
    # "is it traced" is probed by attempting the int() conversion
    # itself rather than isinstance against jax.core.Tracer — the
    # jax.core access path is deprecated/namespace-unstable, while
    # the public error types are a supported API (ADVICE r4).
    try:
        degenerate = int(n_total) <= 1
    except (jax.errors.TracerIntegerConversionError,
            jax.errors.ConcretizationTypeError):
        degenerate = False
    if exclude_self and not degenerate:
        def one(key, i):
            r = jax.random.randint(key, (k,), 0, n_total - 1, dtype=jnp.int32)
            return shift_excluding_self(r, i)
    else:
        def one(key, i):
            del i
            return jax.random.randint(key, (k,), 0, n_total, dtype=jnp.int32)
    return jax.vmap(one)(keys, global_ids.astype(jnp.int32))


def sample_peers_table(round_key: jax.Array, global_ids: jax.Array,
                       nbrs: jax.Array, deg: jax.Array, k: int,
                       sentinel: int) -> jax.Array:
    """k uniform neighbors per node from a padded table -> int32[N_local, k].

    ``nbrs``/``deg`` are the *local rows* for ``global_ids``.  Nodes with
    degree 0 emit the sentinel (dropped by scatters, masked by gathers).
    """
    keys = node_keys(round_key, global_ids)

    def one(key, row, d):
        idx = jax.random.randint(key, (k,), 0, jnp.maximum(d, 1),
                                 dtype=jnp.int32)
        return table_lookup_or_sentinel(idx, row, d, sentinel)

    return jax.vmap(one)(keys, nbrs, deg)


def sample_peers(round_key: jax.Array, global_ids: jax.Array, topo: Topology,
                 k: int, exclude_self: bool = True,
                 local_nbrs: Optional[jax.Array] = None,
                 local_deg: Optional[jax.Array] = None) -> jax.Array:
    """Dispatch on implicit-vs-table topology (static choice, no tracing cost).

    Under shard_map callers pass their local table slice via ``local_nbrs`` /
    ``local_deg``; single-device callers let it default to the full table.
    """
    if topo.implicit:
        return sample_peers_complete(round_key, global_ids, topo.n, k,
                                     exclude_self)
    nbrs = topo.nbrs if local_nbrs is None else local_nbrs
    deg = topo.deg if local_deg is None else local_deg
    return sample_peers_table(round_key, global_ids, nbrs, deg, k,
                              sentinel=topo.n)
