"""Propagation kernels: one gossip round's data movement.

The reference's propagation is the hot loop at main.go:72-88 — sequential
blocking RPC per neighbor, at-least-once via retry, idempotent receipt via the
dedup set (main.go:113).  Batched on TPU this becomes pure data movement:

  * **push**  — scatter: each active node writes its digest row at k sampled
    target rows.  Idempotence is free (OR/max semantics == the dedup set); the
    TOCTOU duplicate-append race of the reference (SURVEY.md §2.2.5) cannot
    exist because a round is one atomic XLA program.
  * **pull**  — gather: each node reads k sampled peers' digest rows and ORs
    them in.
  * **flood** — gather over the *whole* padded neighbor row (Go-parity mode:
    relay-to-all, main.go:72-75).

Push comes in two flavors: boolean scatter-max for single-device, and int32
scatter-add (``push_counts``) whose output is summable across shards with
``psum_scatter`` — OR is not an XLA collective reduction, + is, and
``count > 0`` == OR.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _flat_payload(targets: jax.Array, payload: jax.Array, dtype) -> tuple:
    """[Nl,k] targets + [Nl,R] payload -> flat ([Nl*k], [Nl*k,R]) pairs."""
    nl, k = targets.shape
    r = payload.shape[1]
    flat_t = targets.reshape(-1)
    flat_p = jnp.broadcast_to(payload[:, None, :], (nl, k, r))
    return flat_t, flat_p.reshape(nl * k, r).astype(dtype)


def push_delta(n: int, targets: jax.Array, payload: jax.Array) -> jax.Array:
    """Single-device push: bool[N,R] delta via scatter-max.

    ``targets`` holds global ids in [0, n) or the sentinel ``n`` (dropped).
    ``payload[i]`` is what node i pushes (its active digest row).
    """
    flat_t, flat_p = _flat_payload(targets, payload, jnp.bool_)
    zero = jnp.zeros((n, payload.shape[1]), jnp.bool_)
    return zero.at[flat_t].max(flat_p, mode="drop")


def push_counts(n: int, targets: jax.Array, payload: jax.Array) -> jax.Array:
    """Sharded push: int32[N,R] receive-counts via scatter-add.

    Summable across shards (``lax.psum_scatter``); ``counts > 0`` is the OR.
    int32 because several pushers may hit the same row in the same round.
    """
    flat_t, flat_p = _flat_payload(targets, payload, jnp.int32)
    zero = jnp.zeros((n, payload.shape[1]), jnp.int32)
    return zero.at[flat_t].add(flat_p, mode="drop")


def pull_merge(seen_all: jax.Array, partners: jax.Array,
               valid_sentinel: int) -> jax.Array:
    """Pull: OR of k sampled peers' digest rows -> bool[N_local, R].

    ``seen_all`` is the full (or all-gathered) ``bool[N, R]`` digest table;
    ``partners`` is ``int32[N_local, k]`` with sentinel entries masked out.
    """
    valid = partners < valid_sentinel            # [Nl, k]
    safe = jnp.minimum(partners, valid_sentinel - 1)
    got = seen_all[safe]                         # [Nl, k, R]
    got = got & valid[:, :, None]
    return jnp.any(got, axis=1)                  # [Nl, R]


def flood_gather(seen_all: jax.Array, nbrs_local: jax.Array,
                 n: int) -> jax.Array:
    """Flood (Go-parity): OR over the entire neighbor row -> bool[N_local, R].

    With the symmetric topologies Maelstrom hands out, gather-from-all-
    in-neighbors is identical to the reference's push-to-all-out-neighbors
    (main.go:72-75): after round t, coverage is exactly the BFS ball of
    radius t around the origin.  Sender exclusion (main.go:73-75) does not
    change that set — the sender already has the rumor — so the parity mode
    omits it.
    """
    valid = nbrs_local < n
    safe = jnp.minimum(nbrs_local, n - 1)
    got = seen_all[safe] & valid[:, :, None]
    return jnp.any(got, axis=1)
