"""Pallas TPU kernel: hardware-PRNG peer sampling.

The default sampler (ops/sampling.py) derives one threefry key per node —
``vmap(fold_in)`` over N keys costs ~16 ms at N=10M *standalone*.  This
kernel replaces the whole (keys + randint) pipeline with the TPU's native
PRNG (``pltpu.prng_seed`` / ``pltpu.prng_random_bits``), generating targets
at VPU rate, blocked over rows so the draw for a row depends only on
``(seed, round, block_index)`` — deterministic and independent of anything
outside the block, so results are reproducible run-to-run on any mesh that
keeps the same block size (we fix it at compile time).

**Measured outcome (v5e, N=10M packed pull, 2026-07): the threefry path
wins.**  84 ms/round (threefry, XLA fuses key derivation into the gather's
producer chain) vs 126 ms/round (this kernel: the ``pallas_call`` is a
fusion barrier — targets round-trip through HBM).  The kernel is kept as a
correct, hardware-tested alternative sampler and as the seed (sic) of a
future fully-fused pallas round (sampling + gather in one kernel would
remove the barrier); bench.py uses threefry.  Honest numbers beat wishful
kernels.

Trade-offs vs the threefry sampler, stated honestly:

  * DIFFERENT stream — trajectories are not bitwise comparable with the
    jax.random path (parity tests pin the threefry sampler; this one is the
    opt-in fast path, ``sampler="pallas"``).
  * Mapping uint32 -> [0, n) uses modulo, with selection bias n/2^32
    (< 0.25% at n=10M) — irrelevant for epidemic statistics, documented for
    completeness; chi-square uniformity is tested in tests/test_pallas.py.
  * Requires a real TPU; on CPU the public entry point falls back to the
    threefry sampler (interpret-mode is used only by the unit tests, since
    ``pltpu.prng_*`` interprets fine but slowly).

The reference has no sampling at all (it relays to every neighbor,
main.go:72-75); sampled fanout generalizes it (SURVEY.md §7 layer 1).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gossip_tpu.compat import interpret_impl, pallas_interpret_mode

_BLOCK_ROWS = 4096          # fixed: part of the determinism contract


def _sampler_kernel(seed_ref, out_ref, *, n_total: int, k: int,
                    exclude_self: bool, block_rows: int):
    b = pl.program_id(0)
    # Per-block seed: mixes the caller's (seed, round) scalar with the block
    # index so blocks draw independent streams.
    # -1640531527 == 0x9E3779B9 (golden-ratio mix) as int32
    pltpu.prng_seed(seed_ref[0] + b * jnp.int32(-1640531527))
    bits = pltpu.bitcast(pltpu.prng_random_bits((block_rows, k)),
                         jnp.uint32)
    if exclude_self and n_total > 1:
        # draw in [0, n-1) then bump values >= own row id (shift trick —
        # same scheme as ops/sampling.sample_peers_complete)
        t = (bits % jnp.uint32(n_total - 1)).astype(jnp.int32)
        rows = (b * block_rows
                + jax.lax.broadcasted_iota(jnp.int32, (block_rows, k), 0))
        out_ref[:] = t + (t >= rows).astype(jnp.int32)
    else:
        out_ref[:] = (bits % jnp.uint32(n_total)).astype(jnp.int32)


def _pad_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@functools.partial(jax.jit, static_argnames=("n_rows", "n_total", "k",
                                             "exclude_self", "interpret"))
def sample_targets_pallas(seed: jax.Array, n_rows: int, n_total: int,
                          k: int = 1, exclude_self: bool = True,
                          interpret: bool = False) -> jax.Array:
    """Uniform peers on the implicit complete graph -> int32[n_rows, k].

    ``seed`` is an int32 scalar; callers pass a per-round value (e.g.
    ``seed*prime + round``).  Hardware-PRNG twin of
    ops/sampling.sample_peers_complete (different stream — see module doc).
    """
    rows_pad = _pad_up(n_rows, _BLOCK_ROWS)
    grid = rows_pad // _BLOCK_ROWS
    if interpret_impl(interpret) == "reference":
        # pure-JAX reference of the kernel with the hw PRNG reproduced
        # as the Mosaic interpreter defines it off-TPU (all-zero draws)
        # — compiled by XLA; 'mosaic' forces the real interpreter
        bits = jnp.zeros((rows_pad, k), jnp.uint32)
        if exclude_self and n_total > 1:
            t = (bits % jnp.uint32(n_total - 1)).astype(jnp.int32)
            rows = jax.lax.broadcasted_iota(jnp.int32, (rows_pad, k), 0)
            return (t + (t >= rows).astype(jnp.int32))[:n_rows]
        return (bits % jnp.uint32(n_total)).astype(jnp.int32)[:n_rows]
    kernel = functools.partial(_sampler_kernel, n_total=n_total, k=k,
                               exclude_self=exclude_self,
                               block_rows=_BLOCK_ROWS)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows_pad, k), jnp.int32),
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, k), lambda b: (b, 0),
                               memory_space=pltpu.VMEM),
        # TPU-semantics interpreter (plain interpret=True lacks the TPU
        # PRNG primitives on CPU)
        interpret=pallas_interpret_mode(interpret),
    )(jnp.asarray([seed], jnp.int32))
    return out[:n_rows]


def round_seed(base_seed: int, round_: jax.Array) -> jax.Array:
    """Fold (run seed, round) into the kernel's int32 seed scalar."""
    return (jnp.int32(base_seed) * jnp.int32(1000003)
            + round_.astype(jnp.int32))


def sample_peers_fast(base_seed: int, round_: jax.Array, n_rows: int,
                      n_total: int, k: int = 1,
                      exclude_self: bool = True) -> jax.Array:
    """Public entry: hardware PRNG on TPU, threefry fallback elsewhere.

    The fallback keeps CPU tests/dev runs working; it does NOT reproduce
    the TPU stream (both streams are valid uniform samplers)."""
    if jax.default_backend() == "tpu":
        return sample_targets_pallas(round_seed(base_seed, round_), n_rows,
                                     n_total, k, exclude_self)
    from gossip_tpu.ops.sampling import sample_peers_complete
    key = jax.random.fold_in(jax.random.key(base_seed),
                             round_.astype(jnp.uint32))
    ids = jnp.arange(n_rows, dtype=jnp.int32)
    return sample_peers_complete(key, ids, n_total, k, exclude_self)
