"""Device-resident per-round protocol metrics: the epidemic observables
of Demers et al. (PODC 1987) captured INSIDE the compiled round loops.

Until this layer, a scanned/while-looped driver was a black box between
its first and last round: the ledger (utils/telemetry) records host-side
spans and walls, but nothing observes per-round *protocol* dynamics —
how many nodes a round newly infected, how much of the traffic was
redundant re-delivery, where the coverage front sits per shard.  Those
are the classic epidemic health metrics (residue/traffic/delay), and on
this codebase they must be measured without breaking the one property
every perf PR fought for: **steady state does no per-round host work**
(docs/PERF.md "Dry-run steady-state budget").

So the capture is Dapper-style — where the work happens, at zero
steady-state cost:

  * :func:`init` preallocates small device buffers (`f32[T]` per
    counter, `f32[T, S]` for the per-shard coverage front, one i32
    cursor) that ride the loop CARRY of every instrumented driver;
  * the round body calls :func:`record` — pure in-trace scatter writes
    at the cursor row, no callbacks, no syncs, no RNG consumption (the
    trajectory is bitwise what it was without metrics);
  * the whole stack is flushed to the host ONCE per driver call:
    `utils.trace.maybe_aot_timed` — the chokepoint every instrumented
    driver already returns through — finds :class:`RoundMetrics` leaves
    in the driver's output pytree and emits one ``round_metrics``
    ledger event per stack (:func:`emit`), so no driver threads a
    ledger argument anywhere.

The budget guard (tools/dryrun_budgets.json) runs with the ledger — and
therefore metrics — enabled on every dry run; a green guard is the
standing proof that the in-loop arithmetic costs nothing measurable.

Counter semantics (each a per-round f32; exact unless marked)
-------------------------------------------------------------
``newly``   newly-infected (node, rumor) entries this round — exact,
            from the monotone ``seen`` delta (SWIM: newly
            confirmed-dead wire entries; rumor: newly seen).
``msgs``    protocol messages this round — exact (the drivers' own
            accounting, differenced per round).
``dup``     redundant-delivery estimate: ``offered - newly`` clamped at
            0, where ``offered = rumors * payload_factor(mode) * msgs``
            counts the (receiver, rumor) delivery slots of the round's
            payload-bearing messages (:func:`payload_factor`).  An
            upper bound on true duplicates — it also counts slots whose
            sender had nothing new to offer — except for the rumor
            driver's feedback variant, where the kernel's own counters
            make it exact.
``bytes``   analytic per-device ICI egress of the round's collectives
            (the SparseMeta convention), gated in-trace on quiescent
            anti-entropy rounds.  A formula, not a NIC counter — it
            exists so a collective-layout regression (an accidental
            O(N) gather) is visible per round.
``front``   per-shard coverage fraction after the round (f32[S]) — the
            convergence front: a shard whose column lags shows a
            placement/topology pathology no global mean exposes.

Nemesis observables (present when the stack is built with
``nemesis=True`` — drivers running a :class:`ChurnConfig` schedule,
ops/nemesis):

``alive``     alive node count after the round's churn events — exact.
``cut_pairs`` alive node pairs separated by the open partition cut
              (|A| * |B|; 0 while no window is open) — exact.
``dropped``   messages lost this round to drop coins + the open cut —
              counted exactly by the kernels (the ``lost`` output of
              the churn-aware round steps), never in ``msgs``.

CRDT observables (present when the stack is built with ``crdt=True``
— drivers running a commutative-merge payload, ops/crdt):

``value_conv`` fraction of eventual-alive nodes whose merged state
              equals the global ground truth after the round — the
              eventual-consistency-of-VALUES metric (in-loop f32 for
              observability; the drivers' pinned readout stays the
              integer converged count divided once on host).

Replicated-log observables (present when the stack is built with
``log=True`` — drivers running the ordered per-key offset payload,
ops/logs):

``log_conv``  fraction of eventual-alive nodes whose full log row
              (entry planes + committed-offset vector) equals the
              acked-appends ground truth after the round — the
              ORDERED eventual-consistency metric (same in-loop-f32 /
              integer-readout split as ``value_conv``).

LWW-register observables (present when the stack is built with
``txn=True`` — drivers running the totally-available transaction
payload, ops/registers):

``txn_conv``  fraction of eventual-alive nodes whose full register
              row (value + timestamp planes) equals the acked-writes
              LWW ground truth after the round — the ISOLATION-layer
              convergence metric (same in-loop-f32 / integer-readout
              split as ``value_conv``).

Byzantine observables (present when the stack is built with
``byz=True`` — drivers running a liar program, ops/nemesis
ByzSchedule):

``byz_conv``  fraction of HONEST eventual-alive nodes whose
              HONEST-OWNED components (counter columns / set element
              bits / register keys won by honest writers) equal the
              honest-masked ground truth after the round — the
              byzantine-convergence headline (defended runs reach
              exactly 1.0; the undefended control arm provably
              diverges — docs/ROBUSTNESS.md "Byzantine adversaries").
              Same in-loop-f32 / integer-readout split as
              ``value_conv``.

``GOSSIP_ROUND_METRICS=0`` (or empty) is the kill switch; metrics are
also skipped when no run ledger is active (:func:`wanted`) — the
buffers exist to be ledgered, and dark buffers would tax every test
that never reads them.  Both gates act at TRACE time, so a memoized
driver loop (parallel/sharded_fused) keys its cache on the choice.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from gossip_tpu import config as C

ENV_VAR = "GOSSIP_ROUND_METRICS"


def enabled() -> bool:
    """The env kill switch: on unless GOSSIP_ROUND_METRICS is ""/0/off
    (the GOSSIP_TELEMETRY convention, inverted default: metrics cost
    nothing measurable, so presence is the useful default)."""
    return os.environ.get(ENV_VAR, "1").lower() not in ("", "0", "off")


def wanted() -> bool:
    """Should a driver build its loop WITH metrics buffers?  True iff
    the env switch is on AND a run ledger is active — without a ledger
    the flush is a no-op, so the buffers would be dead carry weight in
    every un-ledgered test/caller.  Read at trace/build time (memoized
    loops key on it)."""
    if not enabled():
        return False
    from gossip_tpu.utils import telemetry
    return bool(getattr(telemetry.current(), "active", False))


class RoundMetrics:
    """The preallocated per-round buffer stack carried through a loop.

    A registered pytree: array fields are children (so it rides scan /
    while_loop carries and crosses jit boundaries), ``label`` is static
    aux data naming the driver for the ledger event.  ``cursor`` is the
    next write row == rounds recorded so far."""

    __slots__ = ("cursor", "newly", "dup", "msgs", "bytes", "front",
                 "alive", "cut_pairs", "dropped", "value_conv",
                 "log_conv", "txn_conv", "byz_conv", "label",
                 "nemesis", "crdt", "log", "txn", "byz")

    def __init__(self, cursor, newly, dup, msgs, bytes, front,
                 alive, cut_pairs, dropped, value_conv, log_conv,
                 txn_conv, byz_conv, label: str, nemesis: bool = False,
                 crdt: bool = False, log: bool = False,
                 txn: bool = False, byz: bool = False):
        self.cursor = cursor
        self.newly = newly
        self.dup = dup
        self.msgs = msgs
        self.bytes = bytes
        self.front = front
        self.alive = alive
        self.cut_pairs = cut_pairs
        self.dropped = dropped
        self.value_conv = value_conv
        self.log_conv = log_conv
        self.txn_conv = txn_conv
        self.byz_conv = byz_conv
        self.label = label
        self.nemesis = nemesis
        self.crdt = crdt
        self.log = log
        self.txn = txn
        self.byz = byz

    def _replace(self, **kw):
        fields = {k: getattr(self, k) for k in self.__slots__}
        fields.update(kw)
        return RoundMetrics(**fields)


def _rm_flatten(m):
    return ((m.cursor, m.newly, m.dup, m.msgs, m.bytes, m.front,
             m.alive, m.cut_pairs, m.dropped, m.value_conv,
             m.log_conv, m.txn_conv, m.byz_conv),
            (m.label, m.nemesis, m.crdt, m.log, m.txn, m.byz))


def _rm_unflatten(aux, children):
    label, nemesis, crdt, log, txn, byz = aux
    return RoundMetrics(*children, label=label, nemesis=nemesis,
                        crdt=crdt, log=log, txn=txn, byz=byz)


jax.tree_util.register_pytree_node(RoundMetrics, _rm_flatten,
                                   _rm_unflatten)


def init(max_rounds: int, n_shards: int, label: str,
         nemesis: bool = False, crdt: bool = False,
         log: bool = False, txn: bool = False,
         byz: bool = False) -> RoundMetrics:
    """Zeroed buffer stack for up to ``max_rounds`` rounds over
    ``n_shards`` shards (1 for single-device drivers).  Tiny: 9 T + T*S
    floats — at the flagship's T=128, S=8 that is 4 KB.  ``nemesis``
    marks a stack that carries the churn observables (alive/cut_pairs/
    dropped are recorded and ledgered; zeros otherwise); ``crdt`` marks
    one carrying the value-convergence column, ``log`` one carrying the
    replicated-log convergence column, ``txn`` one carrying the
    LWW-register convergence column, ``byz`` one carrying the
    honest-component byzantine convergence column (module doc)."""
    if max_rounds < 1:
        raise ValueError(f"max_rounds={max_rounds} must be >= 1")
    if n_shards < 1:
        raise ValueError(f"n_shards={n_shards} must be >= 1")
    z = jnp.zeros((max_rounds,), jnp.float32)
    return RoundMetrics(cursor=jnp.int32(0), newly=z, dup=z, msgs=z,
                        bytes=z,
                        front=jnp.zeros((max_rounds, n_shards),
                                        jnp.float32),
                        alive=z, cut_pairs=z, dropped=z, value_conv=z,
                        log_conv=z, txn_conv=z, byz_conv=z, label=label,
                        nemesis=nemesis, crdt=crdt, log=log, txn=txn,
                        byz=byz)


def record(m: RoundMetrics, *, newly, dup, msgs, bytes,
           front, alive=None, cut_pairs=None,
           dropped=None, value_conv=None,
           log_conv=None, txn_conv=None,
           byz_conv=None) -> RoundMetrics:
    """Write one round's row at the cursor (in-trace; scatter writes
    only).  The cursor is clamped to the last row so an over-long loop
    can never write out of bounds — by contract the drivers size the
    buffers with ``run.max_rounds``, which also bounds their loops.
    The nemesis columns (alive/cut_pairs/dropped), the CRDT
    ``value_conv`` column, the replicated-log ``log_conv`` column, and
    the LWW-register ``txn_conv`` column, and the byzantine
    ``byz_conv`` column are only written when passed — the
    static-fault / non-payload recorders never touch them."""
    i = jnp.minimum(m.cursor, m.newly.shape[0] - 1)
    f32 = lambda v: jnp.asarray(v, jnp.float32)       # noqa: E731
    kw = {}
    if alive is not None:
        kw["alive"] = m.alive.at[i].set(f32(alive))
    if cut_pairs is not None:
        kw["cut_pairs"] = m.cut_pairs.at[i].set(f32(cut_pairs))
    if dropped is not None:
        kw["dropped"] = m.dropped.at[i].set(f32(dropped))
    if value_conv is not None:
        kw["value_conv"] = m.value_conv.at[i].set(f32(value_conv))
    if log_conv is not None:
        kw["log_conv"] = m.log_conv.at[i].set(f32(log_conv))
    if txn_conv is not None:
        kw["txn_conv"] = m.txn_conv.at[i].set(f32(txn_conv))
    if byz_conv is not None:
        kw["byz_conv"] = m.byz_conv.at[i].set(f32(byz_conv))
    return m._replace(
        cursor=m.cursor + 1,
        newly=m.newly.at[i].set(f32(newly)),
        dup=m.dup.at[i].set(f32(dup)),
        msgs=m.msgs.at[i].set(f32(msgs)),
        bytes=m.bytes.at[i].set(f32(bytes)),
        front=m.front.at[i].set(jnp.asarray(front, jnp.float32)), **kw)


# -- per-round counter helpers (all pure in-trace arithmetic) ---------

def payload_factor(mode: str) -> float:
    """Fraction of a mode's counted messages that carry a digest
    payload toward the receiver — the ``offered`` normalizer for the
    ``dup`` estimate.  Push and flood messages all carry payload (1.0);
    pull counts request + response per exchange, only the response
    carries (0.5); push-pull and anti-entropy carry payload on 2 of
    every 3 counted messages (sends + responses vs. sends + requests +
    responses; reconciliation's reverse delta rides the request)."""
    return {C.PUSH: 1.0, C.FLOOD: 1.0, C.RUMOR: 1.0, C.PULL: 0.5,
            C.PUSH_PULL: 2.0 / 3.0, C.ANTI_ENTROPY: 2.0 / 3.0}[mode]


def gate_on_exchange_rounds(value, period: int, round_, off=0.0):
    """``value`` on exchange rounds, ``off`` on quiescent anti-entropy
    rounds — the ONE ``round_ % period == 0`` predicate, shared by
    every recorder so the per-driver ``bytes`` series can never
    disagree with the lax.cond the kernels gate their collectives on
    (dense/packed add a reverse-psum term; sparse drops to the 4-byte
    msgs psum)."""
    value = jnp.asarray(value, jnp.float32)
    if period <= 1:
        return value
    return jnp.where((round_ % period) == 0, value,
                     jnp.asarray(off, jnp.float32))


def dup_estimate(offered, newly):
    """``max(offered - newly, 0)`` — delivery slots that produced no
    new infection (module doc: an upper bound on true duplicates)."""
    return jnp.maximum(jnp.asarray(offered, jnp.float32)
                       - jnp.asarray(newly, jnp.float32), 0.0)


def count_bool(seen, alive):
    """Total set (node, rumor) entries over alive rows of a bool
    digest table ``seen[N, R]``."""
    return jnp.sum(seen & alive[:, None], dtype=jnp.float32)


def count_packed(words, alive):
    """Set-bit total over alive rows of a rumor-packed ``uint32[N, W]``
    table (padding bits beyond ``rumors`` are never set — ops/bitpack
    contract — so no mask is needed)."""
    pc = jnp.where(alive[:, None], jax.lax.population_count(words), 0)
    return jnp.sum(pc, dtype=jnp.float32)


def count_planes(planes):
    """Set-bit total of a fused plane stack ``uint32[W, rows, 128]``.
    The all-ones rumor-padding columns contribute a CONSTANT, which
    cancels in the per-round deltas the drivers record."""
    return jnp.sum(jax.lax.population_count(planes), dtype=jnp.float32)


def front_bool(seen, alive, n_shards: int):
    """Per-shard covered-fraction f32[S] of a row-sharded bool table:
    covered = alive and holding any rumor; the denominator is the
    shard's alive row count (padding rows are dead by construction and
    deflate nothing)."""
    covered = jnp.any(seen, axis=1) & alive
    per = jnp.sum(covered.reshape(n_shards, -1), axis=1,
                  dtype=jnp.float32)
    tot = jnp.sum(alive.reshape(n_shards, -1), axis=1,
                  dtype=jnp.float32)
    return per / jnp.maximum(tot, 1.0)


def front_packed(words, alive, n_shards: int):
    """:func:`front_bool` for the rumor-packed uint32 layout."""
    covered = jnp.any(words != 0, axis=1) & alive
    per = jnp.sum(covered.reshape(n_shards, -1), axis=1,
                  dtype=jnp.float32)
    tot = jnp.sum(alive.reshape(n_shards, -1), axis=1,
                  dtype=jnp.float32)
    return per / jnp.maximum(tot, 1.0)


def front_planes(planes, n: int, n_shards: int):
    """Per-shard min-over-rumors coverage f32[S] of a plane-sharded
    fused stack: each shard's column is the min coverage over the
    planes IT owns (plane p lives on shard p // (W/S) — the
    init_plane_state layout).  Padding planes are all-ones (coverage
    1.0) and never win the min."""
    from gossip_tpu.ops.pallas_round import BITS, coverage_words
    per_plane = jax.vmap(lambda t: coverage_words(t, n, BITS))(planes)
    return jnp.min(per_plane.reshape(n_shards, -1), axis=1)


# -- the once-per-driver-call flush -----------------------------------

def find(out):
    """Every RoundMetrics leaf in a driver output pytree (is_leaf stops
    the flatten from decomposing the stacks into bare arrays)."""
    leaves = jax.tree_util.tree_leaves(
        out, is_leaf=lambda x: isinstance(x, RoundMetrics))
    return [x for x in leaves if isinstance(x, RoundMetrics)]


def emit(out, ledger, fn=None):
    """ONE host transfer + one ``round_metrics`` ledger event per
    RoundMetrics stack in ``out`` — called by utils.trace.maybe_aot_timed
    after the driver's timed region, never per round.  Series are
    truncated to the rounds actually recorded (the cursor: a while_loop
    that exited early leaves its tail rows zero and unreported).

    ``sync=False``: the emit may run inside a CALLER's timed window
    (the dry run's family walls), so it is flush-only like the
    ``driver_timing`` event — durability arrives with the next fsynced
    event (utils/telemetry contract)."""
    stacks = find(out)
    if not stacks:
        return
    import numpy as np
    for m in stacks:
        (cursor, newly, dup, msgs, bytes_, front, alive, cut_pairs,
         dropped, value_conv, log_conv, txn_conv,
         byz_conv) = jax.device_get(
            (m.cursor, m.newly, m.dup, m.msgs, m.bytes, m.front,
             m.alive, m.cut_pairs, m.dropped, m.value_conv,
             m.log_conv, m.txn_conv, m.byz_conv))
        r = min(int(cursor), int(newly.shape[0]))

        def ser(a, nd=3):
            return [round(float(v), nd) for v in np.asarray(a)[:r]]

        front = np.asarray(front)
        extra = {}
        if m.nemesis:
            # the churn observables ride the same event; total dropped
            # joins the totals so ledger_diff can gate it like msgs
            extra = {"alive": ser(alive), "cut_pairs": ser(cut_pairs),
                     "dropped": ser(dropped)}
        if m.crdt:
            # value convergence per round + the final fraction (the
            # eventual-consistency headline an artifact pin asserts)
            extra["value_conv"] = ser(value_conv, nd=4)
        if m.log:
            # replicated-log convergence per round (the ORDERED
            # eventual-consistency headline — ops/logs)
            extra["log_conv"] = ser(log_conv, nd=4)
        if m.txn:
            # LWW-register convergence per round (the isolation-layer
            # headline — ops/registers)
            extra["txn_conv"] = ser(txn_conv, nd=4)
        if m.byz:
            # honest-component convergence per round under a liar
            # program (the byzantine headline — defended runs end at
            # exactly 1.0, the undefended control arm does not)
            extra["byz_conv"] = ser(byz_conv, nd=4)
        totals = {"newly": round(float(np.sum(newly[:r])), 3),
                  "dup": round(float(np.sum(dup[:r])), 3),
                  "msgs": round(float(np.sum(msgs[:r])), 3),
                  "bytes": round(float(np.sum(bytes_[:r])), 3)}
        if m.nemesis:
            totals["dropped"] = round(float(np.sum(dropped[:r])), 3)
        if m.crdt:
            totals["value_conv_final"] = (
                round(float(value_conv[r - 1]), 4) if r else 0.0)
        if m.log:
            totals["log_conv_final"] = (
                round(float(log_conv[r - 1]), 4) if r else 0.0)
        if m.txn:
            totals["txn_conv_final"] = (
                round(float(txn_conv[r - 1]), 4) if r else 0.0)
        if m.byz:
            totals["byz_conv_final"] = (
                round(float(byz_conv[r - 1]), 4) if r else 0.0)
        ledger.event(
            "round_metrics", sync=False, driver=m.label, fn=fn,
            rounds=r, shards=int(front.shape[1]),
            newly=ser(newly), dup=ser(dup), msgs=ser(msgs),
            bytes=ser(bytes_), **extra,
            front=[[round(float(v), 4) for v in row]
                   for row in front[:r]],
            totals=totals,
            front_final=([round(float(v), 4) for v in front[r - 1]]
                         if r else None))
