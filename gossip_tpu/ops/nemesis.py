"""Compiled nemesis: time-varying fault schedules inside the round loops.

Maelstrom's whole robustness story is dynamic — the nemesis partitions
the network *mid-run* and the reference converges after heal via
at-least-once retry (main.go:77-87).  The batched simulator's
:class:`~gossip_tpu.config.FaultConfig` could only express STATIC
faults (a fixed death mask, a constant drop rate, one scripted SWIM
``fail_round``).  This module lowers a
:class:`~gossip_tpu.config.ChurnConfig` — crash/recover churn,
partition windows, drop-rate ramps — into a tiny device-resident
:class:`Schedule` consumed by the loop counter INSIDE every compiled
round loop (`lax.scan` / `lax.while_loop`), the way the literature's
scenarios actually run: Demers et al.'s anti-entropy was designed to
ride out transient link failure, and SWIM (Das et al., DSN 2002) is
meaningless without churn to detect.

The lowering
------------
``Schedule`` is a registered pytree (the RoundMetrics pattern) holding

  * ``die`` / ``rec`` — ``int32[n_pad]``: the round each node goes down
    / comes back (:data:`NEVER` sentinels).  Node ``i`` is churn-down
    during ``die[i] <= r < rec[i]``.
  * ``cut_tbl`` — ``int32[T]``: the partition cut per round (-1 = no
    window open).  Messages whose endpoints straddle the cut are lost
    while a window is open.
  * ``drop_tbl`` — ``f32[T]``: the per-round link drop probability
    (FaultConfig.drop_prob outside the ramp, linear inside, final
    value held after).

``T`` is :func:`canonical_horizon`: the round after which the schedule
is constant by construction (every window closed, ramp finished —
``ChurnConfig.horizon()``), rounded UP to a power-of-two bucket by
repeating the final row.  The final row IS the steady state, so the
clamped lookup ``tbl[min(r, T-1)]`` stays EXACT for every round — the
tables are config-sized, not run-length-sized, and the same schedule
serves a 6-round curve and a 10k-round flagship run.

Schedules are runtime OPERANDS (this PR), not in-trace constants: the
round-step factories call :func:`build` ON THE HOST, append the four
arrays to their ``tables`` tuple (:func:`sched_args`), and the step
peels them back off (:func:`split_tables`) — so the schedule flows
through every driver's existing ``step(state, *tables)`` plumbing as
jit arguments, exactly like the topology tables.  Consequences, which
are the whole point:

  * the lowered HLO carries schedule SHAPES but no schedule CONTENT,
    so two different ChurnConfigs with the same canonical bucket
    produce byte-identical programs — the compile cache / AOT store
    (utils/compile_cache) serves a whole nemesis sweep from one entry;
  * driver-level loop memos (parallel/sharded._cached_dense_loop,
    parallel/sweep._cached_churn_sweep_scan) key on the bucket, never
    the content — K scenarios re-enter ONE compiled loop in-process
    (the fused engine's ``_cached_churn_masks`` alive-word trick,
    generalized to every XLA path);
  * a STACK of K schedules (:func:`build_stack`) vmaps through the one
    compiled loop as a ``[K, ...]`` operand — the scenario-batched
    churn sweep (parallel/sweep.churn_sweep_curves).

:func:`build` stays in-trace safe (small scatters + static sets), so
closure-baking it remains CORRECT — just slow — and the bitwise pins
in tests/data/churn_fingerprints_r06.json hold either way.

Semantics (shared by every kernel — the heal-convergence tests pin
them):

  * a churn-down node neither sends, responds, nor receives; its
    digest goes dark (exactly the static-mask contract, per round);
  * a cross-cut message is lost for that round only — the sender
    retries implicitly next round (at-least-once, main.go:80-87), so
    coverage STALLS at the cut while a window is open and converges
    after heal;
  * the drop coin for round ``r`` is drawn from the same per-(round,
    node) streams as the static path (ops/sampling tags), with
    ``drop_tbl[r]`` as the probability — trajectories are mesh-shape
    invariant for the same reason peer sampling is.

Observables (wired into ops/round_metrics by the drivers' recorders):
per-round ``alive`` count, ``cut_pairs`` (alive node pairs separated
by the open cut — 0 when closed), and ``dropped`` (messages lost to
drop coins + the cut, counted exactly by the kernels).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from gossip_tpu.config import (BYZ_CORRUPT, BYZ_EQUIVOCATE, BYZ_INFLATE,
                               BYZ_REPLAY, ByzConfig, ChurnConfig,
                               FaultConfig)

# Sentinel round for "never": far beyond any realistic max_rounds but
# safely below int32 overflow under the +1 arithmetic of round counters.
NEVER = 1 << 29


def get(fault: Optional[FaultConfig]) -> Optional[ChurnConfig]:
    """The schedule carried by a fault config, or None — the ONE probe
    every kernel factory branches on (FaultConfig normalizes an empty
    ChurnConfig to None, so `get(fault) is None` == static hot path)."""
    return fault.churn if fault is not None else None


def get_byz(fault: Optional[FaultConfig]) -> Optional[ByzConfig]:
    """The byzantine program carried by a fault config, or None — the
    :func:`get` twin (FaultConfig normalizes an empty ByzConfig to
    None, so `get_byz(fault) is None` == honest exchange path)."""
    return fault.byz if fault is not None else None


class Schedule:
    """Device-resident nemesis schedule (module doc).  A registered
    pytree so it can ride loop carries and jit boundaries; all leaves,
    no static aux (cut-side observables count over the padded alive
    mask, whose padding rows are already False)."""

    __slots__ = ("die", "rec", "cut_tbl", "drop_tbl")

    def __init__(self, die, rec, cut_tbl, drop_tbl):
        self.die = die
        self.rec = rec
        self.cut_tbl = cut_tbl
        self.drop_tbl = drop_tbl


def _sched_flatten(s):
    return ((s.die, s.rec, s.cut_tbl, s.drop_tbl), None)


def _sched_unflatten(_, children):
    return Schedule(*children)


jax.tree_util.register_pytree_node(Schedule, _sched_flatten,
                                   _sched_unflatten)


def _event_tables(ch: ChurnConfig, size: int):
    """die/rec int32[size] round tables from the event list (rec < 0 ->
    NEVER; unscripted rows NEVER) — the ONE event-scatter lowering,
    shared by :func:`build` and :func:`fused_word_tables` so the flat
    and word-rendered engines' churn timelines cannot drift.

    Built in NUMPY, converted once: the jnp construction this replaces
    dispatched scatter programs whose shapes were keyed on the EVENT
    COUNT — on the serving path (build_request_stack per admitted
    request) that is one tiny XLA compile per distinct event-list
    length, the jnp-over-K class the staticcheck recompile lint flags
    (docs/STATIC_ANALYSIS.md).  Value-identical: ChurnConfig enforces
    one event per node, so the assignment order cannot matter."""
    import numpy as np
    die = np.full((size,), NEVER, np.int32)
    rec = np.full((size,), NEVER, np.int32)
    if ch.events:
        nodes = np.asarray([e[0] for e in ch.events], np.int32)
        die[nodes] = np.asarray([e[1] for e in ch.events], np.int32)
        rec[nodes] = np.asarray(
            [e[2] if e[2] >= 0 else NEVER for e in ch.events], np.int32)
    return jnp.asarray(die), jnp.asarray(rec)


# Minimum canonical [T] table length.  Bucketing trades a few padded
# rows (repeats of the steady final row — exact under the clamped
# lookup) for SHAPE-stable schedules: every horizon <= 32 shares one
# bucket, so a whole scenario family compiles once (module doc).
SCHED_T_MIN = 32

# How many trailing step arguments a schedule occupies when it rides a
# factory's ``tables`` tuple (sched_args / split_tables).
N_SCHED_OPERANDS = 4


def canonical_horizon(ch: ChurnConfig) -> int:
    """The canonical table length T for a schedule: ``horizon()``
    rounded up to a power-of-two bucket (>= SCHED_T_MIN).  Shape-only
    memo keys and the HLO fingerprint see this bucket, never the
    schedule content."""
    t = ch.horizon()
    return max(SCHED_T_MIN, 1 << (t - 1).bit_length())


def build(fault: FaultConfig, n: int, n_pad: Optional[int] = None,
          t_pad: Optional[int] = None) -> Schedule:
    """Lower ``fault.churn`` to the device tables (host-side in the
    factories — the operand contract, module doc — but in-trace safe:
    small scatters + static-slice sets only).  ``n_pad`` sizes the
    die/rec vectors for mesh-padded kernels; padding rows carry NEVER
    (their deadness comes from the base alive mask, as always).

    The [T] tables pad to :func:`canonical_horizon` (or an explicit
    ``t_pad >= horizon()``, the build_stack alignment hook) by
    REPEATING the final row — the steady state by construction, so the
    clamped lookup is exact at every length and trajectories are
    T-padding-invariant (pinned in tests/test_nemesis.py)."""
    ch = fault.churn
    if ch is None:
        raise ValueError("build() needs a FaultConfig with a churn "
                         "schedule (gate on nemesis.get(fault) first)")
    validate_events(fault, n)
    n_pad = n if n_pad is None else n_pad
    die, rec = _event_tables(ch, n_pad)
    cut_np, drop_np = _cut_drop_rows(fault, t_pad)
    return Schedule(die=die, rec=rec,
                    cut_tbl=jnp.asarray(cut_np, jnp.int32),
                    drop_tbl=jnp.asarray(drop_np, jnp.float32))


def _cut_drop_rows(fault: FaultConfig, t_pad: Optional[int] = None):
    """(cut rows, drop-probability rows) as host Python lists, padded to
    ``t_pad`` (default :func:`canonical_horizon`) by repeating the
    steady final row — the ONE construction of the per-round cut/drop
    timelines, shared by :func:`build` (f32 drop table, the XLA
    engines' operand) and :func:`fused_sched_tables` (20-bit integer
    thresholds, the fused kernels' operand) so the two lowerings of a
    schedule can never drift."""
    ch = fault.churn
    t = ch.horizon()
    cut_np = [-1] * t
    for start, end, cut in ch.partitions:
        for r in range(start, min(end, t)):
            cut_np[r] = cut
    drop_np = [float(fault.drop_prob)] * t
    if ch.ramp is not None:
        start, end, p0, p1 = ch.ramp
        for r in range(start, t):
            frac = min((r - start) / max(end - start, 1), 1.0)
            drop_np[r] = p0 + (p1 - p0) * frac
    t_pad = canonical_horizon(ch) if t_pad is None else t_pad
    if t_pad < t:
        raise ValueError(f"t_pad={t_pad} below the schedule horizon {t}")
    cut_np += [cut_np[-1]] * (t_pad - t)
    drop_np += [drop_np[-1]] * (t_pad - t)
    return cut_np, drop_np


def fused_sched_tables(fault: FaultConfig, n: int,
                       t_pad: Optional[int] = None):
    """(cut_tbl int32[T], thr_tbl int32[T]) — the fused engines'
    schedule operands: the per-round partition cut (-1 closed) and the
    per-round 20-bit drop THRESHOLD (``round(drop_tbl[r] * 2^20)``,
    computed host-side in f64 exactly like
    ops/pallas_round.drop_threshold_for, so a flat schedule's
    thresholds equal the static path's value bit-for-bit and drop-rate
    RAMPS lower for free).  Both numpy (content — the compiled fused
    loops consume them as runtime operands indexed by the clamped
    round lookup, module doc)."""
    import numpy as np
    if get(fault) is None:
        raise ValueError("fused_sched_tables needs a churn schedule")
    validate_events(fault, n)
    cut_np, drop_np = _cut_drop_rows(fault, t_pad)
    thr_np = [int(round(p * (1 << 20))) if p else 0 for p in drop_np]
    return (np.asarray(cut_np, np.int32), np.asarray(thr_np, np.int32))


def build_stack(faults, n: int, n_pad: Optional[int] = None) -> Schedule:
    """K churn-carrying FaultConfigs -> ONE stacked Schedule with a
    leading scenario axis (die/rec ``int32[K, n_pad]``, cut/drop
    ``[K, T]``) — the operand of the scenario-batched churn sweep
    (parallel/sweep.churn_sweep_curves): vmap maps the K axis through
    one compiled loop.  All schedules align to the stack's largest
    canonical bucket (exact: final-row padding is the steady state).

    Only the SCHEDULES stack here; the static fault structure the step
    bakes (death mask, scripted dead_nodes) must match across the
    stack — the sweep driver enforces that, since it owns the step."""
    faults = tuple(faults)
    if not faults:
        raise ValueError("build_stack needs at least one FaultConfig")
    missing = [i for i, f in enumerate(faults) if get(f) is None]
    if missing:
        raise ValueError(
            f"scenario stack entries {missing} carry no churn schedule; "
            "a churn sweep batches fault PROGRAMS (static-only points "
            "belong in the plain ensemble/config sweeps)")
    t_pad = max(canonical_horizon(f.churn) for f in faults)
    scheds = [build(f, n, n_pad, t_pad=t_pad) for f in faults]
    return Schedule(
        die=jnp.stack([s.die for s in scheds]),
        rec=jnp.stack([s.rec for s in scheds]),
        cut_tbl=jnp.stack([s.cut_tbl for s in scheds]),
        drop_tbl=jnp.stack([s.drop_tbl for s in scheds]))


def build_or_static(fault: Optional[FaultConfig], n: int,
                    n_pad: Optional[int] = None,
                    t_pad: Optional[int] = None) -> Schedule:
    """A Schedule for ANY fault — churn-free configs (``fault`` None,
    or carrying no churn) lower to the trivially-steady tables:
    die/rec all :data:`NEVER`, every partition window closed (-1), and
    the drop table flat at the static ``drop_prob``.  Consuming THESE
    tables is bitwise identical to the static kernels' no-schedule
    path (a NEVER row never kills, a closed cut destroys nothing, and
    a constant drop table reproduces the static drop coins exactly —
    the canonical-padding argument: the tables ARE the steady state).
    This is what lets a serving megabatch mix churn-carrying and
    churn-free requests in ONE operand stack (rpc/batcher)."""
    n_pad = n if n_pad is None else n_pad
    if get(fault) is not None:
        return build(fault, n, n_pad=n_pad, t_pad=t_pad)
    t_pad = SCHED_T_MIN if t_pad is None else t_pad
    dp = 0.0 if fault is None else float(fault.drop_prob)
    # numpy, not jnp: a jnp.full per distinct drop_prob VALUE is a
    # fresh constant program — serving assembles schedule content with
    # zero compiles (build_request_stack rationale)
    import numpy as np
    return Schedule(
        die=np.full((n_pad,), NEVER, np.int32),
        rec=np.full((n_pad,), NEVER, np.int32),
        cut_tbl=np.full((t_pad,), -1, np.int32),
        drop_tbl=np.full((t_pad,), dp, np.float32))


def build_request_stack(faults, ns, n_pad: int) -> Schedule:
    """K per-request ``(fault, n)`` pairs -> ONE stacked Schedule with
    a leading request axis — the heterogeneous twin of
    :func:`build_stack` for the admission-batched serving path
    (rpc/batcher + parallel/sweep.request_sweep_curves): entries may be
    churn-free (lowered by :func:`build_or_static`), each request
    validates its events against its OWN ``n``, and all tables align
    to the stack's largest canonical bucket.  The batch-key contract:
    everything here is CONTENT (schedule tables, per-request alive
    masks) and flows as runtime operands; only the bucket SHAPES
    (n_pad, the shared horizon) reach the compiled program."""
    faults = tuple(faults)
    ns = tuple(ns)
    if not faults:
        raise ValueError("build_request_stack needs at least one entry")
    if len(faults) != len(ns):
        raise ValueError(f"{len(faults)} faults vs {len(ns)} sizes")
    t_pad = max([SCHED_T_MIN] + [canonical_horizon(f.churn)
                                 for f in faults if get(f) is not None])
    scheds = [build_or_static(f, n, n_pad=n_pad, t_pad=t_pad)
              for f, n in zip(faults, ns)]
    # NUMPY stacking on purpose: the stack axis K varies tick to tick
    # in serving, and a jnp.stack over K inputs is a fresh tiny XLA
    # program per distinct K — steady-state serving must assemble
    # operand CONTENT without ever touching the compile path (the
    # load-harness all-warm gate)
    import numpy as np
    return Schedule(
        die=np.stack([np.asarray(s.die) for s in scheds]),
        rec=np.stack([np.asarray(s.rec) for s in scheds]),
        cut_tbl=np.stack([np.asarray(s.cut_tbl) for s in scheds]),
        drop_tbl=np.stack([np.asarray(s.drop_tbl) for s in scheds]))


def placeholder_trace_inputs(fault_static: FaultConfig, n: int,
                             have_table: bool):
    """(rep_fault, topo_placeholder) for the shape-only memoized loop
    builders (parallel/sharded._cached_dense_loop, parallel/sweep
    ._cached_churn_sweep_scan): a representative one-event schedule —
    the step's trace reads only ``ch is not None`` and operand SHAPES
    from it — and a topology whose trace-visible facts are exactly
    (n, implicit-vs-table); table ROWS always arrive as runtime
    arguments (the _cached_pod_sweep_scan placeholder pattern).  ONE
    definition so the builders cannot drift on what the trace bakes."""
    import dataclasses
    from gossip_tpu.topology.generators import Topology
    if fault_static.churn is not None:
        raise ValueError("memo key must strip the schedule: pass "
                         "dataclasses.replace(fault, churn=None)")
    rep_fault = dataclasses.replace(
        fault_static, churn=ChurnConfig(events=((0, 1, 2),)))
    if have_table:
        topo_ph = Topology(nbrs=jnp.zeros((0, 2), jnp.int32),
                           deg=jnp.zeros((0,), jnp.int32), n=n,
                           family="placeholder")
    else:
        topo_ph = Topology(nbrs=None, deg=None, n=n, family="complete")
    return rep_fault, topo_ph


def mixed_scenarios(k: int, n: int, *, salt: int = 0,
                    drop_prob: float = 0.0, seed: int = 0,
                    ramp_to: float = 0.15, window_end: int = 4):
    """K mixed nemesis fault programs cycling the four shape classes —
    crash/recover event, partition window, drop-rate ramp, and a
    permanent-crash + window combination — the ONE scenario-family
    generator shared by the dry-run ``churn_sweep`` family, bench.py's
    families leg, and tools/churn_sweep_capture.py, so the three
    surfaces exercise the same scenario shapes by construction.
    ``salt`` varies the CONTENT (node ids, window lengths, ramp levels)
    without changing any array shape: a salted family re-enters the
    same compiled loop (the whole point of schedules-as-operands)."""
    from gossip_tpu.config import ChurnConfig, FaultConfig
    out = []
    for i in range(k):
        kind = i % 4
        if kind == 0:
            ch = ChurnConfig(events=(((3 + i + salt) % n, 1, 4),))
        elif kind == 1:
            ch = ChurnConfig(
                partitions=((0, 2 + (i + salt) % 3, n // 2),))
        elif kind == 2:
            ch = ChurnConfig(ramp=(0, window_end, 0.0,
                                   ramp_to * (1 + i % 3) / 3))
        else:
            ch = ChurnConfig(events=(((11 + i + salt) % n, 1, -1),),
                             partitions=((1, window_end, n // 4),))
        out.append(FaultConfig(drop_prob=drop_prob, seed=seed,
                               churn=ch))
    return out


def schedule_fingerprint(fault: Optional[FaultConfig], n: int,
                         origin: int = 0):
    """sha256 hex digest of the BUILT fault program — the four Schedule
    tables (canonical-horizon padded, so two configs that lower to the
    same program share a digest) plus the eventual-alive denominator —
    or None without a churn schedule.  This is the SEMANTIC twin of the
    CLI's syntactic config fingerprint: a checkpoint stamps it under
    ``extra['fault_program']`` and ``--resume`` refuses a mismatched or
    missing one, because resuming under a different churn/partition/
    ramp program (or a different convergence denominator) would fork
    the trajectory while claiming bitwise continuation.  Host-side and
    cheap: tables are config-sized, never run-length- or n-quadratic."""
    if get(fault) is None:
        return None
    import hashlib

    import numpy as np
    sched = build(fault, n)
    h = hashlib.sha256()
    for arr in (sched.die, sched.rec, sched.cut_tbl, sched.drop_tbl,
                eventual_alive(fault, n, origin)):
        a = np.asarray(arr)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def sched_args(sched: Schedule) -> tuple:
    """The schedule as a flat tail of step arguments — appended to a
    factory's ``tables`` tuple so it rides every driver's existing
    ``step(state, *tables)`` plumbing (and shard_map in_specs stay
    plain per-array PartitionSpecs, all replicated)."""
    return (sched.die, sched.rec, sched.cut_tbl, sched.drop_tbl)


def sched_of_tables(tbl) -> Schedule:
    """The Schedule riding a factory's table tail (:func:`sched_args`
    layout) — for drivers that need the TRACED schedule besides the
    step (the recorders' nemesis observables)."""
    return Schedule(*tbl[-N_SCHED_OPERANDS:])


def split_tables(ch: Optional[ChurnConfig], tbl: tuple):
    """(topology_tables, Schedule-or-None): peel the schedule operands
    :func:`sched_args` appended back off a step's ``*tables`` tail —
    the ONE inverse, so factories and drivers cannot disagree on the
    layout."""
    if ch is None:
        return tbl, None
    return (tbl[:-N_SCHED_OPERANDS],
            Schedule(*tbl[-N_SCHED_OPERANDS:]))


# -- the byzantine program (scripted liars — ByzConfig lowering) -------

# Integer liar-kind codes of the lowered tables (0 = honest; content,
# never shape — two byz programs of the same n_pad share one compiled
# loop).  The config-string -> code map is the ONE translation.
BYZ_HONEST = 0
BYZ_CODES = {BYZ_CORRUPT: 1, BYZ_REPLAY: 2, BYZ_EQUIVOCATE: 3,
             BYZ_INFLATE: 4}

# How many trailing step arguments a byzantine program occupies on a
# factory's ``tables`` tuple (byz_args / split_byz): kind/start/arg
# int32[n_pad] + the traced quorum scalar.
N_BYZ_OPERANDS = 4


class ByzSchedule:
    """Device-resident byzantine program (module doc): per-node liar
    ``kind`` codes (:data:`BYZ_CODES`; 0 honest), the ``start`` round
    each lie begins (:data:`NEVER` for honest rows), the per-liar
    transform ``arg``, and the traced ``quorum`` scalar of the defended
    set kernels.  A registered pytree, like :class:`Schedule`."""

    __slots__ = ("kind", "start", "arg", "quorum")

    def __init__(self, kind, start, arg, quorum):
        self.kind = kind
        self.start = start
        self.arg = arg
        self.quorum = quorum


def _byz_flatten(b):
    return ((b.kind, b.start, b.arg, b.quorum), None)


def _byz_unflatten(_, children):
    return ByzSchedule(*children)


jax.tree_util.register_pytree_node(ByzSchedule, _byz_flatten,
                                   _byz_unflatten)


def build_byz(fault: FaultConfig, n: int,
              n_pad: Optional[int] = None) -> ByzSchedule:
    """Lower ``fault.byz`` to the per-node liar tables.  NUMPY
    construction, converted once (the :func:`_event_tables` rationale:
    a jnp scatter per distinct liar-list length is a tiny recompile
    class the staticcheck lint flags); padding rows are honest with
    ``start = NEVER``."""
    import numpy as np
    bz = get_byz(fault)
    if bz is None:
        raise ValueError("build_byz() needs a FaultConfig with a byz "
                         "program (gate on nemesis.get_byz(fault) "
                         "first)")
    validate_liars(fault, n)
    n_pad = n if n_pad is None else n_pad
    kind = np.zeros((n_pad,), np.int32)
    start = np.full((n_pad,), NEVER, np.int32)
    arg = np.zeros((n_pad,), np.int32)
    for node, rnd, k, a in bz.liars:
        kind[node] = BYZ_CODES[k]
        start[node] = rnd
        arg[node] = a
    return ByzSchedule(kind=jnp.asarray(kind), start=jnp.asarray(start),
                       arg=jnp.asarray(arg),
                       quorum=jnp.asarray(bz.quorum, jnp.int32))


def byz_args(bz: ByzSchedule) -> tuple:
    """The byzantine program as a flat tail of step arguments — the
    :func:`sched_args` twin.  Table-tail order: topology + injection
    (+ schedule) (+ byz) — the byz operands ride OUTERMOST so steps
    peel them first (:func:`split_byz` before :func:`split_tables`)."""
    return (bz.kind, bz.start, bz.arg, bz.quorum)


def byz_of_tables(tbl) -> ByzSchedule:
    """The ByzSchedule riding a factory's table tail (:func:`byz_args`
    layout) — the :func:`sched_of_tables` twin."""
    return ByzSchedule(*tbl[-N_BYZ_OPERANDS:])


def split_byz(bz: Optional[ByzConfig], tbl: tuple):
    """(head_tables, ByzSchedule-or-None): peel the byz operands back
    off a step's ``*tables`` tail — the ONE inverse of
    :func:`byz_args`, called BEFORE :func:`split_tables` (the byz tail
    is outermost)."""
    if bz is None:
        return tbl, None
    return tbl[:-N_BYZ_OPERANDS], ByzSchedule(*tbl[-N_BYZ_OPERANDS:])


def validate_liars(fault: FaultConfig, n: int) -> None:
    """Host-side guard: scripted liars must reference real node ids —
    an out-of-range liar would silently scatter-drop (corrupt
    nobody), the validate_events rule."""
    bz = get_byz(fault)
    if bz is None:
        return
    bad = [a for a in bz.liars if a[0] >= n]
    if bad:
        raise ValueError(f"byz liars reference node ids >= n={n}: "
                         f"{bad}")


def honest_mask(fault: Optional[FaultConfig], n: int) -> jax.Array:
    """bool[n]: True where the node is NOT a scripted liar — the
    ``byz_conv`` numerator/denominator mask (a liar's convergence is
    its own business; honest nodes must agree on honest-owned
    components — docs/ROBUSTNESS.md).  Config-only, host-cheap."""
    import numpy as np
    mask = np.ones((n,), bool)
    bz = get_byz(fault)
    if bz is not None:
        for node, _, _, _ in bz.liars:
            if node < n:
                mask[node] = False
    return jnp.asarray(mask)


def byz_active(byz: ByzSchedule, nodes, round_) -> jax.Array:
    """bool[...]: is ``nodes``'s scripted lie active at ``round_``
    (kind nonzero and the start round reached)?  Broadcasts; callers
    AND in liveness — a churn-down liar serves nothing, so its lie
    transforms nothing (the dead-nodes-are-silent contract)."""
    nodes = jnp.asarray(nodes, jnp.int32)
    r = jnp.asarray(round_, jnp.int32)
    return (byz.kind[nodes] != BYZ_HONEST) & (byz.start[nodes] <= r)


def validate_events(fault: FaultConfig, n: int) -> None:
    """Host-side guard: scripted churn must reference real node ids —
    an out-of-range event would silently scatter-drop (kill nobody)."""
    ch = fault.churn
    if ch is None:
        return
    bad = [e for e in ch.events if e[0] >= n]
    if bad:
        raise ValueError(f"churn events reference node ids >= n={n}: "
                         f"{bad}")
    badc = [w for w in ch.partitions if w[2] >= n]
    if badc:
        raise ValueError(f"partition cuts >= n={n} leave one side "
                         f"empty: {badc}")


def _idx(tbl, round_):
    """Clamped schedule lookup — exact beyond the horizon (module doc:
    the last row is the steady state by construction)."""
    r = jnp.asarray(round_, jnp.int32)
    return tbl[jnp.minimum(jnp.maximum(r, 0), tbl.shape[0] - 1)]


def alive_rows(sched: Schedule, base_alive: jax.Array,
               round_) -> jax.Array:
    """bool[n_pad] liveness at ``round_``: the static base mask minus
    churn-down nodes (die <= r < rec)."""
    r = jnp.asarray(round_, jnp.int32)
    down = (sched.die <= r) & (r < sched.rec)
    return base_alive & ~down


def drop_at(sched: Schedule, round_) -> jax.Array:
    """f32 scalar drop probability for ``round_`` (traced — kernels on
    the churn path always draw their drop coins; p=0 rounds draw
    all-False masks, bitwise a no-op on the trajectory)."""
    return _idx(sched.drop_tbl, round_)


def cut_at(sched: Schedule, round_) -> jax.Array:
    """int32 scalar partition cut for ``round_`` (-1 = closed)."""
    return _idx(sched.cut_tbl, round_)


def same_side(cut, a, b) -> jax.Array:
    """True where a message a -> b is allowed by the cut: the window is
    closed (cut < 0) or both endpoints sit on the same side.  Shapes
    broadcast; sentinel targets (>= n) land on the high side and are
    dropped by the kernels' own validity masks either way."""
    cut = jnp.asarray(cut, jnp.int32)
    return (cut < 0) | ((jnp.asarray(a, jnp.int32) >= cut)
                        == (jnp.asarray(b, jnp.int32) >= cut))


def partition_targets(cut, src_gids: jax.Array, targets: jax.Array,
                      sentinel: int) -> jax.Array:
    """Cross-cut targets -> the kernel's drop sentinel (the same
    lost-for-this-round-only semantics as ops/sampling.apply_drop:
    re-sampled next round, at-least-once).  ``src_gids`` broadcasts
    against ``targets`` ([m] vs [m, k])."""
    allowed = same_side(cut, src_gids[:, None]
                        if targets.ndim == src_gids.ndim + 1
                        else src_gids, targets)
    return jnp.where(allowed, targets, jnp.asarray(sentinel,
                                                   targets.dtype))


def lost_count(pre: jax.Array, post: jax.Array, active: jax.Array,
               n: int) -> jax.Array:
    """f32 messages the nemesis destroyed this round: edge uses that
    were real targets (< n) from ``active`` senders before the drop
    coins + cut, minus those still real after.  ``pre``/``post`` are
    [m, k] target tables around the apply_drop/partition pair;
    ``active`` is the [m] sender-activity mask (an inactive sender's
    slot carried no message to lose)."""
    a = active[:, None]
    return (jnp.sum((pre < n) & a, dtype=jnp.float32)
            - jnp.sum((post < n) & a, dtype=jnp.float32))


def base_alive_or_ones(fault, n: int, origin: int) -> jax.Array:
    """The static alive mask as a real array (churn kernels always mask
    — the None fast path is the static kernels' optimization)."""
    from gossip_tpu.models.state import alive_mask
    alive = alive_mask(fault, n, origin)
    return jnp.ones((n,), jnp.bool_) if alive is None else alive


def eventual_alive(fault: FaultConfig, n: int, origin: int) -> jax.Array:
    """bool[n] steady-state liveness: the static mask minus PERMANENT
    churn deaths (recover_round < 0).  This is the coverage/convergence
    denominator under churn — a temporarily-down node stays in it (it
    will recover and must converge: the heal-convergence contract),
    while a forever-dead node is unreachable like a static death.
    Static (config-only), so drivers can use it for while_loop targets
    without per-round machinery."""
    alive = base_alive_or_ones(fault, n, origin)
    dead = permanent_dead_ids(fault.churn)
    if dead:
        alive = alive.at[jnp.asarray(dead, jnp.int32)].set(False)
    return alive


def eventual_alive_pad(fault: FaultConfig, n: int, n_pad: int,
                       origin: int) -> jax.Array:
    """:func:`eventual_alive` over mesh-padded rows (padding rows dead,
    the parallel/sharded.sharded_alive contract)."""
    alive = eventual_alive(fault, n, origin)
    if n_pad == n:
        return alive
    return jnp.concatenate(
        [alive, jnp.zeros((n_pad - n,), jnp.bool_)], axis=0)


def metric_alive(fault: Optional[FaultConfig], n: int, origin: int):
    """The single-device coverage denominator: the static mask (None
    when fault-free — the hot-path contract of models/state.alive_mask)
    or, under a churn schedule, the EVENTUAL alive set
    (:func:`eventual_alive`): a temporarily-down node stays in the
    denominator because it recovers and must converge — the
    heal-convergence contract."""
    from gossip_tpu.models.state import alive_mask
    if get(fault) is not None:
        return eventual_alive(fault, n, origin)
    return alive_mask(fault, n, origin)


def drop_lost(step, ch: Optional[ChurnConfig]):
    """Normalize a round step to ``state -> state``: a churn-path step
    returns ``(state, lost)`` (models/si.py contract) — drivers that do
    not record the lost observable drop it here."""
    if ch is None:
        return step

    def wrapped(*args):
        out, _lost = step(*args)
        return out

    return wrapped


def permanent_dead_ids(ch: Optional[ChurnConfig]):
    """Node ids the schedule kills forever (recover_round < 0) — the
    metric-dead set SWIM detection should converge on (host-side,
    from the config)."""
    if ch is None:
        return ()
    return tuple(e[0] for e in ch.events if e[2] < 0)


def fused_base_words(fault: FaultConfig, n: int, origin: int) -> jax.Array:
    """The STATIC alive mask rendered in the fused engine's
    one-word-per-node [mr_rows(n), 128] layout (0xFFFFFFFF alive, 0
    dead/phantom) — always a real array, unlike
    ops/pallas_round.fault_masks_word's None fast path: churn kernels
    always mask.  In-trace safe."""
    from gossip_tpu.ops.pallas_round import render_alive_words
    return render_alive_words(base_alive_or_ones(fault, n, origin), n)


def fused_word_tables(fault: FaultConfig, n: int):
    """(die_words, rec_words): the die/rec round tables rendered in the
    fused engine's one-word-per-node [mr_rows(n), 128] layout
    (ops/pallas_round.fault_masks_word geometry) — int32 rounds, NEVER
    on padding rows.  In-trace safe (iota + small scatters)."""
    from gossip_tpu.ops.pallas_round import LANES, mr_rows
    ch = fault.churn
    if ch is None:
        raise ValueError("fused_word_tables needs a churn schedule")
    # same guard as build(): an out-of-range event id would land on a
    # phantom lane (or scatter-drop) and silently kill nobody
    validate_events(fault, n)
    rows = mr_rows(n)
    die, rec = _event_tables(ch, rows * LANES)
    return die.reshape(rows, LANES), rec.reshape(rows, LANES)


def fused_alive_words_at(base_words: jax.Array, die_w: jax.Array,
                         rec_w: jax.Array, round_) -> jax.Array:
    """Per-round alive word mask for the plane-sharded fused engine:
    the static 0xFFFFFFFF/0 mask minus churn-down nodes — the runtime
    OPERAND the compiled fused loops index by their round counter."""
    r = jnp.asarray(round_, jnp.int32)
    down = (die_w <= r) & (r < rec_w)
    return jnp.where(down, jnp.uint32(0), base_words)


def fused_eventual_words(base_words: jax.Array, die_w: jax.Array,
                         rec_w: jax.Array) -> jax.Array:
    """Steady-state alive words: the base mask minus PERMANENT churn
    deaths — the fused engine's coverage/convergence denominator under
    churn (:func:`eventual_alive` rationale, word-rendered)."""
    forever = (die_w < NEVER) & (rec_w >= NEVER)
    return jnp.where(forever, jnp.uint32(0), base_words)


def check_supported(fault: Optional[FaultConfig], *, engine: str,
                    partitions: bool = True, ramp: bool = True,
                    events: bool = True, byz: bool = False) -> None:
    """Reject schedule features an engine cannot honor — loudly, never
    silently (the no-silent-substitution policy).  Since the operand
    PRs (XLA paths, then the fused Pallas kernels: drop threshold as
    an SMEM scalar, partition cuts as rotated side-word masks) the
    remaining rejections are the genuinely-impossible combinations:

      * ``partitions=False`` — ONLY SWIM: probes ride the complete
        membership overlay, which a link cut does not model (the fused
        engines came off this row when the cut lowered to per-round
        side masks through the partner rotation);
      * ``ramp=False`` — NO current engine: kept for future engines
        whose drop coin cannot follow a traced per-round probability
        (the fused kernels came off this row when the threshold became
        a runtime scalar operand indexed from the drop table);
      * ``events=False`` — an engine with no churn support at all:
        ONLY the topo-sparse exchange and the grid config sweeps
        remain (the checkpointed segment drivers came off this list
        when resume grew the fault-program fingerprint +
        absolute-round contract — utils/checkpoint module doc);
      * ``byz=False`` (the default) — an engine that cannot RUN a
        byzantine liar program: only the crdt-pull and register-pull
        exchanges render liar transforms and carry the array-form
        defenses (owner guards / monotonicity clamps / quorum echo —
        ops/crdt.pull_merge_crdt_byz), so every other engine rejects a
        ``fault.byz`` loudly.  Checked FIRST: a byz program without a
        churn schedule must still reject on an unsupported engine."""
    if get_byz(fault) is not None and not byz:
        raise ValueError(
            f"the {engine} engine cannot run a byzantine liar program "
            "(no receiver-side transform/defense hooks in its "
            "exchange); run the crdt-pull or register-pull payloads — "
            "docs/ROBUSTNESS.md \"Byzantine adversaries\" capability "
            "rows")
    ch = get(fault)
    if ch is None:
        return
    if not events:
        # no churn support at all: ANY schedule (a vacuous one already
        # normalized to None) rejects with the one generic message —
        # never the feature-specific ones below, whose reasons describe
        # engines that DO run schedules
        raise ValueError(
            f"the {engine} engine does not run churn schedules; use "
            "the dense/sparse exchanges (docs/ROBUSTNESS.md scenario "
            "catalog)")
    if not partitions and ch.partitions:
        raise ValueError(
            f"the {engine} engine cannot honor partition windows (no "
            "per-pair messages a node-id cut could destroy — SWIM "
            "probes ride the complete membership overlay); run the "
            "dense/sparse/halo/fused exchanges for partition scenarios")
    if not ramp and ch.ramp is not None:
        raise ValueError(
            f"the {engine} engine cannot follow a drop-rate ramp (its "
            "drop coin is not a per-round traced probability); every "
            "current engine — XLA and fused Pallas alike — consumes "
            "the drop table as a runtime operand")


def observables(sched: Schedule, alive: jax.Array, round_):
    """(alive_count, cut_pairs) at ``round_`` — the round_metrics
    observables the recorders stamp per round.  ``alive`` is the
    CURRENT padded liveness row mask (padding rows already False);
    ``cut_pairs`` counts alive pairs separated by the open cut
    (|A| * |B|), 0 while no window is open."""
    cut = cut_at(sched, round_)
    a = jnp.sum(alive, dtype=jnp.float32)
    ids = jnp.arange(alive.shape[0], dtype=jnp.int32)
    hi = jnp.sum(alive & (ids >= cut), dtype=jnp.float32)
    lo = a - hi
    pairs = jnp.where(cut >= 0, lo * hi, 0.0)
    return a, pairs
