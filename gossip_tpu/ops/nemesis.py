"""Compiled nemesis: time-varying fault schedules inside the round loops.

Maelstrom's whole robustness story is dynamic — the nemesis partitions
the network *mid-run* and the reference converges after heal via
at-least-once retry (main.go:77-87).  The batched simulator's
:class:`~gossip_tpu.config.FaultConfig` could only express STATIC
faults (a fixed death mask, a constant drop rate, one scripted SWIM
``fail_round``).  This module lowers a
:class:`~gossip_tpu.config.ChurnConfig` — crash/recover churn,
partition windows, drop-rate ramps — into a tiny device-resident
:class:`Schedule` consumed by the loop counter INSIDE every compiled
round loop (`lax.scan` / `lax.while_loop`), the way the literature's
scenarios actually run: Demers et al.'s anti-entropy was designed to
ride out transient link failure, and SWIM (Das et al., DSN 2002) is
meaningless without churn to detect.

The lowering
------------
``Schedule`` is a registered pytree (the RoundMetrics pattern) holding

  * ``die`` / ``rec`` — ``int32[n_pad]``: the round each node goes down
    / comes back (:data:`NEVER` sentinels).  Node ``i`` is churn-down
    during ``die[i] <= r < rec[i]``.
  * ``cut_tbl`` — ``int32[T]``: the partition cut per round (-1 = no
    window open).  Messages whose endpoints straddle the cut are lost
    while a window is open.
  * ``drop_tbl`` — ``f32[T]``: the per-round link drop probability
    (FaultConfig.drop_prob outside the ramp, linear inside, final
    value held after).

``T = ChurnConfig.horizon()`` is the round after which the schedule is
constant by construction (every window closed, ramp finished), so the
clamped lookup ``tbl[min(r, T-1)]`` is EXACT for every round — the
tables are config-sized, not run-length-sized, and the same schedule
serves a 6-round curve and a 10k-round flagship run.  Everything is
built in-trace from scalars (:func:`build` is called inside the
drivers' jitted loops — no O(N) inline constants in the compile
request, the models/swim.py rule), and the arrays can equally ride a
memoized loop as runtime OPERANDS (parallel/sharded_fused keys its
lru_cache on ``churn: bool`` only — a churn sweep over schedules
shares one compiled loop, the alive-mask runtime-operand trick).

Semantics (shared by every kernel — the heal-convergence tests pin
them):

  * a churn-down node neither sends, responds, nor receives; its
    digest goes dark (exactly the static-mask contract, per round);
  * a cross-cut message is lost for that round only — the sender
    retries implicitly next round (at-least-once, main.go:80-87), so
    coverage STALLS at the cut while a window is open and converges
    after heal;
  * the drop coin for round ``r`` is drawn from the same per-(round,
    node) streams as the static path (ops/sampling tags), with
    ``drop_tbl[r]`` as the probability — trajectories are mesh-shape
    invariant for the same reason peer sampling is.

Observables (wired into ops/round_metrics by the drivers' recorders):
per-round ``alive`` count, ``cut_pairs`` (alive node pairs separated
by the open cut — 0 when closed), and ``dropped`` (messages lost to
drop coins + the cut, counted exactly by the kernels).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from gossip_tpu.config import ChurnConfig, FaultConfig

# Sentinel round for "never": far beyond any realistic max_rounds but
# safely below int32 overflow under the +1 arithmetic of round counters.
NEVER = 1 << 29


def get(fault: Optional[FaultConfig]) -> Optional[ChurnConfig]:
    """The schedule carried by a fault config, or None — the ONE probe
    every kernel factory branches on (FaultConfig normalizes an empty
    ChurnConfig to None, so `get(fault) is None` == static hot path)."""
    return fault.churn if fault is not None else None


class Schedule:
    """Device-resident nemesis schedule (module doc).  A registered
    pytree so it can ride loop carries and jit boundaries; all leaves,
    no static aux (cut-side observables count over the padded alive
    mask, whose padding rows are already False)."""

    __slots__ = ("die", "rec", "cut_tbl", "drop_tbl")

    def __init__(self, die, rec, cut_tbl, drop_tbl):
        self.die = die
        self.rec = rec
        self.cut_tbl = cut_tbl
        self.drop_tbl = drop_tbl


def _sched_flatten(s):
    return ((s.die, s.rec, s.cut_tbl, s.drop_tbl), None)


def _sched_unflatten(_, children):
    return Schedule(*children)


jax.tree_util.register_pytree_node(Schedule, _sched_flatten,
                                   _sched_unflatten)


def _event_tables(ch: ChurnConfig, size: int):
    """die/rec int32[size] round tables from the event list (rec < 0 ->
    NEVER; unscripted rows NEVER) — the ONE event-scatter lowering,
    shared by :func:`build` and :func:`fused_word_tables` so the flat
    and word-rendered engines' churn timelines cannot drift.  In-trace
    safe (small scatters)."""
    die = jnp.full((size,), NEVER, jnp.int32)
    rec = jnp.full((size,), NEVER, jnp.int32)
    if ch.events:
        nodes = jnp.asarray([e[0] for e in ch.events], jnp.int32)
        die = die.at[nodes].set(jnp.asarray(
            [e[1] for e in ch.events], jnp.int32))
        rec = rec.at[nodes].set(jnp.asarray(
            [e[2] if e[2] >= 0 else NEVER for e in ch.events], jnp.int32))
    return die, rec


def build(fault: FaultConfig, n: int, n_pad: Optional[int] = None
          ) -> Schedule:
    """Lower ``fault.churn`` to the device tables (in-trace safe: small
    scatters + static-slice sets only).  ``n_pad`` sizes the die/rec
    vectors for mesh-padded kernels; padding rows carry NEVER (their
    deadness comes from the base alive mask, as always)."""
    ch = fault.churn
    if ch is None:
        raise ValueError("build() needs a FaultConfig with a churn "
                         "schedule (gate on nemesis.get(fault) first)")
    validate_events(fault, n)
    n_pad = n if n_pad is None else n_pad
    die, rec = _event_tables(ch, n_pad)
    t = ch.horizon()
    cut_np = [-1] * t
    for start, end, cut in ch.partitions:
        for r in range(start, min(end, t)):
            cut_np[r] = cut
    drop_np = [float(fault.drop_prob)] * t
    if ch.ramp is not None:
        start, end, p0, p1 = ch.ramp
        for r in range(start, t):
            frac = min((r - start) / max(end - start, 1), 1.0)
            drop_np[r] = p0 + (p1 - p0) * frac
    return Schedule(die=die, rec=rec,
                    cut_tbl=jnp.asarray(cut_np, jnp.int32),
                    drop_tbl=jnp.asarray(drop_np, jnp.float32))


def validate_events(fault: FaultConfig, n: int) -> None:
    """Host-side guard: scripted churn must reference real node ids —
    an out-of-range event would silently scatter-drop (kill nobody)."""
    ch = fault.churn
    if ch is None:
        return
    bad = [e for e in ch.events if e[0] >= n]
    if bad:
        raise ValueError(f"churn events reference node ids >= n={n}: "
                         f"{bad}")
    badc = [w for w in ch.partitions if w[2] >= n]
    if badc:
        raise ValueError(f"partition cuts >= n={n} leave one side "
                         f"empty: {badc}")


def _idx(tbl, round_):
    """Clamped schedule lookup — exact beyond the horizon (module doc:
    the last row is the steady state by construction)."""
    r = jnp.asarray(round_, jnp.int32)
    return tbl[jnp.minimum(jnp.maximum(r, 0), tbl.shape[0] - 1)]


def alive_rows(sched: Schedule, base_alive: jax.Array,
               round_) -> jax.Array:
    """bool[n_pad] liveness at ``round_``: the static base mask minus
    churn-down nodes (die <= r < rec)."""
    r = jnp.asarray(round_, jnp.int32)
    down = (sched.die <= r) & (r < sched.rec)
    return base_alive & ~down


def drop_at(sched: Schedule, round_) -> jax.Array:
    """f32 scalar drop probability for ``round_`` (traced — kernels on
    the churn path always draw their drop coins; p=0 rounds draw
    all-False masks, bitwise a no-op on the trajectory)."""
    return _idx(sched.drop_tbl, round_)


def cut_at(sched: Schedule, round_) -> jax.Array:
    """int32 scalar partition cut for ``round_`` (-1 = closed)."""
    return _idx(sched.cut_tbl, round_)


def same_side(cut, a, b) -> jax.Array:
    """True where a message a -> b is allowed by the cut: the window is
    closed (cut < 0) or both endpoints sit on the same side.  Shapes
    broadcast; sentinel targets (>= n) land on the high side and are
    dropped by the kernels' own validity masks either way."""
    cut = jnp.asarray(cut, jnp.int32)
    return (cut < 0) | ((jnp.asarray(a, jnp.int32) >= cut)
                        == (jnp.asarray(b, jnp.int32) >= cut))


def partition_targets(cut, src_gids: jax.Array, targets: jax.Array,
                      sentinel: int) -> jax.Array:
    """Cross-cut targets -> the kernel's drop sentinel (the same
    lost-for-this-round-only semantics as ops/sampling.apply_drop:
    re-sampled next round, at-least-once).  ``src_gids`` broadcasts
    against ``targets`` ([m] vs [m, k])."""
    allowed = same_side(cut, src_gids[:, None]
                        if targets.ndim == src_gids.ndim + 1
                        else src_gids, targets)
    return jnp.where(allowed, targets, jnp.asarray(sentinel,
                                                   targets.dtype))


def lost_count(pre: jax.Array, post: jax.Array, active: jax.Array,
               n: int) -> jax.Array:
    """f32 messages the nemesis destroyed this round: edge uses that
    were real targets (< n) from ``active`` senders before the drop
    coins + cut, minus those still real after.  ``pre``/``post`` are
    [m, k] target tables around the apply_drop/partition pair;
    ``active`` is the [m] sender-activity mask (an inactive sender's
    slot carried no message to lose)."""
    a = active[:, None]
    return (jnp.sum((pre < n) & a, dtype=jnp.float32)
            - jnp.sum((post < n) & a, dtype=jnp.float32))


def base_alive_or_ones(fault, n: int, origin: int) -> jax.Array:
    """The static alive mask as a real array (churn kernels always mask
    — the None fast path is the static kernels' optimization)."""
    from gossip_tpu.models.state import alive_mask
    alive = alive_mask(fault, n, origin)
    return jnp.ones((n,), jnp.bool_) if alive is None else alive


def eventual_alive(fault: FaultConfig, n: int, origin: int) -> jax.Array:
    """bool[n] steady-state liveness: the static mask minus PERMANENT
    churn deaths (recover_round < 0).  This is the coverage/convergence
    denominator under churn — a temporarily-down node stays in it (it
    will recover and must converge: the heal-convergence contract),
    while a forever-dead node is unreachable like a static death.
    Static (config-only), so drivers can use it for while_loop targets
    without per-round machinery."""
    alive = base_alive_or_ones(fault, n, origin)
    dead = permanent_dead_ids(fault.churn)
    if dead:
        alive = alive.at[jnp.asarray(dead, jnp.int32)].set(False)
    return alive


def eventual_alive_pad(fault: FaultConfig, n: int, n_pad: int,
                       origin: int) -> jax.Array:
    """:func:`eventual_alive` over mesh-padded rows (padding rows dead,
    the parallel/sharded.sharded_alive contract)."""
    alive = eventual_alive(fault, n, origin)
    if n_pad == n:
        return alive
    return jnp.concatenate(
        [alive, jnp.zeros((n_pad - n,), jnp.bool_)], axis=0)


def metric_alive(fault: Optional[FaultConfig], n: int, origin: int):
    """The single-device coverage denominator: the static mask (None
    when fault-free — the hot-path contract of models/state.alive_mask)
    or, under a churn schedule, the EVENTUAL alive set
    (:func:`eventual_alive`): a temporarily-down node stays in the
    denominator because it recovers and must converge — the
    heal-convergence contract."""
    from gossip_tpu.models.state import alive_mask
    if get(fault) is not None:
        return eventual_alive(fault, n, origin)
    return alive_mask(fault, n, origin)


def drop_lost(step, ch: Optional[ChurnConfig]):
    """Normalize a round step to ``state -> state``: a churn-path step
    returns ``(state, lost)`` (models/si.py contract) — drivers that do
    not record the lost observable drop it here."""
    if ch is None:
        return step

    def wrapped(*args):
        out, _lost = step(*args)
        return out

    return wrapped


def permanent_dead_ids(ch: Optional[ChurnConfig]):
    """Node ids the schedule kills forever (recover_round < 0) — the
    metric-dead set SWIM detection should converge on (host-side,
    from the config)."""
    if ch is None:
        return ()
    return tuple(e[0] for e in ch.events if e[2] < 0)


def fused_base_words(fault: FaultConfig, n: int, origin: int) -> jax.Array:
    """The STATIC alive mask rendered in the fused engine's
    one-word-per-node [mr_rows(n), 128] layout (0xFFFFFFFF alive, 0
    dead/phantom) — always a real array, unlike
    ops/pallas_round.fault_masks_word's None fast path: churn kernels
    always mask.  In-trace safe."""
    from gossip_tpu.ops.pallas_round import render_alive_words
    return render_alive_words(base_alive_or_ones(fault, n, origin), n)


def fused_word_tables(fault: FaultConfig, n: int):
    """(die_words, rec_words): the die/rec round tables rendered in the
    fused engine's one-word-per-node [mr_rows(n), 128] layout
    (ops/pallas_round.fault_masks_word geometry) — int32 rounds, NEVER
    on padding rows.  In-trace safe (iota + small scatters)."""
    from gossip_tpu.ops.pallas_round import LANES, mr_rows
    ch = fault.churn
    if ch is None:
        raise ValueError("fused_word_tables needs a churn schedule")
    # same guard as build(): an out-of-range event id would land on a
    # phantom lane (or scatter-drop) and silently kill nobody
    validate_events(fault, n)
    rows = mr_rows(n)
    die, rec = _event_tables(ch, rows * LANES)
    return die.reshape(rows, LANES), rec.reshape(rows, LANES)


def fused_alive_words_at(base_words: jax.Array, die_w: jax.Array,
                         rec_w: jax.Array, round_) -> jax.Array:
    """Per-round alive word mask for the plane-sharded fused engine:
    the static 0xFFFFFFFF/0 mask minus churn-down nodes — the runtime
    OPERAND the compiled fused loops index by their round counter."""
    r = jnp.asarray(round_, jnp.int32)
    down = (die_w <= r) & (r < rec_w)
    return jnp.where(down, jnp.uint32(0), base_words)


def fused_eventual_words(base_words: jax.Array, die_w: jax.Array,
                         rec_w: jax.Array) -> jax.Array:
    """Steady-state alive words: the base mask minus PERMANENT churn
    deaths — the fused engine's coverage/convergence denominator under
    churn (:func:`eventual_alive` rationale, word-rendered)."""
    forever = (die_w < NEVER) & (rec_w >= NEVER)
    return jnp.where(forever, jnp.uint32(0), base_words)


def check_supported(fault: Optional[FaultConfig], *, engine: str,
                    partitions: bool = True, ramp: bool = True,
                    events: bool = True) -> None:
    """Reject schedule features an engine cannot honor — loudly, never
    silently (the no-silent-substitution policy).  The plane-sharded
    fused engine has no per-pair messages to cut and bakes its drop
    threshold into the kernel; SWIM probes ride the complete membership
    overlay, which a link cut does not model; ``events=False`` marks an
    engine with no churn support at all (checkpointed segment drivers,
    the topo-sparse exchange)."""
    ch = get(fault)
    if ch is None:
        return
    if not events and ch.events:
        raise ValueError(
            f"the {engine} engine does not run churn schedules; use "
            "the dense/sparse exchanges (docs/ROBUSTNESS.md scenario "
            "catalog)")
    if not partitions and ch.partitions:
        raise ValueError(
            f"the {engine} engine cannot honor partition windows "
            "(no per-pair messages to cut); run the dense/sparse/halo "
            "exchanges for partition scenarios")
    if not ramp and ch.ramp is not None:
        raise ValueError(
            f"the {engine} engine bakes its drop threshold into the "
            "kernel and cannot honor a drop-rate ramp")


def observables(sched: Schedule, alive: jax.Array, round_):
    """(alive_count, cut_pairs) at ``round_`` — the round_metrics
    observables the recorders stamp per round.  ``alive`` is the
    CURRENT padded liveness row mask (padding rows already False);
    ``cut_pairs`` counts alive pairs separated by the open cut
    (|A| * |B|), 0 while no window is open."""
    cut = cut_at(sched, round_)
    a = jnp.sum(alive, dtype=jnp.float32)
    ids = jnp.arange(alive.shape[0], dtype=jnp.int32)
    hi = jnp.sum(alive & (ids >= cut), dtype=jnp.float32)
    lo = a - hi
    pairs = jnp.where(cut >= 0, lo * hi, 0.0)
    return a, pairs
