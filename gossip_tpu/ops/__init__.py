from gossip_tpu.ops.sampling import (  # noqa: F401
    node_keys,
    sample_peers,
    sample_peers_complete,
    sample_peers_table,
)
from gossip_tpu.ops.propagate import (  # noqa: F401
    flood_gather,
    pull_merge,
    push_counts,
    push_delta,
)
