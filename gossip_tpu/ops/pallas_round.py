"""Fully-fused Pallas TPU pull-gossip round: PRNG + gather + OR in one kernel.

Round 1 measured the XLA hot path honestly: at N=10M the per-round cost is
ONE uint32 gather at ~8 ns/element (HBM random access, latency-bound), so a
27-round pull run is pinned at ~2.28 s no matter how the surrounding ops
fuse (bench.py, ops/pallas_sampling.py).  This module removes the HBM
gather entirely: for a single rumor the whole 10M-node infection bitmap is
1.25 MB packed along the NODE dimension — it fits in VMEM with room to
spare, so one ``pallas_call`` can hold the entire cluster state on-chip and
do partner sampling (TPU hardware PRNG), digest gather, and OR-merge at VPU
rate with zero HBM traffic for the gather.

Layout
------
Node ``n`` lives at bit ``n & 31`` of word ``(n >> 5)``; words are stored
row-major in a ``uint32[R, 128]`` table (R rows of 128 lanes).  N is padded
up to ``R*128*32``; phantom nodes are masked to zero every round, so a pull
that lands on a phantom behaves exactly like a pull from an uninfected node.

Partner sampling (the TPU-shaped part)
--------------------------------------
Mosaic exposes per-element dynamic gather only *within* a 128-lane row
(``take_along_axis(axis=1)`` -> ``tpu.dynamic_gather``); cross-row
per-element gather does not exist.  So the kernel factors the partner draw
``(row t, lane m, bit c)`` into hardware-friendly stages:

1. **Per-lane row shifts.** Draw 128 iid shifts ``s_j ~ U[0, R)`` and build
   ``rot[i, j] = table[(i - s_j) mod R, j]`` with ceil(log2 R) conditional
   *static* ``pltpu.roll`` stages along the row axis (roll by ``2^k`` where
   bit k of ``s_j`` is set — a binary decomposition of the shift, selected
   per lane).
2. **Per-element lane choice.** For each destination bit-plane k, each
   destination word (i, j) draws ``m ~ U[0, 128)`` and lane-gathers
   ``rot[i, m]`` — i.e. the partner word is ``table[(i - s_m) mod R, m]``.
3. **Per-element bit choice.** Draw ``c ~ U[0, 32)`` and take bit ``c`` of
   the partner word as the pulled infection bit for plane k.

Distributional contract (stated honestly, tested in tests/test_pallas_round
.py): the partner of every destination node is EXACTLY uniform over the
padded node set — ``m`` is uniform over lanes, ``(i - s_m)`` is uniform
over rows given any ``m`` (each ``s_j`` is uniform and independent), and
``c`` is uniform over bits.  What differs from the iid threefry sampler
(ops/sampling.py) is the *joint*: destination nodes that pick the same lane
``m`` in the same round share that lane's row shift ``s_m`` (128 shifts per
round), and self-pulls are not excluded (probability 1/N, a no-op for SI).
Per-node marginals — the quantity that drives the mean-field coverage
recurrence c' = 1-(1-c)^2 — are identical, and the measured curves match
the threefry path round-for-round at bench scale (see tests).

This is the fused kernel VERDICT.md round 1 asked for ("sampling + gather +
OR in one pallas_call"); the reference hot path being batched is the
per-neighbor fan-out loop of /root/reference/main.go:72-88.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gossip_tpu.compat import (interpret_impl, pallas_compiler_params,
                               pallas_interpret_mode)

LANES = 128
BITS = 32
NODES_PER_ROW = LANES * BITS            # 4096 nodes per table row
_ROUND_MIX = 1000003                    # seed-mixing prime (ops/pallas_sampling)


def n_rows(n: int) -> int:
    """Rows (multiple of 8 for vreg alignment) covering n nodes."""
    r = -(-n // NODES_PER_ROW)
    return max(8, -(-r // 8) * 8)


def padded_n(n: int) -> int:
    return n_rows(n) * NODES_PER_ROW


def node_pack(infected: jax.Array) -> jax.Array:
    """bool[N] -> node-packed uint32[R, 128] table (phantoms zero)."""
    n = infected.shape[0]
    rows = n_rows(n)
    flat = jnp.zeros((rows * NODES_PER_ROW,), jnp.uint32)
    flat = flat.at[:n].set(infected.astype(jnp.uint32))
    words = flat.reshape(rows * LANES, BITS)
    weights = (jnp.uint32(1) << jnp.arange(BITS, dtype=jnp.uint32))
    packed = jnp.sum(words * weights[None, :], axis=1, dtype=jnp.uint32)
    return packed.reshape(rows, LANES)


def node_unpack(table: jax.Array, n: int) -> jax.Array:
    """node-packed uint32[R, 128] -> bool[n]."""
    flat_words = table.reshape(-1)
    shifts = jnp.arange(BITS, dtype=jnp.uint32)
    bits = (flat_words[:, None] >> shifts[None, :]) & jnp.uint32(1)
    return bits.reshape(-1)[:n].astype(bool)


def coverage_node_packed(table: jax.Array, n: int) -> jax.Array:
    """Infected fraction over the REAL n nodes (phantoms are kept zero)."""
    pop = jnp.sum(jax.lax.population_count(table), dtype=jnp.uint32)
    return pop.astype(jnp.float32) / jnp.float32(n)


# VMEM budget for the fused kernels: the live set is ~4 table-sized
# buffers (aliased in/out table, rot, the rolled temp, acc), kept under
# v5e's 128 MB with headroom for Mosaic's own temporaries.
_VMEM_LIMIT_BYTES = 110 * 1024 * 1024
TABLE_COPIES = 4


def _rotate_rows(table: jax.Array, sbits: jax.Array, rows: int) -> jax.Array:
    """Stage 1 of the partner draw (shared by both fused kernels):
    ``rot[i, j] = table[(i - s_j) mod rows, j]`` with per-lane shifts
    ``s_j = sbits[0, j] mod rows``, built from ceil(log2 rows) conditional
    *static* rolls — a binary decomposition of the shift, selected per
    lane.  (Modulo bias rows/2^32 < 1e-6: documented.)"""
    s = (sbits[0:1, :] % jnp.uint32(rows)).astype(jnp.int32)   # [1, 128]
    rot = table
    shift = 1
    while shift < rows:
        rolled = pltpu.roll(rot, shift, 0)
        take = (s & shift) != 0                                # [1, 128]
        rot = jnp.where(take, rolled, rot)
        shift <<= 1
    return rot


def _rotate_rows_xla(table: jax.Array, sbits: jax.Array,
                     rows: int) -> jax.Array:
    """:func:`_rotate_rows` as plain XLA (``jnp.roll`` in place of
    ``pltpu.roll`` — same function, bitwise).  Stage 1 of the staged
    big-table path and of the reference interpret lowering."""
    s = (sbits[0:1, :] % jnp.uint32(rows)).astype(jnp.int32)   # [1, 128]
    rot = table
    shift = 1
    while shift < rows:
        take = (s & shift) != 0
        rot = jnp.where(take, jnp.roll(rot, shift, axis=0), rot)
        shift <<= 1
    return rot


# interpret routing (compat.interpret_impl): True/'reference' -> the
# pure-JAX reference lowerings below, 'mosaic' -> the real Mosaic
# interpreter.  The reference path is why driver-level interpret runs
# (CPU tests, the multichip dry run) execute as ordinary jitted programs
# instead of paying a Python interpreter callback per pallas_call per
# plane per round — the 8-device dry run's fused families sat at
# ~360-460 ms steady for exactly that reason.
_interpret_impl = interpret_impl


def _phantom_word_keep(rows: int, n_valid_words: int, tail_mask: int):
    """uint32[rows, 128] keep-mask zeroing phantom words (and the tail
    word's phantom bits) — the reference twin of the kernels' inline
    phantom masking."""
    word_id = (jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 0) * LANES
               + jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1))
    full = word_id < (n_valid_words - (1 if tail_mask else 0))
    keep = jnp.where(full, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    if tail_mask:
        keep = jnp.where(word_id == n_valid_words - 1,
                         jnp.uint32(tail_mask), keep)
    return keep


def _fused_round_ref(table, n: int, fanout: int, inject_bits,
                     drop_threshold, alive_table,
                     plane_sharing: int, cut_bits=None) -> jax.Array:
    """Pure-JAX reference of :func:`_fused_round_kernel` (single-rumor,
    node-packed).  Bitwise-equal to the Mosaic interpreter on the same
    operands (tests/test_pallas_round.py); hardware-PRNG draws reproduce
    the interpreter's off-TPU stub (zeros).  ``drop_threshold`` is a
    plain traced scalar here — the reference twin of the real path's
    SMEM operand, bitwise-pinned against it like every fused twin."""
    rows = table.shape[0]
    inject = inject_bits is not None
    if inject:
        sbits = jnp.asarray(inject_bits[0], jnp.uint32)
        rbits = jnp.asarray(inject_bits[1], jnp.uint32)
    else:
        sbits = jnp.zeros((8, LANES), jnp.uint32)
    src = table & alive_table if alive_table is not None else table
    rot = _rotate_rows_xla(src, sbits, rows)
    rot_cut = (_rotate_rows_xla(cut_bits, sbits, rows)
               if cut_bits is not None else None)
    thr = jnp.asarray(drop_threshold, jnp.int32).astype(jnp.uint32)

    acc = table
    for k in range(0, BITS, plane_sharing):
        for f in range(fanout):
            rb = (rbits[(k // plane_sharing) * fanout + f] if inject
                  else jnp.zeros((rows, LANES), jnp.uint32))
            for j in range(plane_sharing):
                sh = jnp.uint32(12 * j)
                m = ((rb >> sh) & jnp.uint32(LANES - 1)).astype(jnp.int32)
                c = (rb >> (sh + jnp.uint32(7))) & jnp.uint32(BITS - 1)
                partner = jnp.take_along_axis(rot, m, axis=1)
                bit = (partner >> c) & jnp.uint32(1)
                keep = (rb >> jnp.uint32(12)) >= thr
                bit = jnp.where(keep, bit, jnp.uint32(0))
                if cut_bits is not None:
                    pside = (jnp.take_along_axis(rot_cut, m, axis=1)
                             >> c) & jnp.uint32(1)
                    dside = (cut_bits >> jnp.uint32(k + j)) & jnp.uint32(1)
                    bit = jnp.where(pside == dside, bit, jnp.uint32(0))
                if alive_table is not None:
                    bit = bit & ((alive_table >> jnp.uint32(k + j))
                                 & jnp.uint32(1))
                acc = acc | (bit << jnp.uint32(k + j))

    n_valid_words = -(-n // BITS)
    tail = n % BITS
    tail_mask = ((1 << tail) - 1) if tail else 0
    return acc & _phantom_word_keep(rows, n_valid_words, tail_mask)


def _fused_mr_round_ref(table, n: int, fanout: int, inject_bits,
                        drop_threshold, alive_words,
                        cut_words=None) -> jax.Array:
    """Pure-JAX reference of :func:`_fused_mr_kernel` (multi-rumor,
    one-word-per-node).  Same contract as :func:`_fused_round_ref`."""
    rows = table.shape[0]
    inject = inject_bits is not None
    if inject:
        sbits_all = jnp.asarray(inject_bits[0], jnp.uint32)
        rbits_all = jnp.asarray(inject_bits[1], jnp.uint32)
    src = table & alive_words if alive_words is not None else table
    thr = jnp.asarray(drop_threshold, jnp.int32).astype(jnp.uint32)

    acc = table
    for f in range(fanout):
        sbits = (sbits_all[f] if inject
                 else jnp.zeros((8, LANES), jnp.uint32))
        rot = _rotate_rows_xla(src, sbits, rows)
        rb = (rbits_all[f] if inject
              else jnp.zeros((rows, LANES), jnp.uint32))
        m = (rb & jnp.uint32(LANES - 1)).astype(jnp.int32)
        partner = jnp.take_along_axis(rot, m, axis=1)
        keep = (rb >> jnp.uint32(12)) >= thr
        partner = jnp.where(keep, partner, jnp.uint32(0))
        if cut_words is not None:
            rot_cut = _rotate_rows_xla(cut_words, sbits, rows)
            pside = jnp.take_along_axis(rot_cut, m, axis=1)
            partner = jnp.where(pside == cut_words, partner,
                                jnp.uint32(0))
        if alive_words is not None:
            partner = partner & alive_words
        acc = acc | partner

    node_id = (jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 0) * LANES
               + jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1))
    return jnp.where(node_id < n, acc, jnp.uint32(0))


def _fused_call(kernel, rows: int, seed, round_, table, inject_bits,
                interpret: bool, round_salt: int = 0, alive_table=None,
                drop_threshold=0, cut_words=None):
    """Shared pallas_call plumbing for the fused kernels: SMEM seed pair,
    the SMEM fault scalar (the 20-bit drop threshold as a scalar-prefetch
    operand — a traced RUNTIME value since the operand PR, so a fault
    sweep over drop rates/ramps re-enters one executable), VMEM table
    aliased into the output, optional injected-bits operands, optional
    alive-bitmap operand, optional partition side-mask operand (fault
    masks — after the inject pair, matching the kernels' ``rest``
    unpack order).

    Donation contract: the whole-table value kernels ALWAYS declare the
    ``{2: 0}`` table->output alias.  It is safe because nothing after
    this call reads the pre-round table — the entry points consume their
    table operand exactly once, and the jit wrappers never donate the
    caller's own buffers — and it is what lets the compiled
    while_loop/scan drivers update the table in place every round
    (pallas_call lowers to a custom call; without the declared alias XLA
    cannot reuse the buffer and copies the full table per round).  The
    staged big-table path has a subtler per-draw rule — see the
    donation-contract comment in :func:`_fused_mr_round_big`."""
    seeds = jnp.stack([jnp.asarray(seed, jnp.int32) * jnp.int32(_ROUND_MIX),
                       jnp.asarray(round_, jnp.int32)
                       ^ jnp.int32(round_salt)])
    fault = jnp.asarray(drop_threshold, jnp.int32).reshape((1,))
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM)]
    operands = [seeds, fault, table]
    if inject_bits is not None:
        sbits, rbits = inject_bits
        in_specs += [pl.BlockSpec(memory_space=pltpu.VMEM),
                     pl.BlockSpec(memory_space=pltpu.VMEM)]
        operands += [jnp.asarray(sbits, jnp.uint32),
                     jnp.asarray(rbits, jnp.uint32)]
    if alive_table is not None:
        in_specs += [pl.BlockSpec(memory_space=pltpu.VMEM)]
        operands += [jnp.asarray(alive_table, jnp.uint32)]
    if cut_words is not None:
        in_specs += [pl.BlockSpec(memory_space=pltpu.VMEM)]
        operands += [jnp.asarray(cut_words, jnp.uint32)]
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.uint32),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        input_output_aliases={2: 0},
        compiler_params=None if interpret else pallas_compiler_params(
            vmem_limit_bytes=_VMEM_LIMIT_BYTES),
        interpret=pallas_interpret_mode(interpret),
    )(*operands)


def _fused_round_kernel(seed_ref, fault_ref, tin_ref, *rest, rows: int,
                        fanout: int, n_valid_words: int, tail_mask: int,
                        inject: bool, has_alive: bool = False,
                        plane_sharing: int = 1, has_cut: bool = False):
    """One pull round, entirely in VMEM.  See module doc for the scheme.

    ``inject=True`` replaces the hardware PRNG with caller-supplied bit
    arrays (extra operands) so the kernel *math* — rolls, gather, bit
    planes, masking — is unit-testable on CPU, where the Mosaic
    interpreter stubs ``prng_random_bits`` with zeros (tests/test_pallas.py
    round-1 finding).  The TPU path draws the same shapes from the hw PRNG.

    Fault operands (static SI semantics round 4, runtime operands since
    the operand PR): ``has_alive`` adds an alive-bitmap operand
    (node-packed like the table) — dead nodes SERVE nothing (their bits
    are cleared from the rotation source) and ACQUIRE nothing (plane
    contributions masked by the destination's alive bit); their own
    initial bits stay put, like the XLA path's dark nodes.  The 20-bit
    drop threshold (round(drop_prob * 2^20)) rides ``fault_ref`` — an
    SMEM SCALAR, not a compile-time constant — and drops an individual
    pull when the free bits 12..31 of its draw fall below it; bits 0..6
    are the lane and 7..11 the bit choice, so the drop coin is
    independent of the partner choice.  The compare always runs
    (threshold 0 keeps every pull — bitwise the old elided lowering),
    which is what lets drop-rate RAMPS move the threshold per round
    with zero recompiles.  ``has_cut`` adds the partition SIDE mask
    (render_cut_bits: bit b of word w is 1 iff node 32w+b sits at or
    above the cut; -1 renders every real node on one side — inert):
    the mask rotates through the SAME per-lane shifts as the table, so
    the partner's side comes out of one extra in-row gather, and a
    pull is kept only when both endpoints share a side — the
    lost-for-this-round-only semantics of ops/nemesis.same_side."""
    if inject:
        if has_alive and has_cut:
            sbits_ref, rbits_ref, alive_ref, cut_ref, tout_ref = rest
        elif has_alive:
            sbits_ref, rbits_ref, alive_ref, tout_ref = rest
        elif has_cut:
            sbits_ref, rbits_ref, cut_ref, tout_ref = rest
        else:
            sbits_ref, rbits_ref, tout_ref = rest
    else:
        if has_alive and has_cut:
            alive_ref, cut_ref, tout_ref = rest
        elif has_alive:
            alive_ref, tout_ref = rest
        elif has_cut:
            cut_ref, tout_ref = rest
        else:
            (tout_ref,) = rest
        pltpu.prng_seed(seed_ref[0], seed_ref[1])
    table = tin_ref[:]
    alive = alive_ref[:] if has_alive else None
    cut_tab = cut_ref[:] if has_cut else None
    thr = fault_ref[0].astype(jnp.uint32)

    # Stage 1: one shared rotation per round (all bit planes and fanout
    # draws reuse it; the MR kernel rotates per fanout draw instead).
    # Dead nodes serve nothing: cleared from the rotation SOURCE only —
    # their own accumulated bits are untouched.  The partition side
    # mask rides the same rotation so the partner's side is one more
    # in-row gather.
    if inject:
        sbits = sbits_ref[:]
    else:
        sbits = pltpu.bitcast(pltpu.prng_random_bits((8, LANES)), jnp.uint32)
    rot = _rotate_rows(table & alive if has_alive else table, sbits, rows)
    rot_cut = _rotate_rows(cut_tab, sbits, rows) if has_cut else None

    # Stages 2+3: per destination bit-plane k, draw (lane m, bit c) per
    # word, gather the partner word in-row, pull bit c into plane k.
    # ``plane_sharing=2`` (round-5 opt-in — the roofline's PRNG-harvest
    # candidate): a PAIR of adjacent planes splits one 32-bit draw —
    # plane j of the pair uses bits 12j..12j+6 (lane) and 12j+7..12j+11
    # (bit choice), disjoint bits of one uniform word, so per-node
    # partner marginals stay exactly uniform while the PRNG word count
    # halves.  A DIFFERENT stream from sharing=1 (engine-level
    # statistical contract, like fused-vs-threefry); incompatible with
    # the drop coin (which owns bits 12..31 at sharing=1), enforced by
    # the caller.
    acc = table
    for k in range(0, BITS, plane_sharing):
        for f in range(fanout):
            if inject:
                rb = rbits_ref[(k // plane_sharing) * fanout + f]
            else:
                rb = pltpu.bitcast(pltpu.prng_random_bits((rows, LANES)),
                                   jnp.uint32)
            for j in range(plane_sharing):
                sh = jnp.uint32(12 * j)
                m = ((rb >> sh) & jnp.uint32(LANES - 1)).astype(jnp.int32)
                c = (rb >> (sh + jnp.uint32(7))) & jnp.uint32(BITS - 1)
                partner = jnp.take_along_axis(rot, m, axis=1)
                bit = (partner >> c) & jnp.uint32(1)
                keep = (rb >> jnp.uint32(12)) >= thr
                bit = jnp.where(keep, bit, jnp.uint32(0))
                if has_cut:
                    pside = (jnp.take_along_axis(rot_cut, m, axis=1)
                             >> c) & jnp.uint32(1)
                    dside = (cut_tab >> jnp.uint32(k + j)) & jnp.uint32(1)
                    bit = jnp.where(pside == dside, bit, jnp.uint32(0))
                if has_alive:
                    bit = bit & ((alive >> jnp.uint32(k + j))
                                 & jnp.uint32(1))
                acc = acc | (bit << jnp.uint32(k + j))

    # Zero phantom words so phantom nodes never read as infected.
    word_id = (jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 0) * LANES
               + jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1))
    full = word_id < (n_valid_words - (1 if tail_mask else 0))
    keep = jnp.where(full, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    if tail_mask:
        keep = jnp.where(word_id == n_valid_words - 1,
                         jnp.uint32(tail_mask), keep)
    tout_ref[:] = acc & keep


@functools.partial(jax.jit,
                   static_argnames=("n", "fanout", "interpret",
                                    "plane_sharing"))
def _fused_pull_round_jit(table, seed, round_, drop_threshold, n: int,
                          fanout: int, interpret, inject_bits,
                          alive_table, plane_sharing: int,
                          cut_words) -> jax.Array:
    if _interpret_impl(interpret) == "reference":
        return _fused_round_ref(table, n, fanout, inject_bits,
                                drop_threshold, alive_table,
                                plane_sharing, cut_words)
    rows = table.shape[0]
    n_valid_words = -(-n // BITS)
    tail = n % BITS
    tail_mask = ((1 << tail) - 1) if tail else 0
    kernel = functools.partial(
        _fused_round_kernel, rows=rows, fanout=fanout,
        n_valid_words=n_valid_words, tail_mask=tail_mask,
        inject=inject_bits is not None,
        has_alive=alive_table is not None,
        plane_sharing=plane_sharing,
        has_cut=cut_words is not None)
    return _fused_call(kernel, rows, seed, round_, table, inject_bits,
                       interpret, alive_table=alive_table,
                       drop_threshold=drop_threshold, cut_words=cut_words)


def fused_pull_round(table: jax.Array, seed: jax.Array, round_: jax.Array,
                     n: int, fanout: int = 1, interpret: bool = False,
                     inject_bits=None, drop_threshold=0,
                     alive_table=None, plane_sharing: int = 1,
                     cut_words=None) -> jax.Array:
    """Apply one fused pull round to a node-packed table. Pure; jittable.

    ``inject_bits`` (tests only): a ``(sbits uint32[8,128], rbits
    uint32[fanout*32//plane_sharing, rows, 128])`` pair replacing the
    hardware PRNG — see _fused_round_kernel.  ``drop_threshold`` is a
    RUNTIME operand since the operand PR (an SMEM scalar on the real
    path, a traced scalar in the reference lowering) — pass the 20-bit
    int OR a traced per-round value from a nemesis drop table;
    ``alive_table`` is the node-packed alive bitmap and ``cut_words``
    the partition side mask (:func:`render_cut_bits`); all default off
    and leave the fault-free trajectory bitwise unchanged.
    ``plane_sharing=2`` halves the PRNG words per round by splitting one
    draw's disjoint bit-fields across an adjacent plane pair — an
    OPT-IN different stream (kernel docstring); requires no drop coin
    and no partition (their bits/side gathers overlap the pair split).

    ``interpret`` may be a bool or an impl name: ``True``/'reference'
    is the pure-JAX reference lowering (fast, compiled by XLA — the
    driver-test and dry-run path), 'mosaic' the real Mosaic interpreter
    (kernel-body tests; see :func:`_interpret_impl`).
    """
    if plane_sharing not in (1, 2):
        raise ValueError(f"plane_sharing must be 1 or 2, "
                         f"got {plane_sharing}")
    # plane sharing requires a provably-ZERO drop coin: a traced
    # threshold cannot be proven zero at trace time, so it is rejected
    # outright — silently correlated drops (the coin bits overlap the
    # pair split) would be worse than the refusal
    concrete_zero = (isinstance(drop_threshold, (int, float))
                     and not drop_threshold)
    if plane_sharing > 1 and (not concrete_zero or cut_words is not None):
        raise ValueError(
            "plane_sharing=2 splits the draw's bit-fields across a "
            "plane pair and leaves no room for the 20-bit drop coin "
            "(concrete or traced) or the partition side gather; use "
            "plane_sharing=1 with drop_prob/partition faults")
    return _fused_pull_round_jit(table, seed, round_,
                                 jnp.asarray(drop_threshold, jnp.int32),
                                 n, fanout, interpret, inject_bits,
                                 alive_table, plane_sharing, cut_words)


# ---------------------------------------------------------------------------
# Multi-rumor variant: one VMEM element = one node's 32-rumor digest word.
# ---------------------------------------------------------------------------
#
# The factored partner draw above works on ANY [rows, 128] uint32 table; for
# up to 32 rumors the element at (row i, lane j) holds node ``i*128 + j``'s
# rumor word (models/si_packed layout, one word per node).  A pull is then
# ONE in-row gather of the partner's whole word OR-ed into the destination —
# no bit-plane loop at all, because a real pull exchanges the full digest
# (one partner per node per round, all rumors ride the same exchange,
# exactly models/si.py's semantics).  At 10M nodes the table is 40 MB —
# VMEM-resident on v5e.  Same distributional contract as the single-rumor
# kernel: partner uniform over the padded node set, 128 shared per-lane row
# shifts per (round, fanout) draw, self-pulls not excluded (1/N no-op).

def mr_rows(n: int) -> int:
    """Rows (multiple of 8) covering n nodes at one word per node."""
    r = -(-n // LANES)
    return max(8, -(-r // 8) * 8)


def word_pack(seen: jax.Array) -> jax.Array:
    """bool[N, R<=32] -> uint32[mr_rows(N), 128] one-word-per-node table."""
    n, r = seen.shape
    if r > BITS:
        raise ValueError(f"multirumor fused kernel holds <= {BITS} rumors "
                         f"per word; got {r}")
    weights = (jnp.uint32(1) << jnp.arange(r, dtype=jnp.uint32))
    words = jnp.sum(seen.astype(jnp.uint32) * weights[None, :], axis=1,
                    dtype=jnp.uint32)
    rows = mr_rows(n)
    flat = jnp.zeros((rows * LANES,), jnp.uint32).at[:n].set(words)
    return flat.reshape(rows, LANES)


def word_unpack(table: jax.Array, n: int, rumors: int) -> jax.Array:
    """uint32[rows, 128] -> bool[n, rumors]."""
    flat = table.reshape(-1)[:n]
    shifts = jnp.arange(rumors, dtype=jnp.uint32)
    return ((flat[:, None] >> shifts[None, :]) & jnp.uint32(1)).astype(bool)


def coverage_words(table: jax.Array, n: int, rumors: int) -> jax.Array:
    """Min-over-rumors infected fraction (phantom words stay zero)."""
    shifts = jnp.arange(rumors, dtype=jnp.uint32)
    per_rumor = jnp.sum(
        ((table.reshape(-1)[:, None] >> shifts[None, :]) & jnp.uint32(1)
         ).astype(jnp.float32), axis=0)
    return jnp.min(per_rumor) / jnp.float32(n)


def _fused_mr_kernel(seed_ref, fault_ref, tin_ref, *rest, rows: int,
                     fanout: int, n: int, inject: bool,
                     has_alive: bool = False, has_cut: bool = False):
    """One multi-rumor pull round, table fully VMEM-resident.

    Fault operands (round 4's static masks, runtime operands since the
    operand PR; same contract as _fused_round_kernel, adapted to the
    one-word-per-NODE layout): the alive operand holds 0xFFFFFFFF for
    alive nodes and 0 for dead ones — dead nodes serve nothing
    (cleared from the rotation source) and acquire nothing (the
    gathered partner word is AND-masked), while their own word stays
    put.  The 20-bit drop threshold rides the ``fault_ref`` SMEM
    scalar and drops a whole pull (all rumors ride one exchange) on
    bits 12..31 of its draw; the lane choice uses bits 0..6, so the
    coin is independent.  The compare always runs (threshold 0 keeps
    everything — bitwise the old elided lowering).  ``has_cut`` adds
    the partition side-word mask (render_cut_words: 0xFFFFFFFF at or
    above the cut): it rotates through the SAME per-lane shifts as the
    table per fanout draw, the partner's side is one extra in-row
    gather, and cross-side pulls are destroyed for this round only."""
    if inject:
        if has_alive and has_cut:
            sbits_ref, rbits_ref, alive_ref, cut_ref, tout_ref = rest
        elif has_alive:
            sbits_ref, rbits_ref, alive_ref, tout_ref = rest
        elif has_cut:
            sbits_ref, rbits_ref, cut_ref, tout_ref = rest
        else:
            sbits_ref, rbits_ref, tout_ref = rest
    else:
        if has_alive and has_cut:
            alive_ref, cut_ref, tout_ref = rest
        elif has_alive:
            alive_ref, tout_ref = rest
        elif has_cut:
            cut_ref, tout_ref = rest
        else:
            (tout_ref,) = rest
        pltpu.prng_seed(seed_ref[0], seed_ref[1])
    table = tin_ref[:]
    alive = alive_ref[:] if has_alive else None
    cut_w = cut_ref[:] if has_cut else None
    thr = fault_ref[0].astype(jnp.uint32)
    src = table & alive if has_alive else table

    acc = table
    for f in range(fanout):
        # fresh per-lane row shifts per fanout draw (128 iid shifts)
        if inject:
            sbits = sbits_ref[f]
        else:
            sbits = pltpu.bitcast(pltpu.prng_random_bits((8, LANES)),
                                  jnp.uint32)
        rot = _rotate_rows(src, sbits, rows)
        # per-element lane choice -> partner's whole rumor word
        if inject:
            rb = rbits_ref[f]
        else:
            rb = pltpu.bitcast(pltpu.prng_random_bits((rows, LANES)),
                               jnp.uint32)
        m = (rb & jnp.uint32(LANES - 1)).astype(jnp.int32)
        partner = jnp.take_along_axis(rot, m, axis=1)
        keep = (rb >> jnp.uint32(12)) >= thr
        partner = jnp.where(keep, partner, jnp.uint32(0))
        if has_cut:
            rot_cut = _rotate_rows(cut_w, sbits, rows)
            pside = jnp.take_along_axis(rot_cut, m, axis=1)
            partner = jnp.where(pside == cut_w, partner, jnp.uint32(0))
        if has_alive:
            partner = partner & alive
        acc = acc | partner

    # zero phantom words (node id >= n)
    node_id = (jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 0) * LANES
               + jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1))
    tout_ref[:] = jnp.where(node_id < n, acc, jnp.uint32(0))


# --- Big-table multi-rumor path: XLA rotation + grid-blocked gather -----
#
# The value kernel holds ~4 table-sized VMEM windows; at N=10M (38.15 MiB
# one-word-per-node table) that is an XLA-measured 152.7 MiB — OOM against
# the 128 MiB chip.  Attempts to squeeze the whole round into one
# whole-table kernel bottom out around 132-134 MiB (3 windows + register
# spill slots), so the big path splits the round on its natural seam
# instead:
#
#   * Stage 1 (XLA): the per-lane row rotation ``rot[i, j] =
#     table[(i - s_j) mod rows, j]`` as ceil(log2 rows) static
#     ``jnp.roll`` + lane-select stages.  Pure blocked data movement —
#     XLA streams it through HBM with no table-sized VMEM resident, at
#     HBM bandwidth (~17 stages x 2 x 38 MiB ≈ 1.3 GB ≈ 2 ms/round at
#     10M nodes).
#   * Stage 2 (Pallas, grid over row blocks): per-element lane choice +
#     in-row partner-word gather (``tpu.dynamic_gather`` — the part XLA
#     cannot do efficiently) + OR-merge + phantom masking, with
#     block-sized double-buffered windows (3 x 512 KiB).
#
# Peak VMEM is block-sized, so this path has NO upper bound on n.  The
# 128 per-lane shifts come from a threefry draw (tiny, XLA stage); the
# per-block gather bits come from the hardware PRNG seeded per block —
# the distributional contract (exactly uniform per-node partner
# marginals, 128 shared per-lane row shifts per round) is identical to
# the value kernel, and on injected bits the two are bitwise-equal
# (tests/test_pallas_round.py).

_MR_GATHER_BLOCK = 1024   # rows per grid step (512 KiB windows)


def _mr_gather_kernel(seed_ref, fault_ref, tin_ref, rot_ref, *rest, n: int,
                      block: int, inject: bool, has_alive: bool = False,
                      has_cut: bool = False):
    """Grid step: partner lane-gather from the pre-rotated table + OR.
    Fault operands as in _fused_mr_kernel — the rotation source is
    already serve-masked by the caller's XLA stage (which also rotated
    the partition side mask when ``has_cut``: sbits live in the XLA
    stage on this path, so the side rotation happens there and this
    kernel only lane-gathers the partner's side); this kernel applies
    the drop coin (the ``fault_ref`` SMEM scalar), the side compare,
    and the destination's acquire mask."""
    b = pl.program_id(0)
    if inject:
        if has_alive and has_cut:
            rbits_ref, alive_ref, rot_cut_ref, cut_ref, tout_ref = rest
        elif has_alive:
            rbits_ref, alive_ref, tout_ref = rest
        elif has_cut:
            rbits_ref, rot_cut_ref, cut_ref, tout_ref = rest
        else:
            rbits_ref, tout_ref = rest
        rb = rbits_ref[0]
    else:
        if has_alive and has_cut:
            alive_ref, rot_cut_ref, cut_ref, tout_ref = rest
        elif has_alive:
            alive_ref, tout_ref = rest
        elif has_cut:
            rot_cut_ref, cut_ref, tout_ref = rest
        else:
            (tout_ref,) = rest
        # per-block stream: fold the block id into the round seed word
        # (prng_set_seed_32 rejects a third traced operand)
        pltpu.prng_seed(seed_ref[0],
                        seed_ref[1] + b * jnp.int32(-1640531527))
        rb = pltpu.bitcast(pltpu.prng_random_bits((block, LANES)),
                           jnp.uint32)
    m = (rb & jnp.uint32(LANES - 1)).astype(jnp.int32)
    partner = jnp.take_along_axis(rot_ref[:], m, axis=1)
    keep = (rb >> jnp.uint32(12)) >= fault_ref[0].astype(jnp.uint32)
    partner = jnp.where(keep, partner, jnp.uint32(0))
    if has_cut:
        pside = jnp.take_along_axis(rot_cut_ref[:], m, axis=1)
        partner = jnp.where(pside == cut_ref[:], partner, jnp.uint32(0))
    if has_alive:
        partner = partner & alive_ref[:]
    node_id = ((jax.lax.broadcasted_iota(jnp.int32, (block, LANES), 0)
                + b * block) * LANES
               + jax.lax.broadcasted_iota(jnp.int32, (block, LANES), 1))
    tout_ref[:] = jnp.where(node_id < n, tin_ref[:] | partner,
                            jnp.uint32(0))


def _fused_mr_round_big(table: jax.Array, seed, round_, n: int,
                        interpret: bool, inject_bits,
                        drop_threshold=0,
                        alive_words=None, fanout: int = 1,
                        cut_words=None) -> jax.Array:
    """One multi-rumor pull round via the staged big-table path.
    Fault masks as in the value kernel: the serve mask is applied to the
    rotation SOURCE in the XLA stage, the drop coin and acquire mask in
    the grid kernel.

    ``fanout > 1`` (round 5, VERDICT r4 task 8) runs the two stages once
    per draw, OR-accumulating into the running table — the value
    kernel's per-fanout loop unrolled at the stage level.  Every draw's
    rotation reads the PRE-round serve-masked table (matching the value
    kernel, whose rotation source is fixed while ``acc`` accumulates),
    and each draw gets its own shift/gather streams (draw 0's streams
    are byte-identical to the old fanout-1 lowering, so existing
    digests and fanout-1 trajectories are unchanged).  Cost is
    ~fanout x the fanout-1 HBM traffic — the natural price of more
    draws on a table too big for VMEM."""
    rows = table.shape[0]
    block = min(_MR_GATHER_BLOCK, rows)
    impl = _interpret_impl(interpret)

    if inject_bits is not None:
        sbits_all = jnp.asarray(inject_bits[0], jnp.uint32)  # [F, 8, 128]
        rbits_all = jnp.asarray(inject_bits[1], jnp.uint32)  # [F, rows, 128]
    else:
        base = jax.random.PRNGKey(
            jnp.uint32(jnp.asarray(seed, jnp.int32)) * jnp.uint32(_ROUND_MIX)
            + jnp.uint32(0x5D0))
        rkey = jax.random.fold_in(base, jnp.asarray(round_, jnp.int32))

    rows_pad = -(-rows // block) * block
    zpad = (jnp.zeros((rows_pad - rows, LANES), jnp.uint32)
            if rows_pad != rows else None)

    def _padded(x):
        return x if zpad is None else jnp.concatenate([x, zpad], axis=0)

    src = table if alive_words is None else table & alive_words
    alive_p = None if alive_words is None else _padded(alive_words)
    cut_p = None if cut_words is None else _padded(cut_words)
    thr = jnp.asarray(drop_threshold, jnp.int32)
    thr_u = thr.astype(jnp.uint32)
    # pad the accumulator ONCE and feed it back padded between draws
    # (the kernel zeroes pad rows in its output anyway); re-padding and
    # re-slicing per draw would add two full-table HBM copies per draw
    acc_p = _padded(table)
    for f in range(fanout):
        if inject_bits is not None:
            sbits = sbits_all[f]
        else:
            # draw 0 keeps the pre-round-5 stream byte-identical; later
            # draws fold the static draw index into the round key
            kf = rkey if f == 0 else jax.random.fold_in(rkey, f)
            sbits = jax.random.bits(kf, (8, LANES), jnp.uint32)

        # Stage 1 (XLA): per-lane row rotation, binary decomposition —
        # always from the PRE-round serve-masked table.  The partition
        # side mask rides the same shifts (the sbits live HERE on the
        # staged path, so the side rotation is an XLA stage too).
        rot = _padded(_rotate_rows_xla(src, sbits, rows))
        rot_cut_p = (None if cut_words is None
                     else _padded(_rotate_rows_xla(cut_words, sbits,
                                                   rows)))

        # Stage 2: lane choice + in-row gather + OR + mask.  Rows pad up
        # to a block multiple (pad rows are phantom nodes — the kernel
        # masks them to zero) so every grid step sees a full block.
        rbits = None
        if inject_bits is not None:
            rbits = rbits_all[f:f + 1]
            if zpad is not None:
                rbits = jnp.concatenate(
                    [rbits, jnp.zeros((1, rows_pad - rows, LANES),
                                      jnp.uint32)], axis=1)

        if impl == "reference":
            # whole-table jnp twin of the grid kernel (the per-block
            # split is pure blocking; with no inject the hw-PRNG draw is
            # the interpreter's off-TPU stub, zeros)
            rb = (rbits[0] if rbits is not None
                  else jnp.zeros((rows_pad, LANES), jnp.uint32))
            m = (rb & jnp.uint32(LANES - 1)).astype(jnp.int32)
            partner = jnp.take_along_axis(rot, m, axis=1)
            keep = (rb >> jnp.uint32(12)) >= thr_u
            partner = jnp.where(keep, partner, jnp.uint32(0))
            if cut_p is not None:
                pside = jnp.take_along_axis(rot_cut_p, m, axis=1)
                partner = jnp.where(pside == cut_p, partner,
                                    jnp.uint32(0))
            if alive_p is not None:
                partner = partner & alive_p
            node_id = (jax.lax.broadcasted_iota(
                jnp.int32, (rows_pad, LANES), 0) * LANES
                + jax.lax.broadcasted_iota(
                    jnp.int32, (rows_pad, LANES), 1))
            acc_p = jnp.where(node_id < n, acc_p | partner, jnp.uint32(0))
            continue

        # draw 0's per-block salt is the pre-round-5 constant; later
        # draws perturb seeds[1] with a static odd multiplier
        seeds = jnp.stack(
            [jnp.asarray(seed, jnp.int32) * jnp.int32(_ROUND_MIX),
             jnp.asarray(round_, jnp.int32)
             ^ jnp.int32(0x5D0 + 0x51ED * f)])
        in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM),
                    pl.BlockSpec(memory_space=pltpu.SMEM),
                    pl.BlockSpec((block, LANES), lambda i: (i, 0)),
                    pl.BlockSpec((block, LANES), lambda i: (i, 0))]
        operands = [seeds, thr.reshape((1,)), acc_p, rot]
        if rbits is not None:
            in_specs.append(pl.BlockSpec((1, block, LANES),
                                         lambda i: (0, i, 0)))
            operands.append(rbits)
        if alive_p is not None:
            in_specs.append(pl.BlockSpec((block, LANES), lambda i: (i, 0)))
            operands.append(alive_p)
        if cut_p is not None:
            in_specs += [pl.BlockSpec((block, LANES), lambda i: (i, 0)),
                         pl.BlockSpec((block, LANES), lambda i: (i, 0))]
            operands += [rot_cut_p, cut_p]
        kernel = functools.partial(_mr_gather_kernel, n=n, block=block,
                                   inject=inject_bits is not None,
                                   has_alive=alive_words is not None,
                                   has_cut=cut_words is not None)
        # Donation contract for the staged path's table operand (the
        # whole-table kernels' simpler rule is at _fused_call; operand
        # index 2 = the table, after the seed pair and the SMEM fault
        # scalar):
        #   * draws f >= 1 always alias {2: 0}: their table operand is
        #     the previous draw's output — dead after this call — so XLA
        #     reuses the buffer in place.
        #   * draw 0 aliases ONLY in a fanout-1 round.  With fanout > 1
        #     every later draw's stage-1 rotation still reads the same
        #     pre-round table buffer (``src``), so a declared draw-0
        #     alias makes XLA re-materialize that still-live buffer via
        #     copy-insertion — a hidden full-table HBM copy per round.
        #     Skipping the alias keeps the table live with no copy; only
        #     the fanout-1 round is in-place, which is the only case the
        #     hot while_loop drivers ever relied on.
        #   * never alias the CALLER's concrete array (block-aligned
        #     rows + eager invocation): donating it would invalidate the
        #     caller's buffer (ADVICE r2).
        eager_caller_buffer = (acc_p is table
                               and not isinstance(table, jax.core.Tracer))
        no_alias = eager_caller_buffer or (f == 0 and fanout > 1)
        acc_p = pl.pallas_call(
            kernel,
            grid=(rows_pad // block,),
            out_shape=jax.ShapeDtypeStruct((rows_pad, LANES), jnp.uint32),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((block, LANES), lambda i: (i, 0)),
            input_output_aliases={} if no_alias else {2: 0},
            interpret=pallas_interpret_mode(interpret),
        )(*operands)
    return acc_p[:rows] if rows_pad != rows else acc_p


def _mr_wants_big(table_bytes: int, fanout: int) -> bool:
    """True when the value kernel cannot fit in VMEM (TABLE_COPIES live
    table windows — the same bound check_fused_fits enforces, one
    constant so routing and eligibility can never drift).  The staged
    big-table path covers ANY fanout since round 5 (multi-pass
    accumulation, ~fanout x the HBM traffic — VERDICT r4 task 8);
    ``fanout`` stays in the signature so the routing contract keeps one
    arity across rounds."""
    del fanout
    return TABLE_COPIES * table_bytes > _VMEM_LIMIT_BYTES


def render_alive_words(alive: jax.Array, n: int) -> jax.Array:
    """bool[n] -> the fused engines' one-word-per-NODE [mr_rows(n), 128]
    mask (0xFFFFFFFF alive, 0 dead/phantom) — the ONE rendering of this
    geometry (ops/nemesis.fused_base_words shares it).  In-trace safe."""
    rows = mr_rows(n)
    flat = jnp.zeros((rows * LANES,), jnp.uint32).at[:n].set(
        jnp.where(alive, jnp.uint32(0xFFFFFFFF), jnp.uint32(0)))
    return flat.reshape(rows, LANES)


def render_cut_words(cut, n: int) -> jax.Array:
    """The per-round partition SIDE mask in the fused one-word-per-NODE
    geometry — rendered by the ONE :func:`render_alive_words` geometry
    (the alive-word trick extended to cut words): 0xFFFFFFFF for real
    nodes at or above the cut, 0 below (and for phantoms).  A closed
    window (``cut < 0``) renders every real node on one side, which is
    value-inert in the kernels' side compare — the compiled churn loops
    pass THIS mask every round so partition-free and partition-bearing
    scenarios share one executable.  In-trace safe (``cut`` traced)."""
    ids = jnp.arange(n, dtype=jnp.int32)
    return render_alive_words(ids >= jnp.asarray(cut, jnp.int32), n)


def render_cut_bits(cut, n: int) -> jax.Array:
    """:func:`render_cut_words`'s node-packed twin for the single-rumor
    kernel: bit ``b`` of word ``w`` is 1 iff node ``32w + b`` sits at
    or above the cut (phantom bits 0) — the :func:`node_pack` geometry.
    In-trace safe."""
    ids = jnp.arange(n, dtype=jnp.int32)
    return node_pack(ids >= jnp.asarray(cut, jnp.int32))


def fault_masks_word(fault, n: int, origin: int = 0):
    """(alive_words-or-None, drop_threshold) for the multi-rumor fused
    fault path: the one-word-per-NODE rendering of
    models/state.alive_mask — 0xFFFFFFFF for alive nodes, 0 for dead
    and phantom rows.  In-trace safe, like fault_masks_node_packed."""
    from gossip_tpu.models.state import alive_mask
    alive = alive_mask(fault, n, origin)
    alive_words = None if alive is None else render_alive_words(alive, n)
    return alive_words, drop_threshold_for(fault)


def coverage_words_alive(table: jax.Array, alive_words: jax.Array,
                         rumors: int) -> jax.Array:
    """Alive-weighted min-over-rumors fraction — the fault-run twin of
    :func:`coverage_words` (alive_words elements are 0xFFFFFFFF/0, so
    bit 0 counts alive nodes)."""
    masked = (table & alive_words).reshape(-1)
    n_alive = jnp.sum(alive_words.reshape(-1) & jnp.uint32(1),
                      dtype=jnp.uint32).astype(jnp.float32)
    shifts = jnp.arange(rumors, dtype=jnp.uint32)
    per_rumor = jnp.sum((masked[:, None] >> shifts[None, :])
                        & jnp.uint32(1), axis=0,
                        dtype=jnp.uint32).astype(jnp.float32) / n_alive
    return jnp.min(per_rumor)


def fused_mr_cov_fn(n: int, rumors: int, fault=None, origin: int = 0):
    """``table -> coverage`` for a multi-rumor fused run — the one place
    the alive-weighting choice lives (cf. fused_cov_fn)."""
    if fault is None or not fault.node_death_rate:
        return lambda t: coverage_words(t, n, rumors)

    def cov(t):
        alive_words, _ = fault_masks_word(fault, n, origin)
        return coverage_words_alive(t, alive_words, rumors)
    return cov


@functools.partial(jax.jit, static_argnames=("n", "fanout", "interpret"))
def _fused_mr_round_jit(table, seed, round_, drop_threshold, n: int,
                        fanout: int, interpret, inject_bits, alive_words,
                        cut_words) -> jax.Array:
    rows = table.shape[0]
    if _mr_wants_big(rows * LANES * 4, fanout):
        return _fused_mr_round_big(table, seed, round_, n, interpret,
                                   inject_bits,
                                   drop_threshold=drop_threshold,
                                   alive_words=alive_words, fanout=fanout,
                                   cut_words=cut_words)
    if _interpret_impl(interpret) == "reference":
        return _fused_mr_round_ref(table, n, fanout, inject_bits,
                                   drop_threshold, alive_words, cut_words)
    kernel = functools.partial(_fused_mr_kernel, rows=rows, fanout=fanout,
                               n=n, inject=inject_bits is not None,
                               has_alive=alive_words is not None,
                               has_cut=cut_words is not None)
    # round_salt: distinct hw-PRNG stream from the single-rumor kernel
    return _fused_call(kernel, rows, seed, round_, table, inject_bits,
                       interpret, round_salt=0x5D0,
                       alive_table=alive_words,
                       drop_threshold=drop_threshold, cut_words=cut_words)


def fused_multirumor_pull_round(table: jax.Array, seed: jax.Array,
                                round_: jax.Array, n: int, fanout: int = 1,
                                interpret: bool = False,
                                inject_bits=None, drop_threshold=0,
                                alive_words=None,
                                cut_words=None) -> jax.Array:
    """One fused pull round on a one-word-per-node table.  Pure; jittable.

    Tables whose 4-window working set exceeds the VMEM budget route to the
    staged big-table path (XLA rotation + grid-blocked gather; fanout > 1
    multi-pass accumulates, round 5) — same math, block-sized VMEM, no
    upper bound on n.

    ``inject_bits`` (tests only): ``(sbits uint32[fanout, 8, 128], rbits
    uint32[fanout, rows, 128])`` replacing the hardware PRNG so the kernel
    math runs under the CPU interpreter.  ``drop_threshold`` is a
    RUNTIME operand since the operand PR (int or traced per-round
    scalar from a nemesis drop table — SMEM on the real path, traced in
    the reference lowering); ``alive_words``/``cut_words`` are the
    alive mask (fault_masks_word) and partition side mask
    (:func:`render_cut_words`); defaults leave the fault-free
    trajectory bitwise unchanged on BOTH routes."""
    return _fused_mr_round_jit(table, seed, round_,
                               jnp.asarray(drop_threshold, jnp.int32),
                               n, fanout, interpret, inject_bits,
                               alive_words, cut_words)


def fused_table_bytes(n: int, rumors: int) -> int:
    """Size of the fused kernel's VMEM table for this (n, rumors)."""
    rows = n_rows(n) if rumors == 1 else mr_rows(n)
    return rows * LANES * 4


def check_fused_fits(n: int, rumors: int, fanout: int = 1) -> int:
    """Raise ValueError if no fused-kernel variant can fit this (n, rumors,
    fanout) in VMEM; return the table size in bytes.  Callers get a
    friendly error instead of an XLA VMEM-exhausted compile failure.

    Multi-rumor tables whose 4-window value-kernel working set is over
    budget still run via the staged big-table path at any fanout
    (block-sized VMEM — no upper bound on n; the flagship 10M-node x
    32-rumor case lands here; fanout > 1 multi-pass accumulates at
    ~fanout x the HBM traffic, round 5)."""
    tb = fused_table_bytes(n, rumors)
    if TABLE_COPIES * tb <= _VMEM_LIMIT_BYTES:
        return tb
    if rumors > 1 and _mr_wants_big(tb, fanout):
        return tb
    layout = "node-packed bitmap" if rumors == 1 else "one-word-per-node"
    raise ValueError(
        f"fused kernel working set (~{TABLE_COPIES} x "
        f"{tb / (1 << 20):.0f} MiB {layout} table) exceeds the VMEM "
        f"budget at n={n}, rumors={rumors}, fanout={fanout}; reduce "
        "n, use engine='auto' (HBM-resident XLA kernels), or shard the "
        "node dimension")


def init_multirumor_state(n: int, rumors: int, origin: int = 0):
    """FusedState whose table is the one-word-per-node layout; rumor r
    starts at node (origin + r) % n (models/state.init_state contract)."""
    if rumors > BITS:
        raise ValueError(f"multirumor fused kernel holds <= {BITS} rumors")
    seen = jnp.zeros((n, rumors), jnp.bool_)
    origins = (origin + jnp.arange(rumors)) % n
    seen = seen.at[origins, jnp.arange(rumors)].set(True)
    return FusedState(table=word_pack(seen), round=jnp.int32(0),
                      msgs=jnp.float32(0.0))


def compiled_curve_fused(n: int, seed: int, fanout: int = 1,
                         max_rounds: int = 128, origin: int = 0,
                         interpret: bool = False, fault=None):
    """(scan, init): fixed-length ``lax.scan`` over the fused
    single-rumor kernel recording per-round coverage — the curve twin of
    :func:`compiled_until_fused` (no early exit; rounds-to-target is
    derived from the curve by the caller).  Same kernel, same fault
    masks, same alive-weighted coverage chooser."""
    drop_threshold = drop_threshold_for(fault)
    has_alive = fault is not None and bool(fault.node_death_rate)
    cov = fused_cov_fn(n, fault, origin)

    @functools.partial(jax.jit, donate_argnums=0)
    def scan(st: FusedState):
        def body(s, _):
            alive_tab = (fault_masks_node_packed(fault, n, origin)[0]
                         if has_alive else None)
            tab = fused_pull_round(s.table, seed, s.round, n, fanout,
                                   interpret,
                                   drop_threshold=drop_threshold,
                                   alive_table=alive_tab)
            s2 = FusedState(table=tab, round=s.round + 1,
                            msgs=s.msgs + 2.0 * fanout * n)
            return s2, cov(s2.table)
        return jax.lax.scan(body, st, None, length=max_rounds)

    return scan, init_fused_state(n, origin)


def compiled_until_fused_multirumor(n: int, rumors: int, seed: int,
                                    fanout: int = 1,
                                    target_coverage: float = 0.99,
                                    max_rounds: int = 128, origin: int = 0,
                                    interpret: bool = False, fault=None):
    """(loop, init): compiled while_loop to min-over-rumors target coverage
    using the multi-rumor fused kernel (hw PRNG — distributionally equal to
    but a different stream from the threefry path).  ``fault`` enables
    the kernel's static fault masks; the cond switches to the
    alive-weighted coverage (fused_mr_cov_fn)."""
    target = jnp.float32(target_coverage)
    drop_threshold = drop_threshold_for(fault)
    has_alive = fault is not None and bool(fault.node_death_rate)
    cov = fused_mr_cov_fn(n, rumors, fault, origin)

    def step(st: FusedState) -> FusedState:
        # alive words rebuilt IN-TRACE (loop-invariant, hoisted): no
        # O(N) constant baked into the donated jit below
        alive_words = (fault_masks_word(fault, n, origin)[0]
                       if has_alive else None)
        tab = fused_multirumor_pull_round(st.table, seed, st.round, n,
                                          fanout, interpret,
                                          drop_threshold=drop_threshold,
                                          alive_words=alive_words)
        return FusedState(table=tab, round=st.round + 1,
                          msgs=st.msgs + 2.0 * fanout * n)

    @functools.partial(jax.jit, donate_argnums=0)
    def loop(st: FusedState) -> FusedState:
        def cond(s):
            return (cov(s.table) < target) & (s.round < max_rounds)
        return jax.lax.while_loop(cond, step, st)

    return loop, init_multirumor_state(n, rumors, origin)


def compiled_curve_fused_multirumor(n: int, rumors: int, seed: int,
                                    fanout: int = 1, max_rounds: int = 128,
                                    origin: int = 0,
                                    interpret: bool = False, fault=None):
    """(scan, init): the curve twin of
    :func:`compiled_until_fused_multirumor` — fixed-length scan
    recording per-round min-over-rumors coverage (alive-weighted under
    deaths)."""
    drop_threshold = drop_threshold_for(fault)
    has_alive = fault is not None and bool(fault.node_death_rate)
    cov = fused_mr_cov_fn(n, rumors, fault, origin)

    @functools.partial(jax.jit, donate_argnums=0)
    def scan(st: FusedState):
        def body(s, _):
            alive_words = (fault_masks_word(fault, n, origin)[0]
                           if has_alive else None)
            tab = fused_multirumor_pull_round(
                s.table, seed, s.round, n, fanout, interpret,
                drop_threshold=drop_threshold, alive_words=alive_words)
            s2 = FusedState(table=tab, round=s.round + 1,
                            msgs=s.msgs + 2.0 * fanout * n)
            return s2, cov(s2.table)
        return jax.lax.scan(body, st, None, length=max_rounds)

    return scan, init_multirumor_state(n, rumors, origin)


class FusedState(NamedTuple):
    table: jax.Array        # uint32[R, 128] node-packed infection bitmap
    round: jax.Array        # int32
    msgs: jax.Array         # float32 — request+digest accounting, si parity


def init_fused_state(n: int, origin: int = 0) -> FusedState:
    if not 0 <= origin < n:
        raise ValueError(f"origin {origin} out of range for n={n}")
    word = origin >> 5
    table = (jnp.zeros((n_rows(n), LANES), jnp.uint32)
             .at[word // LANES, word % LANES].set(
                 jnp.uint32(1) << jnp.uint32(origin & (BITS - 1))))
    return FusedState(table=table, round=jnp.int32(0),
                      msgs=jnp.float32(0.0))


def coverage_node_packed_alive(table: jax.Array, alive_table: jax.Array):
    """Alive-weighted infected fraction: the fault-run twin of
    :func:`coverage_node_packed` (dead nodes are unreachable, not
    uninformed — si.coverage's weighting).  ``alive_table`` is the
    node-packed alive bitmap; phantoms are zero in BOTH tables."""
    pop = jnp.sum(jax.lax.population_count(table & alive_table),
                  dtype=jnp.uint32)
    n_alive = jnp.sum(jax.lax.population_count(alive_table),
                      dtype=jnp.uint32)
    return pop.astype(jnp.float32) / n_alive.astype(jnp.float32)


def drop_threshold_for(fault) -> int:
    """The static 20-bit drop threshold alone (round(drop_prob * 2^20))
    — for drivers that need the Python int WITHOUT paying the O(n)
    alive-mask build the full fault_masks_* helpers do."""
    drop_prob = 0.0 if fault is None else fault.drop_prob
    return int(round(drop_prob * (1 << 20))) if drop_prob else 0


def fault_masks_node_packed(fault, n: int, origin: int = 0):
    """(alive_table-or-None, drop_threshold) for the fused fault path —
    the node-packed rendering of models/state.alive_mask (static SI
    fault semantics: node_death_rate draws a static dead set, origin
    pinned alive; drop_prob drops individual pulls).  The 20-bit
    threshold quantizes drop_prob to 1/2^20 (< 1e-6), documented like
    the rotation's modulo bias.  Safe to call IN-TRACE: the bitmap is
    pure jnp from the fault config, so jitted callers rebuild it
    loop-invariantly (XLA hoists it) instead of closing over an O(N)
    inline constant — the bind_tables rule."""
    from gossip_tpu.models.state import alive_mask
    alive = alive_mask(fault, n, origin)
    alive_table = None if alive is None else node_pack(alive)
    return alive_table, drop_threshold_for(fault)


def fused_cov_fn(n: int, fault=None, origin: int = 0):
    """``table -> coverage`` for a fused run: alive-weighted exactly when
    the fault draws deaths.  The ONE place the weighting choice lives —
    the while-loop cond and the driver's final report both use it, so
    they can never disagree.  In-trace callers rebuild the alive bitmap
    per call (hoisted); eager callers pay one small draw."""
    if fault is None or not fault.node_death_rate:
        return lambda t: coverage_node_packed(t, n)

    def cov(t):
        alive_tab, _ = fault_masks_node_packed(fault, n, origin)
        return coverage_node_packed_alive(t, alive_tab)
    return cov


def compiled_until_fused(n: int, seed: int, fanout: int = 1,
                         target_coverage: float = 0.99,
                         max_rounds: int = 128, origin: int = 0,
                         interpret: bool = False, fault=None):
    """(loop, init): compiled while_loop to target coverage, fused kernel.

    Same contract as models/si_packed.compiled_until_packed: every node
    issues `fanout` pull requests per round, each answered by one digest
    (msgs += 2*fanout*N per round — phantom/self pulls are counted as real
    requests, matching the threefry path's accounting of dropped pulls;
    dropped and dead-partner pulls likewise).  ``fault`` (round 4)
    enables the kernel's static fault masks; the loop's target compare
    switches to the alive-weighted coverage (fused_cov_fn).
    """
    target = jnp.float32(target_coverage)
    drop_threshold = drop_threshold_for(fault)
    has_alive = fault is not None and bool(fault.node_death_rate)
    cov = fused_cov_fn(n, fault, origin)

    def step(st: FusedState) -> FusedState:
        # alive bitmap rebuilt IN-TRACE (loop-invariant, hoisted): no
        # O(N) constant baked into the donated jit below
        alive_tab = (fault_masks_node_packed(fault, n, origin)[0]
                     if has_alive else None)
        tab = fused_pull_round(st.table, seed, st.round, n, fanout,
                               interpret, drop_threshold=drop_threshold,
                               alive_table=alive_tab)
        return FusedState(table=tab, round=st.round + 1,
                          msgs=st.msgs + 2.0 * fanout * n)

    @functools.partial(jax.jit, donate_argnums=0)
    def loop(st: FusedState) -> FusedState:
        def cond(s):
            return (cov(s.table) < target) & (s.round < max_rounds)
        return jax.lax.while_loop(cond, step, st)

    return loop, init_fused_state(n, origin)
