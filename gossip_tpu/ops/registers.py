"""Last-writer-wins registers: totally-available transaction payloads
on the gossip fabric.

The rung above the Gossip Glomers ladder (ROADMAP item 4): Maelstrom's
``txn-rw-register`` workload — multi-key read/write transactions over
replicated registers — batched into array form.  Where the
counter/set/log payloads (ops/crdt, ops/logs) demand eventual
agreement on a *monotone* value, registers are overwritten: the merge
must pick a WINNER, and the winner must be the same on every replica
no matter the gossip order — which is exactly a lattice join on the
pair ``(timestamp, value)`` ordered lexicographically.

Array form (one row per node, the ops/crdt layout discipline): K
registers flatten to one ``int32[N, 2K]`` row —

  * columns ``0 .. K-1`` — the **value planes**: column k holds the
    currently-winning value of register k (0 = never written;
    TxnConfig requires values >= 1);
  * columns ``K .. 2K-1`` — the **timestamp planes**: column K+k holds
    the winning write's timestamp, the lexicographic ``(round, owner)``
    key packed into one int32 by :func:`pack_ts`
    (``round * n + owner + 1``; 0 = never written).  Packing makes the
    total order ONE integer compare, so the tie-break at equal rounds
    is the owner order by construction — deterministic, test-pinned.

:func:`merge_lww` is the per-key join: take the larger timestamp and
its value.  Because every applied write carries a UNIQUE timestamp
(TxnConfig rejects duplicate ``(key, round, node)`` writes), the pair
order is total on real trajectories; on arbitrary states the
equal-timestamp case resolves to ``max(value)`` so the join stays
commutative, associative, and idempotent unconditionally — the algebra
pins in tests/test_txn.py hold bitwise on random states, not just
reachable ones.

Transactions as programs over rounds
------------------------------------
A transaction's write micro-ops lower to padded runtime operands on
the step's ``tables`` tail (:func:`inject_args` — the nemesis/CRDT/log
pattern: compiled loops carry shapes, never content).  The default
program is a SKEWED traffic generator built by closed forms over the
TxnConfig scalars (:func:`txn_writes`): zipfian key popularity,
optional hot-key storms, uniform or diurnal load curves — no RNG, no
O(T) config object, so a scenario sweep across skews re-enters one
executable per padded arity bucket.

Ground truth and the txn-convergence metric
-------------------------------------------
A write is **applied** iff its owner is alive at the write round AND
eventually alive under the fault program — the acked-adds rule shared
with ops/crdt/ops/logs through the same ``_applied_mask`` /
``alive_at_fn`` predicates, so a node destined for permanent death
wins nothing.  :func:`ground_truth` picks each key's max-timestamp
applied write IN-TRACE from the same operands as the in-loop
injection (target and trajectory cannot drift), and convergence is
judged integer-exact: ``ops/crdt.converged_count`` full-row equality
(value AND timestamp planes — a node holding the right value under
the wrong timestamp could still lose it to a later merge), divided
ONCE on the host.  ``txn_conv`` is the RoundMetrics column.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from gossip_tpu.config import TxnConfig
# ONE definition each for the padding bucket, the no-injection round
# sentinel, and the shared liveness predicates (ops/crdt): the txn,
# log, and CRDT injection lowerings must agree on all of them by
# construction.
from gossip_tpu.ops.crdt import (NO_ROUND, _applied_mask, _pad_pow2,
                                 alive_at_fn, converged_count,
                                 eventual_alive_crdt, value_conv_frac)

__all__ = ["N_INJECT_OPERANDS", "alive_at_fn", "byz_conv_frac",
           "byz_converged_count", "converged_count",
           "eventual_alive_crdt", "ground_truth", "honest_key_mask",
           "inject_args", "inject_rows", "merge_lww", "pack_ts",
           "payload_count", "pull_merge_reg", "pull_merge_reg_byz",
           "split_inject", "state_width", "truth_summary",
           "txn_writes", "value_conv_frac"]

# Trailing step arguments the write program occupies on a factory's
# ``tables`` tuple: (w_node, w_key, w_round, w_val), each padded
# int32[A].
N_INJECT_OPERANDS = 4


def state_width(cfg: TxnConfig) -> int:
    """2K: value planes then timestamp planes (module doc)."""
    return 2 * cfg.keys


def check_ts_packable(cfg: TxnConfig, n: int) -> None:
    """The packed timestamp ``round * n + owner + 1`` must fit int32 —
    reject the overflow loudly instead of silently wrapping the total
    order (which would fork LWW winners between replicas)."""
    last = cfg.horizon() - 1
    if (last + 1) * n + 1 > 2 ** 31 - 1:
        raise ValueError(
            f"packed (round, owner) timestamp overflows int32 at "
            f"round {last} with n={n} (needs (round+1)*n+1 < 2^31); "
            "shrink the write program's horizon or n")


def pack_ts(rounds: jax.Array, owners: jax.Array, n: int) -> jax.Array:
    """int32 lexicographic ``(round, owner)`` key: ``round * n + owner
    + 1`` (0 = never written, so zeros are the merge identity).  The
    ONE packing, shared by the in-loop injection and the ground truth;
    padding rows carry NO_ROUND and map to 0 here (never a winner)."""
    rounds = jnp.asarray(rounds, jnp.int32)
    owners = jnp.asarray(owners, jnp.int32)
    real = rounds < NO_ROUND
    rc = jnp.where(real, rounds, 0)
    return jnp.where(real, rc * n + owners + 1, 0)


# -- the LWW join ------------------------------------------------------

def merge_lww(a: jax.Array, b: jax.Array) -> jax.Array:
    """Per-key last-writer-wins join of two ``[..., 2K]`` rows: the
    larger timestamp wins and brings its value.  At equal timestamps
    the values are equal on any reachable trajectory (timestamps are
    unique per applied write — TxnConfig); on arbitrary states the tie
    resolves to ``max(value)`` so the join is a total lattice join —
    commutative, associative, idempotent, an upper bound — pinned
    bitwise in tests/test_txn.py."""
    k = a.shape[-1] // 2
    va, ta = a[..., :k], a[..., k:]
    vb, tb = b[..., :k], b[..., k:]
    v = jnp.where(ta > tb, va,
                  jnp.where(tb > ta, vb, jnp.maximum(va, vb)))
    return jnp.concatenate([v, jnp.maximum(ta, tb)], axis=-1)


def pull_merge_reg(rows_all: jax.Array, partners: jax.Array,
                   sentinel: int) -> jax.Array:
    """LWW merge of k sampled peers' register rows -> ``[N_local, 2K]``
    — the ops/logs.pull_merge_log shape with :func:`merge_lww` as the
    join (all-zero rows are the identity: ts 0 never wins)."""
    valid = partners < sentinel
    safe = jnp.minimum(partners, sentinel - 1)
    got = rows_all[safe]                              # [Nl, k, 2K]
    got = jnp.where(valid[:, :, None], got,
                    jnp.zeros((), rows_all.dtype))
    out = got[:, 0, :]
    for j in range(1, got.shape[1]):
        out = merge_lww(out, got[:, j, :])
    return out


# -- byzantine exchange: liar transforms + the owner/clamp defense -----
#
# Register twin of ops/crdt's byzantine kernels (module comment there;
# docs/ROBUSTNESS.md "Byzantine adversaries").  The packed timestamp
# CARRIES provenance — ``(ts - 1) % n`` is the claimed owner and
# ``(ts - 1) // n`` the claimed round — so the defense is two integer
# compares per key: admit an entry from partner p only when p IS the
# claimed owner (owner-column write guard) and the claimed round is
# not in the future (the monotonicity clamp — a forged
# fresher-than-now timestamp is discarded as forged).  Every scripted
# liar transform forges only entries claimed-owned by OTHER nodes
# (own-entry lies are legitimate writes by definition — the BFT
# limitation), so the defended admission rejects all of it, while the
# undefended ts-max join locks any inflated timestamp in forever.

def _byz_serve_reg(got, safe, active, gids, byz, n: int):
    """Render what liar partners SERVE (register rows [Nl, k, 2K]):
    corrupt = foreign nonzero entries get ts + n (claimed round + 1,
    claimed owner PRESERVED — a plausible forged foreign write) and a
    value xor'd with arg; replay = the genesis snapshot (all zeros —
    pure withholding); equivocate = foreign timestamps inflated by a
    receiver-id-keyed number of rounds; inflate = foreign timestamps
    raised by ``arg * n`` rounds (the value is left alone — the lie is
    freshness, which pins the stale value above all later honest
    writes undefended).  ts == 0 entries are never touched: an
    unwritten key has no claimed owner to preserve, and fabricating
    one would alias node n-1's provenance."""
    from gossip_tpu.ops import nemesis as NE
    k = got.shape[-1] // 2
    v, t = got[..., :k], got[..., k:]
    kindp = byz.kind[safe][:, :, None]                 # [Nl, k, 1]
    argp = byz.arg[safe][:, :, None]
    foreign = (t > 0) & (((t - 1) % n) != safe[:, :, None])
    t_cor = jnp.where(foreign, t + n, t)
    v_cor = jnp.where(foreign, v ^ argp, v)
    t_inf = jnp.where(foreign, t + n * argp, t)
    t_eqv = jnp.where(foreign,
                      t + n * (1 + (gids[:, None, None] & 3)), t)
    vv = jnp.where(kindp == NE.BYZ_CODES["corrupt"], v_cor, v)
    tt = jnp.where(kindp == NE.BYZ_CODES["corrupt"], t_cor, t)
    vv = jnp.where(kindp == NE.BYZ_CODES["replay"], 0, vv)
    tt = jnp.where(kindp == NE.BYZ_CODES["replay"], 0, tt)
    tt = jnp.where(kindp == NE.BYZ_CODES["equivocate"], t_eqv, tt)
    tt = jnp.where(kindp == NE.BYZ_CODES["inflate"], t_inf, tt)
    out = jnp.concatenate([vv, tt], axis=-1)
    return jnp.where(active[:, :, None], out, got)


def pull_merge_reg_byz(rows_all: jax.Array, partners: jax.Array,
                       sentinel: int, *, byz, round_,
                       gids: jax.Array, n: int, alive_fn,
                       defend: bool) -> jax.Array:
    """:func:`pull_merge_reg` under a byzantine program (section
    comment).  Defended admission per key, from partner p at round r:
    ``(ts > 0) & ((ts - 1) % n == p) & ((ts - 1) // n <= r)`` — then
    the LWW join of the admitted entries.  Owner-direct propagation
    only (honest relayed entries are rejected too — slower, still
    exact); the control arm ``defend=False`` merges the rendered rows
    unguarded and provably diverges under any ts-inflating liar."""
    valid = partners < sentinel
    safe = jnp.minimum(partners, sentinel - 1)
    got = rows_all[safe]                              # [Nl, k, 2K]
    got = jnp.where(valid[:, :, None], got,
                    jnp.zeros((), rows_all.dtype))
    from gossip_tpu.ops import nemesis as NE
    active = (valid & NE.byz_active(byz, safe, round_)
              & alive_fn(safe, round_))
    got = _byz_serve_reg(got, safe, active, gids, byz, n)
    if defend:
        k = got.shape[-1] // 2
        v, t = got[..., :k], got[..., k:]
        r = jnp.asarray(round_, jnp.int32)
        admit = (valid[:, :, None] & (t > 0)
                 & (((t - 1) % n) == safe[:, :, None])
                 & (((t - 1) // n) <= r))
        got = jnp.concatenate([jnp.where(admit, v, 0),
                               jnp.where(admit, t, 0)], axis=-1)
    out = got[:, 0, :]
    for j in range(1, got.shape[1]):
        out = merge_lww(out, got[:, j, :])
    return out


# -- honest-component convergence (the byz_conv metric) ----------------

def honest_key_mask(cfg: TxnConfig, inj: tuple, fault, n: int,
                    origin: int, honest: jax.Array) -> jax.Array:
    """bool[K]: keys whose ground-truth winner is honest-owned (or
    never written).  The byz_conv equality is restricted to these — a
    liar may withhold its own scripted wins (replay) or overwrite its
    own entries arbitrarily, both undetectable by construction, so
    honest convergence is only claimable where truth itself is honest
    (docs/ROBUSTNESS.md).  Built from :func:`_write_plan`'s winning
    timestamps — the same decomposition as the ground truth."""
    _, _, best = _write_plan(cfg, inj, fault, n, origin)
    owner = jnp.where(best > 0, (best - 1) % n, 0)
    return (best == 0) | honest[owner]


def byz_converged_count(cfg: TxnConfig, rows: jax.Array,
                        truth: jax.Array, alive_honest: jax.Array,
                        key_mask: jax.Array) -> jax.Array:
    """int32 count of honest eventually-alive rows equal to truth on
    every honest-won key, BOTH planes (value and timestamp — the
    full-row discipline of ``converged_count``): the byz_conv
    numerator, divided once on the host."""
    m2 = jnp.concatenate([key_mask, key_mask])
    eq = jnp.all(jnp.where(m2[None, :], rows == truth[None, :], True),
                 axis=-1)
    return jnp.sum(eq & alive_honest, dtype=jnp.int32)


def byz_conv_frac(cfg: TxnConfig, rows: jax.Array, truth: jax.Array,
                  alive_honest: jax.Array,
                  key_mask: jax.Array) -> jax.Array:
    """f32 in-trace byz_conv fraction — RoundMetrics column only; the
    pinned readout is the integer count."""
    c = byz_converged_count(cfg, rows, truth, alive_honest,
                            key_mask).astype(jnp.float32)
    return c / jnp.maximum(jnp.sum(alive_honest, dtype=jnp.float32),
                           1.0)


# -- the skewed default traffic program (closed forms, no RNG) ---------

def _hash01(i: int, salt: int = 0) -> float:
    """Deterministic quasi-uniform in [0, 1): Knuth's multiplicative
    hash on the write index — a closed form, not an RNG stream, so the
    program is a pure function of the config scalars."""
    x = ((i * 2654435761) ^ (salt * 40503)) & 0xFFFFFFFF
    x = (x * 2246822519 + 3266489917) & 0xFFFFFFFF
    return x / 2 ** 32


def _zipf_key(u: float, keys: int, alpha: float) -> int:
    """Inverse-CDF zipf(alpha) pick over ``keys`` ranks for quantile
    ``u`` — key 0 is the most popular rank."""
    weights = [1.0 / (r + 1) ** alpha for r in range(keys)]
    total = sum(weights)
    acc = 0.0
    for k, w in enumerate(weights):
        acc += w / total
        if u < acc:
            return k
    return keys - 1


def _load_round(q: float, load: str, spread: int) -> int:
    """Round for program quantile ``q`` in [0, 1) under the load
    curve: ``uniform`` spreads evenly; ``diurnal`` follows the
    inverse CDF of density ``1 + sin`` (one day-shaped peak
    mid-window), computed by bisection on the closed-form CDF."""
    if load == "uniform" or spread == 1:
        return min(spread - 1, int(q * spread))

    def cdf(x):    # integral of (1 + sin(pi * x)) / norm over [0, 1]
        return (x + (1.0 - math.cos(math.pi * x)) / math.pi) / \
            (1.0 + 2.0 / math.pi)

    lo, hi = 0.0, 1.0
    for _ in range(30):
        mid = (lo + hi) / 2
        if cdf(mid) < q:
            lo = mid
        else:
            hi = mid
    return min(spread - 1, int(lo * spread))


def txn_writes(cfg: TxnConfig, n: int):
    """The effective write list ``[(node, key, round, value), ...]`` —
    scripted, or the default SKEWED program's closed form: write i
    picks its key by zipf(``zipf_alpha``) inverse CDF on a hashed
    quantile, redirected to key 0 with probability ``hot_key`` during
    the middle third of the program (the storm window), lands on the
    round given by the ``load`` curve over ``spread_rounds`` (rounds
    are nondecreasing in i by construction), is written by node
    ``(5 * key + c) % n`` where ``c`` counts the EARLIER writes in
    the same (key, round) bucket — distinct writers per bucket
    whenever ``c < n``, so the default program is collision-free by
    construction (the unique-timestamp contract; a bucket needing
    more than n writers is a pigeonhole impossibility — more than n
    same-(key, round) writes cannot carry unique (round, owner)
    timestamps — and errors loudly naming the knobs), with value
    ``1 + (5 * i + 11 * key) % 97``.  A formula, not a config table;
    the ONE definition shared by the lowering and ground truth."""
    if cfg.writes:
        return list(cfg.writes)
    t = cfg.txns
    out = []
    bucket: dict = {}
    for i in range(t):
        q = (i + 0.5) / t
        key = _zipf_key(_hash01(i, 1), cfg.keys, cfg.zipf_alpha)
        if (cfg.hot_key > 0 and t // 3 <= i < (2 * t) // 3
                and _hash01(i, 2) < cfg.hot_key):
            key = 0
        rnd = _load_round(q, cfg.load, cfg.spread_rounds)
        c = bucket.get((key, rnd), 0)
        bucket[(key, rnd)] = c + 1
        if c >= n:
            raise ValueError(
                f"the default txn program places {c + 1} writes on "
                f"key {key} at round {rnd} but only n={n} distinct "
                "writers exist — more than n same-(key, round) "
                "writes cannot carry unique (round, owner) "
                "timestamps; lower --txns, raise --spread (or ease "
                "--hot-key/--zipf-alpha), or raise --n")
        node = (5 * key + c) % n
        out.append((node, key, rnd, 1 + (5 * i + 11 * key) % 97))
    return out


def inject_args(cfg: TxnConfig, n: int) -> tuple:
    """Lower the write program to the 4-operand tuple (module doc),
    padded to a power-of-two bucket so same-arity programs are
    shape-identical and share one compiled loop.  Re-validates the
    unique-(key, round, node) timestamp contract on the EFFECTIVE list
    (a default program is built here, after n is known) and the int32
    packability of every timestamp."""
    check_ts_packable(cfg, n)
    writes = txn_writes(cfg, n)
    bad = [w for w in writes if w[0] >= n]
    if bad:
        raise ValueError(f"txn writes reference node ids >= n={n}: "
                         f"{bad}")
    trips = [(k, r, nd) for nd, k, r, _ in writes]
    if len(set(trips)) != len(trips):
        dup = sorted({t for t in trips if trips.count(t) > 1})
        raise ValueError(
            f"txn write program carries duplicate (key, round, node) "
            f"triples {dup[:4]} — two writes would share one "
            "(round, owner) timestamp and fork the LWW winner; "
            "script distinct writers or rounds")
    a_pad = _pad_pow2(len(writes))
    cols = [[w[j] for w in writes] for j in range(4)]
    cols[0] += [0] * (a_pad - len(writes))            # node
    cols[1] += [0] * (a_pad - len(writes))            # key
    cols[2] += [NO_ROUND] * (a_pad - len(writes))     # round
    cols[3] += [0] * (a_pad - len(writes))            # value
    return tuple(jnp.asarray(c, jnp.int32) for c in cols)


def split_inject(cfg: TxnConfig, tbl: tuple):
    """(head_tables, inject_operands): peel the 4 operands
    :func:`inject_args` appended back off a step's ``*tables`` tail —
    the ONE inverse (the nemesis split_tables discipline)."""
    return tbl[:-N_INJECT_OPERANDS], tbl[-N_INJECT_OPERANDS:]


# -- ground truth + in-loop injection (shared decomposition) -----------

def _write_plan(cfg: TxnConfig, inj: tuple, fault, n: int, origin: int):
    """The shared in-trace decomposition of the 4 operands: the applied
    mask, each write's packed timestamp, and each key's winning
    timestamp — used by BOTH the in-loop injection and the ground
    truth so the two can never drift."""
    w_node, w_key, w_round, _ = inj
    alive_fn = alive_at_fn(fault, n, origin)
    eventual = eventual_alive_crdt(fault, n, origin)
    applied = _applied_mask(w_round, w_node, alive_fn, eventual)
    ts = jnp.where(applied, pack_ts(w_round, w_node, n), 0)
    best = jnp.zeros((cfg.keys,), jnp.int32).at[w_key].max(
        ts, mode="drop")
    return applied, ts, best


def ground_truth(cfg: TxnConfig, inj: tuple, fault, n: int,
                 origin: int) -> jax.Array:
    """The row ``[2K]`` every eventually-alive node must reach: per
    key, the max-timestamp APPLIED write's value and timestamp (module
    doc; unwritten keys stay (0, 0)).  In-trace, integer-exact, built
    from the SAME operands + liveness predicate as
    :func:`inject_rows` — unique timestamps make the winner select
    exact, never a blend."""
    w_key, w_val = inj[1], inj[3]
    applied, ts, best = _write_plan(cfg, inj, fault, n, origin)
    win = applied & (ts > 0) & (ts == best[w_key])
    val = jnp.zeros((cfg.keys,), jnp.int32).at[w_key].max(
        jnp.where(win, w_val, 0), mode="drop")
    return jnp.concatenate([val, best])


def inject_rows(cfg: TxnConfig, inj: tuple, gids: jax.Array, round_,
                n: int, origin: int, fault) -> jax.Array:
    """The rows each node LWW-merges into its OWN state at ``round_``
    — ``int32[len(gids), 2K]``, zero except where this round's applied
    writes land on a ``gids`` row (the writer owns the write — the
    owner-indexed discipline).  A node writes at most one value per
    (key, round) by the unique-timestamp contract, so the per-row
    scatter is collision-free."""
    r = jnp.asarray(round_, jnp.int32)
    w_node, w_key, w_round, w_val = inj
    applied, ts, _ = _write_plan(cfg, inj, fault, n, origin)
    fire = (w_round == r) & applied
    mine = w_node[None, :] == gids[:, None]             # [Nl, A]
    hit = fire[None, :] & mine
    nl = gids.shape[0]
    rows = jnp.zeros((nl, state_width(cfg)), jnp.int32)
    rows = rows.at[:, w_key].max(jnp.where(hit, w_val[None, :], 0),
                                 mode="drop")
    return rows.at[:, cfg.keys + w_key].max(
        jnp.where(hit, ts[None, :], 0), mode="drop")


# -- readouts ----------------------------------------------------------

def payload_count(cfg: TxnConfig, rows: jax.Array,
                  alive: jax.Array) -> jax.Array:
    """f32 total timestamp mass over alive rows — the ``newly``
    integrand (ops/round_metrics): timestamps are monotone under the
    LWW merge (values are not), so the per-round delta is exact.
    Observability-plane f32 only; every pinned readout is the integer
    converged count."""
    ts = rows[..., cfg.keys:]
    return jnp.sum(jnp.where(alive[:, None], ts, 0),
                   dtype=jnp.float32)


def truth_summary(cfg: TxnConfig, truth, n: int) -> dict:
    """Human-readable ground truth for reports and the CLI: per-key
    winning values plus the unpacked (round, owner) of each winner
    (-1 for never-written keys), integer-exact."""
    import numpy as np
    truth = np.asarray(truth)
    vals = truth[:cfg.keys]
    ts = truth[cfg.keys:]
    rounds = [int((t - 1) // n) if t > 0 else -1 for t in ts]
    owners = [int((t - 1) % n) if t > 0 else -1 for t in ts]
    return {"values": [int(v) for v in vals],
            "ts_round": rounds, "ts_owner": owners,
            "written_keys": int((ts > 0).sum())}
