"""Bit-packed rumor state: 32 rumors per uint32 word.

Why this exists (measured on the target TPU, see bench notes in bench.py):
XLA's random gather costs ~8 ns *per element* regardless of element width,
so gathering a ``uint32`` word moves 32 rumors for the price of one bool —
the multi-rumor pull round gets ~32x denser.  The packed digest table is
also 8x smaller than ``bool`` rows on the wire: the sharded pull round
all-gathers ``N x W`` words (1.25 MB at N=10M, R=1) instead of 10 MB of
bools, and HBM residency at the 10M-node / 64-rumor scale drops from 640 MB
to 80 MB.

Layout: rumor ``r`` lives in word ``r // 32``, bit ``r % 32`` — so
``packed[i, w] >> (r % 32) & 1 == seen[i, r]``.  Rumor counts that are not
multiples of 32 leave zero padding bits in the last word; every consumer
masks by the real rumor count (coverage would otherwise report the padding
bits' 0% and clamp the min).

Pure ``jnp`` — bitwise ops fuse fine under XLA; no Pallas needed here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

WORD = 32


def n_words(rumors: int) -> int:
    return (rumors + WORD - 1) // WORD


def pack(seen: jax.Array) -> jax.Array:
    """bool[N, R] -> uint32[N, ceil(R/32)]."""
    n, r = seen.shape
    w = n_words(r)
    pad = w * WORD - r
    if pad:
        seen = jnp.concatenate(
            [seen, jnp.zeros((n, pad), seen.dtype)], axis=1)
    bits = seen.reshape(n, w, WORD).astype(jnp.uint32)
    shifts = jnp.arange(WORD, dtype=jnp.uint32)
    return jnp.sum(bits << shifts[None, None, :], axis=2, dtype=jnp.uint32)


def unpack(packed: jax.Array, rumors: int) -> jax.Array:
    """uint32[N, W] -> bool[N, rumors]."""
    n, w = packed.shape
    shifts = jnp.arange(WORD, dtype=jnp.uint32)
    bits = (packed[:, :, None] >> shifts[None, None, :]) & 1
    return bits.reshape(n, w * WORD)[:, :rumors].astype(jnp.bool_)


def coverage_packed(packed: jax.Array, rumors: int,
                    alive: jax.Array | None = None) -> jax.Array:
    """Min-over-rumors coverage of a packed state (twin of
    models/si.coverage; padding bits masked out of the min)."""
    n, w = packed.shape
    shifts = jnp.arange(WORD, dtype=jnp.uint32)
    bits = (packed[:, :, None] >> shifts[None, None, :]) & 1   # [N, W, 32]
    if alive is None:
        per_bit = jnp.mean(bits.astype(jnp.float32), axis=0)   # [W, 32]
    else:
        wgt = alive.astype(jnp.float32)
        per_bit = (bits.astype(jnp.float32)
                   * wgt[:, None, None]).sum(0) / wgt.sum()
    per_rumor = per_bit.reshape(w * WORD)[:rumors]
    return jnp.min(per_rumor)
