"""Replicated kafka-style logs: ordered per-key offset payloads on the
gossip fabric.

The last Gossip Glomers sibling of the reference's broadcast (after the
PR 8 counters and sets): a **replicated log** — per-key append streams
with offsets, committed offsets, and poll semantics.  What is
qualitatively new is *order*: the counter/set payloads only demand
eventual agreement on an unordered value, while the kafka invariants
demand that acked sends appear **exactly once per key in offset
order**, that committed offsets **never regress**, and that polls from
a committed offset see **no gaps**.

Array form (one row per node, the ops/crdt layout discipline): each
node carries K fixed-capacity per-key ring buffers plus a per-key
committed-offset vector, flattened to one ``int32[N, S]`` row with
``S = K * (C + 1)``:

  * columns ``0 .. K*C-1`` — the **entry planes**: slot ``(k, c)``
    (column ``k*C + c``) holds the value appended at offset ``c`` of
    key ``k`` (0 = empty; LogConfig requires values >= 1).  The slot's
    ring position is ``offset % C``; LogConfig caps sends-per-key at C
    so the ring never wraps onto an unconsumed slot (a wrap would
    silently alias two offsets — rejected loudly instead).
  * columns ``K*C .. K*C+K-1`` — the **committed-offset vector**:
    column ``K*C + k`` holds key ``k``'s committed count (offsets
    below it are committed; 0 = nothing committed).

Why elementwise max is the exact join (the G-Counter column
discipline): every entry slot is written by exactly ONE owner — the
appender of the unique applied send that lands on that offset — and
written once, monotonically 0 -> value; committed counts are monotone
by the kafka contract (commits never regress) and the ground-truth
commit value is the max of all applied commits.  So merge =
elementwise max over the owner-indexed slot planes is commutative,
associative, idempotent, and an upper bound — gossip order,
duplication, and loss can never corrupt the log.

Offset assignment and the acked-appends ground truth
----------------------------------------------------
Sends are a scripted *program over rounds* — ``(node, key, round,
value)`` — lowered to padded runtime operands on the step's ``tables``
tail (:func:`inject_args`, the nemesis/CRDT pattern: compiled loops
carry injection shapes, never content).  A send is **applied** iff its
appender is alive at the send round AND eventually alive under the
fault program (the acked-adds semantics of ops/crdt: a node destined
for permanent death contributes nothing).  A key's applied sends take
offsets ``0 .. m-1`` in script order (:func:`send_offsets` — LogConfig
requires per-key script order to be round-nondecreasing, so offset
order IS time order), compacted over unapplied sends so the acked log
is gap-free by construction.  Commits ``(node, key, round, upto)``
apply under the same liveness predicate and commit
``min(upto, truth_len[key])`` — the clamp to the eventually-acked log
length keeps a committed offset from ever pointing past the final log.

:func:`ground_truth` builds the merged truth row from the SAME
operands and liveness predicate as the in-loop injection, so target
and trajectory cannot drift; convergence is judged integer-exact
(``ops/crdt.converged_count`` full-row equality, divided ONCE on the
host — the ``log_conv`` readout and round-metrics column).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from gossip_tpu.config import LogConfig
# ONE definition each for the padding bucket, the no-injection round
# sentinel, and the shared liveness predicates (ops/crdt): the log and
# CRDT injection lowerings must agree on all of them by construction.
from gossip_tpu.ops.crdt import (NO_ROUND, _applied_mask, _pad_pow2,
                                 alive_at_fn, converged_count,
                                 eventual_alive_crdt, merge_max,
                                 value_conv_frac)

__all__ = ["N_INJECT_OPERANDS", "alive_at_fn", "converged_count",
           "eventual_alive_crdt", "ground_truth", "inject_args",
           "inject_rows", "log_commits", "log_len", "log_sends",
           "merge_max", "payload_count", "pull_merge_log",
           "send_offsets", "split_inject", "state_width",
           "truth_summary", "value_conv_frac"]

# Trailing step arguments the injection program occupies on a factory's
# ``tables`` tuple: (s_node, s_key, s_round, s_val) sends +
# (c_node, c_key, c_round, c_upto) commits, each padded int32[A].
N_INJECT_OPERANDS = 8


def state_width(cfg: LogConfig) -> int:
    """S = K*C entry slots + K committed columns (module doc)."""
    return cfg.keys * (cfg.capacity + 1)


def pull_merge_log(rows_all: jax.Array, partners: jax.Array,
                   sentinel: int) -> jax.Array:
    """Merge of k sampled peers' log rows -> ``[N_local, S]`` — the
    ops/crdt.pull_merge_crdt shape with the max join (0 is the merge
    identity: entries and commits are nonnegative by contract)."""
    valid = partners < sentinel
    safe = jnp.minimum(partners, sentinel - 1)
    got = rows_all[safe]                              # [Nl, k, S]
    got = jnp.where(valid[:, :, None], got,
                    jnp.zeros((), rows_all.dtype))
    out = got[:, 0, :]
    for j in range(1, got.shape[1]):
        out = merge_max(out, got[:, j, :])
    return out


# -- injection programs (closed-form defaults, the counter_adds rule) --

def log_sends(cfg: LogConfig, n: int):
    """The effective send list ``[(node, key, round, value), ...]`` —
    scripted, or the default program's closed form: each key k gets 4
    sends, send j appended by node ``(k + 3*j) % n`` at round j with
    value ``1 + (7*k + 3*j) % 23``.  A formula, not a config table
    (no O(N)/O(K) config object); the ONE definition shared by the
    lowering and ground truth through :func:`inject_args`."""
    if cfg.sends:
        return list(cfg.sends)
    return [(int((k + 3 * j) % n), k, j, 1 + (7 * k + 3 * j) % 23)
            for k in range(cfg.keys) for j in range(4)]


def log_commits(cfg: LogConfig, n: int):
    """The effective commit list ``[(node, key, round, upto), ...]`` —
    scripted, or the default: node ``(k + 1) % n`` commits key k up to
    2 entries at round 4 (after the default sends)."""
    if cfg.commits:
        return list(cfg.commits)
    return [(int((k + 1) % n), k, 4, 2) for k in range(cfg.keys)]


def inject_args(cfg: LogConfig, n: int) -> tuple:
    """Lower the send + commit programs to the 8-operand tuple (module
    doc), padded to a power-of-two bucket so same-arity programs are
    shape-identical and share one compiled loop."""
    sends = log_sends(cfg, n)
    commits = log_commits(cfg, n)
    bad = [s for s in sends if s[0] >= n] + \
        [c for c in commits if c[0] >= n]
    if bad:
        raise ValueError(f"log sends/commits reference node ids >= "
                         f"n={n}: {bad}")

    def quad(items):
        a_pad = _pad_pow2(len(items)) if items else _pad_pow2(0)
        cols = [[it[j] for it in items] for j in range(4)]
        cols[0] += [0] * (a_pad - len(items))            # node
        cols[1] += [0] * (a_pad - len(items))            # key
        cols[2] += [NO_ROUND] * (a_pad - len(items))     # round
        cols[3] += [0] * (a_pad - len(items))            # value/upto
        return tuple(jnp.asarray(c, jnp.int32) for c in cols)

    return quad(sends) + quad(commits)


def split_inject(cfg: LogConfig, tbl: tuple):
    """(head_tables, inject_operands): peel the 8 operands
    :func:`inject_args` appended back off a step's ``*tables`` tail —
    the ONE inverse (the nemesis split_tables discipline)."""
    return tbl[:-N_INJECT_OPERANDS], tbl[-N_INJECT_OPERANDS:]


def send_offsets(s_key: jax.Array, applied: jax.Array) -> jax.Array:
    """int32[A]: each send's offset within its key — the count of
    APPLIED sends with the same key at a strictly earlier script index
    (module doc: script order is round-nondecreasing per key by
    LogConfig contract, so offset order is time order; unapplied sends
    are compacted over).  O(A^2) pairwise compare on the tiny padded
    list — in-trace, shapes only."""
    a = s_key.shape[0]
    idx = jnp.arange(a, dtype=jnp.int32)
    earlier = idx[None, :] < idx[:, None]                 # [A, A]
    same_key = s_key[None, :] == s_key[:, None]
    return jnp.sum(earlier & same_key & applied[None, :],
                   axis=1, dtype=jnp.int32)


def _send_plan(cfg: LogConfig, inj: tuple, fault, n: int, origin: int):
    """The shared in-trace decomposition of the 8 operands: applied
    masks, per-send flat slot indices, per-key truth lengths, and
    per-commit clamped values — used by BOTH the in-loop injection and
    the ground truth so the two can never drift."""
    s_node, s_key, s_round, s_val = inj[:4]
    c_node, c_key, c_round, c_upto = inj[4:]
    alive_fn = alive_at_fn(fault, n, origin)
    eventual = eventual_alive_crdt(fault, n, origin)
    applied_s = _applied_mask(s_round, s_node, alive_fn, eventual)
    off = send_offsets(s_key, applied_s)
    slot = s_key * cfg.capacity + off                     # flat entry col
    truth_len = jnp.zeros((cfg.keys,), jnp.int32).at[s_key].add(
        jnp.where(applied_s, 1, 0), mode="drop")
    applied_c = _applied_mask(c_round, c_node, alive_fn, eventual)
    cval = jnp.minimum(c_upto, truth_len[c_key])
    return applied_s, slot, truth_len, applied_c, cval


def ground_truth(cfg: LogConfig, inj: tuple, fault, n: int,
                 origin: int) -> jax.Array:
    """The merged row ``[S]`` every eventually-alive node must reach:
    entry slots from the applied sends at their compacted offsets,
    committed counts = max over applied commits of the clamped value
    (module doc).  In-trace, integer-exact, built from the SAME
    operands + liveness predicate as :func:`inject_rows`."""
    s_val = inj[3]
    c_key = inj[5]
    applied_s, slot, _, applied_c, cval = _send_plan(cfg, inj, fault,
                                                     n, origin)
    ent = jnp.zeros((cfg.keys * cfg.capacity,), jnp.int32).at[slot].max(
        jnp.where(applied_s, s_val, 0), mode="drop")
    com = jnp.zeros((cfg.keys,), jnp.int32).at[c_key].max(
        jnp.where(applied_c, cval, 0), mode="drop")
    return jnp.concatenate([ent, com])


def inject_rows(cfg: LogConfig, inj: tuple, gids: jax.Array, round_,
                n: int, origin: int, fault) -> jax.Array:
    """The rows each node max-merges into its OWN state at ``round_``
    — ``int32[len(gids), S]``, zero except where this round's applied
    sends/commits land on a ``gids`` row (the appender/committer owns
    the write — the owner-indexed slot discipline)."""
    r = jnp.asarray(round_, jnp.int32)
    s_node, _, s_round, s_val = inj[:4]
    c_node, c_key, c_round, _ = inj[4:]
    applied_s, slot, _, applied_c, cval = _send_plan(cfg, inj, fault,
                                                     n, origin)
    nl = gids.shape[0]
    s_width = state_width(cfg)
    fire_s = (s_round == r) & applied_s
    mine_s = s_node[None, :] == gids[:, None]             # [Nl, A]
    ent = jnp.zeros((nl, s_width), jnp.int32).at[:, slot].max(
        jnp.where(fire_s[None, :] & mine_s, s_val[None, :], 0),
        mode="drop")
    fire_c = (c_round == r) & applied_c
    mine_c = c_node[None, :] == gids[:, None]
    com_col = cfg.keys * cfg.capacity + c_key
    return ent.at[:, com_col].max(
        jnp.where(fire_c[None, :] & mine_c, cval[None, :], 0),
        mode="drop")


# -- readouts ----------------------------------------------------------

def log_len(cfg: LogConfig, rows: jax.Array) -> jax.Array:
    """int32[..., K]: contiguous filled-prefix length per key — the
    per-key append cursor derived from the entry planes (a replica may
    transiently hold later slots before earlier ones; polls serve the
    contiguous prefix, the gapless contract)."""
    ent = rows[..., :cfg.keys * cfg.capacity]
    filled = ent.reshape(ent.shape[:-1] + (cfg.keys, cfg.capacity)) != 0
    return jnp.sum(jnp.cumprod(filled.astype(jnp.int32), axis=-1),
                   axis=-1, dtype=jnp.int32)


def committed_of(cfg: LogConfig, rows: jax.Array) -> jax.Array:
    """int32[..., K]: the committed-count vector columns."""
    return rows[..., cfg.keys * cfg.capacity:]


def payload_count(cfg: LogConfig, rows: jax.Array,
                  alive: jax.Array) -> jax.Array:
    """f32 total payload mass over alive rows — filled entry slots +
    committed counts, both monotone under the max merge, so the
    per-round delta (the ``newly`` counter) is exact."""
    ent = rows[..., :cfg.keys * cfg.capacity]
    com = committed_of(cfg, rows)
    filled = jnp.where(alive[:, None], (ent != 0).astype(jnp.int32), 0)
    return (jnp.sum(filled, dtype=jnp.float32)
            + jnp.sum(jnp.where(alive[:, None], com, 0),
                      dtype=jnp.float32))


def truth_summary(cfg: LogConfig, truth) -> dict:
    """Human-readable ground truth for reports and the CLI: per-key
    acked lengths and committed counts, integer-exact."""
    import numpy as np
    truth = np.asarray(truth)
    ent = truth[:cfg.keys * cfg.capacity].reshape(cfg.keys,
                                                  cfg.capacity)
    lens = [int((row != 0).cumprod().sum()) for row in ent]
    committed = [int(c) for c in truth[cfg.keys * cfg.capacity:]]
    return {"lens": lens, "committed": committed,
            "total_entries": int(sum(lens))}
