"""Static-analysis core: findings, parsed modules, and the suppression
baseline every checker family shares.

Thirteen PRs of hard-won invariants lived only in prose and reviewer
memory — the PR 9/12 serving recompile lessons, the PR 13 batcher
shutdown race, the repo's provenance/budget/`Ledger.event` contract
conventions (docs/STATIC_ANALYSIS.md has the full catalog).  The Go
reference culture leans on ``go vet`` + the race detector for exactly
this bug class; this package is that discipline pointed at our own
source: pure-stdlib AST passes, no jax import anywhere (the analyzer
must run on a box with a wedged tunnel — the round-5 lesson applies to
lint too).

Contracts:

  * a :class:`Finding` is identified by ``(rule, path, symbol)`` — the
    suppression key is content-addressed (qualified name), never a
    line number, so an unrelated edit above a baselined site cannot
    orphan its suppression;
  * the baseline (tools/staticcheck_baseline.json) is the
    validate_artifacts allowlist discipline applied to lint: every
    entry carries a non-empty ``rationale`` string, a stale entry (one
    matching no live finding) is itself a finding, and the committed
    entry count is pinned by tests/test_staticcheck.py so the file can
    only shrink;
  * checkers are pure functions ``(modules, ...) -> [Finding]`` over
    pre-parsed :class:`Module` objects, so the planted-violation
    fixtures under tests/data/staticcheck/ run through exactly the
    code path the live tree does.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: the one committed suppression file (runner + tests share the path)
BASELINE_PATH = os.path.join("tools", "staticcheck_baseline.json")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One invariant violation at one site.

    ``checker`` is the family (``recompile`` / ``locks`` /
    ``conventions`` / ``baseline``); ``rule`` the specific invariant;
    ``symbol`` the dotted qualname of the enclosing def/class (or ""
    at module level) — the stable half of the suppression key."""

    checker: str
    rule: str
    path: str            # repo-relative, forward slashes
    line: int
    symbol: str
    message: str

    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.symbol}"

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return (f"{self.path}:{self.line}: {self.checker}/{self.rule}"
                f"{sym}: {self.message}")


class Module:
    """A parsed source file plus the parent/qualname maps every
    checker needs (computed once here, never per pass)."""

    def __init__(self, path: str, relpath: str, source: str,
                 tree: ast.Module):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.tree = tree
        self.parents: Dict[ast.AST, ast.AST] = {}
        self._qualnames: Dict[ast.AST, str] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    def qualname(self, node: ast.AST) -> str:
        """Dotted name of the innermost enclosing def/class chain
        (``Batcher._admit``), "" at module level."""
        if node in self._qualnames:
            return self._qualnames[node]
        parts: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = self.parents.get(cur)
        qn = ".".join(reversed(parts))
        self._qualnames[node] = qn
        return qn

    def enclosing_function(self, node: ast.AST):
        """The innermost FunctionDef/AsyncFunctionDef containing
        ``node``, or None at module/class level."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    def enclosing_class(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = self.parents.get(cur)
        return None


def parse_file(path: str, root: str) -> Module:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    rel = os.path.relpath(path, root)
    return Module(path, rel, source, ast.parse(source, filename=path))


def load_modules(root: str, relpaths: Iterable[str]) -> Dict[str, Module]:
    """{relpath: Module} for every existing path; a missing file is
    skipped (scope lists name optional modules), a SYNTAX error is
    not — the analyzer refuses to bless a tree it cannot parse."""
    out: Dict[str, Module] = {}
    for rel in relpaths:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            continue
        mod = parse_file(path, root)
        out[mod.relpath] = mod
    return out


def iter_py_files(root: str, subdirs: Iterable[str],
                  exclude_dirs: Tuple[str, ...] = ("tests/data",
                                                   "__pycache__")):
    """Repo-relative paths of every .py under ``subdirs`` (or the
    files themselves), excluding fixture/cache dirs — the planted
    violations under tests/data/staticcheck must never count against
    the live tree."""
    for sub in subdirs:
        base = os.path.join(root, sub)
        if os.path.isfile(base):
            yield sub
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            rel_dir = os.path.relpath(dirpath, root).replace(os.sep, "/")
            if any(rel_dir == e or rel_dir.startswith(e + "/")
                   for e in exclude_dirs):
                dirnames[:] = []
                continue
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield f"{rel_dir}/{fn}"


# -- small AST helpers shared by the checker families -----------------

def call_name(node: ast.Call) -> str:
    """Dotted text of the call target (``jnp.stack``, ``self._stop
    .is_set``) — terminal-name matching beats full resolution for
    passes that must stay import-free."""
    return expr_text(node.func)


def expr_text(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:           # pragma: no cover - unparse is total on 3.10
        return ""


def keyword_arg(node: ast.Call, name: str):
    for kw in node.keywords:
        if kw.arg == name:
            return kw
    return None


def has_decorator(fn, *names: str) -> bool:
    """True when any decorator's terminal name matches (``lru_cache``
    matches ``functools.lru_cache(maxsize=32)`` and bare
    ``@lru_cache``)."""
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        text = expr_text(target)
        term = text.rsplit(".", 1)[-1]
        if term in names:
            return True
    return False


def str_const(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# -- suppression baseline ---------------------------------------------

REQUIRED_ENTRY_KEYS = ("rule", "path", "symbol", "rationale")


def load_baseline(path: str):
    """(entries, problems): the committed suppressions plus any
    baseline-discipline findings — a malformed entry or one with a
    missing/empty rationale is a FINDING (checker ``baseline``), not a
    parse warning: a suppression nobody can justify is exactly the
    silent grandfathering this file exists to forbid."""
    problems: List[Finding] = []
    if not os.path.isfile(path):
        return [], problems
    rel = os.path.basename(path)
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (ValueError, OSError) as e:
        # an unreadable/unparseable baseline is a FINDING, never a
        # traceback: the analyzer must exit 1 with a named reason, not
        # crash every dry run on a hand-edit's trailing comma
        return [], [Finding(
            "baseline", "malformed-baseline", rel, 1, "",
            f"baseline does not parse: {e}")]
    if not isinstance(doc, dict):
        return [], [Finding(
            "baseline", "malformed-baseline", rel, 1, "",
            "baseline must be a JSON object with a 'suppressions' "
            f"list, got {type(doc).__name__}")]
    entries = doc.get("suppressions", [])
    if not isinstance(entries, list):
        return [], [Finding(
            "baseline", "malformed-baseline", rel, 1, "",
            "'suppressions' must be a list of entry objects")]
    good = []
    for i, e in enumerate(entries):
        if not isinstance(e, dict) or any(k not in e
                                          for k in REQUIRED_ENTRY_KEYS):
            problems.append(Finding(
                "baseline", "malformed-baseline", rel, 1, "",
                f"entry {i} must carry the keys "
                f"{REQUIRED_ENTRY_KEYS}: {e!r:.120}"))
            continue
        if not str(e["rationale"]).strip():
            problems.append(Finding(
                "baseline", "missing-rationale", rel, 1,
                str(e.get("symbol", "")),
                f"entry {i} ({e['rule']}:{e['path']}) has an empty "
                "rationale — every accepted finding must say WHY it "
                "is accepted (the allowlist-only-shrinks contract)"))
            continue
        good.append(e)
    return good, problems


def apply_baseline(findings: List[Finding], entries: List[dict],
                   baseline_rel: str = BASELINE_PATH):
    """(unsuppressed, suppressed, stale) — a finding is suppressed iff
    some entry matches its ``(rule, path, symbol)`` exactly; an entry
    matching NOTHING is stale and becomes a finding itself, so fixing
    a violation forces its suppression out of the file (the baseline
    only shrinks — tests/test_staticcheck.py pins the count)."""
    by_key = {}
    for e in entries:
        by_key[f"{e['rule']}:{e['path']}:{e['symbol']}"] = e
    unsuppressed, suppressed = [], []
    used = set()
    for f in findings:
        e = by_key.get(f.key())
        if e is not None:
            used.add(f.key())
            suppressed.append(f)
        else:
            unsuppressed.append(f)
    stale = [Finding(
        "baseline", "stale-suppression",
        baseline_rel.replace(os.sep, "/"), 1, str(e["symbol"]),
        f"suppression {k} matches no live finding — the violation "
        "was fixed (or the symbol moved); delete the entry, the "
        "baseline only shrinks")
        for k, e in by_key.items() if k not in used]
    return unsuppressed, suppressed, stale
