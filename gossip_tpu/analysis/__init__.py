"""AST-based invariant analyzer (``gossip_tpu staticcheck``): the
repo's hard-won invariants as machine-checked lint — recompile-hazard
rules for the serving/sweep paths, lock discipline for rpc/, and the
contract conventions (provenance, budget rows, ``Ledger.event``
collisions, capability-string pairs).  Pure stdlib; never imports jax.
See docs/STATIC_ANALYSIS.md for the checker catalog and
tools/staticcheck_baseline.json for the suppression contract."""

from gossip_tpu.analysis.core import Finding  # noqa: F401
from gossip_tpu.analysis.runner import (Report, main,  # noqa: F401
                                        run_tree, write_ledger)
