"""Checker family 1: recompile hazards on the serving/sweep paths.

The invariants this family encodes are the PR 9/12 serving lessons
(docs/SERVING.md, docs/PERF.md):

  * **jnp-over-k** — a ``jnp.stack``/``concatenate``/``array``/
    ``asarray`` over a Python-sized sequence (list/tuple literal or
    comprehension) is a fresh tiny XLA program per distinct K.  Solo
    it is invisible; per-request it is a compile inside the serving
    window (PR 9's operand assembly is deliberately NUMPY for exactly
    this reason).
  * **jit-in-request-path** — a ``jax.jit``/``pjit`` call inside a
    function reachable per-request builds a fresh jit closure per
    CALL and retraces every time (the solo ``simulate_curve`` baseline
    measured ~0.5 rps against the batcher's 95.7x because of this).
  * **content-in-memo-key** — an ``lru_cache``-decorated builder that
    produces an executable (a ``jax.jit`` in its body) keyed on
    fault/schedule CONTENT compiles one executable per scenario: the
    exact ``_cached_churn_masks`` bug PR 12 deleted (its fix caches
    VALUES eagerly and keys the compiled loops on no fault content).
    The repo's naming convention is the escape hatch: a parameter
    named ``*_static`` declares "statics only, content stripped
    upstream" (``_cached_dense_loop(fault_static=...)``) and is not
    flagged; a bare content name on an executable-producing memo key
    is.
  * **byz-table-in-memo-key** — the same hazard for the byzantine
    layer: an executable-producing memo keyed on liar-program
    CONTENT (``byz``/``liars``/``byz_kind``/``quorum``/...) compiles
    one program per adversary scenario, defeating the operand
    discipline that makes the salted dry-run re-entry free
    (ops/nemesis ``byz_args``: liar content is data on the table
    tail, never shape).  Same ``*_static`` escape hatch.  Kept as
    its OWN rule — the byz param vocabulary must not dilute the
    fault/schedule regex, and a byz finding names the byz-specific
    fix (thread the program through ``tabled=True`` operands).
  * **blocking-fetch-in-segment-loop** — planner/stream's segment
    loop is a three-stage software pipeline (dispatch tile *k*, drain
    tile *k−1*); a synchronous ``np.asarray``/``np.array``/
    ``block_until_ready`` inside any of its loops stalls the host on
    the device and re-serializes fetch against compute.  A function
    named ``_drain*`` is the sanctioned deferred-fetch site and is
    exempt — the same declared-escape naming convention as
    ``*_static``.

Reachability: the per-request roots are every function in the rpc
modules plus the ``request_*`` entry points in parallel/sweep; the
call graph is terminal-name matched (an over-approximation — more
reachable means stricter).  ``lru_cache``-decorated functions are
BOUNDARIES for the first two rules: inside a memoized builder,
trace-time Python runs once per key by construction (that is the
pattern the serving layer is built on), so only the third rule looks
inside them — at their keys.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set

from gossip_tpu.analysis.core import (Finding, Module, call_name,
                                      expr_text, has_decorator)

CHECKER = "recompile"

#: serving/sweep scope — the modules whose functions can run
#: per-request or per-scenario (docs/STATIC_ANALYSIS.md scope table)
SCOPE = (
    "gossip_tpu/rpc/batcher.py",
    "gossip_tpu/rpc/router.py",
    "gossip_tpu/rpc/sidecar.py",
    "gossip_tpu/parallel/sweep.py",
    "gossip_tpu/ops/nemesis.py",
)

#: modules whose lru_cache keys the content-in-memo-key rule audits
#: (every jax-bearing package — the hazard is not serving-specific)
MEMO_SCOPE_PREFIXES = ("gossip_tpu/",)

#: the streamed executor's scope: its segment loop is a three-stage
#: pipeline (planner/stream module doc), and a synchronous fetch
#: inside any of its loops collapses the pipeline back to
#: compute-plus-transfer serial
STREAM_SCOPE = ("gossip_tpu/planner/stream.py",)

#: call names that block the host on device results (D2H fetch or
#: synchronization) — the pipeline-defeating set
_BLOCKING_FETCHES = ("np.asarray", "np.array", "numpy.asarray",
                     "numpy.array", "jax.block_until_ready")

#: the sanctioned deferred-fetch helper prefix: planner/stream routes
#: every blocking fetch through its ``_drain`` helper, which runs one
#: tile BEHIND the dispatch — the same declared-escape naming
#: convention as ``*_static`` memo params above
_DRAIN_PREFIX = "_drain"

_JNP_BUILDERS = ("stack", "concatenate", "array", "asarray")

#: parameter names that carry fault/schedule CONTENT; ``*_static`` is
#: the declared-static naming convention and never matches
_CONTENT_PARAM = re.compile(
    r"^(fault|churn|sched|schedule|events?|drop|drop_tbl|cut|cut_tbl"
    r"|die|rec|program|tables)$")

#: parameter names that carry byzantine liar-program content (the
#: ByzConfig lowering: kind/start/arg tables + the traced quorum
#: scalar — ops/nemesis).  Deliberately NOT folded into
#: :data:`_CONTENT_PARAM`: the finding names the byz-specific fix
_BYZ_PARAM = re.compile(
    r"^(byz|byz_cfg|byz_tbl|byz_kind|byz_start|byz_arg|liars?"
    r"|quorum)$")

_PY_SIZED = (ast.List, ast.Tuple, ast.ListComp, ast.GeneratorExp,
             ast.SetComp)


def _is_jit_call(node: ast.Call) -> bool:
    name = call_name(node)
    return name in ("jax.jit", "pjit", "jax.pjit") or name.endswith(
        ".jit")


def _module_jit_refs(fn: ast.AST) -> bool:
    """True when ``fn``'s body references jax.jit/pjit anywhere — as a
    call OR a decorator on an inner def (the memoized-scan idiom wraps
    the inner ``scan`` with ``@jax.jit``)."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and _is_jit_call(node):
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if expr_text(target) in ("jax.jit", "pjit", "jax.pjit"):
                    return True
    return False


def _functions(mod: Module) -> Dict[str, ast.FunctionDef]:
    out = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[mod.qualname(node)] = node
    return out


def _roots(mod: Module, fns: Dict[str, ast.FunctionDef]) -> Set[str]:
    if "/rpc/" in mod.relpath:
        return set(fns)                       # the whole serving layer
    return {qn for qn in fns
            if qn.split(".")[-1].startswith(("request_", "_request"))}


def _reachable(modules: Dict[str, Module]):
    """(per-module reachable qualname set) from the per-request roots,
    terminal-name call matching across the scope modules; traversal
    stops at lru_cache boundaries (their bodies run once per key)."""
    # global name -> [(relpath, qualname, fn)]
    by_name: Dict[str, List] = {}
    all_fns: Dict[str, Dict[str, ast.FunctionDef]] = {}
    for rel, mod in modules.items():
        fns = _functions(mod)
        all_fns[rel] = fns
        for qn, fn in fns.items():
            by_name.setdefault(qn.split(".")[-1], []).append(
                (rel, qn, fn))
    reach: Set = set()
    work = []
    for rel, mod in modules.items():
        for qn in _roots(mod, all_fns[rel]):
            work.append((rel, qn))
    while work:
        rel, qn = work.pop()
        if (rel, qn) in reach:
            continue
        reach.add((rel, qn))
        fn = all_fns[rel][qn]
        if has_decorator(fn, "lru_cache", "cache"):
            continue                           # memo boundary
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            term = call_name(node).rsplit(".", 1)[-1]
            for rel2, qn2, _ in by_name.get(term, ()):
                if (rel2, qn2) not in reach:
                    work.append((rel2, qn2))
    per_mod: Dict[str, Set[str]] = {}
    for rel, qn in reach:
        per_mod.setdefault(rel, set()).add(qn)
    return per_mod, all_fns


def check_stream_fetch(modules: Dict[str, Module]) -> List[Finding]:
    """**blocking-fetch-in-segment-loop** over :data:`STREAM_SCOPE`: a
    ``np.asarray``/``np.array``/``block_until_ready`` call lexically
    inside a For/While loop stalls the host mid-pipeline — the fetch
    the three-stage segment loop exists to hide (planner/stream module
    doc).  Calls enclosed by a function named ``_drain*`` are the
    sanctioned deferred-fetch site and never flag; fixture tests prove
    both directions."""
    findings: List[Finding] = []
    for rel, mod in modules.items():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not (name in _BLOCKING_FETCHES
                    or name.rsplit(".", 1)[-1] == "block_until_ready"):
                continue
            in_loop = sanctioned = False
            cur = mod.parents.get(node)
            while cur is not None:
                if isinstance(cur, (ast.For, ast.While)):
                    in_loop = True
                elif isinstance(cur, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) \
                        and cur.name.startswith(_DRAIN_PREFIX):
                    sanctioned = True
                cur = mod.parents.get(cur)
            if in_loop and not sanctioned:
                findings.append(Finding(
                    CHECKER, "blocking-fetch-in-segment-loop", rel,
                    node.lineno, mod.qualname(node),
                    f"{name} inside a segment-loop body blocks the "
                    "host on the device and collapses the three-stage "
                    "tile pipeline to serial (planner/stream module "
                    "doc); defer the fetch one tile and route it "
                    "through a _drain* helper"))
    return findings


def check(modules: Dict[str, Module],
          memo_modules: Dict[str, Module]) -> List[Finding]:
    """``modules``: the serving-scope set (reachability rules);
    ``memo_modules``: the wider set whose lru_cache keys are audited.
    Fixture tests pass their planted files as both."""
    findings: List[Finding] = []
    per_mod, all_fns = _reachable(modules)

    for rel, mod in modules.items():
        fns = all_fns.get(rel, {})
        for qn in sorted(per_mod.get(rel, ())):
            fn = fns[qn]
            if has_decorator(fn, "lru_cache", "cache"):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                # findings attach to the INNERMOST def so the
                # suppression key names the actual site, but nested
                # defs are only scanned via their own reachability
                # when called; here we scan the whole body — a nested
                # helper inside a reachable function runs per-request
                # too
                name = call_name(node)
                if (name.split(".")[0] == "jnp"
                        and name.rsplit(".", 1)[-1] in _JNP_BUILDERS
                        and any(isinstance(a, _PY_SIZED)
                                for a in node.args)):
                    findings.append(Finding(
                        CHECKER, "jnp-over-k", rel, node.lineno,
                        mod.qualname(node),
                        f"{name} over a Python-sized sequence in a "
                        "per-request path — a fresh tiny XLA program "
                        "per distinct K; assemble operands in numpy "
                        "and convert once (the PR 9 serving lesson, "
                        "docs/SERVING.md)"))
                elif _is_jit_call(node):
                    findings.append(Finding(
                        CHECKER, "jit-in-request-path", rel,
                        node.lineno, mod.qualname(node),
                        f"{name} inside a function reachable "
                        "per-request builds a fresh jit closure per "
                        "call and retraces every time (the solo-"
                        "retrace trap, docs/SERVING.md); hoist it "
                        "behind an lru_cache keyed on statics only"))

    for rel, mod in memo_modules.items():
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not has_decorator(node, "lru_cache", "cache"):
                continue
            if not _module_jit_refs(node):
                continue        # caches values, not executables — the
                #                 _cached_churn_masks fix pattern
            args = node.args
            params = [a.arg for a in (args.posonlyargs + args.args
                                      + args.kwonlyargs)]
            for p in params:
                if _CONTENT_PARAM.match(p):
                    findings.append(Finding(
                        CHECKER, "content-in-memo-key", rel,
                        node.lineno, mod.qualname(node),
                        f"lru_cache'd executable builder keyed on "
                        f"content-named parameter '{p}' — one "
                        "compiled program per scenario (the "
                        "_cached_churn_masks bug PR 12 deleted); "
                        "strip content upstream and rename the "
                        "parameter '*_static', or cache eager VALUES "
                        "instead of a jit closure"))
                elif _BYZ_PARAM.match(p):
                    findings.append(Finding(
                        CHECKER, "byz-table-in-memo-key", rel,
                        node.lineno, mod.qualname(node),
                        f"lru_cache'd executable builder keyed on "
                        f"byz-program parameter '{p}' — one compiled "
                        "program per adversary scenario, defeating "
                        "the operand discipline (liar content rides "
                        "the step table tail as data, never shape — "
                        "ops/nemesis byz_args); thread the program "
                        "through tabled=True operands or rename the "
                        "parameter '*_static'"))
    # dedup: in rpc modules every def (nested ones included) is a
    # root, and the enclosing function's body walk visits nested-def
    # sites too — the same violation must count once, not once per
    # covering walk.  The message joins the key so two distinct
    # content params on ONE memoized def each keep their finding
    seen, unique = set(), []
    for f in findings:
        k = (f.rule, f.path, f.line, f.symbol, f.message)
        if k not in seen:
            seen.add(k)
            unique.append(f)
    return unique
