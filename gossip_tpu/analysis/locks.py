"""Checker family 2: lock discipline in the rpc/ serving layer.

PAPER.md §L3's handler contract is single-goroutine only because the
reference library guarantees it; our serving layer has real threads
(admission collector, fleet prober, gRPC handler pool) and has already
shipped one real race — the PR 13 batcher shutdown bug, where the
``Closed`` check ran OUTSIDE the queue lock so an admission could
serialize between the stop-flag read and the final drain and strand
its handler forever.  That bug's shape (and its fix's shape) are now
machine-checked:

  * **blocking-under-lock** — a blocking call (``.wait()``,
    ``time.sleep``, thread/process ``.join``, ``os.fsync``,
    subprocess spawns, gRPC stub dispatch, or a ledger emit WITHOUT
    ``sync=False`` — ``Ledger.event`` fsyncs by default) inside a held
    ``threading.Lock`` region stalls every thread contending for that
    lock.  The ``sync=False`` convention on in-lock telemetry
    (rpc/batcher backpressure, rpc/router transitions) is exactly the
    discipline this rule pins.
  * **stopflag-outside-lock** — reading a stop/closed flag OUTSIDE
    the lock that guards the queue it gates, in a method that touches
    the guarded queue (the PR 13 race, planted as a fixture).
  * **lock-order** — the acquisition-order graph over every lock in
    the rpc modules (syntactic ``with`` nesting plus propagation
    through same-class method calls and the ``*_locked`` naming
    convention) must be acyclic; a cycle is a deadlock waiting for
    load.

Under-lock regions propagate through same-class ``self.m()`` calls and
through methods named ``*_locked`` (the repo convention for
"caller holds the lock"); cross-object calls are a boundary — the
analyzer over-approximates reachability, never lock ownership.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from gossip_tpu.analysis.core import (Finding, Module, call_name,
                                      expr_text, keyword_arg)

CHECKER = "locks"

SCOPE = (
    "gossip_tpu/rpc/batcher.py",
    "gossip_tpu/rpc/router.py",
    "gossip_tpu/rpc/sidecar.py",
)

_LOCK_CTORS = ("threading.Lock", "threading.RLock",
               "threading.Condition", "Lock", "RLock", "Condition")
_STOPFLAG_NAME = re.compile(r"stop|clos|shut|halt|quit|done")
_THREADISH = re.compile(r"thread|proc|worker|child", re.I)
_LEDGERISH = re.compile(r"telemetry|ledger|\bled\b", re.I)


def _is_lock_ctor(node) -> bool:
    return (isinstance(node, ast.Call)
            and call_name(node) in _LOCK_CTORS)


class _ClassInfo:
    def __init__(self, name: str):
        self.name = name
        self.locks: Set[str] = set()        # self attrs that are locks
        self.stop_flags: Set[str] = set()   # threading.Event stop/closed
        self.guarded: Set[str] = set()      # attrs mutated under lock
        self.methods: Dict[str, ast.FunctionDef] = {}


def _collect_classes(mod: Module) -> Dict[str, _ClassInfo]:
    out: Dict[str, _ClassInfo] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        info = _ClassInfo(node.name)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.methods[item.name] = item
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Assign):
                continue
            for tgt in sub.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    if _is_lock_ctor(sub.value):
                        info.locks.add(tgt.attr)
                    elif (isinstance(sub.value, ast.Call)
                          and call_name(sub.value) in
                          ("threading.Event", "Event")
                          and _STOPFLAG_NAME.search(tgt.attr)):
                        info.stop_flags.add(tgt.attr)
        out[node.name] = info
    return out


def _module_locks(mod: Module) -> Set[str]:
    out = set()
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


def _lock_id(mod: Module, cls: Optional[_ClassInfo],
             mod_locks: Set[str], ctx_expr) -> Optional[str]:
    """Stable identity of the lock a ``with`` item acquires, or None
    when the expression is not a known lock.  ``Class.attr`` for self
    locks (identically-named classes unify across modules — the
    acquisition ORDER contract is per-type, not per-file);
    ``module:NAME`` for module-level locks."""
    if (isinstance(ctx_expr, ast.Attribute)
            and isinstance(ctx_expr.value, ast.Name)
            and ctx_expr.value.id == "self"
            and cls is not None and ctx_expr.attr in cls.locks):
        return f"{cls.name}.{ctx_expr.attr}"
    if isinstance(ctx_expr, ast.Name) and ctx_expr.id in mod_locks:
        stem = mod.relpath.rsplit("/", 1)[-1].rsplit(".", 1)[0]
        return f"{stem}:{ctx_expr.id}"
    return None


class _LockWalk:
    """Per-module walk computing, for every statement, the stack of
    locks held when it executes (syntactic nesting + same-class call
    propagation + the ``*_locked`` convention)."""

    def __init__(self, mod: Module, classes: Dict[str, _ClassInfo],
                 mod_locks: Set[str]):
        self.mod = mod
        self.classes = classes
        self.mod_locks = mod_locks
        self.findings: List[Finding] = []
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self.lock_sites: Dict[str, Tuple[str, int]] = {}
        # (method qualname) -> lock stack it was entered under; seeds
        # re-walks for propagation
        self._seen: Set[Tuple[str, Tuple[str, ...]]] = set()

    def run(self):
        for cname, cls in self.classes.items():
            for mname, fn in cls.methods.items():
                held: Tuple[str, ...] = ()
                if mname.endswith("_locked") and cls.locks:
                    # convention: caller holds the instance lock(s)
                    held = tuple(f"{cls.name}.{a}"
                                 for a in sorted(cls.locks))
                self._walk_fn(fn, cls, held)
        for node in self.mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_fn(node, None, ())
        return self

    # -- statement walk ------------------------------------------------

    def _walk_fn(self, fn, cls, held: Tuple[str, ...]):
        key = (self.mod.qualname(fn), held)
        if key in self._seen:
            return
        self._seen.add(key)
        for stmt in fn.body:
            self._walk_stmt(stmt, cls, held)

    def _walk_stmt(self, stmt, cls, held: Tuple[str, ...]):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return          # a nested def does not run under the lock
        if isinstance(stmt, ast.With):
            inner = held
            for item in stmt.items:
                lid = _lock_id(self.mod, cls, self.mod_locks,
                               item.context_expr)
                if lid is not None:
                    self.lock_sites.setdefault(
                        lid, (self.mod.relpath, stmt.lineno))
                    for outer in inner:
                        if outer != lid:
                            self.edges.setdefault(
                                (outer, lid),
                                (self.mod.relpath, stmt.lineno))
                    inner = inner + (lid,)
                else:
                    self._scan_expr(item.context_expr, cls, held)
            for sub in stmt.body:
                self._walk_stmt(sub, cls, inner)
            return
        for child in ast.iter_child_nodes(stmt):
            if not isinstance(child, ast.stmt):
                self._scan_expr(child, cls, held)
        for attr in ("body", "orelse", "finalbody"):
            for sub in getattr(stmt, attr, ()):
                if isinstance(sub, ast.stmt):
                    self._walk_stmt(sub, cls, held)
        for handler in getattr(stmt, "handlers", ()):
            for sub in handler.body:
                self._walk_stmt(sub, cls, held)

    # -- expression scan ----------------------------------------------

    def _scan_expr(self, expr, cls, held: Tuple[str, ...]):
        if not isinstance(expr, ast.AST):
            return
        # manual walk skipping nested def/lambda subtrees: a function
        # BUILT under the lock does not RUN under it
        todo = [expr]
        while todo:
            node = todo.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            todo.extend(ast.iter_child_nodes(node))
            if not isinstance(node, ast.Call):
                continue
            if held:
                self._check_blocking(node, held)
            # propagate held locks through same-class self.m() calls
            if (held and cls is not None
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"
                    and node.func.attr in cls.methods):
                self._walk_fn(cls.methods[node.func.attr], cls, held)

    def _check_blocking(self, node: ast.Call, held: Tuple[str, ...]):
        name = call_name(node)
        term = name.rsplit(".", 1)[-1]
        lock = held[-1]
        msg = None
        if name in ("time.sleep", "sleep"):
            msg = f"time.sleep under held {lock}"
        elif term == "wait":
            msg = (f"blocking {name}() under held {lock} — every "
                   "thread contending for the lock stalls with it")
        elif term == "join" and _THREADISH.search(
                expr_text(node.func)):
            msg = f"thread/process join under held {lock}"
        elif name in ("os.fsync", "fsync"):
            msg = f"fsync under held {lock}"
        elif name.startswith("subprocess."):
            msg = f"subprocess spawn under held {lock}"
        elif ".stubs[" in expr_text(node.func):
            msg = (f"RPC dispatch under held {lock} — a slow replica "
                   "would serialize the whole router")
        elif (term in ("event", "gauge", "counter")
              and _LEDGERISH.search(expr_text(node.func))):
            kw = keyword_arg(node, "sync")
            sync_off = (kw is not None
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is False)
            if term == "counter" or not sync_off:
                msg = (f"fsync'd ledger {term}() under held {lock} — "
                       "pass sync=False inside lock regions (the "
                       "rpc/batcher backpressure convention) or emit "
                       "after release")
        if msg is not None:
            self.findings.append(Finding(
                CHECKER, "blocking-under-lock", self.mod.relpath,
                node.lineno, self.mod.qualname(node), msg))


def _check_stopflags(mod: Module, classes: Dict[str, _ClassInfo],
                     mod_locks: Set[str]) -> List[Finding]:
    """The PR 13 rule: a stop/closed-flag READ outside the lock, in a
    method that also touches the lock-guarded queue."""
    findings: List[Finding] = []
    for cls in classes.values():
        if not cls.locks:
            continue
        # guarded attrs: self attrs mutated inside a with-self-lock
        guarded: Set[str] = set()
        for fn in cls.methods.values():
            for node in ast.walk(fn):
                if not isinstance(node, ast.With):
                    continue
                if not any(_lock_id(mod, cls, mod_locks,
                                    i.context_expr)
                           for i in node.items):
                    continue
                for sub in ast.walk(node):
                    tgt = None
                    if isinstance(sub, ast.Assign):
                        tgt = sub.targets[0]
                    elif isinstance(sub, (ast.AugAssign, ast.Delete)):
                        tgt = getattr(sub, "target", None) or \
                            (sub.targets[0] if getattr(
                                sub, "targets", None) else None)
                    elif (isinstance(sub, ast.Call)
                          and isinstance(sub.func, ast.Attribute)
                          and sub.func.attr in ("append", "pop",
                                                "extend", "insert",
                                                "remove", "clear")):
                        tgt = sub.func.value
                    while isinstance(tgt, ast.Subscript):
                        tgt = tgt.value
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                            and tgt.attr not in cls.locks):
                        guarded.add(tgt.attr)
        if not guarded:
            continue
        for mname, fn in cls.methods.items():
            if mname.endswith("_locked"):
                continue
            touches = any(
                isinstance(n, ast.Attribute)
                and isinstance(n.value, ast.Name)
                and n.value.id == "self" and n.attr in guarded
                for n in ast.walk(fn))
            if not touches:
                continue
            # locked line ranges (approximate by with-block extents)
            locked_spans = []
            for node in ast.walk(fn):
                if isinstance(node, ast.With) and any(
                        _lock_id(mod, cls, mod_locks, i.context_expr)
                        for i in node.items):
                    locked_spans.append((node.lineno,
                                         node.end_lineno or node.lineno))
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "is_set"):
                    continue
                recv = node.func.value
                if not (isinstance(recv, ast.Attribute)
                        and isinstance(recv.value, ast.Name)
                        and recv.value.id == "self"
                        and recv.attr in cls.stop_flags):
                    continue
                if any(lo <= node.lineno <= hi
                       for lo, hi in locked_spans):
                    continue
                findings.append(Finding(
                    CHECKER, "stopflag-outside-lock", mod.relpath,
                    node.lineno, mod.qualname(node),
                    f"self.{recv.attr}.is_set() read outside the "
                    f"lock guarding {sorted(guarded)} in a method "
                    "that touches the guarded state — an admission "
                    "can serialize between the flag read and the "
                    "final drain (the PR 13 batcher shutdown race; "
                    "move the check inside the lock, the "
                    "rpc/batcher._admit pattern)"))
    return findings


def _find_cycles(edges: Dict[Tuple[str, str], Tuple[str, int]]):
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    cycles = []
    color: Dict[str, int] = {}
    stack: List[str] = []

    def dfs(u):
        color[u] = 1
        stack.append(u)
        for v in sorted(graph[u]):
            if color.get(v, 0) == 0:
                dfs(v)
            elif color.get(v) == 1:
                cycles.append(stack[stack.index(v):] + [v])
        stack.pop()
        color[u] = 2

    for u in sorted(graph):
        if color.get(u, 0) == 0:
            dfs(u)
    return cycles


def check(modules: Dict[str, Module]) -> List[Finding]:
    findings: List[Finding] = []
    all_edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for rel in sorted(modules):
        mod = modules[rel]
        classes = _collect_classes(mod)
        mod_locks = _module_locks(mod)
        walk = _LockWalk(mod, classes, mod_locks).run()
        findings.extend(walk.findings)
        findings.extend(_check_stopflags(mod, classes, mod_locks))
        for edge, site in walk.edges.items():
            all_edges.setdefault(edge, site)
    for cycle in _find_cycles(all_edges):
        a, b = cycle[0], cycle[1]
        rel, line = all_edges.get((a, b), ("", 1))
        findings.append(Finding(
            CHECKER, "lock-order", rel, line, "",
            "inconsistent lock-acquisition order across the rpc "
            f"modules: cycle {' -> '.join(cycle)} — two threads "
            "taking these locks in opposite orders deadlock under "
            "load; pick one global order (docs/STATIC_ANALYSIS.md)"))
    return findings
