"""Checker family 3: repo contract conventions, machine-checked.

Each rule encodes a convention a past PR learned the hard way
(docs/STATIC_ANALYSIS.md names them all):

  * **ledger-event-kind** — ``Ledger.event(kind, ...)`` takes the
    event name POSITIONALLY; a keyword field named ``kind`` collides
    with it (the rpc/batcher ``req_kind`` rename exists because of
    this).  Any ``.event(..., kind=...)`` call flags.
  * **artifact-writer-provenance** — a tools/ script that writes an
    artifact must embed ``telemetry.provenance()`` (or write through
    a ``Ledger``, which stamps it): the validate_artifacts legacy
    allowlist keeps old FILES green by name, so a tool that never
    learned provenance can silently regenerate unattributed evidence
    forever — the gate must sit on the WRITER, not just the output.
  * **dryrun-budget-row** — every dry-run family measured by
    ``__graft_entry__`` (the ``rec("family", ...)`` calls) needs rows
    in BOTH tools/dryrun_budgets.json tables, and every budget row
    must name a live family: an unbudgeted family ships unguarded, a
    stale row guards nothing.
  * **capability-singleton** — ``check_supported(engine="...")``
    capability strings follow the factory-pair convention (the
    single-device model and its sharded twin declare the same row); a
    string appearing at exactly ONE call site is a typo'd or orphaned
    capability row — the rejection message would name an engine no
    other factory registers.
  * **sync-emit-in-request-path** — every ``.event(...)`` /
    ``.gauge(...)`` reachable from a serving request-path root
    (``Router.dispatch``, the batcher admission/tick scope, the
    sidecar handlers — :data:`REQUEST_PATH_ROOTS`) must pass a
    literal ``sync=False``: one defaulted emit puts an fsync on the
    hot path and the zero-new-fsyncs serving contract
    (docs/OBSERVABILITY.md "Request tracing") dies silently.
    Reachability is the same-module call graph by terminal name —
    the import-free discipline every family here uses.
  * **unattributed-compile** — an executable acquired by a raw
    ``.lower(...).compile(...)`` chain in gossip_tpu scope bypasses
    the ONE attribution chokepoint (utils/compile_cache
    .load_or_compile): no ``xla_compile`` ledger event, no cache
    verdict, no cost/memory attribution — the compile happened but
    the cost plane never saw it (the planner/stream memory probe was
    the live true positive this rule retired).  The chokepoint module
    itself is exempt; a function named ``*_unattributed`` declares a
    reviewed escape (the ``_drain*`` naming-escape convention).
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, List, Optional

from gossip_tpu.analysis.core import (REPO, Finding, Module, call_name,
                                      expr_text, keyword_arg, str_const)

CHECKER = "conventions"

#: .event(kind=...) scope: every module that can hold a ledger emit
EVENT_SCOPE_DIRS = ("gossip_tpu", "tools", "bench.py",
                    "__graft_entry__.py")

#: artifact-writer scope: the tools scripts (helpers prefixed "_" are
#: loaders, not writers, but scanning them is harmless)
TOOLS_DIR = "tools"

GRAFT_ENTRY = "__graft_entry__.py"
BUDGETS_JSON = os.path.join("tools", "dryrun_budgets.json")

_ART_PATH = re.compile(r"(?i)artifacts|\bart\b|_art\(")
_PROV_REFS = ("provenance", "Ledger", "artifact_ledger", "open_ledger")

#: sync-emit-in-request-path roots: per module, the qualnames whose
#: same-module call graph IS the timed serving path.  Router.dispatch
#: covers failover/shed/trace emits (and mark_down/mark_up via the
#: transport-failure branch); the batcher admission + tick scopes
#: cover backpressure/batch/request_trace; the sidecar handlers cover
#: the solo-trace and client-retry emits.
REQUEST_PATH_ROOTS = {
    "gossip_tpu/rpc/router.py": ("Router.dispatch",),
    "gossip_tpu/rpc/batcher.py": ("Batcher._admit", "Batcher._loop"),
    "gossip_tpu/rpc/sidecar.py": ("_run", "_ensemble",
                                  "SidecarClient._call_with_retry"),
}


#: unattributed-compile exemption: the chokepoint is the ONE module
#: allowed to lower and compile directly — everything else routes
#: through it (or carries a ``*_unattributed`` escape name)
UNATTRIBUTED_EXEMPT = ("gossip_tpu/utils/compile_cache.py",)


def check_unattributed_compile(modules: Dict[str, Module]
                               ) -> List[Finding]:
    """``unattributed-compile`` (module doc): flag every
    ``<expr>.lower(...).compile(...)`` acquisition chain outside the
    chokepoint module.  The AST shape is exact — a ``Call`` whose func
    is ``Attribute(attr='compile')`` over a ``Call`` whose func is
    ``Attribute(attr='lower')`` — so string ``.lower()`` calls never
    false-positive (their result is never ``.compile()``d)."""
    findings = []
    for rel in sorted(modules):
        if rel.replace(os.sep, "/") in UNATTRIBUTED_EXEMPT:
            continue
        mod = modules[rel]
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "compile"
                    and isinstance(node.func.value, ast.Call)
                    and isinstance(node.func.value.func, ast.Attribute)
                    and node.func.value.func.attr == "lower"):
                continue
            fn = mod.enclosing_function(node)
            if fn is not None and fn.name.endswith("_unattributed"):
                continue
            findings.append(Finding(
                CHECKER, "unattributed-compile", rel, node.lineno,
                mod.qualname(node),
                "raw .lower().compile() bypasses the attribution "
                "chokepoint — this executable emits no xla_compile "
                "event (no label, no cache verdict, no cost/memory "
                "attribution); acquire it through utils/compile_cache"
                ".load_or_compile(fn, *args, label=...) or name the "
                "enclosing function *_unattributed with a reviewed "
                "reason (docs/STATIC_ANALYSIS.md)"))
    return findings


def check_event_kind(modules: Dict[str, Module]) -> List[Finding]:
    findings = []
    for rel in sorted(modules):
        mod = modules[rel]
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "event"):
                continue
            if keyword_arg(node, "kind") is not None:
                findings.append(Finding(
                    CHECKER, "ledger-event-kind", rel, node.lineno,
                    mod.qualname(node),
                    ".event(kind=...) collides with Ledger.event's "
                    "positional event-name parameter — rename the "
                    "field (the rpc/batcher req_kind convention, "
                    "utils/telemetry.Ledger.event doc)"))
    return findings


def _artifact_writes(mod: Module):
    """Line numbers of writes whose target path looks artifact-bound:
    ``open(<expr>, "w"|"a")`` where the unparsed path expression
    mentions artifacts (ART constants, ``_art(...)`` helpers,
    literal artifacts/ joins)."""
    lines = []
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and call_name(node) in ("open", "os.fdopen")):
            continue
        mode = None
        if len(node.args) >= 2:
            mode = str_const(node.args[1])
        kw = keyword_arg(node, "mode")
        if kw is not None:
            mode = str_const(kw.value)
        if not mode or not any(c in mode for c in "wax"):
            continue
        if node.args and _ART_PATH.search(expr_text(node.args[0])):
            lines.append(node.lineno)
    return lines


def check_artifact_provenance(modules: Dict[str, Module]) -> List[Finding]:
    findings = []
    for rel in sorted(modules):
        mod = modules[rel]
        writes = _artifact_writes(mod)
        if not writes:
            continue
        refs = {n.id for n in ast.walk(mod.tree)
                if isinstance(n, ast.Name)}
        refs |= {n.attr for n in ast.walk(mod.tree)
                 if isinstance(n, ast.Attribute)}
        if any(r in refs for r in _PROV_REFS):
            continue
        findings.append(Finding(
            CHECKER, "artifact-writer-provenance", rel, writes[0], "",
            "writes an artifact but never references telemetry"
            ".provenance()/Ledger — the committed output may ride the "
            "validate_artifacts legacy allowlist, but every "
            "REGENERATION must be attributable (embed provenance "
            "under a 'provenance' key, the tools/roofline.py idiom)"))
    return findings


def check_sync_emit(modules: Dict[str, Module],
                    roots: Optional[Dict[str, tuple]] = None
                    ) -> List[Finding]:
    """``sync-emit-in-request-path`` (module doc): walk the same-module
    call graph from each root qualname by terminal callee name (the
    :func:`gossip_tpu.analysis.core.call_name` convention), and flag
    every ``.event(``/``.gauge(`` call whose ``sync`` keyword is
    absent or not the literal ``False``.  Terminal-name reachability
    over-approximates (a helper shared with a cold path still counts)
    — exactly right for this rule: a shared helper that fsyncs is a
    request-path fsync whenever the hot path reaches it."""
    roots = REQUEST_PATH_ROOTS if roots is None else roots
    findings: List[Finding] = []
    for rel in sorted(roots):
        mod = modules.get(rel)
        if mod is None:
            continue
        by_name: Dict[str, list] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_name.setdefault(node.name, []).append(node)
        work = []
        for qn in roots[rel]:
            term = qn.rsplit(".", 1)[-1]
            work += [fn for fn in by_name.get(term, ())
                     if mod.qualname(fn) == qn]
        seen, flagged = set(), set()
        while work:
            fn = work.pop()
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = call_name(node).rsplit(".", 1)[-1]
                if callee in ("event", "gauge"):
                    kw = keyword_arg(node, "sync")
                    if (kw is not None
                            and isinstance(kw.value, ast.Constant)
                            and kw.value.value is False):
                        continue
                    if node.lineno in flagged:
                        continue
                    flagged.add(node.lineno)
                    findings.append(Finding(
                        CHECKER, "sync-emit-in-request-path", rel,
                        node.lineno, mod.qualname(node),
                        "ledger emit reachable from a request-path "
                        "root without a literal sync=False — one "
                        "defaulted emit fsyncs the timed serving path "
                        "and silently breaks the zero-new-fsyncs "
                        "contract (docs/OBSERVABILITY.md \"Request "
                        "tracing\"; roots: "
                        f"{', '.join(roots[rel])})"))
                elif callee in by_name:
                    work.extend(by_name[callee])
    return findings


def check_dryrun_budgets(root: str = REPO,
                         graft_rel: str = GRAFT_ENTRY,
                         budgets_rel: str = BUDGETS_JSON
                         ) -> List[Finding]:
    findings: List[Finding] = []
    graft_path = os.path.join(root, graft_rel)
    budgets_path = os.path.join(root, budgets_rel)
    if not (os.path.isfile(graft_path) and os.path.isfile(budgets_path)):
        return findings
    with open(graft_path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=graft_path)
    families = set()
    fam_lines = {}
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "rec" and node.args):
            fam = str_const(node.args[0])
            if fam:
                families.add(fam)
                fam_lines.setdefault(fam, node.lineno)
    with open(budgets_path, encoding="utf-8") as f:
        budgets = json.load(f)
    budgets_rel = budgets_rel.replace(os.sep, "/")
    for table in ("steady_ms", "first_warm_ms"):
        rows = budgets.get(table, {})
        for fam in sorted(families - set(rows)):
            findings.append(Finding(
                CHECKER, "dryrun-budget-row", graft_rel,
                fam_lines.get(fam, 1), "",
                f"dry-run family '{fam}' has no {table} row in "
                f"{budgets_rel} — an unbudgeted family ships with no "
                "wall guard (every family gates like-for-like, "
                "docs/OBSERVABILITY.md)"))
        for fam in sorted(set(rows) - families):
            findings.append(Finding(
                CHECKER, "dryrun-budget-row", budgets_rel, 1, "",
                f"{table} row '{fam}' names no live dry-run family "
                "(rec() call in __graft_entry__) — a stale budget "
                "row guards nothing; delete it or restore the "
                "family"))
    return findings


def check_capability_strings(modules: Dict[str, Module]) -> List[Finding]:
    sites: Dict[str, List] = {}
    for rel in sorted(modules):
        mod = modules[rel]
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and call_name(node).rsplit(".", 1)[-1]
                    == "check_supported"):
                continue
            kw = keyword_arg(node, "engine")
            engine = str_const(kw.value) if kw is not None else None
            if engine:
                sites.setdefault(engine, []).append(
                    (rel, node.lineno, mod.qualname(node)))
    findings = []
    for engine, locs in sorted(sites.items()):
        if len(locs) > 1:
            continue
        rel, line, sym = locs[0]
        findings.append(Finding(
            CHECKER, "capability-singleton", rel, line, sym,
            f"capability string engine='{engine}' appears at exactly "
            "one check_supported call site — the factory-pair "
            "convention declares every engine's row in both its "
            "single-device and sharded factories; a singleton is a "
            "typo'd or orphaned capability row"))
    return findings
