"""Orchestrate the checker families over the live tree and emit the
provenance-stamped findings ledger.

``run_tree()`` is the one entry every consumer shares — CLI
``gossip_tpu staticcheck``, tools/staticcheck.py (CI / hw_refresh
step), the dry-run staticcheck step, and tests/test_staticcheck.py's
clean-tree gate — so the scope tables and baseline application cannot
drift between them.  Pure stdlib: importing this module never imports
jax (the analyzer must run on a wedged-tunnel box).

Ledger schema (docs/OBSERVABILITY.md):

  * the usual ``provenance`` first line (telemetry.artifact_ledger);
  * one ``checker`` event per family: ``{checker, findings,
    suppressed}`` counts;
  * one ``finding`` event per live finding (rule/path/line/symbol/
    message) — dirty runs leave mechanically checkable evidence;
  * a final ``staticcheck`` verdict event: ``{verdict: clean|dirty,
    findings, suppressed, baseline_entries, files_scanned}`` — the
    committed artifacts/ledger_staticcheck_r19.jsonl pins it tier-1.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional

from gossip_tpu.analysis import conventions, locks, recompile
from gossip_tpu.analysis.core import (BASELINE_PATH, REPO, Finding,
                                      apply_baseline, iter_py_files,
                                      load_baseline, load_modules)

FAMILIES = ("recompile", "locks", "conventions", "baseline")


@dataclasses.dataclass
class Report:
    findings: List[Finding]          # live (unsuppressed) findings
    suppressed: List[Finding]        # baselined, rationale on file
    baseline_entries: int
    files_scanned: int

    @property
    def clean(self) -> bool:
        return not self.findings

    def counts(self) -> Dict[str, Dict[str, int]]:
        out = {fam: {"findings": 0, "suppressed": 0}
               for fam in FAMILIES}
        for f in self.findings:
            out.setdefault(f.checker,
                           {"findings": 0, "suppressed": 0})[
                "findings"] += 1
        for f in self.suppressed:
            out.setdefault(f.checker,
                           {"findings": 0, "suppressed": 0})[
                "suppressed"] += 1
        return out


def run_tree(root: str = REPO,
             baseline_path: Optional[str] = None) -> Report:
    """All four checker families over the tree at ``root`` with the
    committed suppression baseline applied.  ``baseline_path=None``
    uses tools/staticcheck_baseline.json under ``root``; pass "" to
    run baseline-free (the raw-findings view)."""
    if baseline_path is None:
        baseline_path = os.path.join(root, BASELINE_PATH)

    # parse every in-scope file exactly ONCE and hand the checkers
    # filtered views — the scopes overlap heavily (rpc/ and sweep are
    # inside both the serving and memo/event sets), and Module's
    # parent-map construction is the analyzer's dominant cost
    memo_files = list(iter_py_files(root, ("gossip_tpu",)))
    event_files = list(iter_py_files(root, conventions.EVENT_SCOPE_DIRS))
    tool_files = list(iter_py_files(root, (conventions.TOOLS_DIR,)))
    all_mods = load_modules(
        root, sorted(set(memo_files) | set(event_files)
                     | set(tool_files) | set(recompile.SCOPE)
                     | set(recompile.STREAM_SCOPE)
                     | set(locks.SCOPE)))

    def view(paths):
        return {p: all_mods[p] for p in paths if p in all_mods}

    serving = view(recompile.SCOPE)
    stream = view(recompile.STREAM_SCOPE)
    memo = view(memo_files)
    rpc = view(locks.SCOPE)
    event_mods = view(event_files)
    tool_mods = view(tool_files)

    findings: List[Finding] = []
    findings += recompile.check(serving, memo)
    findings += recompile.check_stream_fetch(stream)
    findings += locks.check(rpc)
    findings += conventions.check_event_kind(event_mods)
    findings += conventions.check_sync_emit(event_mods)
    findings += conventions.check_artifact_provenance(tool_mods)
    findings += conventions.check_dryrun_budgets(root)
    findings += conventions.check_capability_strings(memo)
    findings += conventions.check_unattributed_compile(memo)

    entries, problems = (load_baseline(baseline_path)
                         if baseline_path else ([], []))
    live, suppressed, stale = apply_baseline(findings, entries)
    live = sorted(live + problems + stale,
                  key=lambda f: (f.path, f.line, f.rule))
    scanned = set(memo_files) | set(event_files) | set(tool_files) \
        | set(serving) | set(rpc)
    return Report(findings=live, suppressed=suppressed,
                  baseline_entries=len(entries),
                  files_scanned=len(scanned))


def write_ledger(report: Report, path: str) -> None:
    """The findings ledger (module doc schema) through the one shared
    provenance-stamping helper — the same writer discipline as
    tests/conftest.py's duration ledger, by construction."""
    from gossip_tpu.utils import telemetry
    with telemetry.artifact_ledger(path) as led:
        for fam, cnt in sorted(report.counts().items()):
            led.event("checker", checker=fam, **cnt)
        for f in report.findings:
            led.event("finding", checker=f.checker, rule=f.rule,
                      path=f.path, line=f.line, symbol=f.symbol,
                      message=f.message)
        led.event("staticcheck",
                  verdict="clean" if report.clean else "dirty",
                  findings=len(report.findings),
                  suppressed=len(report.suppressed),
                  baseline_entries=report.baseline_entries,
                  files_scanned=report.files_scanned)


def main(argv: Optional[List[str]] = None) -> int:
    """CLI body shared by ``gossip_tpu staticcheck`` and
    tools/staticcheck.py: print findings (one line each), optionally
    write the ledger, exit 0 iff clean."""
    import argparse
    import json as _json

    ap = argparse.ArgumentParser(
        prog="gossip_tpu staticcheck",
        description="AST invariant analyzer: recompile-hazard lint, "
                    "rpc lock discipline, convention gates "
                    "(docs/STATIC_ANALYSIS.md)")
    ap.add_argument("--root", default=REPO,
                    help="tree to analyze (default: this repo)")
    ap.add_argument("--baseline", default=None, metavar="JSON",
                    help="suppression baseline (default: tools/"
                         "staticcheck_baseline.json under --root; "
                         "'' disables)")
    ap.add_argument("--ledger", default=None, metavar="PATH",
                    help="write the provenance-stamped findings "
                         "ledger here")
    ap.add_argument("--json", action="store_true",
                    help="emit one summary JSON line instead of "
                         "per-finding text")
    a = ap.parse_args(argv)
    report = run_tree(a.root, a.baseline)
    if a.ledger:
        write_ledger(report, a.ledger)
    counts = report.counts()
    if a.json:
        print(_json.dumps({
            "verdict": "clean" if report.clean else "dirty",
            "findings": len(report.findings),
            "suppressed": len(report.suppressed),
            "baseline_entries": report.baseline_entries,
            "files_scanned": report.files_scanned,
            "counts": counts,
            **({"ledger": a.ledger} if a.ledger else {})}))
    else:
        for f in report.findings:
            print(f.render())
        print(f"staticcheck: {len(report.findings)} finding(s), "
              f"{len(report.suppressed)} baselined "
              f"(rationales on file), {report.files_scanned} files — "
              + ("clean" if report.clean else "DIRTY"))
    return 0 if report.clean else 1
