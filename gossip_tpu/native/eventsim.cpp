// Native discrete-event core for the go-native parity backend.
//
// Mirrors gossip_tpu/runtime/gonative.py event-for-event (that module's
// docstring is the semantics contract; both implement reference
// main.go:65-158 behavior — ack-before-process, dedup, sender exclusion,
// sequential blocking fan-out, the per-neighbor 2s-context retry loop with
// the reference's ctx-expiry liveness defect toggleable).  The Python
// implementation stays as the readable reference and CPU fallback; this
// core exists because parity sweeps at N=1024+ with many messages are
// event-throughput-bound in Python (~1e5 events/s) while this runs ~1e7/s.
//
// Equivalence is enforced by tests/test_native.py: identical deliveries,
// message counts, hop depths, and logs on shared scenarios, including
// partition windows and both ctx-bug modes.
//
// Exposed as a C API for ctypes (no pybind11 in this environment).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <queue>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

struct Event {
  double t;
  uint64_t seq;
  // kind 0: deliver(dst, src, msg, hop)
  // kind 1: fanout(src, msg, hop, tgt_list_id, idx, attempt, ctx_start)
  int kind;
  int32_t a, b;     // deliver: dst, src       | fanout: src, tgt_list_id
  int64_t msg;
  int32_t hop;
  int32_t idx, attempt;
  double ctx_start;
};

struct EventCmp {
  bool operator()(const Event& x, const Event& y) const {
    if (x.t != y.t) return x.t > y.t;   // min-heap by (t, seq)
    return x.seq > y.seq;
  }
};

struct Delivery {
  double t;
  int32_t node;
  int64_t msg;
  int32_t hop;
};

struct Partition {
  int32_t a, b;
  double t0, t1;
};

struct Sim {
  // config (defaults match gonative.NetConfig)
  double latency = 0.001;
  double rpc_timeout = 2.0;
  double backoff_base = 0.1;
  bool faithful_ctx_bug = true;
  int max_backoff_doublings = 40;
  double horizon = 120.0;

  int n = 0;
  std::vector<std::vector<int32_t>> neighbors;
  std::vector<std::vector<int64_t>> log;
  std::vector<std::unordered_set<int64_t>> seen;
  std::vector<Partition> partitions;
  std::priority_queue<Event, std::vector<Event>, EventCmp> q;
  uint64_t seq = 0;
  int64_t msgs_sent = 0;
  double now = 0.0;
  std::vector<Delivery> deliveries;
  // (node, msg) -> min hop over all arrivals (dedup'd arrivals included)
  std::unordered_map<int64_t, std::unordered_map<int64_t, int32_t>> min_hop;
  // fan-out target lists are interned so events stay POD
  std::vector<std::vector<int32_t>> tgt_lists;

  bool link_open(int32_t a, int32_t b, double t) const {
    for (const auto& p : partitions) {
      if (((p.a == a && p.b == b) || (p.a == b && p.b == a)) &&
          p.t0 <= t && t < p.t1)
        return false;
    }
    return true;
  }

  void push(double t, Event e) {
    // mirror gonative._push_event: never drop — run() bounds the clock
    e.t = t;
    e.seq = seq++;
    q.push(e);
  }

  void deliver(double t, int32_t dst, int32_t src, int64_t msg, int32_t hop) {
    msgs_sent += 2;   // the broadcast request + the ack sent FIRST
    auto& mh = min_hop[dst];
    auto it = mh.find(msg);
    if (it == mh.end() || hop < it->second) mh[msg] = hop;
    auto& s = seen[dst];
    if (s.count(msg)) return;                    // dedup (main.go:113)
    s.insert(msg);
    log[dst].push_back(msg);                     // append (main.go:117)
    deliveries.push_back({t, dst, msg, hop});
    // fan-out, excluding the sender (main.go:72-75)
    std::vector<int32_t> targets;
    for (int32_t nb : neighbors[dst])
      if (nb != src) targets.push_back(nb);
    if (!targets.empty()) {
      tgt_lists.push_back(std::move(targets));
      Event e{};
      e.kind = 1;
      e.a = dst;
      e.b = static_cast<int32_t>(tgt_lists.size() - 1);
      e.msg = msg;
      e.hop = hop;
      e.idx = 0;
      e.attempt = 0;
      e.ctx_start = t;
      push(t, e);
    }
  }

  void fanout(double t, int32_t src, int32_t list_id, int64_t msg,
              int32_t hop, int32_t idx, int32_t attempt, double ctx_start) {
    const auto& targets = tgt_lists[list_id];
    if (idx >= static_cast<int32_t>(targets.size())) return;
    int32_t nb = targets[idx];
    double deadline = ctx_start + rpc_timeout;
    if (link_open(src, nb, t)) {
      Event d{};
      d.kind = 0;
      d.a = nb;
      d.b = src;
      d.msg = msg;
      d.hop = hop + 1;
      push(t + latency, d);
      if (t + 2 * latency <= deadline) {
        Event nxt{};
        nxt.kind = 1;
        nxt.a = src;
        nxt.b = list_id;
        nxt.msg = msg;
        nxt.hop = hop;
        nxt.idx = idx + 1;
        nxt.attempt = 0;
        nxt.ctx_start = t + 2 * latency;
        push(t + 2 * latency, nxt);
        return;
      }
    }
    double fail_at = t < deadline ? deadline : t;
    int k = attempt < max_backoff_doublings ? attempt : max_backoff_doublings;
    double retry_at = fail_at + backoff_base * std::pow(2.0, k);
    Event r{};
    r.kind = 1;
    r.a = src;
    r.b = list_id;
    r.msg = msg;
    r.hop = hop;
    r.idx = idx;
    r.attempt = attempt + 1;
    r.ctx_start = faithful_ctx_bug ? ctx_start : retry_at;
    push(retry_at, r);
  }

  void run(double until) {
    while (!q.empty() && q.top().t <= until) {
      Event e = q.top();
      q.pop();
      now = e.t;
      if (e.kind == 0)
        deliver(e.t, e.a, e.b, e.msg, e.hop);
      else
        fanout(e.t, e.a, e.b, e.msg, e.hop, e.idx, e.attempt, e.ctx_start);
    }
  }
};

}  // namespace

extern "C" {

void* gsim_create(int32_t n) {
  Sim* s = new Sim();
  s->n = n;
  s->neighbors.resize(n);
  s->log.resize(n);
  s->seen.resize(n);
  return s;
}

void gsim_destroy(void* p) { delete static_cast<Sim*>(p); }

void gsim_config(void* p, double latency, double rpc_timeout,
                 double backoff_base, int32_t faithful,
                 int32_t max_doublings, double horizon) {
  Sim* s = static_cast<Sim*>(p);
  s->latency = latency;
  s->rpc_timeout = rpc_timeout;
  s->backoff_base = backoff_base;
  s->faithful_ctx_bug = faithful != 0;
  s->max_backoff_doublings = max_doublings;
  s->horizon = horizon;
}

void gsim_set_neighbors(void* p, int32_t node, const int32_t* nbrs,
                        int32_t count) {
  Sim* s = static_cast<Sim*>(p);
  s->neighbors[node].assign(nbrs, nbrs + count);
}

void gsim_partition(void* p, int32_t a, int32_t b, double t0, double t1) {
  static_cast<Sim*>(p)->partitions.push_back({a, b, t0, t1});
}

void gsim_broadcast(void* p, int32_t origin, int64_t msg, double t) {
  Sim* s = static_cast<Sim*>(p);
  Event e{};
  e.kind = 0;
  e.a = origin;
  e.b = -1;                     // client src: excluded from nothing
  e.msg = msg;
  e.hop = 0;
  s->push(t, e);
}

void gsim_run(void* p, double until) {
  Sim* s = static_cast<Sim*>(p);
  s->run(until < 0 ? s->horizon : until);
}

int64_t gsim_msgs_sent(void* p) { return static_cast<Sim*>(p)->msgs_sent; }
double gsim_now(void* p) { return static_cast<Sim*>(p)->now; }

int32_t gsim_read_len(void* p, int32_t node) {
  return static_cast<int32_t>(static_cast<Sim*>(p)->log[node].size());
}

void gsim_read(void* p, int32_t node, int64_t* out) {
  const auto& l = static_cast<Sim*>(p)->log[node];
  std::memcpy(out, l.data(), l.size() * sizeof(int64_t));
}

int32_t gsim_min_hop(void* p, int32_t node, int64_t msg) {
  Sim* s = static_cast<Sim*>(p);
  auto nit = s->min_hop.find(node);
  if (nit == s->min_hop.end()) return -1;
  auto mit = nit->second.find(msg);
  return mit == nit->second.end() ? -1 : mit->second;
}

int32_t gsim_delivery_count(void* p) {
  return static_cast<int32_t>(static_cast<Sim*>(p)->deliveries.size());
}

void gsim_deliveries(void* p, double* times, int32_t* nodes, int64_t* msgs,
                     int32_t* hops) {
  const auto& d = static_cast<Sim*>(p)->deliveries;
  for (size_t i = 0; i < d.size(); ++i) {
    times[i] = d[i].t;
    nodes[i] = d[i].node;
    msgs[i] = d[i].msg;
    hops[i] = d[i].hop;
  }
}

}  // extern "C"
