"""Native (C++) runtime components, loaded via ctypes.

Built on demand by :func:`load_eventsim` itself — a single ``g++ -O2
-shared`` subprocess invocation (no pybind11 in this environment; the
Python<->C boundary is a flat C API).  ``load_eventsim()`` returns the
shared library handle or None when no compiler is available — callers fall
back to the pure-Python implementation.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libeventsim.so")
_SRC = os.path.join(_DIR, "eventsim.cpp")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def build_native(src: str, out: str, shared: bool = True) -> bool:
    """One g++ invocation: compile ``src`` to ``out`` (shared lib or
    binary) via a per-process temp path + os.replace, so concurrent
    builders (parallel pytest workers, two CLIs on a fresh checkout) can
    never interleave writes into a torn artifact.  Shared by the event
    sim (.so) and the native router (binary)."""
    tmp = f"{out}.{os.getpid()}.tmp"
    cmd = ["g++", "-O2", "-std=c++17"]
    if shared:
        cmd += ["-shared", "-fPIC"]
    cmd += [src, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
        return True
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def native_fresh(src: str, out: str) -> bool:
    """True when ``out`` exists and is at least as new as ``src``."""
    return (os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src))


def _build() -> bool:
    return build_native(_SRC, _SO, shared=True)


def load_eventsim() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the event-sim core; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not native_fresh(_SRC, _SO) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            # stale/truncated/wrong-arch .so (e.g. an interrupted build
            # left a fresh mtime): rebuild once, else fall back to Python
            if not _build():
                return None
            try:
                lib = ctypes.CDLL(_SO)
            except OSError:
                return None
        c = ctypes
        lib.gsim_create.restype = c.c_void_p
        lib.gsim_create.argtypes = [c.c_int32]
        lib.gsim_destroy.argtypes = [c.c_void_p]
        lib.gsim_config.argtypes = [c.c_void_p, c.c_double, c.c_double,
                                    c.c_double, c.c_int32, c.c_int32,
                                    c.c_double]
        lib.gsim_set_neighbors.argtypes = [c.c_void_p, c.c_int32,
                                           c.POINTER(c.c_int32), c.c_int32]
        lib.gsim_partition.argtypes = [c.c_void_p, c.c_int32, c.c_int32,
                                       c.c_double, c.c_double]
        lib.gsim_broadcast.argtypes = [c.c_void_p, c.c_int32, c.c_int64,
                                       c.c_double]
        lib.gsim_run.argtypes = [c.c_void_p, c.c_double]
        lib.gsim_msgs_sent.restype = c.c_int64
        lib.gsim_msgs_sent.argtypes = [c.c_void_p]
        lib.gsim_now.restype = c.c_double
        lib.gsim_now.argtypes = [c.c_void_p]
        lib.gsim_read_len.restype = c.c_int32
        lib.gsim_read_len.argtypes = [c.c_void_p, c.c_int32]
        lib.gsim_read.argtypes = [c.c_void_p, c.c_int32,
                                  c.POINTER(c.c_int64)]
        lib.gsim_min_hop.restype = c.c_int32
        lib.gsim_min_hop.argtypes = [c.c_void_p, c.c_int32, c.c_int64]
        lib.gsim_delivery_count.restype = c.c_int32
        lib.gsim_delivery_count.argtypes = [c.c_void_p]
        lib.gsim_deliveries.argtypes = [c.c_void_p, c.POINTER(c.c_double),
                                        c.POINTER(c.c_int32),
                                        c.POINTER(c.c_int64),
                                        c.POINTER(c.c_int32)]
        _lib = lib
        return _lib
