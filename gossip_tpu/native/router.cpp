// Native mini-Maelstrom router: the L-1 harness as a standalone C++ binary.
//
// The reference was tested by the external Maelstrom harness — a process
// orchestrator that spawns N copies of the node binary, routes newline-
// delimited JSON envelopes between their stdin/stdout pipes, injects
// latency and partitions, and checks the broadcast workload's invariant
// (SURVEY.md §1 L-1, §4).  The Python twin lives in
// runtime/maelstrom_harness.py; this file is the NATIVE twin: same
// envelope protocol, same workload, same checker semantics, one poll()
// event loop, zero dependencies.  Build + drive via
// runtime/native_router.py; equivalence against the Python harness is
// tested in tests/test_native_router.py.
//
// Usage:
//   router --n 5 --latency-ms 2 --ops 20 --rate 50 --topology line \
//          [--partition] [--seed 0] -- python -m gossip_tpu.runtime.maelstrom_node
//
// Prints one JSON stats line (msgs routed, per-op latencies, invariant)
// and exits 0 iff every broadcast value eventually appears in every
// node's read (the Maelstrom checker's invariant).

#include <cerrno>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <fcntl.h>
#include <poll.h>
#include <signal.h>
#include <sys/wait.h>
#include <unistd.h>

#include <algorithm>
#include <map>
#include <memory>
#include <queue>
#include <set>
#include <string>
#include <vector>

// ---------------------------------------------------------------- util --
static double now_s() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + 1e-9 * ts.tv_nsec;
}

[[noreturn]] static void die(const std::string& msg) {
  fprintf(stderr, "router: %s\n", msg.c_str());
  exit(2);
}

// ------------------------------------------------- minimal JSON reader --
// Machine-generated JSON only (the nodes emit json.dumps output).  Parses
// the full value tree; numbers as double (msg ids / payloads fit).
struct JV {
  enum T { NUL, BOO, NUM, STR, ARR, OBJ } t = NUL;
  bool b = false;
  double num = 0;
  std::string str;
  std::vector<JV> arr;
  std::map<std::string, JV> obj;

  const JV* get(const std::string& k) const {
    if (t != OBJ) return nullptr;
    auto it = obj.find(k);
    return it == obj.end() ? nullptr : &it->second;
  }
};

struct JParser {
  const char* p;
  const char* end;
  bool ok = true;

  explicit JParser(const std::string& s) : p(s.data()), end(s.data() + s.size()) {}

  void ws() { while (p < end && (*p == ' ' || *p == '\t' || *p == '\r' || *p == '\n')) p++; }
  bool lit(const char* s) {
    size_t l = strlen(s);
    if ((size_t)(end - p) >= l && !strncmp(p, s, l)) { p += l; return true; }
    return false;
  }

  JV parse() { ws(); JV v = value(); ws(); if (p != end) ok = false; return v; }

  JV value() {
    ws();
    if (p >= end) { ok = false; return {}; }
    switch (*p) {
      case '{': return object();
      case '[': return array();
      case '"': { JV v; v.t = JV::STR; v.str = string(); return v; }
      case 't': { JV v; v.t = JV::BOO; v.b = true; if (!lit("true")) ok = false; return v; }
      case 'f': { JV v; v.t = JV::BOO; v.b = false; if (!lit("false")) ok = false; return v; }
      case 'n': { JV v; if (!lit("null")) ok = false; return v; }
      default: return number();
    }
  }

  std::string string() {
    std::string out;
    p++;                                   // opening quote
    while (p < end && *p != '"') {
      if (*p == '\\' && p + 1 < end) {
        p++;
        switch (*p) {
          case 'n': out += '\n'; break;
          case 't': out += '\t'; break;
          case 'r': out += '\r'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'u': {                       // \uXXXX: raw byte for BMP ASCII
            if (end - p >= 5) {
              unsigned code = strtoul(std::string(p + 1, p + 5).c_str(), nullptr, 16);
              if (code < 0x80) out += (char)code; else out += '?';
              p += 4;
            } else ok = false;
            break;
          }
          default: out += *p;
        }
      } else out += *p;
      p++;
    }
    if (p < end) p++; else ok = false;      // closing quote
    return out;
  }

  JV number() {
    char* e = nullptr;
    JV v; v.t = JV::NUM;
    v.num = strtod(p, &e);
    if (e == p) { ok = false; return v; }
    p = e;
    return v;
  }

  JV array() {
    JV v; v.t = JV::ARR;
    p++; ws();
    if (p < end && *p == ']') { p++; return v; }
    while (p < end) {
      v.arr.push_back(value()); ws();
      if (p < end && *p == ',') { p++; continue; }
      if (p < end && *p == ']') { p++; return v; }
      break;
    }
    ok = false; return v;
  }

  JV object() {
    JV v; v.t = JV::OBJ;
    p++; ws();
    if (p < end && *p == '}') { p++; return v; }
    while (p < end) {
      ws();
      if (p >= end || *p != '"') break;
      std::string k = string(); ws();
      if (p >= end || *p != ':') break;
      p++;
      v.obj[k] = value(); ws();
      if (p < end && *p == ',') { p++; continue; }
      if (p < end && *p == '}') { p++; return v; }
      break;
    }
    ok = false; return v;
  }
};

// ------------------------------------------------------------ children --
struct Node {
  std::string id;
  pid_t pid = -1;
  int to_fd = -1;      // our write end -> node stdin (nonblocking)
  int from_fd = -1;    // our read end  <- node stdout (nonblocking)
  std::string buf;     // partial-line read buffer
  std::string outq;    // pending bytes for the node's stdin — writes are
                       // nonblocking + queued so a node stalled on its
                       // own full stdout can never deadlock the router
};

static void try_flush(Node& nd) {
  while (!nd.outq.empty()) {
    ssize_t w = write(nd.to_fd, nd.outq.data(), nd.outq.size());
    if (w > 0) { nd.outq.erase(0, (size_t)w); continue; }
    if (w < 0 && errno == EINTR) continue;
    if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return;
    nd.outq.clear();                       // dead node: drop its queue
    return;
  }
}

static void enqueue(Node& nd, const std::string& s) {
  nd.outq += s;
  try_flush(nd);
}

// ------------------------------------------------------------- router --
struct Delivery {
  double at;
  int dest;
  std::string line;
  bool operator>(const Delivery& o) const { return at > o.at; }
};

struct Router {
  std::vector<Node> nodes;
  std::map<std::string, int> by_id;
  double latency = 0.002;
  long routed = 0;
  double last_activity = 0;
  // one partition window (a, b, t0, t1), both directions
  int part_a = -1, part_b = -1;
  double part_t0 = 0, part_t1 = 0;
  std::priority_queue<Delivery, std::vector<Delivery>, std::greater<Delivery>> delayed;
  long next_msg_id = 1000;
  // pending client RPC: msg_id -> reply (filled by pump)
  std::map<long, JV> replies;

  bool link_open(int a, int b, double t) const {
    if (part_a < 0) return true;
    bool cut = ((a == part_a && b == part_b) || (a == part_b && b == part_a));
    return !(cut && t >= part_t0 && t < part_t1);
  }

  void flush_delayed(double t) {
    while (!delayed.empty() && delayed.top().at <= t) {
      const Delivery& d = delayed.top();
      enqueue(nodes[d.dest], d.line);
      delayed.pop();
    }
  }

  // Read whatever is available on node stdouts, drain pending stdin
  // queues; route node->node traffic, stash client replies.  Returns
  // after at most max_wait_s.
  void pump(double max_wait_s) {
    double t = now_s();
    flush_delayed(t);
    double wait = max_wait_s;
    if (!delayed.empty())
      wait = std::min(wait, std::max(0.0, delayed.top().at - t));
    std::vector<pollfd> fds(nodes.size());
    for (size_t i = 0; i < nodes.size(); i++) {
      fds[i] = {nodes[i].from_fd, POLLIN, 0};
      if (!nodes[i].outq.empty())
        fds.push_back({nodes[i].to_fd, POLLOUT, 0});
    }
    int rc = poll(fds.data(), fds.size(), (int)(wait * 1000));
    if (rc <= 0) { flush_delayed(now_s()); return; }
    // writable stdin queues first: frees nodes blocked on their input
    for (size_t k = nodes.size(); k < fds.size(); k++)
      if (fds[k].revents & (POLLOUT | POLLERR))
        for (auto& nd : nodes)
          if (nd.to_fd == fds[k].fd) { try_flush(nd); break; }
    char tmp[65536];
    for (size_t i = 0; i < nodes.size(); i++) {
      if (!(fds[i].revents & (POLLIN | POLLHUP))) continue;
      ssize_t r = read(nodes[i].from_fd, tmp, sizeof tmp);
      if (r <= 0) continue;
      nodes[i].buf.append(tmp, (size_t)r);
      size_t pos;
      while ((pos = nodes[i].buf.find('\n')) != std::string::npos) {
        std::string line = nodes[i].buf.substr(0, pos + 1);
        nodes[i].buf.erase(0, pos + 1);
        route(line);
      }
    }
    flush_delayed(now_s());
  }

  void route(const std::string& line) {
    JParser jp(line);
    JV msg = jp.parse();
    if (!jp.ok || msg.t != JV::OBJ) return;
    const JV* dest = msg.get("dest");
    if (!dest || dest->t != JV::STR) return;
    last_activity = now_s();
    if (dest->str == "c1") {
      const JV* body = msg.get("body");
      const JV* irt = body ? body->get("in_reply_to") : nullptr;
      if (irt && irt->t == JV::NUM) replies[(long)irt->num] = msg;
      return;
    }
    auto it = by_id.find(dest->str);
    if (it == by_id.end()) return;
    const JV* src = msg.get("src");
    int s = -1;
    if (src && src->t == JV::STR) {
      auto sit = by_id.find(src->str);
      if (sit != by_id.end()) s = sit->second;
    }
    double t = now_s();
    if (s >= 0 && !link_open(s, it->second, t)) return;   // dropped in cut
    routed++;
    delayed.push({t + latency, it->second, line});
  }

  // Blocking client RPC that pumps the loop until the reply arrives.
  JV rpc(int dest, const std::string& body_json, double timeout) {
    long mid = ++next_msg_id;
    char head[256];
    snprintf(head, sizeof head, "{\"src\": \"c1\", \"dest\": \"%s\", \"body\": ",
             nodes[dest].id.c_str());
    // splice msg_id into the body object (body_json ends with '}')
    std::string body = body_json.substr(0, body_json.size() - 1);
    if (body.back() != '{') body += ", ";
    body += "\"msg_id\": " + std::to_string(mid) + "}";
    enqueue(nodes[dest], std::string(head) + body + "}\n");
    double deadline = now_s() + timeout;
    while (now_s() < deadline) {
      auto it = replies.find(mid);
      if (it != replies.end()) {
        JV r = it->second;
        replies.erase(it);
        return r;
      }
      pump(0.01);
    }
    return {};                                   // NUL on timeout
  }
};

// ------------------------------------------------------------ workload --
int main(int argc, char** argv) {
  int n = 5, ops = 20, seed = 0;
  double latency_ms = 2.0, rate = 50.0;
  std::string topology = "line";
  bool partition = false;
  std::vector<char*> cmd;
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&]() -> char* { if (i + 1 >= argc) die("missing value for " + a); return argv[++i]; };
    if (a == "--n") n = atoi(next());
    else if (a == "--latency-ms") latency_ms = atof(next());
    else if (a == "--ops") ops = atoi(next());
    else if (a == "--rate") rate = atof(next());
    else if (a == "--topology") topology = next();
    else if (a == "--partition") partition = true;
    else if (a == "--seed") seed = atoi(next());
    else if (a == "--") { for (int j = i + 1; j < argc; j++) cmd.push_back(argv[j]); break; }
    else die("unknown arg " + a);
  }
  if (cmd.empty()) die("node command required after --");
  if (n < 1 || ops < 1 || rate <= 0) die("bad workload parameters");
  cmd.push_back(nullptr);
  signal(SIGPIPE, SIG_IGN);

  Router rt;
  rt.latency = latency_ms / 1e3;
  for (int i = 0; i < n; i++) {
    Node nd;
    nd.id = "n" + std::to_string(i);
    int in_pipe[2], out_pipe[2];
    if (pipe(in_pipe) || pipe(out_pipe)) die("pipe failed");
    pid_t pid = fork();
    if (pid < 0) die("fork failed");
    if (pid == 0) {
      dup2(in_pipe[0], 0);
      dup2(out_pipe[1], 1);
      close(in_pipe[0]); close(in_pipe[1]);
      close(out_pipe[0]); close(out_pipe[1]);
      execvp(cmd[0], cmd.data());
      _exit(127);
    }
    close(in_pipe[0]); close(out_pipe[1]);
    fcntl(out_pipe[0], F_SETFL, O_NONBLOCK);
    fcntl(in_pipe[1], F_SETFL, O_NONBLOCK);
    // CLOEXEC: later-forked siblings must not inherit this node's
    // parent-side pipe ends.  The load-bearing one is in_pipe[1] (the
    // write end of this node's STDIN pipe): a sibling holding it would
    // keep the node from ever seeing EOF after the router closes to_fd.
    // (POLLHUP on a crashed node's stdout was never at risk — the
    // parent closes out_pipe[1] above, before any later fork.)
    fcntl(out_pipe[0], F_SETFD, FD_CLOEXEC);
    fcntl(in_pipe[1], F_SETFD, FD_CLOEXEC);
    nd.pid = pid;
    nd.to_fd = in_pipe[1];
    nd.from_fd = out_pipe[0];
    rt.by_id[nd.id] = i;
    rt.nodes.push_back(nd);
  }

  // init handshake
  std::string ids_json;
  for (int i = 0; i < n; i++)
    ids_json += (i ? ", " : "") + ("\"" + rt.nodes[i].id + "\"");
  for (int i = 0; i < n; i++) {
    JV r = rt.rpc(i, "{\"type\": \"init\", \"node_id\": \"" + rt.nodes[i].id +
                     "\", \"node_ids\": [" + ids_json + "]}", 15.0);
    const JV* b = r.get("body");
    const JV* ty = b ? b->get("type") : nullptr;
    if (!ty || ty->str != "init_ok") die("init failed for " + rt.nodes[i].id);
  }

  // topology (line or square-ish grid), sent to every node
  int cols = topology == "grid" ? std::max(1, (int)std::lround(std::sqrt((double)n))) : 1;
  std::vector<std::vector<int>> nbrs(n);
  for (int i = 0; i < n; i++) {
    if (topology == "grid") {
      int r = i / cols, c = i % cols;
      int cand[4][2] = {{r - 1, c}, {r + 1, c}, {r, c - 1}, {r, c + 1}};
      for (auto& rc : cand) {
        int j = rc[0] * cols + rc[1];
        if (rc[0] >= 0 && rc[1] >= 0 && rc[1] < cols && j >= 0 && j < n && rc[0] <= (n - 1) / cols)
          nbrs[i].push_back(j);
      }
    } else {
      if (i > 0) nbrs[i].push_back(i - 1);
      if (i < n - 1) nbrs[i].push_back(i + 1);
    }
  }
  std::string topo_json = "{";
  for (int i = 0; i < n; i++) {
    topo_json += (i ? ", " : "") + ("\"" + rt.nodes[i].id + "\": [");
    for (size_t k = 0; k < nbrs[i].size(); k++)
      topo_json += (k ? ", " : "") + ("\"" + rt.nodes[nbrs[i][k]].id + "\"");
    topo_json += "]";
  }
  topo_json += "}";
  for (int i = 0; i < n; i++) {
    JV r = rt.rpc(i, "{\"type\": \"topology\", \"topology\": " + topo_json + "}", 15.0);
    const JV* b = r.get("body");
    const JV* ty = b ? b->get("type") : nullptr;
    if (!ty || ty->str != "topology_ok") die("topology failed");
  }

  // optional mid-cluster cut over the middle third of the send window,
  // on a REAL edge (runtime/maelstrom_harness.py semantics)
  if (partition && n >= 2) {
    int a = n / 2;
    int b = nbrs[a].empty() ? a : nbrs[a][0];
    double span = ops / rate;
    rt.part_a = a; rt.part_b = b;
    rt.part_t0 = now_s() + span / 3;
    rt.part_t1 = rt.part_t0 + span / 3;
  }

  // broadcasts at the target rate to seeded-random nodes
  srand(seed);
  std::vector<double> op_lat;
  for (int v = 0; v < ops; v++) {
    int target = rand() % n;
    double t0 = now_s();
    rt.rpc(target, "{\"type\": \"broadcast\", \"message\": " + std::to_string(v) + "}", 15.0);
    op_lat.push_back(now_s() - t0);
    double until = t0 + 1.0 / rate;
    while (now_s() < until) rt.pump(until - now_s());
  }

  // quiesce: no traffic for 0.3 s (bounded), then EVENTUAL-delivery check
  double qdeadline = now_s() + 60.0;
  while (now_s() < qdeadline && now_s() - rt.last_activity < 0.3)
    rt.pump(0.1);
  bool invariant = false;
  double cdeadline = now_s() + 30.0;
  while (true) {
    invariant = true;
    for (int i = 0; i < n && invariant; i++) {
      JV r = rt.rpc(i, "{\"type\": \"read\"}", 15.0);
      const JV* b = r.get("body");
      const JV* msgs = b ? b->get("messages") : nullptr;
      std::set<long> have;
      if (msgs && msgs->t == JV::ARR)
        for (const JV& e : msgs->arr)
          if (e.t == JV::NUM) have.insert((long)e.num);
      for (int v = 0; v < ops; v++)
        if (!have.count(v)) { invariant = false; break; }
    }
    if (invariant || now_s() > cdeadline) break;
    double until = now_s() + 0.5;
    while (now_s() < until) rt.pump(until - now_s());
  }

  // stats (checker-style; matches maelstrom_harness.stats())
  std::sort(op_lat.begin(), op_lat.end());
  auto pct = [&](double p) {
    if (op_lat.empty()) return 0.0;
    size_t i = std::min(op_lat.size() - 1, (size_t)(p * op_lat.size()));
    return op_lat[i] * 1e3;
  };
  double mean = 0;
  for (double x : op_lat) mean += x;
  mean = op_lat.empty() ? 0 : mean * 1e3 / op_lat.size();
  printf("{\"engine\": \"native-router\", \"nodes\": %d, \"broadcast_ops\": %d, "
         "\"msgs_routed\": %ld, \"msgs_per_op\": %.3f, "
         "\"op_latency_ms\": {\"mean\": %.3f, \"p50\": %.3f, \"p99\": %.3f, \"max\": %.3f}, "
         "\"link_latency_ms\": %.3f, \"invariant_ok\": %s, \"values\": %d, "
         "\"partitioned\": %s}\n",
         n, ops, rt.routed, ops ? (double)rt.routed / ops : 0.0,
         mean, pct(0.50), pct(0.99),
         op_lat.empty() ? 0.0 : op_lat.back() * 1e3,
         latency_ms, invariant ? "true" : "false", ops,
         partition ? "true" : "false");
  fflush(stdout);

  for (auto& nd : rt.nodes) { kill(nd.pid, SIGKILL); }
  for (auto& nd : rt.nodes) { int st; waitpid(nd.pid, &st, 0); }
  return invariant ? 0 : 1;
}
