"""The ``Backend`` seam: one interface, two engines.

BASELINE.json's north star requires the simulator to sit behind a backend
interface — ``go-native`` (the event-driven engine reproducing the reference
semantics, :mod:`gossip_tpu.runtime.gonative`) vs ``jax-tpu`` (the batched
round-synchronous engine) — "so the existing CLI selects the simulator at
runtime".  The CLI (:mod:`gossip_tpu.cli`) and the gRPC sidecar
(:mod:`gossip_tpu.rpc.sidecar`) both speak only this seam.

The two engines report on their native clocks (SURVEY.md §7, the parity
mapping documented in runtime/gonative.py): ``jax-tpu`` rounds are
synchronous gossip rounds; ``go-native`` "rounds" are hop depths, plus
wall-clock convergence in ``meta``.  Coverage values and curves are directly
comparable (the parity artifact).  Message counts are NOT: go-native counts
every wire message including the per-delivery ``broadcast_ok`` ack
(reference semantics, main.go:109), while the batched kernels count
transmissions only — roughly a 2x accounting gap on flood, recorded per
backend in ``meta["msgs_counts"]``.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

from gossip_tpu.config import (FaultConfig, LogConfig, MeshConfig,
                               ProtocolConfig, RunConfig,
                               TopologyConfig, TxnConfig)

BACKENDS = ("jax-tpu", "go-native")

# go-native materializes every edge as python objects; past this it is no
# longer the quick parity fixture it exists to be.
_GONATIVE_MAX_NODES = 20_000
# engine='native' forces the C++ event core (20-100x the Python engine,
# README) and raises the ceiling so large-N parity spot checks stay
# CLI-reachable (VERDICT r2 item 8).
_GONATIVE_NATIVE_MAX_NODES = 1_000_000


@dataclasses.dataclass
class RunReport:
    """One simulation's outcome, backend-agnostic (JSON-serializable)."""

    backend: str
    mode: str
    n: int
    rounds: int              # rounds (jax-tpu) / hop depth (go-native)
    coverage: float
    msgs: float
    wall_s: float
    curve: Optional[List[float]] = None
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _build_topology(tc: TopologyConfig, for_gonative: bool):
    from gossip_tpu.topology import generators as G
    if for_gonative and tc.family == "complete":
        # the event sim needs explicit neighbor lists
        if tc.n > 2048:
            raise ValueError(
                "go-native on a complete graph materializes n^2 edges; "
                f"n={tc.n} is past sanity (use a sparse family or jax-tpu)")
        return G.complete_table(tc.n)
    return G.build(tc)


def run_gonative(proto: ProtocolConfig, tc: TopologyConfig, run: RunConfig,
                 fault: Optional[FaultConfig] = None,
                 want_curve: bool = False) -> RunReport:
    """Event-driven reference-semantics run (flood relay — the only protocol
    the reference implements; SURVEY.md §2).  Faults map to partitions is
    not supported here: the event sim exposes explicit partition windows via
    its own API for targeted tests."""
    from gossip_tpu.runtime.gonative import topology_from_table
    from gossip_tpu.runtime.native_sim import (make_event_sim,
                                               native_available)
    if run.engine in ("xla", "fused"):
        raise ValueError(
            f"engine {run.engine!r} selects jax kernels; the go-native "
            "backend takes engine 'auto' (C++ core when buildable, "
            "Python otherwise) or 'native' (force the C++ core, 1M cap)")
    force_native = run.engine == "native"
    if force_native and not native_available():
        # ValueError like every sibling misconfiguration: the CLI turns
        # these into 'error: ...' + exit 2 instead of a traceback
        raise ValueError(
            "engine='native' needs the C++ event core and no compiler is "
            "available; drop the flag for the Python engine (20k cap)")
    cap = _GONATIVE_NATIVE_MAX_NODES if force_native else _GONATIVE_MAX_NODES
    if tc.n > cap:
        raise ValueError(
            f"go-native backend capped at {cap} nodes "
            + ("(C++ event core ceiling); " if force_native else
               "(parity fixture, not the scale path; engine='native' "
               "raises the cap to 1M); ")
            + f"got n={tc.n}")
    if proto.mode != "flood":
        raise ValueError(
            "go-native reproduces the reference's relay-to-all-neighbors "
            f"semantics (flood); mode {proto.mode!r} has no Go equivalent")
    if fault is not None:
        raise ValueError(
            "go-native takes no FaultConfig: faults there are explicit "
            "partition windows on the GoNativeSim API (Maelstrom-style), "
            "not per-round masks")
    topo = _build_topology(tc, for_gonative=True)
    t0 = time.perf_counter()
    # C++ event core when a compiler is present (equivalence proven in
    # tests/test_native.py), pure Python otherwise
    sim = make_event_sim(topology_from_table(topo))
    for r in range(proto.rumors):
        sim.broadcast(origin=(run.origin + r) % tc.n, message=r)
    sim.run()
    wall = time.perf_counter() - t0
    max_h = run.max_rounds
    # one hop_depths pass per rumor serves curve, convergence AND final
    # coverage (delivered <=> present in the log <=> min_hop exists) —
    # the per-node read() loop this replaces marshalled every node's
    # whole log and dominated wall time past ~100k nodes.  The curve is
    # a bincount-cumsum (O(n + max_h)), not a python double loop: nodes
    # first reached PAST max_h land in the overflow bucket and stay out
    # of every curve entry.
    import numpy as np
    depths = [sim.hop_depths(r) for r in range(proto.rumors)]
    curves = []
    for dp in depths:
        vals = np.fromiter(dp.values(), np.int64, count=len(dp))
        hist = np.bincount(np.clip(vals, 0, max_h + 1),
                           minlength=max_h + 2)
        curves.append(hist[:max_h + 1].cumsum() / tc.n)
    curve = [float(min(c[h] for c in curves)) for h in range(max_h + 1)]
    hops = next((h for h in range(max_h + 1)
                 if curve[h] >= run.target_coverage), -1)
    final_cov = min(len(dp) / tc.n for dp in depths)
    return RunReport(
        backend="go-native", mode="flood", n=tc.n,
        rounds=hops, coverage=final_cov, msgs=float(sim.msgs_sent),
        wall_s=round(wall, 4),
        curve=curve[1:] if want_curve else None,
        meta={"clock": "hop-depth", "sim_time_s": sim.now,
              "deliveries": sim.delivery_count(),
              "msgs_counts": "requests+acks",
              "engine": type(sim).__name__})


def _timing_meta(timing: Dict[str, float],
                 wall: Optional[float] = None) -> Dict[str, float]:
    """compile_s / steady_wall_s meta columns from a driver timing dict
    (round-2 verdict: reported walls must not mix one-off compile cost
    with steady-state throughput).  Empty when the driver didn't run
    the AOT split.

    With ``wall`` (the engine wall the report carries), also reconciles
    it: ``driver_overhead_s = wall - compile_s - steady_wall_s`` — the
    state/table builders, host transfers, and dispatch inside the timed
    driver but outside the AOT-split call — so every reported wall
    decomposes in the artifact itself (VERDICT r4 task 5: wall must ~=
    sum of reported parts)."""
    if not timing:
        return {}
    out = {"compile_s": round(timing["compile_s"], 4),
           "steady_wall_s": round(timing["steady_s"], 4)}
    if "init_build_s" in timing:
        # drivers that decompose further (sharded_fused._init_and_masks)
        # report the state/mask build — a named slice of the overhead
        out["init_build_s"] = round(timing["init_build_s"], 4)
    if wall is not None:
        out["driver_overhead_s"] = round(
            max(0.0, wall - timing["compile_s"] - timing["steady_s"]), 4)
    return out


def _curve_summary(covs, msgs, target):
    """(rounds_to_target, final_cov, final_msgs, curve) from per-round
    series — the one place the -1 sentinel / target comparison lives."""
    hit = [i for i, c in enumerate(covs) if c >= target]
    return ((hit[0] + 1) if hit else -1, float(covs[-1]), float(msgs[-1]),
            [float(c) for c in covs])


def _run_fused(proto: ProtocolConfig, tc: TopologyConfig, run: RunConfig,
               fault: Optional[FaultConfig], n_dev: int,
               want_curve: bool) -> RunReport:
    """engine='fused': the Pallas VMEM pull kernels as a product surface.

    Single device: the node-packed (rumors=1) or one-word-per-node
    (rumors<=32) kernel.  Multi-device: rumor-plane sharding
    (parallel/sharded_fused.py) — planes of 32 rumors across the mesh,
    identical partner stream per device, zero per-round ICI.

    Validates eagerly and loudly — the fused kernels cover exactly the
    flagship envelope (TPU, pull, implicit complete graph; static fault
    masks on EVERY layout since round 4 — node-packed,
    one-word-per-node, staged big path, plane-sharded — with scripted
    dead_nodes still rejected) and silently substituting a different
    engine would mislabel the wall-clock numbers, same policy as the
    exchange routing above.
    """
    import jax as _jax
    import jax.numpy as jnp

    from gossip_tpu.ops.pallas_round import (
        BITS, compiled_until_fused, compiled_until_fused_multirumor,
        coverage_node_packed, coverage_words, fused_table_bytes)

    reason = _fused_ineligible_reason(proto, tc, fault, n_dev)
    if reason is not None:
        raise ValueError(reason)
    # multi-device shards rumor PLANES, so the per-device table is always
    # the one-word-per-node layout regardless of total rumor count
    table_bytes = fused_table_bytes(tc.n,
                                    proto.rumors if n_dev == 1 else BITS)

    n = tc.n
    if n_dev > 1:
        from gossip_tpu.parallel.sharded_fused import (
            make_plane_mesh, plane_count, simulate_curve_sharded_fused,
            simulate_until_sharded_fused)
        mesh = make_plane_mesh(n_dev)
        w = plane_count(proto.rumors, n_dev)
        timing: Dict[str, float] = {}
        t0 = time.perf_counter()
        if want_curve:
            # fixed-length scan (no early exit): rounds-to-target and
            # the -1 sentinel derive from the curve like the XLA paths
            covs, final = simulate_curve_sharded_fused(
                n, proto.rumors, run, mesh, fanout=proto.fanout,
                fault=fault, timing=timing)
            _jax.block_until_ready(final)
            wall = time.perf_counter() - t0
            # _curve_summary reads only msgs[-1]; the fused accounting
            # is the closed form 2*fanout*n per round over the full scan
            rounds, cov, msgs, curve = _curve_summary(
                covs, [2.0 * proto.fanout * n * run.max_rounds],
                run.target_coverage)
        else:
            rounds_ex, cov, msgs, final = simulate_until_sharded_fused(
                n, proto.rumors, run, mesh, fanout=proto.fanout,
                fault=fault, timing=timing)
            _jax.block_until_ready(final)
            wall = time.perf_counter() - t0
            hit = cov >= float(jnp.float32(run.target_coverage))
            rounds, curve = (rounds_ex if hit else -1), None
        return RunReport(
            backend="jax-tpu", mode=proto.mode, n=n,
            rounds=rounds, coverage=cov, msgs=msgs,
            wall_s=round(wall, 4), curve=curve,
            meta={"clock": "rounds", "devices": n_dev,
                  "msgs_counts": "transmissions",
                  "engine": "fused-pallas-planes",
                  "layout": f"{w} rumor planes x one 32-rumor word per node",
                  "vmem_table_bytes_per_plane": table_bytes,
                  "ici_bytes_per_round": 0.0,
                  **_timing_meta(timing, wall)})

    if want_curve:
        from gossip_tpu.ops.pallas_round import (
            compiled_curve_fused, compiled_curve_fused_multirumor)
        if proto.rumors == 1:
            scan, init = compiled_curve_fused(
                n, seed=run.seed, fanout=proto.fanout,
                max_rounds=run.max_rounds, origin=run.origin,
                interpret=False, fault=fault)
        else:
            scan, init = compiled_curve_fused_multirumor(
                n, proto.rumors, seed=run.seed, fanout=proto.fanout,
                max_rounds=run.max_rounds, origin=run.origin,
                interpret=False, fault=fault)
        from gossip_tpu.utils.trace import maybe_aot_timed
        timing: Dict[str, float] = {}
        t0 = time.perf_counter()
        final, covs = maybe_aot_timed(scan, timing, init, label="solo")
        wall = time.perf_counter() - t0
        # the scanned state already accumulated the closed-form total
        rounds, cov, msgs, curve = _curve_summary(
            covs, [float(final.msgs)], run.target_coverage)
        return RunReport(
            backend="jax-tpu", mode=proto.mode, n=n, rounds=rounds,
            coverage=cov, msgs=msgs, wall_s=round(wall, 4), curve=curve,
            meta={"clock": "rounds", "devices": 1,
                  "msgs_counts": "transmissions", "engine": "fused-pallas",
                  "layout": ("node-packed bitmap" if proto.rumors == 1
                             else "one 32-rumor word per node"),
                  "vmem_table_bytes": table_bytes,
                  **_timing_meta(timing, wall)})

    if proto.rumors == 1:
        loop, init = compiled_until_fused(
            n, seed=run.seed, fanout=proto.fanout,
            target_coverage=run.target_coverage, max_rounds=run.max_rounds,
            origin=run.origin, fault=fault)
        # the SAME weighting chooser the loop's cond uses — cannot drift
        from gossip_tpu.ops.pallas_round import fused_cov_fn
        cov_fn = fused_cov_fn(n, fault, run.origin)
    else:
        loop, init = compiled_until_fused_multirumor(
            n, proto.rumors, seed=run.seed, fanout=proto.fanout,
            target_coverage=run.target_coverage, max_rounds=run.max_rounds,
            origin=run.origin, fault=fault)
        from gossip_tpu.ops.pallas_round import fused_mr_cov_fn
        cov_fn = fused_mr_cov_fn(n, proto.rumors, fault, run.origin)

    from gossip_tpu.utils.trace import maybe_aot_timed
    timing: Dict[str, float] = {}
    t0 = time.perf_counter()
    final = maybe_aot_timed(loop, timing, init, label="solo")
    wall = time.perf_counter() - t0
    cov = float(cov_fn(final.table))
    rounds = int(final.round)
    # float32 target compare, same threshold the loop's cond used
    hit = cov >= float(jnp.float32(run.target_coverage))
    return RunReport(
        backend="jax-tpu", mode=proto.mode, n=n,
        rounds=rounds if hit else -1, coverage=cov,
        msgs=float(final.msgs), wall_s=round(wall, 4),
        meta={"clock": "rounds", "devices": 1,
              "msgs_counts": "transmissions", "engine": "fused-pallas",
              "layout": ("node-packed bitmap" if proto.rumors == 1
                         else "one 32-rumor word per node"),
              "vmem_table_bytes": table_bytes,
              **_timing_meta(timing, wall)})


def _fused_ineligible_reason(proto: ProtocolConfig, tc: TopologyConfig,
                             fault: Optional[FaultConfig],
                             n_dev: int,
                             plane_stack: bool = False) -> Optional[str]:
    """Why this run cannot use the fused Pallas engine, or None if it can.

    The ONE list of preconditions: engine='fused' raises it verbatim,
    engine='auto' checks it quietly — so the two can never drift apart.
    Config reasons come before the platform probe so forced-fused config
    errors surface identically on any backend.  ``plane_stack``: the
    caller routes to the plane-sharded drivers regardless of n_dev (the
    checkpointed CLI path), which run churn EVENTS as alive-word
    operands — only that combination relaxes the churn rejection."""
    from gossip_tpu.ops.pallas_round import BITS, check_fused_fits
    import jax as _jax
    if proto.mode != "pull":
        return (f"engine='fused' implements pull rounds only "
                f"(got mode {proto.mode!r})")
    if tc.family != "complete":
        return ("engine='fused' runs on the implicit complete "
                f"topology only (got family {tc.family!r})")
    if fault is not None and fault.dead_nodes:
        # scripted dead_nodes/fail_round is a SWIM scenario; the fused
        # kernels' static masks do not implement it — reject loudly
        # rather than run fault-free under a fault flag
        return ("engine='fused' does not implement scripted dead_nodes/"
                "fail_round; use engine='auto' (or node_death_rate for "
                "random static deaths)")
    if (fault is not None and fault.churn is not None
            and not plane_stack and n_dev == 1):
        # the plane-sharded fused drivers run the FULL nemesis — churn
        # events, partition windows (per-round side-word cut masks),
        # and drop-rate ramps (the threshold table behind the SMEM
        # scalar operand): every multi-device fused route and the
        # plane_stack surfaces (--checkpoint, churn-sweep --engine
        # fused) land there.  Only the SINGLE-device fused routing
        # rejects churn: its compiled_*_fused paths predate the churn
        # denominator — auto falls back to the XLA kernels, which run
        # every schedule
        return ("engine='fused' routing does not run churn "
                "schedules single-device; use engine='auto' (XLA "
                "kernels run the full nemesis scenario catalog — "
                "docs/ROBUSTNESS.md), or the plane-sharded fused "
                "surfaces (--devices > 1, --checkpoint, churn-sweep "
                "--engine fused), which run events + partitions + "
                "ramps as runtime operands")
    # node_death_rate / drop_prob: in-kernel static fault masks cover
    # every fused layout since round 4 (node-packed, one-word-per-node,
    # staged big path, plane-sharded) — no restriction to return
    if n_dev == 1 and proto.rumors > BITS:
        return (f"engine='fused' packs <= {BITS} rumors per word "
                f"on one device (got rumors={proto.rumors}); "
                "shard rumor planes with --devices")
    # curve capture is no longer a restriction — round 4 added
    # fixed-length scan twins of every fused driver (compiled_curve_*,
    # simulate_curve_sharded_fused), so eligibility no longer consults
    # want_curve at all
    try:
        check_fused_fits(tc.n, proto.rumors if n_dev == 1 else BITS,
                         proto.fanout)
    except ValueError as e:
        return str(e)
    if _jax.default_backend() != "tpu":
        return ("engine='fused' needs a TPU (the kernel samples partners "
                "with the TPU hardware PRNG, which has no CPU "
                "equivalent); use engine='auto' for the XLA bit-packed "
                "path")
    return None


def swim_scenario(proto: ProtocolConfig, n: int,
                  fault: Optional[FaultConfig]):
    """Failure scenario for a SWIM run, shared by the streaming and
    checkpointed drivers: ``(dead_subjects, fail_round,
    default_scenario)``.  From the FaultConfig (CLI --dead-nodes /
    --fail-round, RPC fault.dead_nodes); default: node ``1 % S`` fails
    at round 2 (recorded in run meta so the scenario is discoverable).
    Scripted CHURN events are a scenario too — a churn-only run gets no
    default death injected on top of its schedule, and the detection
    metric targets the permanent churn crashes
    (models/swim.detection_targets).  Validates the metric targets
    against ``n`` and — without rotation — against the fixed subject
    window."""
    from gossip_tpu.models.swim import detection_targets
    from gossip_tpu.ops import nemesis as NE
    churn = NE.get(fault)
    scripted = fault is not None and (
        bool(fault.dead_nodes) or (churn is not None and churn.events))
    default_scenario = not scripted
    if default_scenario:
        dead = (1 % proto.swim_subjects,)
        fail_round = 2
    else:
        dead = fault.dead_nodes
        fail_round = fault.fail_round
    targets = detection_targets(dead, fault)
    bad = [d for d in targets if d >= n]
    if bad:
        raise ValueError(f"dead_nodes {bad} out of range for n={n}")
    if not proto.swim_rotate:
        outside = [d for d in targets if d >= proto.swim_subjects]
        if outside:
            raise ValueError(
                f"dead/churn-dead nodes {outside} are outside the fixed "
                f"subject window 0..{proto.swim_subjects - 1}; enable "
                "--swim-rotate for full-membership detection")
    return dead, fail_round, default_scenario


def swim_scenario_meta(proto: ProtocolConfig, n: int,
                       fault: Optional[FaultConfig]):
    """(dead, fail_round, meta) — the scenario plus the discoverability
    meta keys EVERY swim driver reports (streaming, checkpointed,
    ensemble), so the three surfaces cannot drift."""
    dead, fail_round, default_scenario = swim_scenario(proto, n, fault)
    from gossip_tpu.models.swim import detection_targets
    meta = {"metric": "detection_fraction",
            # what the metric actually measures: static scripted deaths
            # + permanent churn deaths (== dead for no-churn configs)
            "dead_subjects": list(detection_targets(dead, fault)),
            "fail_round": fail_round, "default_scenario": default_scenario}
    return dead, fail_round, meta


def _fused_auto_ok(proto: ProtocolConfig, tc: TopologyConfig,
                   fault: Optional[FaultConfig]) -> bool:
    """True when a single-device run is eligible for the fused Pallas
    engine and it is safe to pick it silently under engine='auto'."""
    return _fused_ineligible_reason(proto, tc, fault, 1) is None


def run_jax(proto: ProtocolConfig, tc: TopologyConfig, run: RunConfig,
            fault: Optional[FaultConfig] = None,
            mesh_cfg: Optional[MeshConfig] = None,
            want_curve: bool = False) -> RunReport:
    """Batched round-synchronous run; shards over a device mesh when
    ``mesh_cfg.n_devices > 1``.

    The returned report's ``wall_s`` is the ENGINE wall (driver call
    only); ``meta["topo_build_s"]`` carries the device-side topology
    build separately — on a cold backend the first device op also pays
    client/runtime init here, which round 4's hardware table left as
    ~10 s of unattributed wall on its first row (VERDICT r4 task 5)."""
    from gossip_tpu.topology import generators as G
    if run.engine == "native":
        raise ValueError(
            "engine='native' is the go-native backend's C++ event core; "
            "jax-tpu engines are auto|xla|fused (use --backend go-native)")
    import jax as _jax
    t0_build = time.perf_counter()
    topo = G.build(tc)
    if topo.nbrs is not None:
        _jax.block_until_ready((topo.nbrs, topo.deg))
    topo_build_s = time.perf_counter() - t0_build
    rep = _run_jax_with_topo(proto, tc, run, fault, mesh_cfg, want_curve,
                             topo)
    rep.meta["topo_build_s"] = round(topo_build_s, 4)
    return rep


def _run_jax_with_topo(proto: ProtocolConfig, tc: TopologyConfig,
                       run: RunConfig, fault: Optional[FaultConfig],
                       mesh_cfg: Optional[MeshConfig], want_curve: bool,
                       topo) -> RunReport:
    n_dev = 1 if mesh_cfg is None else mesh_cfg.n_devices
    _exchange = "dense" if mesh_cfg is None else mesh_cfg.exchange
    if _exchange != "dense":
        # never silently substitute the dense path for a requested
        # sparse/halo run — the traffic numbers would be mislabeled
        if n_dev == 1:
            raise ValueError(
                f"exchange={_exchange!r} is a cross-shard pattern; it needs "
                "n_devices > 1 (single-device runs have no exchange)")
        if proto.mode in ("swim", "rumor"):
            raise ValueError(
                f"exchange={_exchange!r} is not implemented for "
                f"{proto.mode}; swim and rumor shard via the dense "
                "kernels (pmax / psum_scatter + all_gather)")

    if run.engine == "fused":
        if _exchange != "dense":
            raise ValueError(
                f"exchange={_exchange!r} requests a cross-shard digest "
                "pattern; engine='fused' shards rumor planes with zero "
                "per-round ICI and implements no exchange — use "
                "engine='auto' for sparse/halo runs")
        return _run_fused(proto, tc, run, fault, n_dev, want_curve)

    # engine='auto' picks the fused Pallas kernel when a single-device run
    # is eligible — it is strictly faster than the XLA paths there.
    # Multi-device auto keeps the node-dim sharded engines (fused shards
    # rumor PLANES, a different scaling story the user opts into).
    if (run.engine == "auto" and n_dev == 1
            and _fused_auto_ok(proto, tc, fault)):
        rep = _run_fused(proto, tc, run, fault, 1, want_curve)
        rep.meta["engine_auto"] = "fused"
        return rep

    if proto.mode == "swim":
        from gossip_tpu.models.swim import (resolve_epoch_rounds,
                                            suggested_suspect_rounds)
        from gossip_tpu.runtime.simulator import simulate_swim_curve
        mesh = None
        if n_dev > 1:
            from gossip_tpu.parallel.sharded import make_mesh
            mesh = make_mesh(n_dev)
        dead, fail_round, meta = swim_scenario_meta(proto, tc.n, fault)
        swim_topo = None if tc.family == "complete" else topo
        from gossip_tpu.models.swim import effective_diss
        meta.update({"clock": "rounds",
                     "suggested_suspect_rounds":
                         suggested_suspect_rounds(tc.n, proto.fanout),
                     "devices": n_dev,
                     # the lowering disseminate_max actually ran: 'pack'
                     # degrades to 'sort' when max_rounds proves no
                     # transport-lane bound (bitwise-identical results,
                     # but a benchmark must see the substitution)
                     "swim_diss_effective": effective_diss(
                         proto.swim_diss, run.max_rounds),
                     "swim_rng": proto.swim_rng})
        if proto.swim_rotate:
            meta["subject_window"] = "rotating"
            meta["epoch_rounds"] = resolve_epoch_rounds(proto, tc.n)
        t0 = time.perf_counter()
        if want_curve:
            fracs, final = simulate_swim_curve(
                proto, tc.n, run.max_rounds, dead_nodes=dead,
                fail_round=fail_round, fault=fault, topo=swim_topo,
                seed=run.seed, mesh=mesh)
            wall = time.perf_counter() - t0
            hit = [i for i, f in enumerate(fracs)
                   if f >= run.target_coverage]
            rounds_out = (hit[0] + 1) if hit else -1
            det_final = float(fracs[-1])
            if proto.swim_rotate:
                # rotation: detection is scoped to the dead node's epoch;
                # the headline number is the best in-window detection
                meta["peak_detection"] = float(max(fracs))
            curve = [float(f) for f in fracs]
        else:
            # early-exit driver: stops the round detection hits the
            # target instead of scanning the full max_rounds budget
            import jax.numpy as jnp

            from gossip_tpu.runtime.simulator import simulate_swim_until
            timing: Dict[str, float] = {}
            r, det_final, det_peak, final = simulate_swim_until(
                proto, tc.n, run.max_rounds, run.target_coverage,
                dead_nodes=dead, fail_round=fail_round, fault=fault,
                topo=swim_topo, seed=run.seed, mesh=mesh, timing=timing)
            wall = time.perf_counter() - t0
            meta.update(_timing_meta(timing, wall))
            # same f32 threshold the loop's cond compared against
            tgt32 = float(jnp.float32(run.target_coverage))
            rounds_out = r if det_final >= tgt32 else -1
            if proto.swim_rotate:
                # peak over the whole run, like the curve path's
                # max(fracs): the window may have rotated past the dead
                # node's epoch by the time the loop stops
                meta["peak_detection"] = det_peak
            curve = None
        return RunReport(
            backend="jax-tpu", mode="swim", n=tc.n, rounds=rounds_out,
            coverage=det_final, msgs=float(final.msgs),
            wall_s=round(wall, 4), curve=curve, meta=meta)

    if proto.mode == "rumor":
        import jax.numpy as jnp

        from gossip_tpu.models.rumor import (simulate_curve_rumor,
                                             simulate_until_rumor)
        t0 = time.perf_counter()
        if want_curve:
            if n_dev > 1:
                from gossip_tpu.parallel.sharded import make_mesh
                from gossip_tpu.parallel.sharded_rumor import (
                    simulate_curve_rumor_sharded)
                covs, hots, msgs, final = simulate_curve_rumor_sharded(
                    proto, topo, run, make_mesh(n_dev), fault)
            else:
                covs, hots, msgs, final = simulate_curve_rumor(
                    proto, topo, run, fault)
            wall = time.perf_counter() - t0
            _, cov, msgs_f, curve = _curve_summary(
                covs, msgs, run.target_coverage)
            # rounds means ROUNDS-TO-EXTINCTION for rumor mongering, same
            # as the non-curve path (meta["rounds_semantics"]); -1 if the
            # hot set survived to max_rounds
            import numpy as _np
            dead_at = _np.nonzero(_np.asarray(hots) == 0.0)[0]
            rounds = int(dead_at[0]) + 1 if len(dead_at) else -1
            residue = 1.0 - float(covs[-1])
            hot_left = float(hots[-1])
        else:
            if n_dev > 1:
                from gossip_tpu.parallel.sharded import make_mesh
                from gossip_tpu.parallel.sharded_rumor import (
                    simulate_until_rumor_sharded)
                mesh = make_mesh(n_dev)
                rounds_ext, cov, residue, msgs_f, final = (
                    simulate_until_rumor_sharded(proto, topo, run, mesh,
                                                 fault))
            else:
                rounds_ext, cov, residue, msgs_f, final = (
                    simulate_until_rumor(proto, topo, run, fault))
            wall = time.perf_counter() - t0
            curve = None
            # rounds reports rounds-to-extinction; -1 only if hot pairs
            # survived to max_rounds (no self-termination).  Slice to the
            # real n rows: the sharded state pads to the mesh.
            hot_left = float(jnp.mean(jnp.any(final.hot[:tc.n], axis=1)
                                      .astype(jnp.float32)))
            rounds = rounds_ext if hot_left == 0.0 else -1
        return RunReport(
            backend="jax-tpu", mode="rumor", n=tc.n, rounds=rounds,
            coverage=cov, msgs=msgs_f, wall_s=round(wall, 4), curve=curve,
            meta={"clock": "rounds", "devices": n_dev,
                  "msgs_counts": "transmissions",
                  "rounds_semantics": "extinction",
                  "variant": proto.rumor_variant, "rumor_k": proto.rumor_k,
                  "residue": round(residue, 6),
                  "hot_fraction_final": hot_left,
                  "terminated": hot_left == 0.0})

    if n_dev > 1 and _exchange == "sparse":
        from gossip_tpu.parallel.sharded import make_mesh
        from gossip_tpu.parallel.sharded_sparse import (
            simulate_curve_sparse, simulate_curve_topo_sparse,
            simulate_until_sparse, simulate_until_topo_sparse)
        mesh = make_mesh(n_dev)
        if tc.family != "complete":
            # Explicit topology: capacity-capped all_to_all by partner's
            # owning shard (VERDICT r2 item 5) — pull and anti-entropy;
            # the factory raises loudly for other modes (never silently
            # densified).
            timing: Dict[str, float] = {}
            t0 = time.perf_counter()
            overflow = None
            if want_curve:
                covs, msgs, _, smeta, ovfs = simulate_curve_topo_sparse(
                    proto, topo, run, mesh, fault, timing=timing)
                wall = time.perf_counter() - t0
                rounds, cov, msgs_f, curve = _curve_summary(
                    covs, msgs, run.target_coverage)
                overflow = float(ovfs[-1])
            else:
                (rounds, cov, msgs_f, _, smeta,
                 overflow) = simulate_until_topo_sparse(
                    proto, topo, run, mesh, fault, timing=timing)
                wall = time.perf_counter() - t0
                curve = None
            return RunReport(
                backend="jax-tpu", mode=proto.mode, n=tc.n, rounds=rounds,
                coverage=cov, msgs=msgs_f, wall_s=round(wall, 4),
                curve=curve,
                meta={"clock": "rounds", "devices": n_dev,
                      "msgs_counts": "transmissions", "exchange": "sparse",
                      "overflow_dropped_requests": overflow,
                      "bucket_cap": smeta.cap,
                      # for anti-entropy with period>1 the WHOLE
                      # exchange is cond-skipped on quiescent rounds, so
                      # every sparse byte figure is per EXCHANGE round
                      # (steady average = /period — SparseMeta doc);
                      # reverse broken out as the AE-only payload
                      "ici_bytes_per_round": {
                          "sparse": smeta.sparse_bytes,
                          "dense_equivalent": smeta.dense_bytes,
                          "reverse_exchange_only": smeta.reverse_bytes},
                      **_timing_meta(timing, wall)})
        timing = {}
        t0 = time.perf_counter()
        if want_curve:
            covs, msgs, _, smeta = simulate_curve_sparse(
                proto, tc.n, run, mesh, fault, timing=timing)
            wall = time.perf_counter() - t0
            rounds, cov, msgs_f, curve = _curve_summary(
                covs, msgs, run.target_coverage)
        else:
            rounds, cov, msgs_f, _, smeta = simulate_until_sparse(
                proto, tc.n, run, mesh, fault, timing=timing)
            wall = time.perf_counter() - t0
            curve = None
        return RunReport(
            backend="jax-tpu", mode=proto.mode, n=tc.n, rounds=rounds,
            coverage=cov, msgs=msgs_f, wall_s=round(wall, 4), curve=curve,
            meta={"clock": "rounds", "devices": n_dev,
                  "msgs_counts": "transmissions", "exchange": "sparse",
                  "ici_bytes_per_round": {
                      "sparse": smeta.sparse_bytes,
                      "dense_equivalent": smeta.dense_bytes,
                      "reverse_exchange_only": smeta.reverse_bytes},
                  **_timing_meta(timing, wall)})

    if n_dev > 1 and _exchange == "halo":
        from gossip_tpu.parallel.halo import (simulate_curve_halo,
                                              simulate_until_halo)
        from gossip_tpu.parallel.sharded import make_mesh
        mesh = make_mesh(n_dev)
        timing = {}
        t0 = time.perf_counter()
        if want_curve:
            covs, msgs, _, band = simulate_curve_halo(proto, topo, run,
                                                      mesh, fault,
                                                      timing=timing)
            wall = time.perf_counter() - t0
            rounds, cov, msgs_f, curve = _curve_summary(
                covs, msgs, run.target_coverage)
        else:
            rounds, cov, msgs_f, _, band = simulate_until_halo(
                proto, topo, run, mesh, fault, timing=timing)
            wall = time.perf_counter() - t0
            curve = None
        return RunReport(
            backend="jax-tpu", mode=proto.mode, n=tc.n, rounds=rounds,
            coverage=cov, msgs=msgs_f, wall_s=round(wall, 4), curve=curve,
            meta={"clock": "rounds", "devices": n_dev,
                  "msgs_counts": "transmissions", "exchange": "halo",
                  "band": band, **_timing_meta(timing, wall)})

    # Pull and anti-entropy route through the bit-packed engines (32 rumor
    # bits per gathered word) — bitwise-identical trajectories to the bool
    # kernels (tests/test_packed.py), just less HBM/ICI traffic.  The curve
    # drivers stay on the bool path (no packed scan driver yet).
    packed_ok = proto.mode in ("pull", "antientropy") and not want_curve

    if n_dev > 1:
        from gossip_tpu.parallel.sharded import (
            make_mesh, simulate_curve_sharded, simulate_until_sharded)
        mesh = make_mesh(n_dev)
        if packed_ok:
            from gossip_tpu.parallel.sharded_packed import (
                simulate_until_packed_sharded)
            timing = {}
            t0 = time.perf_counter()
            rounds, cov, msgs, _ = simulate_until_packed_sharded(
                proto, topo, run, mesh, fault, timing=timing)
            wall = time.perf_counter() - t0
            return RunReport(backend="jax-tpu", mode=proto.mode, n=tc.n,
                             rounds=rounds, coverage=cov, msgs=msgs,
                             wall_s=round(wall, 4),
                             meta={"clock": "rounds", "devices": n_dev,
                                   "msgs_counts": "transmissions",
                                   "engine": "bit-packed",
                                   **_timing_meta(timing, wall)})
        timing = {}
        t0 = time.perf_counter()
        if want_curve:
            covs, msgs, _ = simulate_curve_sharded(proto, topo, run, mesh,
                                                   fault, timing=timing)
            wall = time.perf_counter() - t0
            rounds, cov, msgs_f, curve = _curve_summary(
                covs, msgs, run.target_coverage)
            return RunReport(
                backend="jax-tpu", mode=proto.mode, n=tc.n, rounds=rounds,
                coverage=cov, msgs=msgs_f,
                wall_s=round(wall, 4), curve=curve,
                meta={"clock": "rounds", "devices": n_dev,
                      "msgs_counts": "transmissions",
                      **_timing_meta(timing, wall)})
        rounds, cov, msgs, _ = simulate_until_sharded(proto, topo, run, mesh,
                                                      fault, timing=timing)
        wall = time.perf_counter() - t0
        return RunReport(backend="jax-tpu", mode=proto.mode, n=tc.n,
                         rounds=rounds, coverage=cov, msgs=msgs,
                         wall_s=round(wall, 4),
                         meta={"clock": "rounds", "devices": n_dev,
                               "msgs_counts": "transmissions",
                               **_timing_meta(timing, wall)})

    if packed_ok:
        from gossip_tpu.models.si_packed import simulate_until_packed
        timing: Dict[str, float] = {}
        t0 = time.perf_counter()
        rounds, cov, msgs, _ = simulate_until_packed(proto, topo, run,
                                                     fault, timing=timing)
        wall = time.perf_counter() - t0
        return RunReport(backend="jax-tpu", mode=proto.mode, n=tc.n,
                         rounds=rounds, coverage=cov, msgs=msgs,
                         wall_s=round(wall, 4),
                         meta={"clock": "rounds", "devices": 1,
                               "msgs_counts": "transmissions",
                               "engine": "bit-packed",
                               **_timing_meta(timing, wall)})

    from gossip_tpu.runtime.simulator import simulate_curve, simulate_until
    t0 = time.perf_counter()
    if want_curve:
        res = simulate_curve(proto, topo, run, fault)
        wall = time.perf_counter() - t0
        return RunReport(
            backend="jax-tpu", mode=proto.mode, n=tc.n,
            rounds=res.rounds_to_target, coverage=res.final_coverage,
            msgs=float(res.msgs[-1]), wall_s=round(wall, 4),
            curve=[float(c) for c in res.coverage],
            meta={"clock": "rounds", "devices": 1,
                  "msgs_counts": "transmissions"})
    timing = {}
    res = simulate_until(proto, topo, run, fault, timing=timing)
    wall = time.perf_counter() - t0
    return RunReport(backend="jax-tpu", mode=proto.mode, n=tc.n,
                     rounds=res.rounds, coverage=res.coverage, msgs=res.msgs,
                     wall_s=round(wall, 4),
                     meta={"clock": "rounds", "devices": 1,
                           "msgs_counts": "transmissions",
                           **_timing_meta(timing, wall)})


def run_log_workload(proto: ProtocolConfig, tc: TopologyConfig,
                     run: RunConfig, log_cfg: LogConfig,
                     fault: Optional[FaultConfig] = None,
                     want_curve: bool = False) -> RunReport:
    """The replicated-log workload behind the ``Run`` RPC's ``log``
    field (models/log.py drivers; single-process single-device — the
    node mesh shards via the library API, the Ensemble RPC rule).
    ``coverage`` reports the final log_conv; meta carries the
    acked-appends truth summary."""
    from gossip_tpu.models.log import (check_log_mode,
                                       simulate_curve_log,
                                       simulate_until_log)
    from gossip_tpu.topology import generators as G
    check_log_mode(proto)
    if run.engine not in ("auto", "xla"):
        raise ValueError(f"engine={run.engine!r} cannot run the log "
                         "workload (XLA pull kernels only)")
    topo = G.build(tc)
    t0 = time.perf_counter()
    if want_curve:
        conv, msgs, _, truth = simulate_curve_log(log_cfg, proto, topo,
                                                  run, fault)
        hit = [i for i, c in enumerate(conv)
               if c >= run.target_coverage]
        rounds = (hit[0] + 1) if hit else -1
        lc, msgs_f = float(conv[-1]), float(msgs[-1])
        curve = [float(c) for c in conv]
    else:
        rounds, lc, msgs_f, _, truth = simulate_until_log(
            log_cfg, proto, topo, run, fault)
        curve = None
    wall = time.perf_counter() - t0
    return RunReport(
        backend="jax-tpu", mode="log", n=tc.n, rounds=rounds,
        coverage=lc, msgs=msgs_f, wall_s=round(wall, 4), curve=curve,
        meta={"clock": "rounds", "devices": 1,
              "msgs_counts": "transmissions", "engine": "log-xla",
              "workload": "log", "truth": truth})


def run_txn_workload(proto: ProtocolConfig, tc: TopologyConfig,
                     run: RunConfig, txn_cfg: TxnConfig,
                     fault: Optional[FaultConfig] = None,
                     want_curve: bool = False) -> RunReport:
    """The LWW-register transaction workload behind the ``Run`` RPC's
    ``txn`` field (models/register.py drivers; single-process
    single-device — the node mesh shards via the library API, the
    Ensemble RPC rule).  ``coverage`` reports the final txn_conv; meta
    carries the acked-writes LWW truth summary."""
    from gossip_tpu.models.register import (check_txn_mode,
                                            simulate_curve_txn,
                                            simulate_until_txn)
    from gossip_tpu.topology import generators as G
    check_txn_mode(proto)
    if run.engine not in ("auto", "xla"):
        raise ValueError(f"engine={run.engine!r} cannot run the txn "
                         "workload (XLA pull kernels only)")
    topo = G.build(tc)
    t0 = time.perf_counter()
    if want_curve:
        conv, msgs, _, truth = simulate_curve_txn(txn_cfg, proto, topo,
                                                  run, fault)
        hit = [i for i, c in enumerate(conv)
               if c >= run.target_coverage]
        rounds = (hit[0] + 1) if hit else -1
        tcv, msgs_f = float(conv[-1]), float(msgs[-1])
        curve = [float(c) for c in conv]
    else:
        rounds, tcv, msgs_f, _, truth = simulate_until_txn(
            txn_cfg, proto, topo, run, fault)
        curve = None
    wall = time.perf_counter() - t0
    return RunReport(
        backend="jax-tpu", mode="txn", n=tc.n, rounds=rounds,
        coverage=tcv, msgs=msgs_f, wall_s=round(wall, 4), curve=curve,
        meta={"clock": "rounds", "devices": 1,
              "msgs_counts": "transmissions", "engine": "txn-xla",
              "workload": "txn", "truth": truth})


def run_simulation(backend: str, proto: ProtocolConfig, tc: TopologyConfig,
                   run: RunConfig, fault: Optional[FaultConfig] = None,
                   mesh_cfg: Optional[MeshConfig] = None,
                   want_curve: bool = False,
                   log_cfg: Optional[LogConfig] = None,
                   txn_cfg: Optional[TxnConfig] = None) -> RunReport:
    """The one entry point both the CLI and the sidecar call."""
    if log_cfg is not None and txn_cfg is not None:
        raise ValueError("a request carries at most one payload "
                         "workload; pick 'log' or 'txn'")
    if txn_cfg is not None:
        if backend != "jax-tpu":
            raise ValueError("the txn workload needs the jax-tpu "
                             "backend")
        if mesh_cfg is not None:
            raise ValueError("the txn workload over RPC is "
                             "single-process single-device; shard the "
                             "node mesh via the library API "
                             "(parallel/sharded_register)")
        return run_txn_workload(proto, tc, run, txn_cfg, fault,
                                want_curve)
    if log_cfg is not None:
        if backend != "jax-tpu":
            raise ValueError("the log workload needs the jax-tpu "
                             "backend")
        if mesh_cfg is not None:
            raise ValueError("the log workload over RPC is "
                             "single-process single-device; shard the "
                             "node mesh via the library API "
                             "(parallel/sharded_log)")
        return run_log_workload(proto, tc, run, log_cfg, fault,
                                want_curve)
    if backend == "go-native" and run.engine not in ("auto", "native"):
        raise ValueError(f"engine={run.engine!r} is a jax-tpu kernel "
                         "selection; go-native takes 'auto' (C++ core "
                         "when buildable, Python otherwise) or 'native' "
                         "(force the C++ core, 1M node cap)")
    if backend == "go-native":
        return run_gonative(proto, tc, run, fault, want_curve)
    if backend == "jax-tpu":
        return run_jax(proto, tc, run, fault, mesh_cfg, want_curve)
    raise ValueError(f"unknown backend {backend!r}; choose from {BACKENDS}")


# -- (de)serialization for the RPC/CLI boundary --------------------------

_CFG_TYPES = {"proto": ProtocolConfig, "topology": TopologyConfig,
              "run": RunConfig, "fault": FaultConfig,
              "mesh": MeshConfig, "log": LogConfig, "txn": TxnConfig}


def run_ensemble(proto: ProtocolConfig, tc: TopologyConfig, run: RunConfig,
                 fault: Optional[FaultConfig] = None, seeds=None,
                 count: Optional[int] = None, mesh=None):
    """Mode-dispatched seed ensemble — the ONE place the CLI's
    ``--ensemble`` and the sidecar's ``Ensemble`` RPC share: SI modes,
    SIR rumor mongering (residue/extinction distributions), and SWIM
    failure detection (detection-latency distribution for one scenario).
    Pass ``seeds`` explicitly or ``count`` (seeds become ``run.seed +
    i`` — the ONE place that default lives); ``mesh`` shards the seed
    axis (value-invariant).  Flood is admitted but varies across seeds
    only through fault randomness (its relay has no peer draw).
    Returns ``(ens, extra)`` — the ensemble result and the
    mode-specific report keys."""
    from gossip_tpu.parallel.sweep import (ensemble_curves,
                                           ensemble_rumor_curves,
                                           ensemble_swim_curves)
    from gossip_tpu.topology import generators as G
    if run.engine == "fused":
        raise ValueError("ensembles run the threefry XLA kernels; "
                         "engine='fused' is single-run only")
    if seeds is None and count is not None:
        seeds = [run.seed + i for i in range(int(count))]
    seeds = list(seeds) if seeds else None
    if not seeds:
        raise ValueError("need at least one seed (pass seeds or count)")
    extra: Dict[str, Any] = {}
    if proto.mode == "rumor":
        ens = ensemble_rumor_curves(proto, G.build(tc), run, seeds,
                                    fault, mesh=mesh)
    elif proto.mode == "swim":
        dead, fail_round, extra = swim_scenario_meta(proto, tc.n, fault)
        swim_topo = None if tc.family == "complete" else G.build(tc)
        ens = ensemble_swim_curves(proto, tc.n, run, seeds,
                                   dead_nodes=dead, fail_round=fail_round,
                                   fault=fault, topo=swim_topo, mesh=mesh)
        if proto.swim_rotate:
            # rotation: detection drops after the window leaves the dead
            # node's epoch — the headline is the per-seed PEAK (the solo
            # drivers' contract)
            peaks = ens.curves.max(axis=1)
            extra["subject_window"] = "rotating"
            extra["peak_detection_mean"] = float(peaks.mean())
            extra["peak_detection_min"] = float(peaks.min())
    else:
        ens = ensemble_curves(proto, G.build(tc), run, seeds, fault,
                              mesh=mesh)
    return ens, extra


def request_to_args(req: Dict[str, Any]) -> Dict[str, Any]:
    """JSON request dict -> kwargs for :func:`run_simulation`.  Unknown
    fields are rejected (typos should not silently become defaults)."""
    known_top = set(_CFG_TYPES) | {"backend", "curve"}
    bad_top = set(req) - known_top
    if bad_top:
        raise ValueError(f"unknown request fields: {sorted(bad_top)}")
    curve = req.get("curve", False)
    if not isinstance(curve, bool):
        raise ValueError(f"curve must be a bool, got {curve!r}")
    out: Dict[str, Any] = {"backend": req.get("backend", "jax-tpu"),
                           "want_curve": curve}
    for key, cls in _CFG_TYPES.items():
        val = req.get(key)
        if val is None:
            cfg = None
        else:
            known = {f.name for f in dataclasses.fields(cls)}
            bad = set(val) - known
            if bad:
                raise ValueError(f"unknown {key} fields: {sorted(bad)}")
            cfg = cls(**val)
        out[{"proto": "proto", "topology": "tc", "run": "run",
             "fault": "fault", "mesh": "mesh_cfg",
             "log": "log_cfg", "txn": "txn_cfg"}[key]] = cfg
    if out["proto"] is None:
        out["proto"] = ProtocolConfig()
    if out["tc"] is None:
        out["tc"] = TopologyConfig()
    if out["run"] is None:
        out["run"] = RunConfig()
    return out
