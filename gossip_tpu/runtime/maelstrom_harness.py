"""Mini-Maelstrom: spawn N protocol-node processes and route their traffic.

The reference was tested exclusively by the external Maelstrom harness — N
OS processes on one machine, all networking simulated by a router over
stdin/stdout pipes, with injected latency and partitions (SURVEY.md §4,
"the same trick the TPU framework should replay as a parity fixture").
This module IS that fixture: a small asyncio router speaking the Maelstrom
envelope protocol as client ``c1``, driving
:mod:`gossip_tpu.runtime.maelstrom_node` processes (or any binary speaking
the protocol) for black-box conformance tests.

No jax imports — pure stdlib.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class MaelstromHarness:
    """Router + client for N Maelstrom protocol nodes.

    Usage::

        h = MaelstromHarness(5, latency=0.005)
        await h.start()
        await h.set_topology({"n0": ["n1"], ...})
        await h.broadcast("n0", 42)
        await h.quiesce()
        assert 42 in await h.read("n3")
        await h.stop()
    """

    CLIENT = "c1"

    def __init__(self, n: int, latency: float = 0.002,
                 argv: Optional[List[str]] = None):
        self.n = n
        self.latency = latency
        self.argv = argv or [sys.executable, "-u", "-m",
                             "gossip_tpu.runtime.maelstrom_node"]
        self.ids = [f"n{i}" for i in range(n)]
        self.procs: Dict[str, asyncio.subprocess.Process] = {}
        self._pump_tasks: List[asyncio.Task] = []
        self._pending: Dict[int, asyncio.Future] = {}
        self._next_msg_id = 1000
        self._partitions: List[Tuple[str, str, float, float]] = []
        self._loop_t0 = 0.0
        self.routed = 0              # inter-node messages routed
        self._last_activity = 0.0
        self.op_latencies: List[float] = []   # client RPC round trips (s)
        self.broadcast_ops = 0
        self.client_ops = 0          # all workload-generator ops

    # -- lifecycle -------------------------------------------------------

    async def start(self) -> None:
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        # The protocol nodes are jax-free; drop the axon-TPU trigger so the
        # environment's sitecustomize doesn't spend ~2 s per process
        # registering a TPU backend N times on one host.
        env.pop("PALLAS_AXON_POOL_IPS", None)
        loop = asyncio.get_running_loop()
        self._loop_t0 = loop.time()
        for nid in self.ids:
            proc = await asyncio.create_subprocess_exec(
                *self.argv,
                stdin=asyncio.subprocess.PIPE,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.PIPE,
                limit=16 * 1024 * 1024,   # read_ok lines grow with the log
                env=env)
            self.procs[nid] = proc
            self._pump_tasks.append(asyncio.ensure_future(
                self._pump(nid, proc)))
            self._pump_tasks.append(asyncio.ensure_future(
                self._drain_stderr(nid, proc)))
        await asyncio.gather(*[
            self._client_rpc(nid, {"type": "init", "node_id": nid,
                                   "node_ids": list(self.ids)})
            for nid in self.ids])

    async def stop(self) -> None:
        for proc in self.procs.values():
            try:
                proc.kill()
            except ProcessLookupError:
                pass
        await asyncio.gather(*[p.wait() for p in self.procs.values()],
                             return_exceptions=True)
        # pumps return on EOF once the processes are gone; awaiting them
        # (rather than cancelling mid-read) lets the pipe transports close
        # inside the running loop, not in __del__ after it's gone
        await asyncio.gather(*self._pump_tasks, return_exceptions=True)
        for proc in self.procs.values():
            if proc.stdin:
                proc.stdin.close()

    # -- network simulation ----------------------------------------------

    def _now(self) -> float:
        return asyncio.get_running_loop().time() - self._loop_t0

    def partition(self, a: str, b: str, duration: float,
                  start: Optional[float] = None) -> None:
        """Block the (a, b) link both ways for ``duration`` from now (or
        from ``start``, in harness time)."""
        t0 = self._now() if start is None else start
        self._partitions.append((a, b, t0, t0 + duration))

    def _link_open(self, a: str, b: str) -> bool:
        t = self._now()
        for (x, y, t0, t1) in self._partitions:
            if {a, b} == {x, y} and t0 <= t < t1:
                return False
        return True

    def _write_to(self, nid: str, envelope: dict) -> None:
        proc = self.procs.get(nid)
        if proc is None or proc.stdin is None or proc.stdin.is_closing():
            return
        proc.stdin.write((json.dumps(envelope) + "\n").encode())

    async def _deliver_later(self, nid: str, envelope: dict) -> None:
        if self.latency > 0:
            await asyncio.sleep(self.latency)
        self._write_to(nid, envelope)

    async def _pump(self, nid: str, proc) -> None:
        """Route node ``nid``'s stdout: replies to the client resolve RPC
        futures; node-to-node traffic is delivered with latency unless the
        link is partitioned (messages in a cut are dropped, Maelstrom
        style — the nodes' retries provide at-least-once)."""
        try:
            while True:
                raw = await proc.stdout.readline()
                if not raw:
                    return
                try:
                    msg = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                dest = msg.get("dest")
                self._last_activity = self._now()
                if dest == self.CLIENT:
                    irt = msg.get("body", {}).get("in_reply_to")
                    fut = self._pending.pop(irt, None)
                    if fut is not None and not fut.done():
                        fut.set_result(msg)
                    continue
                if dest in self.procs and self._link_open(msg.get("src"),
                                                          dest):
                    self.routed += 1
                    asyncio.ensure_future(self._deliver_later(dest, msg))
        except Exception as e:   # a dead pump black-holes the node: say so
            print(f"[harness] pump for {nid} died: {e!r}", file=sys.stderr)
            raise

    async def _drain_stderr(self, nid: str, proc) -> None:
        while True:
            raw = await proc.stderr.readline()
            if not raw:
                return
            print(f"[{nid} stderr] {raw.decode().rstrip()}", file=sys.stderr)

    # -- client ops (what the Maelstrom workload generator sends) ---------

    async def _client_rpc(self, dest: str, body: dict,
                          timeout: float = 15.0) -> dict:
        body = dict(body)
        self._next_msg_id += 1
        mid = body["msg_id"] = self._next_msg_id
        fut = asyncio.get_running_loop().create_future()
        self._pending[mid] = fut
        try:
            self._write_to(dest,
                           {"src": self.CLIENT, "dest": dest, "body": body})
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(mid, None)

    async def set_topology(self, topo: Dict[str, List[str]]) -> None:
        replies = await asyncio.gather(*[
            self._client_rpc(nid, {"type": "topology", "topology": topo})
            for nid in self.ids])
        assert all(r["body"]["type"] == "topology_ok" for r in replies)

    async def _timed_op(self, node: str, body: dict) -> dict:
        """One workload-generator op: latency-recorded, op-counted —
        the shared accounting of every workload's write path, so
        ``stats()`` means the same thing for all of them."""
        t0 = self._now()
        r = await self._client_rpc(node, body)
        self.op_latencies.append(self._now() - t0)
        self.client_ops += 1
        return r

    async def broadcast(self, node: str, value: int) -> dict:
        r = await self._timed_op(node, {"type": "broadcast",
                                        "message": value})
        self.broadcast_ops += 1
        return r

    async def add(self, node: str, delta: int) -> dict:
        """Counter-workload ``add`` op (Gossip Glomers challenge #4);
        the caller checks the reply type — only an ``add_ok`` counts
        toward the acked-sum invariant."""
        return await self._timed_op(node, {"type": "add",
                                           "delta": delta})

    async def read(self, node: str) -> List[int]:
        r = await self._client_rpc(node, {"type": "read"})
        assert r["body"]["type"] == "read_ok"
        return r["body"]["messages"]

    async def read_counter(self, node: str) -> int:
        r = await self._client_rpc(node, {"type": "read"})
        assert r["body"]["type"] == "read_ok"
        return int(r["body"]["value"])

    async def kafka_send(self, node: str, key: str, msg: int) -> dict:
        """Kafka-workload ``send`` op; the caller checks for
        ``send_ok`` (only acked sends join the exactly-once-in-order
        invariant) and reads the assigned ``offset`` off the reply."""
        return await self._timed_op(node, {"type": "send", "key": key,
                                           "msg": msg})

    async def kafka_poll(self, node: str, offsets: dict) -> dict:
        """``poll`` from the given per-key offsets ->
        ``{key: [[offset, msg], ...]}``."""
        r = await self._client_rpc(node, {"type": "poll",
                                          "offsets": offsets})
        assert r["body"]["type"] == "poll_ok"
        return r["body"]["msgs"]

    async def kafka_commit(self, node: str, offsets: dict) -> dict:
        """``commit_offsets`` op (op-counted like every write)."""
        return await self._timed_op(node, {"type": "commit_offsets",
                                           "offsets": offsets})

    async def kafka_list_committed(self, node: str, keys: list) -> dict:
        r = await self._client_rpc(node, {
            "type": "list_committed_offsets", "keys": keys})
        assert r["body"]["type"] == "list_committed_offsets_ok"
        return r["body"]["offsets"]

    async def txn(self, node: str, ops: list) -> dict:
        """txn-rw-register workload ``txn`` op (op-counted like every
        write-bearing op); the caller inspects the reply — ``txn_ok``
        commits, an error reply is a definite abort (the TxnServer
        validates before applying anything)."""
        return await self._timed_op(node, {"type": "txn", "txn": ops})

    async def send_raw(self, dest: str, body: dict, timeout: float = 15.0
                       ) -> dict:
        """Arbitrary client RPC (conformance probes, e.g. unknown types)."""
        return await self._client_rpc(dest, body, timeout)

    async def quiesce(self, idle: float = 0.3, timeout: float = 30.0) -> None:
        """Wait until no message has moved for ``idle`` seconds."""
        deadline = self._now() + timeout
        while self._now() < deadline:
            if self._now() - self._last_activity >= idle:
                return
            await asyncio.sleep(idle / 4)
        raise TimeoutError("cluster did not quiesce")


    def stats(self) -> dict:
        """Maelstrom-checker-style workload stats (SURVEY.md §4: the real
        harness reports messages-per-op and op latencies externally).
        ``ops``/``msgs_per_op`` count every workload-generator op (the
        counter workload's adds included); ``broadcast_ops`` stays the
        broadcast-specific count for the batching artifacts."""
        lats = sorted(self.op_latencies)

        def pct(p):
            return lats[min(len(lats) - 1, int(p * len(lats)))] if lats else 0.0
        return {
            "nodes": self.n,
            "ops": self.client_ops,
            "broadcast_ops": self.broadcast_ops,
            "msgs_routed": self.routed,
            "msgs_per_op": (self.routed / self.client_ops
                            if self.client_ops else 0.0),
            "op_latency_ms": {
                "mean": 1e3 * sum(lats) / len(lats) if lats else 0.0,
                "p50": 1e3 * pct(0.50), "p99": 1e3 * pct(0.99),
                "max": 1e3 * (lats[-1] if lats else 0.0)},
            "link_latency_ms": 1e3 * self.latency,
        }


async def _start_workload(n: int, ops: int, rate: float, latency: float,
                          topology: str, partition_mid: bool,
                          argv: Optional[List[str]]) -> MaelstromHarness:
    """The spawn/topology/partition scaffolding EVERY workload runner
    shares — one definition, so :func:`run_broadcast_workload` and
    :func:`run_counter_workload` cannot drift on how a cluster is
    brought up or how the fault-tolerance variant cuts it."""
    h = MaelstromHarness(n, latency=latency, argv=argv)
    await h.start()
    try:
        topo = (line_topology(h.ids) if topology == "line"
                else grid_topology(h.ids, max(1, int(n ** 0.5))))
        await h.set_topology(topo)
        if partition_mid and n >= 2:
            # cut a REAL edge near the middle of the cluster —
            # consecutive ids are only adjacent in the line topology;
            # on a grid an arbitrary pair is usually not an edge and
            # the cut would drop nothing while still reporting
            # partitioned=true (both built families give every middle
            # node a neighbor at n >= 2)
            a = h.ids[n // 2]
            b = topo[a][0]
            # cut the middle third of the send window, anchored NOW
            # (the send loop starts now) — anchoring at loop start
            # would let process-spawn/init time expire the window
            # before the first broadcast and make the fault variant
            # vacuous
            span = ops / rate
            h.partition(a, b, duration=span / 3,
                        start=h._now() + span / 3)
    except BaseException:
        # the callers' try/finally h.stop() only guards AFTER this
        # returns: a topology failure here (a node that crashed on
        # spawn, a never-answered topology_ok) must not strand n
        # stdin-blocked node processes
        await h.stop()
        raise
    return h


async def _finish_workload(h: MaelstromHarness, check,
                           poll_deadline: float = 30.0) -> dict:
    """The quiesce + eventual-invariant polling every workload runner
    shares: quiesce (reported, never fatal — a retry loop can look
    idle mid-backoff), then poll ``check()`` (an async predicate) until
    it holds or the deadline passes.  Returns the stats dict with
    ``invariant_ok`` / ``quiesce_timeout`` filled."""
    timed_out = False
    try:
        await h.quiesce(timeout=60.0)
    except TimeoutError:
        timed_out = True           # report, don't crash: reads still run
    deadline = h._now() + poll_deadline
    while True:
        ok = await check()
        if ok or h._now() > deadline:
            break
        await asyncio.sleep(0.5)
    out = h.stats()
    out["invariant_ok"] = ok
    out["quiesce_timeout"] = timed_out
    return out


async def run_broadcast_workload(n: int, ops: int, rate: float = 50.0,
                                 latency: float = 0.002,
                                 topology: str = "line",
                                 partition_mid: bool = False,
                                 seed: int = 0,
                                 argv: Optional[List[str]] = None) -> dict:
    """The Maelstrom ``broadcast`` workload as a callable: spawn ``n``
    protocol nodes, send ``ops`` broadcasts at ``rate`` ops/s to random
    nodes, optionally cut a mid-cluster link for the middle third of the
    run (the fault-tolerance variant), quiesce, then check the checker's
    invariant — EVERY value appears in EVERY node's read (SURVEY.md §4).
    Returns the stats dict (+ ``invariant_ok``, ``values``)."""
    import random
    rng = random.Random(seed)
    h = await _start_workload(n, ops, rate, latency, topology,
                              partition_mid, argv)
    try:
        for v in range(ops):
            await h.broadcast(rng.choice(h.ids), v)
            await asyncio.sleep(1.0 / rate)
        # The checker invariant is EVENTUAL delivery: a quiesce can look
        # idle while a node's partition-dropped push sits in its ~2 s
        # RPC-timeout retry loop, so poll the reads until every value is
        # everywhere or the deadline passes (nodes retry with capped
        # backoff — runtime/maelstrom_node.py).
        want = set(range(ops))

        async def check():
            reads = await asyncio.gather(*[h.read(nid)
                                           for nid in h.ids])
            return all(want <= set(r) for r in reads)

        out = await _finish_workload(h, check)
        out["values"] = ops
        out["partitioned"] = bool(partition_mid)
        return out
    finally:
        await h.stop()


async def run_counter_workload(n: int, ops: int, rate: float = 50.0,
                               latency: float = 0.002,
                               topology: str = "line",
                               partition_mid: bool = False,
                               seed: int = 0,
                               max_delta: int = 10,
                               argv: Optional[List[str]] = None) -> dict:
    """The Gossip Glomers ``g-counter`` workload: spawn ``n`` counter
    nodes (runtime/maelstrom_node.CounterServer — per-node CRDT shards,
    merge = per-key max), send ``ops`` random-delta ``add`` ops at
    ``rate`` ops/s to random nodes, optionally cut a mid-cluster link
    mid-run, quiesce, then check the checker's invariant: the final
    ``read`` on EVERY node equals the **sum of acked adds** — exact
    integer equality, through the partition.  Returns the stats dict
    (+ ``invariant_ok``, ``expected``, ``final_values``)."""
    import random
    rng = random.Random(seed)
    if argv is None:
        argv = [sys.executable, "-u", "-m",
                "gossip_tpu.runtime.maelstrom_node",
                "--workload", "counter"]
    h = await _start_workload(n, ops, rate, latency, topology,
                              partition_mid, argv)
    try:
        acked_sum = 0
        for _ in range(ops):
            delta = rng.randint(1, max_delta)
            r = await h.add(rng.choice(h.ids), delta)
            if r["body"]["type"] == "add_ok":   # only acked adds count
                acked_sum += delta
            await asyncio.sleep(1.0 / rate)

        finals: List[int] = []

        async def check():
            finals[:] = await asyncio.gather(*[h.read_counter(nid)
                                               for nid in h.ids])
            return all(v == acked_sum for v in finals)

        out = await _finish_workload(h, check)
        out["expected"] = acked_sum
        out["final_values"] = list(finals)
        out["partitioned"] = bool(partition_mid)
        return out
    finally:
        await h.stop()


async def run_kafka_workload(n: int, ops: int, rate: float = 50.0,
                             latency: float = 0.002,
                             topology: str = "line",
                             partition_mid: bool = False,
                             seed: int = 0, keys: int = 3,
                             argv: Optional[List[str]] = None) -> dict:
    """The Gossip Glomers ``kafka`` (replicated log) workload: spawn
    ``n`` kafka nodes (runtime/maelstrom_node.KafkaServer), send
    ``ops`` unique-value ``send`` ops at ``rate`` ops/s to random
    nodes over ``keys`` keys, interleave polls and commits, optionally
    cut a mid-cluster link mid-run, then check the three kafka
    invariants (SURVEY.md §4 checker style):

      1. **exactly-once in offset order** — every ACKED send appears
         in every node's final ``poll(key, 0)`` at exactly its acked
         offset, no send (acked or not) appears twice, and offsets
         are consecutive.  A send whose client RPC timed out or drew
         an error reply is **indeterminate** (the Maelstrom
         info-timeout convention: the owner may have applied a
         forwarded send whose ack was lost) — it MAY appear, but
         still at most once (the owner dedups retried forwards by
         value);
      2. **monotone committed offsets** — every
         ``list_committed_offsets`` sample taken during the run
         (including across the partition) never regresses per
         (node, key), and the final committed map agrees on every
         node;
      3. **gapless polls** — every poll reply's offsets are
         consecutive from the requested offset (checked on every
         in-run poll, not just the final ones).

    In-run probes that time out across the partition are skipped,
    never crashed on (the client timeout is a harness budget, not a
    verdict).  Returns the stats dict (+ ``invariant_ok``,
    ``monotone_ok``, ``gapless_ok``, ``acked``, ``indeterminate``,
    ``partitioned``)."""
    import random
    rng = random.Random(seed)
    if argv is None:
        argv = [sys.executable, "-u", "-m",
                "gossip_tpu.runtime.maelstrom_node",
                "--workload", "kafka"]
    h = await _start_workload(n, ops, rate, latency, topology,
                              partition_mid, argv)
    try:
        key_names = [str(k) for k in range(keys)]
        acked: Dict[str, Dict[int, int]] = {k: {} for k in key_names}
        # client-timeout / error-reply sends: the owner MAY have
        # applied a forwarded send whose ack was lost (at-least-once),
        # so these values may legitimately appear in polls — but never
        # twice (docstring invariant 1)
        indeterminate: Dict[str, set] = {k: set() for k in key_names}
        committed_seen: Dict[Tuple[str, str], int] = {}
        monotone_ok = True
        gapless_ok = True
        exactly_once_ok = True

        def check_gapless(polled: dict, offsets: dict) -> bool:
            return all(
                [int(o) for o, _ in lst]
                == list(range(int(offsets[k]),
                              int(offsets[k]) + len(lst)))
                for k, lst in polled.items())

        async def sample_committed(node: str) -> None:
            nonlocal monotone_ok
            got = await h.kafka_list_committed(node, key_names)
            for k, off in got.items():
                prev = committed_seen.get((node, k))
                if prev is not None and int(off) < prev:
                    monotone_ok = False
                committed_seen[(node, k)] = int(off)

        for i in range(ops):
            key = rng.choice(key_names)
            try:
                r = await h.kafka_send(rng.choice(h.ids), key, i)
            except asyncio.TimeoutError:
                # a long partition can outlast the client RPC budget
                # while the node's forward retries keep going — the
                # send is indeterminate, never a harness crash
                indeterminate[key].add(i)
            else:
                if r["body"]["type"] == "send_ok":
                    off = int(r["body"]["offset"])
                    if off in acked[key]:        # duplicate offset ack
                        exactly_once_ok = False
                    acked[key][off] = i
                else:                            # error reply: the
                    indeterminate[key].add(i)    # forward may have
                                                 # landed at the owner
            try:
                if i % 3 == 2:                   # in-run gapless probe
                    node = rng.choice(h.ids)
                    offsets = {k: 0 for k in key_names}
                    polled = await h.kafka_poll(node, offsets)
                    if not check_gapless(polled, offsets):
                        gapless_ok = False
                if i % 4 == 3 and acked[key]:    # commit what we saw
                    await h.kafka_commit(rng.choice(h.ids),
                                         {key: max(acked[key])})
                if i % 5 == 4:                   # monotonicity probe
                    await sample_committed(rng.choice(h.ids))
            except asyncio.TimeoutError:
                pass       # probe across the cut: skip, retry later
            await asyncio.sleep(1.0 / rate)

        want_committed = {k: max((off for (nd, kk), off
                                  in committed_seen.items() if kk == k),
                                 default=None) for k in key_names}

        def key_log_ok(k: str, lst) -> bool:
            """Invariant 1 on one node's full poll of key ``k``: every
            acked send at exactly its acked offset, every other entry
            a known indeterminate value, nothing twice."""
            got = {int(o): m for o, m in lst}
            msgs = [m for _, m in lst]
            if len(set(msgs)) != len(msgs):      # a value twice: the
                return False                     # owner dedup failed
            if any(got.get(o) != m for o, m in acked[k].items()):
                return False
            return all(m in indeterminate[k] for o, m in got.items()
                       if acked[k].get(o) != m)

        async def check() -> bool:
            nonlocal gapless_ok
            try:
                for nid in h.ids:
                    polled = await h.kafka_poll(
                        nid, {k: 0 for k in key_names})
                    if not check_gapless(polled,
                                         {k: 0 for k in key_names}):
                        gapless_ok = False
                        return False
                    if not all(key_log_ok(k, polled.get(k, []))
                               for k in key_names):
                        return False
                    await sample_committed(nid)  # monotone across polls
                    listed = await h.kafka_list_committed(nid, key_names)
                    for k, want in want_committed.items():
                        if want is not None \
                                and int(listed.get(k, -1)) < want:
                            return False
            except asyncio.TimeoutError:
                return False                     # still healing: poll
            return True                          # again until deadline

        out = await _finish_workload(h, check)
        out["invariant_ok"] = bool(out["invariant_ok"]
                                   and exactly_once_ok and monotone_ok
                                   and gapless_ok)
        out["monotone_ok"] = monotone_ok
        out["gapless_ok"] = gapless_ok
        out["acked"] = {k: len(v) for k, v in acked.items()}
        out["indeterminate"] = {k: len(v) for k, v
                                in indeterminate.items()}
        out["committed"] = {k: v for k, v in want_committed.items()
                            if v is not None}
        out["partitioned"] = bool(partition_mid)
        return out
    finally:
        await h.stop()


async def run_txn_workload(n: int, ops: int, rate: float = 50.0,
                           latency: float = 0.002,
                           topology: str = "line",
                           partition_mid: bool = False,
                           seed: int = 0, keys: int = 4,
                           argv: Optional[List[str]] = None) -> dict:
    """The Maelstrom ``txn-rw-register`` workload: spawn ``n`` txn
    nodes (runtime/maelstrom_node.TxnServer — LWW registers, Lamport-
    pair timestamps), run ``ops`` random multi-key read/write
    transactions at ``rate`` ops/s against random nodes (1-3 micro-ops
    each, UNIQUE write values — the attribution contract), optionally
    cut a mid-cluster link mid-run, then hand the trace to the
    weak-isolation checker (runtime/txn_checker.check_txn_trace):

      * **G0 (dirty write)** — no cycle in the per-key LWW version
        orders across transactions;
      * **G1a (aborted read)** — no committed read observes an
        aborted transaction's write (error replies are definite
        aborts: the TxnServer validates before applying);
      * **convergence** — after heal, every node's final read-all
        transaction returns the SAME state, and each key's final
        value is its max-timestamp write's (total availability is
        only meaningful if the replicas agree eventually).

    A transaction whose client RPC times out across the partition is
    INDETERMINATE (the Maelstrom info-timeout convention): its writes
    may appear — they are never G1a evidence — and the harness never
    crashes on it.  Returns the stats dict (+ ``invariant_ok``,
    ``anomalies`` with the checker verdict, ``partitioned``)."""
    import random
    rng = random.Random(seed)
    if argv is None:
        argv = [sys.executable, "-u", "-m",
                "gossip_tpu.runtime.maelstrom_node",
                "--workload", "txn"]
    h = await _start_workload(n, ops, rate, latency, topology,
                              partition_mid, argv)
    try:
        key_names = [str(k) for k in range(keys)]
        trace: List[dict] = []
        next_value = [1]          # unique write values, monotone

        def gen_ops():
            out = []
            for _ in range(rng.randint(1, 3)):
                k = rng.choice(key_names)
                if rng.random() < 0.5:
                    out.append(["r", k, None])
                else:
                    out.append(["w", k, next_value[0]])
                    next_value[0] += 1
            return out

        for i in range(ops):
            requested = gen_ops()
            rec = {"id": i, "node": rng.choice(h.ids),
                   "reads": [], "writes": []}
            try:
                r = await h.txn(rec["node"], requested)
            except asyncio.TimeoutError:
                # a long partition can outlast the client RPC budget
                # while the node would still answer after heal — the
                # txn is indeterminate, never a harness crash; its
                # writes (values are in `requested`) may appear later
                rec["status"] = "indeterminate"
                rec["writes"] = [{"key": k, "value": v,
                                  "ts": None}
                                 for f, k, v in requested if f == "w"]
            else:
                body = r["body"]
                if body.get("type") == "txn_ok":
                    rec["status"] = "committed"
                    ts = body.get("ts")
                    for f, k, v in body.get("txn", []):
                        if f == "r":
                            rec["reads"].append([k, v])
                        else:
                            rec["writes"].append(
                                {"key": k, "value": v, "ts": ts})
                else:
                    # definite abort: the node validated and refused
                    # BEFORE applying anything (TxnServer contract)
                    rec["status"] = "aborted"
                    rec["writes"] = [{"key": k, "value": v,
                                      "ts": None}
                                     for f, k, v in requested
                                     if f == "w"]
            trace.append(rec)
            await asyncio.sleep(1.0 / rate)

        final_reads: Dict[str, dict] = {}
        read_all = [["r", k, None] for k in key_names]

        async def check() -> bool:
            try:
                for nid in h.ids:
                    r = await h.txn(nid, list(read_all))
                    if r["body"].get("type") != "txn_ok":
                        return False
                    final_reads[nid] = {k: v for _, k, v
                                        in r["body"]["txn"]}
            except asyncio.TimeoutError:
                return False                 # still healing: poll
            states = list(final_reads.values())
            return (len(states) == n
                    and all(s == states[0] for s in states[1:]))

        out = await _finish_workload(h, check)
        # the RAW trace goes to the checker, aborted writes included:
        # G1a detection is only real if an aborted transaction's
        # writes stay attributable (the checker itself skips ts-less
        # writes where no version order exists — review finding)
        from gossip_tpu.runtime.txn_checker import check_txn_trace
        verdict = check_txn_trace(trace, final_reads=final_reads)
        out["invariant_ok"] = bool(out["invariant_ok"]
                                   and verdict["ok"])
        out["anomalies"] = {"g0": len(verdict["g0"]),
                            "g1a": len(verdict["g1a"]),
                            "g1b": len(verdict["g1b"]),
                            "g1c": len(verdict["g1c"]),
                            "lost_update": len(verdict["lost_update"]),
                            "defects": len(verdict["defects"])}
        out["g0_ok"] = not verdict["g0"]
        out["g1a_ok"] = not verdict["g1a"]
        out["converged"] = verdict.get("converged", False)
        out["committed"] = verdict["committed"]
        out["aborted"] = verdict["aborted"]
        out["indeterminate"] = verdict["indeterminate"]
        out["partitioned"] = bool(partition_mid)
        return out
    finally:
        await h.stop()


def line_topology(ids: List[str]) -> Dict[str, List[str]]:
    topo = {}
    for i, nid in enumerate(ids):
        nbrs = []
        if i > 0:
            nbrs.append(ids[i - 1])
        if i < len(ids) - 1:
            nbrs.append(ids[i + 1])
        topo[nid] = nbrs
    return topo


def grid_topology(ids: List[str], cols: int) -> Dict[str, List[str]]:
    topo = {nid: [] for nid in ids}
    rows = (len(ids) + cols - 1) // cols
    for i, nid in enumerate(ids):
        r, c = divmod(i, cols)
        for (rr, cc) in ((r - 1, c), (r + 1, c), (r, c - 1), (r, c + 1)):
            j = rr * cols + cc
            if 0 <= rr < rows and 0 <= cc < cols and j < len(ids):
                topo[nid].append(ids[j])
    return topo
