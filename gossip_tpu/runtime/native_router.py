"""Build + drive the native (C++) mini-Maelstrom router.

``native/router.cpp`` is the standalone L-1 harness twin of
:mod:`gossip_tpu.runtime.maelstrom_harness`: one poll() event loop that
spawns the protocol-node processes, routes envelopes with latency and a
partition window, runs the broadcast workload, and checks the
eventual-delivery invariant.  This module compiles it on demand (same
policy as native/__init__.load_eventsim: g++ or graceful None) and
parses its one-line JSON stats.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
from typing import List, Optional

_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))), "native")
_BIN = os.path.join(_DIR, "router")
_SRC = os.path.join(_DIR, "router.cpp")
_REPO = os.path.dirname(os.path.dirname(_DIR))
_lock = threading.Lock()


def build_router() -> Optional[str]:
    """Path to the router binary, building it if stale; None if no
    compiler is available."""
    from gossip_tpu.native import build_native, native_fresh
    with _lock:
        if native_fresh(_SRC, _BIN):
            return _BIN
        return _BIN if build_native(_SRC, _BIN, shared=False) else None


def run_native_workload(n: int, ops: int, rate: float = 50.0,
                        latency: float = 0.002, topology: str = "line",
                        partition_mid: bool = False, seed: int = 0,
                        argv: Optional[List[str]] = None,
                        timeout: float = 180.0) -> dict:
    """The broadcast workload through the NATIVE router; same stats dict
    shape as maelstrom_harness.run_broadcast_workload (plus
    ``engine: native-router``).  Raises RuntimeError if no compiler."""
    binary = build_router()
    if binary is None:
        raise RuntimeError("no C++ compiler available for the native "
                           "router; use the python harness "
                           "(runtime/maelstrom_harness.py)")
    node_cmd = argv or [sys.executable, "-u", "-m",
                        "gossip_tpu.runtime.maelstrom_node"]
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)   # jax-free protocol nodes
    cmd = [binary, "--n", str(n), "--latency-ms", str(latency * 1e3),
           "--ops", str(ops), "--rate", str(rate),
           "--topology", topology, "--seed", str(seed)]
    if partition_mid:
        cmd.append("--partition")
    cmd += ["--"] + node_cmd
    p = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout,
                       env=env)
    lines = [line for line in p.stdout.splitlines() if line.strip()]
    if not lines:
        raise RuntimeError(f"native router produced no stats "
                           f"(rc={p.returncode}): {p.stderr[-300:]}")
    return json.loads(lines[-1])
