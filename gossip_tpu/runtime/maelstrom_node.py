"""Maelstrom-protocol broadcast node: one OS process per cluster node.

A drop-in functional replacement for the reference Go binary under the
Maelstrom / Jepsen harness (fly.io "Gossip Glomers" broadcast workload):
newline-delimited JSON envelopes ``{"src","dest","body":{...}}`` on
stdin/stdout, stderr for logs (SURVEY.md §2.5's L0 contract, inferred from
the reference's call sites of the maelstrom demo/go library, reference
go.mod:5).

Implemented surface, matching the reference handler set (main.go:99-158):

  * built-in ``init`` handshake — record ``node_id``/``node_ids``, reply
    ``init_ok`` (the Go library does this invisibly; SURVEY.md §2.5);
  * ``broadcast`` — ack FIRST with ``broadcast_ok`` (main.go:109), dedup
    (main.go:113), append (main.go:117), then gossip to all topology
    neighbors except the sender (main.go:72-75) with per-neighbor retry;
  * ``read`` — ordered message log as ``read_ok`` (main.go:123-130);
  * ``topology`` — store the neighbor map, reply ``topology_ok``
    (main.go:132-149);
  * ``broadcast_ok`` — no-op sink for acks with no outstanding RPC
    (main.go:151-153); acks that match a pending RPC wake its waiter;
  * unknown types — Maelstrom error reply, code 10 (not-supported).

Deliberate fix vs the reference (flagged per SURVEY.md §2.2): the retry loop
creates a FRESH 2 s context per attempt, so a healed partition lets the
fan-out proceed (the reference reuses one expired context forever —
main.go:77-87, the §2.2.7 liveness hole; that faithful behavior is modeled
by :mod:`gossip_tpu.runtime.gonative` where parity needs it).  Dedup and the
topology write are also race-free here by construction: each message is
handled on the single asyncio loop (the reference's §2.2.5-6 races came from
per-message goroutines).

This module imports neither jax nor numpy — it must start fast, N processes
at a time, under a harness.
"""

from __future__ import annotations

import asyncio
import json
import sys
from typing import Any, Callable, Dict, List, Optional

ERR_NOT_SUPPORTED = 10


class MaelstromNode:
    """Minimal async Maelstrom node runtime (the L0 layer, SURVEY.md §1).

    Handlers run as their own asyncio task per message — the cooperative
    analog of the Go library's goroutine-per-message dispatch, so a handler
    blocked in :meth:`rpc` never stalls the read loop."""

    def __init__(self):
        self.node_id: Optional[str] = None
        self.node_ids: List[str] = []
        self.handlers: Dict[str, Callable] = {}
        self._next_msg_id = 0
        self._pending: Dict[int, asyncio.Future] = {}
        self._write_lock: Optional[asyncio.Lock] = None

    def handle(self, typ: str, fn: Callable) -> None:
        self.handlers[typ] = fn

    def _msg_id(self) -> int:
        self._next_msg_id += 1
        return self._next_msg_id

    async def _write(self, dest: str, body: Dict[str, Any]) -> None:
        line = json.dumps({"src": self.node_id, "dest": dest, "body": body})
        async with self._write_lock:
            sys.stdout.write(line + "\n")
            sys.stdout.flush()

    async def send(self, dest: str, body: Dict[str, Any]) -> None:
        await self._write(dest, body)

    async def reply(self, req: Dict[str, Any], body: Dict[str, Any]) -> None:
        body = dict(body)
        body["in_reply_to"] = req["body"].get("msg_id")
        await self._write(req["src"], body)

    async def rpc(self, dest: str, body: Dict[str, Any],
                  timeout: float = 2.0) -> Dict[str, Any]:
        """SyncRPC analog (main.go:81): fresh msg_id, block until the
        matching ``in_reply_to`` arrives or the timeout expires."""
        body = dict(body)
        mid = self._msg_id()
        body["msg_id"] = mid
        fut = asyncio.get_running_loop().create_future()
        self._pending[mid] = fut
        try:
            await self._write(dest, body)
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(mid, None)

    async def _dispatch(self, msg: Dict[str, Any]) -> None:
        body = msg.get("body", {})
        typ = body.get("type")
        irt = body.get("in_reply_to")
        if irt is not None and irt in self._pending:
            fut = self._pending.pop(irt)
            if not fut.done():
                fut.set_result(msg)
            return
        if typ == "init":
            self.node_id = body["node_id"]
            self.node_ids = list(body.get("node_ids", []))
            await self.reply(msg, {"type": "init_ok"})
            return
        fn = self.handlers.get(typ)
        if fn is None:
            await self.reply(msg, {"type": "error", "code": ERR_NOT_SUPPORTED,
                                   "text": f"unhandled type {typ!r}"})
            return
        try:
            await fn(msg)
        except Exception as e:  # crash log on stderr, Maelstrom-style
            print(f"handler {typ} failed: {e!r}", file=sys.stderr)

    async def run(self) -> None:
        self._write_lock = asyncio.Lock()
        loop = asyncio.get_running_loop()
        while True:
            line = await loop.run_in_executor(None, sys.stdin.readline)
            if not line:
                return                      # EOF: harness closed us
            line = line.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
            except json.JSONDecodeError as e:
                print(f"bad json: {e}", file=sys.stderr)
                continue
            asyncio.ensure_future(self._dispatch(msg))


class BroadcastServer:
    """The L1-L3 layers: message store + gossip engine + handlers.

    ``gossip_interval > 0`` switches the relay from the reference's
    immediate per-message fan-out (main.go:72-88 relays every broadcast
    to every neighbor the moment it arrives) to INTERVAL BATCHING — the
    efficiency variant the reference never addressed (SURVEY.md §4, the
    Gossip Glomers "efficient broadcast" challenge): new values
    accumulate per neighbor and ride one internal ``gossip`` RPC per
    neighbor per tick, acked with ``gossip_ok``.  A value stays pending
    for a neighbor until that neighbor acks a batch containing it, so
    delivery remains at-least-once across partitions (unacked batches
    simply retry next tick — there is no give-up; Maelstrom's checker
    demands eventual delivery).  Per-hop latency is bounded by
    ``interval + rtt``; messages-per-op drops from O(edges x values)
    toward O(edges x ticks) (measured in tests/test_maelstrom.py).
    Client-facing ``broadcast``/``read`` semantics are unchanged."""

    def __init__(self, node: MaelstromNode, rpc_timeout: float = 2.0,
                 backoff_base: float = 0.1, max_retries: int = 64,
                 gossip_interval: float = 0.0):
        self.node = node
        self.rpc_timeout = rpc_timeout
        self.backoff_base = backoff_base
        self.max_retries = max_retries    # int-overflow guard (ref has none)
        self.gossip_interval = gossip_interval
        self.messages: List[int] = []     # ordered log (main.go:23)
        self.seen: set = set()            # dedup set (main.go:24)
        self.topology: Dict[str, List[str]] = {}
        self.pending: Dict[str, set] = {}   # neighbor -> values owed
        self._in_flight: set = set()        # neighbors with a live batch RPC
        self._flusher: Optional[asyncio.Task] = None
        node.handle("broadcast", self.on_broadcast)
        node.handle("read", self.on_read)
        node.handle("topology", self.on_topology)
        node.handle("broadcast_ok", self.on_broadcast_ok)
        node.handle("gossip", self.on_gossip)
        node.handle("gossip_ok", self.on_broadcast_ok)   # same sink

    async def on_broadcast(self, msg) -> None:
        body = msg["body"]
        m = body["message"]
        sender = msg["src"]                        # main.go:107
        await self.node.reply(msg, {"type": "broadcast_ok"})  # ack FIRST
        if m in self.seen:                         # dedup (main.go:113)
            return
        self.seen.add(m)
        self.messages.append(m)                    # append (main.go:117)
        if self.gossip_interval > 0:
            self._enqueue([m], exclude=sender)
        else:
            await self.gossip(m, exclude=sender)   # fan-out (main.go:118)

    # -- interval batching ------------------------------------------------

    def _enqueue(self, ms: List[int], exclude: str) -> None:
        assert self.gossip_interval > 0   # callers gate on the mode
        for nb in self.topology.get(self.node.node_id, []):
            if nb != exclude:
                self.pending.setdefault(nb, set()).update(ms)
        if self._flusher is None:
            self._flusher = asyncio.ensure_future(self._flush_loop())

    async def _flush_loop(self) -> None:
        while True:
            await asyncio.sleep(self.gossip_interval)
            try:
                for nb, owed in self.pending.items():
                    if owed and nb not in self._in_flight:
                        self._in_flight.add(nb)
                        asyncio.ensure_future(
                            self._flush_one(nb, sorted(owed)))
            except Exception as e:
                # a poisoned batch (e.g. unsortable mixed-type values
                # from a hostile peer) must not kill the ONLY flusher —
                # that would silently strand every pending value forever
                print(f"flush loop error (continuing): {e!r}",
                      file=sys.stderr)

    async def _flush_one(self, nb: str, batch: List[int]) -> None:
        """One batch RPC; on ack the batch leaves the neighbor's owed
        set, on timeout/error it stays for the next tick (at-least-once
        with interval-paced retries instead of the immediate path's
        exponential backoff)."""
        try:
            reply = await self.node.rpc(nb, {"type": "gossip",
                                             "messages": batch},
                                        timeout=self.rpc_timeout)
            if reply.get("body", {}).get("type") != "error":
                self.pending[nb] -= set(batch)
        except asyncio.TimeoutError:
            pass
        finally:
            self._in_flight.discard(nb)

    async def on_gossip(self, msg) -> None:
        body = msg["body"]
        sender = msg["src"]
        await self.node.reply(msg, {"type": "gossip_ok"})     # ack FIRST
        new = [m for m in body.get("messages", []) if m not in self.seen]
        for m in new:
            self.seen.add(m)
            self.messages.append(m)
        if not new:
            return
        if self.gossip_interval > 0:
            self._enqueue(new, exclude=sender)
        else:
            # an immediate-mode node in a heterogeneous cluster relays a
            # received batch through its own immediate path — it must
            # never start the tick flusher (interval 0 would busy-spin)
            for m in new:
                await self.gossip(m, exclude=sender)

    async def gossip(self, m: int, exclude: str) -> None:
        """Sequential fan-out with retry (main.go:65-89), fixed-context
        variant: fresh 2 s deadline per attempt (see module doc)."""
        neighbors = self.topology.get(self.node.node_id, [])
        for nb in neighbors:
            if nb == exclude:                      # sender exclusion
                continue
            for attempt in range(self.max_retries):
                try:
                    reply = await self.node.rpc(nb, {"type": "broadcast",
                                                     "message": m},
                                                timeout=self.rpc_timeout)
                except asyncio.TimeoutError:
                    pass                           # lost/late: retry
                else:
                    # An error reply is a failed delivery, not an ack: the
                    # reference's SyncRPC returns error replies as Go errors
                    # and stays in the retry loop (main.go:81-87).
                    if reply.get("body", {}).get("type") != "error":
                        break
                if attempt + 1 < self.max_retries:   # no sleep before give-up
                    await asyncio.sleep(
                        self.backoff_base * (2 ** min(attempt, 12)))
            else:
                # at-least-once exhausted (the capped variant of the
                # reference's unbounded loop) — surface it, don't lose it
                # silently
                print(f"gossip: giving up on {nb} after "
                      f"{self.max_retries} attempts (message {m!r})",
                      file=sys.stderr)

    async def on_read(self, msg) -> None:
        await self.node.reply(msg, {"type": "read_ok",
                                    "messages": list(self.messages)})

    async def on_topology(self, msg) -> None:
        self.topology = {k: list(v)
                         for k, v in msg["body"]["topology"].items()}
        await self.node.reply(msg, {"type": "topology_ok"})

    async def on_broadcast_ok(self, msg) -> None:
        pass                                       # sink (main.go:151-153)


class CounterServer:
    """The Gossip Glomers grow-only / PN counter workload node: the
    SAME epidemic machinery as :class:`BroadcastServer` with a
    commutative-merge payload instead of the dedup set (the batched
    twin is gossip_tpu/models/crdt.py; docs/WORKLOADS.md).

    State is the classic per-node counter shards — ``pos``/``neg`` maps
    ``node_id -> contribution`` where only the owner ever raises its
    own entry — so merge is **per-key max** and gossip order,
    duplication, and loss cannot corrupt the value.  Client ops:

      * ``add {delta}`` — ack ``add_ok`` FIRST (the reference's
        ack-before-process shape, main.go:109), then bump the own
        shard (negative deltas land in the ``neg`` plane — the PN
        variant; a grow-only workload simply never sends one);
      * ``read`` — ``read_ok {value}``, value = sum(pos) - sum(neg).

    Dissemination is interval-ticked full-state gossip: every
    ``interval`` seconds each neighbor that has not acked the CURRENT
    shard maps gets one ``counter_gossip`` RPC carrying them; an ack
    records the acked snapshot, a timeout/partition leaves the
    neighbor dirty for the next tick — at-least-once with idempotent
    merge, so a healed partition converges with no special casing
    (the BroadcastServer batching layer's retry shape)."""

    def __init__(self, node: MaelstromNode, rpc_timeout: float = 2.0,
                 gossip_interval: float = 0.05):
        self.node = node
        self.rpc_timeout = rpc_timeout
        self.gossip_interval = gossip_interval
        self.pos: Dict[str, int] = {}
        self.neg: Dict[str, int] = {}
        self.topology: Dict[str, List[str]] = {}
        self.acked: Dict[str, tuple] = {}   # nbr -> last acked snapshot
        self._in_flight: set = set()
        self._flusher: Optional[asyncio.Task] = None
        node.handle("add", self.on_add)
        node.handle("read", self.on_read)
        node.handle("topology", self.on_topology)
        node.handle("counter_gossip", self.on_gossip)
        node.handle("counter_gossip_ok", self.on_sink)
        node.handle("add_ok", self.on_sink)

    def _value(self) -> int:
        return sum(self.pos.values()) - sum(self.neg.values())

    def _snapshot(self) -> tuple:
        return (tuple(sorted(self.pos.items())),
                tuple(sorted(self.neg.items())))

    def _merge(self, pos: Dict[str, int], neg: Dict[str, int]) -> bool:
        """Per-key max join; True when anything changed (a change means
        neighbors may be stale, which the snapshot compare picks up)."""
        changed = False
        for mine, theirs in ((self.pos, pos), (self.neg, neg)):
            for nid, v in theirs.items():
                if int(v) > mine.get(nid, 0):
                    mine[nid] = int(v)
                    changed = True
        return changed

    def _ensure_flusher(self) -> None:
        if self._flusher is None:
            self._flusher = asyncio.ensure_future(self._flush_loop())

    async def on_add(self, msg) -> None:
        body = msg["body"]
        delta = int(body.get("delta", 0))
        await self.node.reply(msg, {"type": "add_ok"})   # ack FIRST
        me = self.node.node_id
        if delta >= 0:
            self.pos[me] = self.pos.get(me, 0) + delta
        else:
            self.neg[me] = self.neg.get(me, 0) - delta
        self._ensure_flusher()

    async def on_read(self, msg) -> None:
        await self.node.reply(msg, {"type": "read_ok",
                                    "value": self._value()})

    async def on_topology(self, msg) -> None:
        self.topology = {k: list(v)
                         for k, v in msg["body"]["topology"].items()}
        await self.node.reply(msg, {"type": "topology_ok"})

    async def on_gossip(self, msg) -> None:
        body = msg["body"]
        await self.node.reply(msg, {"type": "counter_gossip_ok"})
        if self._merge(body.get("pos", {}), body.get("neg", {})):
            self._ensure_flusher()

    async def on_sink(self, msg) -> None:
        pass

    async def _flush_loop(self) -> None:
        while True:
            await asyncio.sleep(self.gossip_interval)
            try:
                snap = self._snapshot()
                for nb in self.topology.get(self.node.node_id, []):
                    if (self.acked.get(nb) != snap
                            and nb not in self._in_flight):
                        self._in_flight.add(nb)
                        asyncio.ensure_future(self._flush_one(nb, snap))
            except Exception as e:    # never kill the only flusher
                print(f"counter flush loop error (continuing): {e!r}",
                      file=sys.stderr)

    async def _flush_one(self, nb: str, snap: tuple) -> None:
        try:
            reply = await self.node.rpc(
                nb, {"type": "counter_gossip",
                     "pos": dict(self.pos), "neg": dict(self.neg)},
                timeout=self.rpc_timeout)
            if reply.get("body", {}).get("type") != "error":
                self.acked[nb] = snap
        except asyncio.TimeoutError:
            pass                      # partitioned/lost: retry next tick
        finally:
            self._in_flight.discard(nb)


class KafkaServer:
    """The Gossip Glomers replicated-log ("kafka") workload node: the
    last challenge-family sibling (the batched twin is
    gossip_tpu/models/log.py; docs/WORKLOADS.md "Replicated logs").

    Per-key logs with a single OFFSET-ASSIGNER: key ``k`` is owned by
    a deterministic node (``crc32(key) % n`` over the init-ordered
    ``node_ids`` — every node computes the same owner with no
    coordination), and only the owner assigns offsets, so each key's
    log is gap-free and append-ordered at the source.  Replicas learn
    entries by interval-ticked full-state gossip with an idempotent
    union merge — every gossiped map is a union of owner prefixes of
    the same sequence, so **every replica always holds a contiguous
    prefix per key** (gapless polls are structural, not checked-for).
    Committed offsets merge by per-key max (monotone — they can never
    regress, the second kafka invariant).  Client ops:

      * ``send {key, msg}`` — owner appends and replies ``send_ok
        {offset}``; a non-owner FORWARDS to the owner with
        fresh-deadline retries (the BroadcastServer.gossip shape) and
        acks the client only with the owner's offset — so an acked
        send is in the log exactly once at its acked offset.  Retried
        forwards are deduplicated at the owner BY VALUE per key (the
        workload sends unique values — the CrdtConfig one-add-tag
        convention, documented); exhausted retries reply a Maelstrom
        error (code 11).  An errored or client-timed-out send is
        INDETERMINATE, not absent: the forward may have landed at the
        owner with its ack lost (at-least-once), so the workload
        checker admits such values in polls — but still at most once,
        which the owner's value dedup guarantees.
      * ``poll {offsets: {key: off}}`` — ``poll_ok {msgs: {key:
        [[off, msg], ...]}}``: the contiguous local run from ``off``.
      * ``commit_offsets {offsets}`` — per-key max into the committed
        map, ack, gossip.
      * ``list_committed_offsets {keys}`` — the committed map slice.
    """

    ERR_TEMP_UNAVAILABLE = 11

    def __init__(self, node: MaelstromNode, rpc_timeout: float = 2.0,
                 gossip_interval: float = 0.05,
                 backoff_base: float = 0.1, max_retries: int = 64):
        self.node = node
        self.rpc_timeout = rpc_timeout
        self.gossip_interval = gossip_interval
        self.backoff_base = backoff_base
        self.max_retries = max_retries
        self.entries: Dict[str, Dict[int, Any]] = {}  # key -> off -> msg
        self.by_val: Dict[str, Dict[Any, int]] = {}   # owner dedup
        self.committed: Dict[str, int] = {}
        self.topology: Dict[str, List[str]] = {}
        self.acked: Dict[str, tuple] = {}   # nbr -> last acked snapshot
        self._in_flight: set = set()
        self._flusher: Optional[asyncio.Task] = None
        node.handle("send", self.on_send)
        node.handle("poll", self.on_poll)
        node.handle("commit_offsets", self.on_commit_offsets)
        node.handle("list_committed_offsets",
                    self.on_list_committed_offsets)
        node.handle("topology", self.on_topology)
        node.handle("kafka_gossip", self.on_gossip)
        node.handle("kafka_gossip_ok", self.on_sink)

    def _owner(self, key: str) -> str:
        import zlib
        ids = self.node.node_ids
        return ids[zlib.crc32(str(key).encode()) % len(ids)]

    def _ensure_flusher(self) -> None:
        if self._flusher is None:
            self._flusher = asyncio.ensure_future(self._flush_loop())

    def _append_as_owner(self, key: str, msg) -> int:
        """Owner-side append: next offset = local log length (the
        owner's log is gap-free by construction); a retried forward of
        an already-appended value returns its existing offset."""
        vals = self.by_val.setdefault(key, {})
        if msg in vals:
            return vals[msg]
        log = self.entries.setdefault(key, {})
        off = len(log)
        log[off] = msg
        vals[msg] = off
        self._ensure_flusher()
        return off

    async def on_send(self, msg) -> None:
        body = msg["body"]
        key, value = str(body["key"]), body["msg"]
        if self._owner(key) == self.node.node_id:
            off = self._append_as_owner(key, value)
            await self.node.reply(msg, {"type": "send_ok",
                                        "offset": off})
            return
        # forward to the owner with fresh-deadline retries; ack the
        # client only with the owner's assigned offset
        for attempt in range(self.max_retries):
            try:
                reply = await self.node.rpc(
                    self._owner(key), {"type": "send", "key": key,
                                       "msg": value},
                    timeout=self.rpc_timeout)
            except asyncio.TimeoutError:
                pass                           # lost/partitioned: retry
            else:
                rb = reply.get("body", {})
                if rb.get("type") == "send_ok":
                    await self.node.reply(msg, {
                        "type": "send_ok", "offset": rb["offset"]})
                    return
            if attempt + 1 < self.max_retries:
                await asyncio.sleep(
                    self.backoff_base * (2 ** min(attempt, 12)))
        await self.node.reply(msg, {
            "type": "error", "code": self.ERR_TEMP_UNAVAILABLE,
            "text": f"could not reach owner of key {key!r}"})

    async def on_poll(self, msg) -> None:
        out: Dict[str, list] = {}
        for key, off in (msg["body"].get("offsets") or {}).items():
            log = self.entries.get(str(key), {})
            o, lst = int(off), []
            while o in log:                  # contiguous run: gapless
                lst.append([o, log[o]])
                o += 1
            out[key] = lst
        await self.node.reply(msg, {"type": "poll_ok", "msgs": out})

    async def on_commit_offsets(self, msg) -> None:
        await self.node.reply(msg, {"type": "commit_offsets_ok"})
        changed = False
        for key, off in (msg["body"].get("offsets") or {}).items():
            key = str(key)
            if int(off) > self.committed.get(key, -1):
                self.committed[key] = int(off)
                changed = True
        if changed:
            self._ensure_flusher()

    async def on_list_committed_offsets(self, msg) -> None:
        keys = [str(k) for k in msg["body"].get("keys") or []]
        await self.node.reply(msg, {
            "type": "list_committed_offsets_ok",
            "offsets": {k: self.committed[k] for k in keys
                        if k in self.committed}})

    async def on_topology(self, msg) -> None:
        self.topology = {k: list(v)
                         for k, v in msg["body"]["topology"].items()}
        await self.node.reply(msg, {"type": "topology_ok"})

    async def on_gossip(self, msg) -> None:
        body = msg["body"]
        await self.node.reply(msg, {"type": "kafka_gossip_ok"})
        changed = False
        for key, ent in (body.get("entries") or {}).items():
            log = self.entries.setdefault(str(key), {})
            for off_s, value in ent.items():
                off = int(off_s)             # JSON keys arrive as str
                if off not in log:
                    log[off] = value
                    changed = True
        for key, off in (body.get("committed") or {}).items():
            if int(off) > self.committed.get(str(key), -1):
                self.committed[str(key)] = int(off)
                changed = True
        if changed:
            self._ensure_flusher()

    async def on_sink(self, msg) -> None:
        pass

    def _snapshot(self) -> tuple:
        return (tuple(sorted((k, tuple(sorted(v.items())))
                             for k, v in self.entries.items())),
                tuple(sorted(self.committed.items())))

    async def _flush_loop(self) -> None:
        while True:
            await asyncio.sleep(self.gossip_interval)
            try:
                snap = self._snapshot()
                for nb in self.topology.get(self.node.node_id, []):
                    if (self.acked.get(nb) != snap
                            and nb not in self._in_flight):
                        self._in_flight.add(nb)
                        asyncio.ensure_future(self._flush_one(nb, snap))
            except Exception as e:    # never kill the only flusher
                print(f"kafka flush loop error (continuing): {e!r}",
                      file=sys.stderr)

    async def _flush_one(self, nb: str, snap: tuple) -> None:
        try:
            reply = await self.node.rpc(
                nb, {"type": "kafka_gossip",
                     "entries": {k: {str(o): m for o, m in v.items()}
                                 for k, v in self.entries.items()},
                     "committed": dict(self.committed)},
                timeout=self.rpc_timeout)
            if reply.get("body", {}).get("type") != "error":
                self.acked[nb] = snap
        except asyncio.TimeoutError:
            pass                      # partitioned/lost: retry next tick
        finally:
            self._in_flight.discard(nb)


class TxnServer:
    """The Maelstrom ``txn-rw-register`` workload node: totally-
    available multi-key read/write transactions over gossip-replicated
    last-writer-wins registers (the batched twin is
    gossip_tpu/models/register.py; docs/WORKLOADS.md "Transactions").

    State: ``store[key] = (value, ts)`` with ``ts = (counter,
    node_index)`` — a Lamport pair, totally ordered, merged per key by
    LWW.  The counter is a Lamport clock: bumped past every counter
    seen in gossip, so a transaction's timestamp always exceeds every
    write it could have read — the commit discipline that makes the
    healthy system read-uncommitted-clean (every write of a txn shares
    ONE timestamp, so cross-key version orders collapse onto the total
    timestamp order and G0 cycles are impossible; the checker verifies
    rather than trusts this, runtime/txn_checker.py).

    Client op: ``txn {txn: [["r", k, null], ["w", k, v], ...]}`` —
    the micro-op list is VALIDATED first (an error reply is therefore
    a definite abort: nothing was applied — the G1a contract), then
    applied atomically on the single event loop: one timestamp for the
    whole transaction, reads see the local LWW state *as of this
    transaction* (its own earlier writes included), writes install
    ``(value, ts)``.  The reply is ``txn_ok {txn: [...completed...],
    ts: [counter, node_index]}`` — the timestamp rides the reply so
    the external checker can reconstruct per-key version orders from
    the trace alone.  Total availability: a partitioned node still
    answers from local state; convergence resumes with gossip.

    Dissemination is the CounterServer shape: interval-ticked
    full-state gossip with per-neighbor acked-snapshot dirtiness —
    at-least-once with idempotent LWW merge, so a healed partition
    converges with no special casing."""

    ERR_MALFORMED = 13            # Maelstrom "malformed-request"

    def __init__(self, node: MaelstromNode, rpc_timeout: float = 2.0,
                 gossip_interval: float = 0.05):
        self.node = node
        self.rpc_timeout = rpc_timeout
        self.gossip_interval = gossip_interval
        self.store: Dict[str, list] = {}   # key -> [value, [c, idx]]
        self.counter = 0                   # Lamport clock
        self.topology: Dict[str, List[str]] = {}
        self.acked: Dict[str, tuple] = {}  # nbr -> last acked snapshot
        self._in_flight: set = set()
        self._flusher: Optional[asyncio.Task] = None
        node.handle("txn", self.on_txn)
        node.handle("topology", self.on_topology)
        node.handle("txn_gossip", self.on_gossip)
        node.handle("txn_gossip_ok", self.on_sink)

    def _my_index(self) -> int:
        return self.node.node_ids.index(self.node.node_id)

    def _ensure_flusher(self) -> None:
        if self._flusher is None:
            self._flusher = asyncio.ensure_future(self._flush_loop())

    async def on_txn(self, msg) -> None:
        body = msg["body"]
        ops = body.get("txn")
        # validate the WHOLE micro-op list before touching state: an
        # error reply must be a definite abort (nothing applied), or
        # G1a stops being checkable (runtime/txn_checker.py)
        if not isinstance(ops, list) or not all(
                isinstance(op, list) and len(op) == 3
                and op[0] in ("r", "w")
                and (op[0] == "r" or op[2] is not None)
                for op in ops):
            await self.node.reply(msg, {
                "type": "error", "code": self.ERR_MALFORMED,
                "text": "txn must be a list of [\"r\"|\"w\", key, "
                        "value] micro-ops (write values non-null)"})
            return
        # one Lamport timestamp for the whole transaction — every
        # write shares it, which is what collapses cross-key version
        # orders onto one total order (class doc)
        self.counter += 1
        ts = [self.counter, self._my_index()]
        done = []
        wrote = False
        for f, k, v in ops:
            k = str(k)
            if f == "r":
                cur = self.store.get(k)
                done.append(["r", k, cur[0] if cur else None])
            else:
                cur = self.store.get(k)
                # >= not >: an equal timestamp can only be this
                # transaction's OWN earlier write (the counter bumps
                # per txn and the owner is us), and program order says
                # the later micro-op wins — a strict compare would
                # silently drop a txn's second write to the same key
                # while still acking it (review finding)
                if cur is None or ts >= cur[1]:
                    self.store[k] = [v, list(ts)]
                wrote = True
                done.append(["w", k, v])
        await self.node.reply(msg, {"type": "txn_ok", "txn": done,
                                    "ts": ts})
        if wrote:
            self._ensure_flusher()

    async def on_topology(self, msg) -> None:
        self.topology = {k: list(v)
                         for k, v in msg["body"]["topology"].items()}
        await self.node.reply(msg, {"type": "topology_ok"})

    async def on_gossip(self, msg) -> None:
        body = msg["body"]
        await self.node.reply(msg, {"type": "txn_gossip_ok"})
        changed = False
        for k, (v, ts) in (body.get("store") or {}).items():
            ts = [int(ts[0]), int(ts[1])]
            cur = self.store.get(str(k))
            if cur is None or ts > cur[1]:
                self.store[str(k)] = [v, ts]
                changed = True
        # Lamport merge: local events after this gossip must order
        # after everything the peer had seen
        peer_c = int(body.get("counter", 0))
        if peer_c > self.counter:
            self.counter = peer_c
            changed = True
        if changed:
            self._ensure_flusher()

    async def on_sink(self, msg) -> None:
        pass

    def _snapshot(self) -> tuple:
        return tuple(sorted((k, v[0], tuple(v[1]))
                            for k, v in self.store.items()))

    async def _flush_loop(self) -> None:
        while True:
            await asyncio.sleep(self.gossip_interval)
            try:
                snap = self._snapshot()
                for nb in self.topology.get(self.node.node_id, []):
                    if (self.acked.get(nb) != snap
                            and nb not in self._in_flight):
                        self._in_flight.add(nb)
                        asyncio.ensure_future(self._flush_one(nb, snap))
            except Exception as e:    # never kill the only flusher
                print(f"txn flush loop error (continuing): {e!r}",
                      file=sys.stderr)

    async def _flush_one(self, nb: str, snap: tuple) -> None:
        try:
            reply = await self.node.rpc(
                nb, {"type": "txn_gossip",
                     "store": {k: [v[0], list(v[1])]
                               for k, v in self.store.items()},
                     "counter": self.counter},
                timeout=self.rpc_timeout)
            if reply.get("body", {}).get("type") != "error":
                self.acked[nb] = snap
        except asyncio.TimeoutError:
            pass                      # partitioned/lost: retry next tick
        finally:
            self._in_flight.discard(nb)


WORKLOADS = ("broadcast", "counter", "kafka", "txn")


async def amain(gossip_interval: float = 0.0,
                workload: str = "broadcast") -> None:
    node = MaelstromNode()
    if workload == "counter":
        CounterServer(node,
                      gossip_interval=gossip_interval or 0.05)
    elif workload == "kafka":
        KafkaServer(node, gossip_interval=gossip_interval or 0.05)
    elif workload == "txn":
        TxnServer(node, gossip_interval=gossip_interval or 0.05)
    else:
        BroadcastServer(node, gossip_interval=gossip_interval)
    await node.run()


def main(argv: Optional[List[str]] = None) -> None:
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--gossip-interval", type=float, default=0.0,
                    help="batch relays per neighbor every INTERVAL "
                         "seconds (0 = the reference's immediate "
                         "per-message fan-out; the counter workload "
                         "always ticks, default 0.05)")
    ap.add_argument("--workload", default="broadcast",
                    choices=WORKLOADS,
                    help="protocol personality: the reference's "
                         "broadcast log, the Gossip Glomers counter "
                         "(per-node CRDT shards, merge = per-key "
                         "max), the replicated kafka-style log "
                         "(owner-assigned offsets, committed-offset "
                         "max merge), or txn-rw-register (totally-"
                         "available transactions over LWW "
                         "registers, Lamport-pair timestamps)")
    args = ap.parse_args(argv)
    asyncio.run(amain(args.gossip_interval, args.workload))


if __name__ == "__main__":
    main()
