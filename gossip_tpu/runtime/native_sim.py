"""ctypes wrapper presenting the C++ event-sim core with the GoNativeSim
API (runtime/gonative.py is the semantics contract and the fallback).

``make_event_sim(topology, net, horizon, prefer_native=True)`` returns
whichever engine is available; both expose the subset of the GoNativeSim
surface the backend seam and the parity tests use: ``partition``,
``broadcast``, ``run``, ``read``, ``hop_depths``, ``coverage_by_hop``,
``coverage_at``, ``msgs_sent``, ``now``, ``nodes`` (seen-sets).
Equivalence between the two engines is proven event-for-event in
tests/test_native.py.
"""

from __future__ import annotations

import ctypes
from typing import Dict, List, Optional

from gossip_tpu.runtime.gonative import GoNativeSim, NetConfig


class _SeenView:
    """Duck-types GoNativeNode for `msg in sim.nodes[i].seen` checks."""

    __slots__ = ("_sim", "_nid")

    def __init__(self, sim, nid):
        self._sim = sim
        self._nid = nid

    @property
    def seen(self):
        return set(self._sim.read(self._nid))

    @property
    def log(self):
        return self._sim.read(self._nid)


class NativeGoSim:
    """C++-backed event simulator (see gossip_tpu/native/eventsim.cpp)."""

    def __init__(self, topology: Dict[int, List[int]],
                 net: NetConfig = NetConfig(), horizon: float = 120.0):
        from gossip_tpu.native import load_eventsim
        lib = load_eventsim()
        if lib is None:
            raise RuntimeError("native eventsim unavailable (no g++?)")
        self._lib = lib
        self.net = net
        self.horizon = horizon
        self.n = (max(topology) + 1) if topology else 0
        self._h = lib.gsim_create(self.n)
        lib.gsim_config(self._h, net.latency, net.rpc_timeout,
                        net.backoff_base, int(net.faithful_ctx_bug),
                        net.max_backoff_doublings, horizon)
        for node, nbrs in topology.items():
            arr = (ctypes.c_int32 * len(nbrs))(*nbrs)
            lib.gsim_set_neighbors(self._h, node, arr, len(nbrs))
        self.nodes = {i: _SeenView(self, i) for i in range(self.n)}

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.gsim_destroy(self._h)
            self._h = None

    # -- GoNativeSim API --------------------------------------------------

    def partition(self, a: int, b: int, t0: float, t1: float) -> None:
        self._lib.gsim_partition(self._h, a, b, t0, t1)

    def broadcast(self, origin: int, message: int, t: float = 0.0) -> None:
        self._lib.gsim_broadcast(self._h, origin, message, t)

    def run(self, until: Optional[float] = None) -> None:
        self._lib.gsim_run(self._h, -1.0 if until is None else until)

    @property
    def msgs_sent(self) -> int:
        return int(self._lib.gsim_msgs_sent(self._h))

    @property
    def now(self) -> float:
        return float(self._lib.gsim_now(self._h))

    @property
    def deliveries(self):
        cnt = self._lib.gsim_delivery_count(self._h)
        times = (ctypes.c_double * cnt)()
        nodes = (ctypes.c_int32 * cnt)()
        msgs = (ctypes.c_int64 * cnt)()
        hops = (ctypes.c_int32 * cnt)()
        self._lib.gsim_deliveries(self._h, times, nodes, msgs, hops)
        return [(times[i], nodes[i], msgs[i], hops[i]) for i in range(cnt)]

    def read(self, node: int) -> List[int]:
        ln = self._lib.gsim_read_len(self._h, node)
        out = (ctypes.c_int64 * ln)()
        self._lib.gsim_read(self._h, node, out)
        return list(out)

    def delivery_count(self) -> int:
        return int(self._lib.gsim_delivery_count(self._h))

    def hop_depths(self, message: int) -> Dict[int, int]:
        out = {}
        for i in range(self.n):
            h = self._lib.gsim_min_hop(self._h, i, message)
            if h >= 0:
                out[i] = h
        return out

    def coverage_by_hop(self, message: int, max_hops: int) -> List[float]:
        depths = self.hop_depths(message)
        return [sum(1 for d in depths.values() if d <= h) / self.n
                for h in range(max_hops + 1)]

    def coverage_at(self, message: int, t: float) -> float:
        holders = {nid for (tt, nid, m, _) in self.deliveries
                   if m == message and tt <= t}
        return len(holders) / self.n


def native_available() -> bool:
    from gossip_tpu.native import load_eventsim
    return load_eventsim() is not None


def make_event_sim(topology: Dict[int, List[int]],
                   net: NetConfig = NetConfig(), horizon: float = 120.0,
                   prefer_native: bool = True):
    """Factory: C++ core when buildable, pure Python otherwise."""
    if prefer_native and native_available():
        return NativeGoSim(topology, net, horizon)
    return GoNativeSim(topology, net, horizon)
