from gossip_tpu.runtime.simulator import (  # noqa: F401
    CurveResult,
    UntilResult,
    simulate_curve,
    simulate_until,
)
