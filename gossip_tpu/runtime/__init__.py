"""Runtimes: the round-batched JAX backend, the go-native event-driven
parity backend, and the Maelstrom protocol node.

The simulator API pulls in jax (~seconds of import time); load it lazily so
jax-free entry points — the Maelstrom protocol node (spawned as one OS
process per cluster node, reference-style), the go-native event simulator,
``--help`` — start instantly (PEP 562).
"""

_LAZY = ("CurveResult", "UntilResult", "simulate_curve", "simulate_until",
         "compiled_until")

__all__ = list(_LAZY)


def __getattr__(name):
    if name in _LAZY:
        from gossip_tpu.runtime import simulator
        return getattr(simulator, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
