"""Weak-isolation anomaly checker for the txn-rw-register workload.

Maelstrom's ``txn-rw-register`` workload claims *total availability*:
every node answers every transaction, partitions included — which is
only an interesting claim if the isolation level it provides is
CHECKED, not asserted.  This module classifies the two anomaly classes
the read-uncommitted / read-committed boundary is defined by (Adya's
portable phenomena, the classes Jepsen's Elle checks first):

  * **G0 (dirty write)** — a cycle in the write-depends graph: for
    transactions T1, T2 (or a longer chain), T1's write to some key
    precedes T2's on that key while T2's write to another key precedes
    T1's.  Version order per key is the LWW timestamp order — the
    SAME total order the replicas converge by, so the checker judges
    the system against its own commit discipline.  The server stamps
    one timestamp per transaction (all its writes share it), which is
    exactly why a live run can never produce G0: cross-key version
    orders all collapse onto the one total timestamp order.  The
    checker does not assume that — it detects cycles over per-WRITE
    timestamps, so a planted violation on a synthetic trace is flagged
    (a checker that cannot fail is not a checker).
  * **G1a (aborted read / dirty read)** — a committed transaction
    read a value written by an ABORTED transaction.  The TxnServer
    validates a transaction's micro-op list BEFORE applying anything,
    so an error reply is a definite abort whose writes must never be
    visible; a client-side timeout is INDETERMINATE (the Maelstrom
    info-timeout convention — the txn may have applied with its ack
    lost) and its writes are legitimate reads, never G1a.
  * **G1b (intermediate read)** — a committed transaction read a
    value some OTHER transaction overwrote within itself: the writer
    wrote the same key again later in its own micro-op list, so only
    the final write is ever a committed version.  A transaction
    reading its own in-progress write is internal program order, not
    an isolation phenomenon — self-reads never flag.
  * **G1c (circular information flow)** — a cycle in ww ∪ wr that a
    read-depends edge closes (a ww-only cycle is already G0).  wr
    edges attribute each committed read to its unique writer; aborted
    writers are excluded — reading one is G1a, not information flow.
  * **lost update** — two COMMITTED transactions both read the same
    (key, pre-value) — ``None`` meaning the initial state — and both
    wrote that key: one update was computed from a version the
    other's write superseded.  REPORTED but excluded from ``ok``:
    the LWW register claims read-committed, not snapshot isolation,
    and losing concurrent updates across a partition is its
    documented merge semantics — the list is surfaced so captures
    can pin its presence or absence, never treated as a violation of
    a claim the system does not make.

Trace format (built by runtime/maelstrom_harness.run_txn_workload, or
synthesized by tests): a list of transaction records

    {"id": int, "node": str,
     "status": "committed" | "aborted" | "indeterminate",
     "reads":  [[key, value-or-None], ...],      # committed only
     "writes": [{"key": k, "value": v, "ts": [c, o]}, ...]}

``ts`` is the lexicographic (counter, owner-index) pair the server
assigned — compared as tuples.  Write values are UNIQUE per run (the
workload generator's contract, the one-add-tag convention), which is
what lets a read be attributed to exactly one writing transaction.

No jax imports — pure stdlib, shared by the harness, the CLI verdict
path, and the unit tests that plant anomalies.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Tuple

__all__ = ["check_txn_trace", "ww_edges", "wr_edges"]


def _writer_index(txns) -> Tuple[Dict[object, dict], list]:
    """``(value -> writing txn record, duplicate values)`` — write
    values are unique by contract; a duplicate is reported as a trace
    defect, not silently folded."""
    by_value: Dict[object, dict] = {}
    dups = []
    for t in txns:
        for w in t.get("writes", ()):
            v = w["value"]
            if v in by_value:
                dups.append(v)
            by_value[v] = t
    return by_value, dups


def ww_edges(txns) -> List[Tuple[int, int, object]]:
    """The write-depends edges: ``(t1_id, t2_id, key)`` whenever both
    wrote ``key`` and t1's write timestamp precedes t2's.  Timestamps
    compare as tuples (lexicographic (counter, owner) — the LWW total
    order)."""
    per_key: Dict[object, List[Tuple[tuple, int]]] = {}
    for t in txns:
        if t.get("status") == "aborted":
            continue            # an aborted write installs no version
        for w in t.get("writes", ()):
            if w.get("ts") is None:
                continue        # indeterminate: no server timestamp,
            per_key.setdefault(w["key"], []).append(  # no version order
                (tuple(w["ts"]), t["id"]))
    edges = []
    for key, writes in per_key.items():
        writes.sort()
        for i, (ts1, id1) in enumerate(writes):
            for ts2, id2 in writes[i + 1:]:
                if id1 != id2:
                    edges.append((id1, id2, key))
    return edges


def _find_cycle(edges) -> Optional[List[int]]:
    """A cycle in the ww digraph as a txn-id list, or None — iterative
    DFS with color marking (the trace can be long; no recursion)."""
    adj: Dict[int, list] = {}
    for a, b, _ in edges:
        adj.setdefault(a, []).append(b)
    color: Dict[int, int] = {}          # 0/absent=white, 1=grey, 2=black
    parent: Dict[int, int] = {}
    for root in adj:
        if color.get(root):
            continue
        stack = [(root, iter(adj.get(root, ())))]
        color[root] = 1
        while stack:
            node, it = stack[-1]
            advanced = False
            for nxt in it:
                if color.get(nxt, 0) == 0:
                    color[nxt] = 1
                    parent[nxt] = node
                    stack.append((nxt, iter(adj.get(nxt, ()))))
                    advanced = True
                    break
                if color.get(nxt) == 1:      # back edge: cycle
                    cyc = [nxt, node]
                    cur = node
                    while cur != nxt:
                        cur = parent[cur]
                        cyc.append(cur)
                    cyc.reverse()
                    return cyc
            if not advanced:
                color[node] = 2
                stack.pop()
    return None


def wr_edges(txns) -> List[Tuple[int, int, object]]:
    """The read-depends edges: ``(writer_id, reader_id, key)`` whenever
    a COMMITTED transaction read a value some other transaction wrote
    (unique write values attribute each read to exactly one writer).
    Aborted writers are excluded — a committed read of one is G1a, not
    information flow — and self-reads carry no cross-txn dependency."""
    by_value, _ = _writer_index(txns)
    edges = []
    for t in txns:
        if t.get("status") != "committed":
            continue
        for key, value in t.get("reads", ()):
            if value is None:
                continue
            w = by_value.get(value)
            if (w is None or w["id"] == t["id"]
                    or w.get("status") == "aborted"):
                continue
            edges.append((w["id"], t["id"], key))
    return edges


def _cycle_through(edges, start: int, nxt: int) -> Optional[List[int]]:
    """A closed walk ``[start, nxt, ..., start]`` that returns from
    ``nxt`` to ``start`` over ``edges``, or None — BFS, so the
    reported cycle is the shortest one the (start → nxt) edge closes."""
    adj: Dict[int, list] = {}
    for a, b, _ in edges:
        adj.setdefault(a, []).append(b)
    if nxt == start:
        return [start, start]
    parent: Dict[int, int] = {}
    seen = {nxt}
    q = deque([nxt])
    while q:
        cur = q.popleft()
        for m in adj.get(cur, ()):
            if m == start:
                back = [cur]
                while back[-1] != nxt:
                    back.append(parent[back[-1]])
                back.reverse()
                return [start] + back + [start]
            if m not in seen:
                seen.add(m)
                parent[m] = cur
                q.append(m)
    return None


def check_txn_trace(txns, final_reads: Optional[Dict] = None) -> dict:
    """Classify the trace; returns

    ``{"ok": bool, "g0": [...], "g1a": [...], "g1b": [...],
    "g1c": [...], "lost_update": [...], "defects": [...],
    "committed": int, "aborted": int, "indeterminate": int}``

    * ``g0``: each entry a dict with the offending txn-id cycle and
      the keys whose version orders close it;
    * ``g1a``: each entry ``{"reader": id, "key": k, "value": v,
      "writer": id}`` — a committed read of an aborted write;
    * ``g1b``: each entry adds ``"final"`` — a committed read of a
      write the writing transaction itself overwrote (intermediate
      state; self-reads never flag);
    * ``g1c``: a witness cycle in ww ∪ wr closed by a wr edge
      (``{"cycle": [...], "wr_edge": [w, r, key]}``);
    * ``lost_update``: ``{"key": k, "pre": v, "txns": [ids]}`` —
      committed read-modify-writes of the same version; REPORTED but
      excluded from ``ok`` (LWW read-committed loses concurrent
      updates by design — see module docstring);
    * ``defects``: trace-integrity problems that would make the
      verdict unsound (duplicate write values, same-key timestamp
      collisions) — reported separately so a broken harness can never
      masquerade as a clean isolation verdict.

    ``final_reads`` (optional): ``{node: {key: value}}`` final
    register states; checked for cross-node agreement and — when the
    winner is attributable — that each key's final value is the
    max-timestamp write's (the LWW convergence cross-check; verdict
    key ``converged``)."""
    txns = list(txns)
    by_value, dup_values = _writer_index(txns)
    defects = [f"duplicate write value {v!r} (unique-value contract)"
               for v in dup_values]

    # same-key timestamp collisions fork the LWW winner: a trace
    # carrying one cannot certify anything
    per_key_ts: Dict[tuple, int] = {}
    for t in txns:
        if t.get("status") == "aborted":
            continue
        for w in t.get("writes", ()):
            if w.get("ts") is None:
                continue                 # indeterminate: unordered
            sig = (w["key"], tuple(w["ts"]))
            if sig in per_key_ts and per_key_ts[sig] != t["id"]:
                defects.append(
                    f"timestamp collision on key {w['key']!r} at "
                    f"{w['ts']} (txns {per_key_ts[sig]} and "
                    f"{t['id']})")
            per_key_ts[sig] = t["id"]

    # -- G0: cycles in the write-depends graph -------------------------
    g0 = []
    edges = ww_edges(txns)
    cycle = _find_cycle(edges)
    if cycle is not None:
        pairs = set(zip(cycle, cycle[1:]))
        keys = sorted({str(k) for a, b, k in edges if (a, b) in pairs})
        g0.append({"cycle": cycle, "keys": keys})

    # -- G1a: committed reads of aborted writes ------------------------
    g1a = []
    for t in txns:
        if t.get("status") != "committed":
            continue
        for key, value in t.get("reads", ()):
            if value is None:
                continue
            writer = by_value.get(value)
            if writer is not None and writer.get("status") == "aborted":
                g1a.append({"reader": t["id"], "key": key,
                            "value": value, "writer": writer["id"]})

    # -- G1b: committed reads of intermediate writes -------------------
    # Only a transaction's LAST write to a key is ever a committed
    # version; a foreign read of an earlier one observed state that
    # never existed between transactions.  Self-reads are program
    # order (a txn reading its own in-progress write), never flagged.
    g1b = []
    for t in txns:
        if t.get("status") != "committed":
            continue
        for key, value in t.get("reads", ()):
            if value is None:
                continue
            writer = by_value.get(value)
            if (writer is None or writer["id"] == t["id"]
                    or writer.get("status") == "aborted"):
                continue        # unattributed, self, or already G1a
            same_key = [w["value"] for w in writer.get("writes", ())
                        if w["key"] == key]
            if value in same_key and same_key[-1] != value:
                g1b.append({"reader": t["id"], "writer": writer["id"],
                            "key": key, "value": value,
                            "final": same_key[-1]})

    # -- G1c: circular information flow --------------------------------
    # A cycle in ww ∪ wr that a read-depends edge closes; ww-only
    # cycles are already G0, so each candidate starts from a wr edge.
    g1c = []
    wr = wr_edges(txns)
    for a, b, key in wr:
        cyc = _cycle_through(edges + wr, a, b)
        if cyc is not None:
            g1c.append({"cycle": cyc, "wr_edge": [a, b, str(key)]})
            break               # one witness cycle, like G0

    # -- lost update: two committed read-modify-writes of one version --
    # Reported, not folded into ``ok`` (see module docstring): LWW
    # read-committed registers lose concurrent updates by design.
    lost_update = []
    rmw: Dict[Tuple[object, object], List[int]] = {}
    for t in txns:
        if t.get("status") != "committed":
            continue
        wrote = {w["key"] for w in t.get("writes", ())}
        for key, value in t.get("reads", ()):
            if key in wrote:
                rmw.setdefault((key, value), []).append(t["id"])
    for (key, pre), ids in sorted(rmw.items(),
                                  key=lambda kv: (str(kv[0][0]),
                                                  str(kv[0][1]))):
        ids = sorted(set(ids))
        if len(ids) >= 2:
            lost_update.append({"key": key, "pre": pre, "txns": ids})

    out = {"g0": g0, "g1a": g1a, "g1b": g1b, "g1c": g1c,
           "lost_update": lost_update, "defects": defects,
           "committed": sum(1 for t in txns
                            if t.get("status") == "committed"),
           "aborted": sum(1 for t in txns
                          if t.get("status") == "aborted"),
           "indeterminate": sum(1 for t in txns
                                if t.get("status") == "indeterminate")}

    if final_reads is not None:
        states = list(final_reads.values())
        agree = all(s == states[0] for s in states[1:])
        lww_ok = True
        if states:
            # expected winner per key: the max-ts committed write —
            # >= so a transaction's SECOND write to one key (same
            # txn timestamp, later program order) is the winner, the
            # TxnServer's apply rule
            best: Dict[object, Tuple[tuple, object]] = {}
            indet_vals: Dict[object, set] = {}
            for t in txns:
                if t.get("status") == "aborted":
                    continue
                for w in t.get("writes", ()):
                    if w.get("ts") is None:
                        # a timed-out txn's write MAY have applied
                        # with its ack lost (the info-timeout
                        # convention) — admissible as a final winner,
                        # never required
                        indet_vals.setdefault(w["key"], set()).add(
                            w["value"])
                        continue
                    ts = tuple(w["ts"])
                    cur = best.get(w["key"])
                    if cur is None or ts >= cur[0]:
                        best[w["key"]] = (ts, w["value"])
            for key, (_, value) in best.items():
                got = states[0].get(key, states[0].get(str(key)))
                if got != value and got not in indet_vals.get(key,
                                                              ()):
                    lww_ok = False
            # an ABORTED write visible in the final state is a failure
            # on ANY key — including one `best` never covers (no
            # committed write): a server that applied before its error
            # reply must not certify clean (review finding)
            aborted_vals = {w["value"] for t in txns
                            if t.get("status") == "aborted"
                            for w in t.get("writes", ())}
            for got in states[0].values():
                if got is not None and got in aborted_vals:
                    lww_ok = False
        out["converged"] = bool(agree and lww_ok)

    out["ok"] = (not (g0 or g1a or g1b or g1c or defects)
                 and out.get("converged", True))
    return out
