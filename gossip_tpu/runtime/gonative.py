"""``go-native`` backend: discrete-event simulator of the reference semantics.

This is the Backend seam's parity side (SURVEY.md §7 layer 5): a faithful
event-driven reimplementation of the reference node's behavior so the batched
TPU kernels can be validated against it curve-for-curve (BASELINE.json north
star: "convergence curves matching the Go reference at N=1024").

Semantics reproduced (SURVEY.md §2.2, reference main.go):

  1. **Ack-before-process** (main.go:109-118): ``broadcast_ok`` is sent
     before dedup/append/fan-out.
  2. **At-least-once + idempotent receipt** (main.go:80-87 + 113): unbounded
     retries; duplicates suppressed by the per-node dedup set.
  3. **Sender exclusion** (main.go:73-75): never relay back to the peer the
     message came from.
  4. **Sequential, blocking fan-out** (main.go:72-88): neighbor i+1's RPC
     starts only after neighbor i's ack returns.
  5. **Retry liveness hole** (main.go:77-87, defect §2.2.7): the 2 s context
     is created once per neighbor, *before* the retry loop; after it expires
     every retry's ``SyncRPC`` fails instantly, so the loop never exits and
     later neighbors are never contacted by this relayer.  Crucially the
     resends still go on the wire (the send precedes the ctx check), so a
     healed partition still eventually delivers — but only via growing
     backoff.  ``NetConfig.faithful_ctx_bug=False`` models the fixed node
     (fresh context per attempt, loop proceeds after success).

Not reproducible single-threaded (and deliberately absent): the dedup TOCTOU
race and the unsynchronized topology write (§2.2.5-6) — the batched kernels
make both structurally impossible, and so does this sequential event loop.

The "network" is the event queue itself: per-link one-way latency plus
partition windows, standing in for Maelstrom's external fault injection
(SURVEY.md §4).

**The parity clock** (SURVEY.md §7 "Event-driven vs. round-synchronous
parity", mapping documented here as required): the round-synchronous flood
kernel advances one BFS shell per round, so its coverage after round t is
exactly the BFS ball of radius t (tests/test_gonative.py checks this against
an independent numpy BFS).  The event-driven node is *faster than its own hop
count*: transitive relays race ahead of the origin's sequential fan-out loop,
so a node's first receipt may travel a longer-hop path that was quicker in
wall-time.  Hence per-hop coverage satisfies an inequality, not equality:

    event_sim.coverage_by_hop(m, t)  <=  flood_kernel.coverage[t]  (= BFS)

with equality (a) in the limit (both converge to the same covered set — the
Maelstrom checker's actual invariant, SURVEY.md §4) and (b) exactly, per
round, on graphs where every node has at most one non-sender neighbor (paths,
k=2 rings), where no relay race exists.  ``hop_depths`` records the *minimum*
hop over all arrivals (duplicates included — a deduped arrival still arrived),
which is the tightest observable bound on BFS distance.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class NetConfig:
    """Network + protocol constants (reference values from BASELINE.md)."""

    latency: float = 0.001        # one-way message latency, seconds
    rpc_timeout: float = 2.0      # SyncRPC context (main.go:77)
    backoff_base: float = 0.1     # 100 ms * 2^k (main.go:85-86)
    faithful_ctx_bug: bool = True # reproduce defect §2.2.7 (True = as shipped)
    max_backoff_doublings: int = 40  # int-overflow guard the reference lacks


class GoNativeNode:
    """Per-node state: the MessageKeeper analog (main.go:22-58)."""

    __slots__ = ("id", "neighbors", "log", "seen")

    def __init__(self, node_id: int):
        self.id = node_id
        self.neighbors: List[int] = []
        self.log: List[int] = []       # append-only ordered log (read_ok)
        self.seen: set = set()         # dedup set (broadcasted map)


class GoNativeSim:
    """Event-driven cluster simulation.

    ``topology`` maps node id -> neighbor list (the reference's runtime
    ``topology`` message, main.go:132-149).  Client broadcasts are injected
    with :meth:`broadcast`; :meth:`run` drains the event queue to the horizon.
    """

    def __init__(self, topology: Dict[int, List[int]],
                 net: NetConfig = NetConfig(), horizon: float = 120.0):
        self.net = net
        self.horizon = horizon
        self.nodes: Dict[int, GoNativeNode] = {}
        for nid, nbrs in topology.items():
            node = GoNativeNode(nid)
            node.neighbors = list(nbrs)
            self.nodes[nid] = node
        self._q: List[Tuple[float, int, tuple]] = []
        self._seq = itertools.count()
        self._partitions: List[Tuple[int, int, float, float]] = []
        self.msgs_sent = 0          # every wire message (requests + acks)
        self.deliveries: List[Tuple[float, int, int, int]] = []
        # (time, node, message, hop) — first receipt only
        self._min_hop: Dict[Tuple[int, int], int] = {}  # (node, msg) -> hop
        self.now = 0.0

    # -- network ---------------------------------------------------------

    def partition(self, a: int, b: int, t0: float, t1: float) -> None:
        """Block the (a, b) link in both directions during [t0, t1)."""
        self._partitions.append((a, b, t0, t1))

    def _link_open(self, a: int, b: int, t: float) -> bool:
        for (x, y, t0, t1) in self._partitions:
            if {a, b} == {x, y} and t0 <= t < t1:
                return False
        return True

    def _push_event(self, t: float, ev: tuple) -> None:
        # Never drop: events beyond the current horizon stay queued so a
        # later run(until=...) can still process them (at-least-once holds
        # across resumed runs); run() bounds the clock, not the queue.
        heapq.heappush(self._q, (t, next(self._seq), ev))

    # -- protocol --------------------------------------------------------

    def broadcast(self, origin: int, message: int, t: float = 0.0) -> None:
        """Client injection: a Maelstrom client `broadcast` op landing at one
        node (main.go:102).  The client is not in the topology, so sender
        exclusion does not apply to it (§2.2.3)."""
        self._push_event(t, ("deliver", origin, -1, message, 0))

    def _deliver(self, t: float, dst: int, src: int, message: int,
                 hop: int) -> None:
        node = self.nodes[dst]
        self.msgs_sent += 1               # the broadcast request itself
        # 1. ack FIRST (main.go:109) — before dedup or fan-out.
        self.msgs_sent += 1               # broadcast_ok back to src/client
        k = (dst, message)
        if k not in self._min_hop or hop < self._min_hop[k]:
            self._min_hop[k] = hop
        # 2. dedup (main.go:113).
        if message in node.seen:
            return
        node.seen.add(message)
        node.log.append(message)          # append (main.go:117)
        self.deliveries.append((t, dst, message, hop))
        # 3. fan-out (main.go:118): sequential, excluding the sender.
        targets = [nb for nb in node.neighbors if nb != src]
        if targets:
            self._push_event(t, ("fanout", dst, message, hop, tuple(targets),
                                 0, 0, t))

    def _fanout(self, t: float, src: int, message: int, hop: int,
                targets: tuple, idx: int, attempt: int,
                ctx_start: float) -> None:
        """One retry-loop step of the sequential fan-out (main.go:72-88).

        ``idx`` is the neighbor being worked; ``attempt`` the retry count for
        it; ``ctx_start`` when its 2 s context was created (main.go:77)."""
        if idx >= len(targets):
            return
        nb = targets[idx]
        net = self.net
        deadline = ctx_start + net.rpc_timeout
        # SyncRPC sends unconditionally, then waits on the reply channel with
        # the (possibly already expired) context.
        if self._link_open(src, nb, t):
            self._push_event(t + net.latency,
                             ("deliver", nb, src, message, hop + 1))
            if t + 2 * net.latency <= deadline:
                # Reply arrives in time: this neighbor succeeds; move to the
                # next neighbor once the ack is back (blocking fan-out).
                self._push_event(t + 2 * net.latency,
                                 ("fanout", src, message, hop, targets,
                                  idx + 1, 0, t + 2 * net.latency))
                return
            # else: delivered, but SyncRPC still errors at the deadline.
        # Failure path: SyncRPC returns error — at the ctx deadline for the
        # first in-window attempt, instantly once the ctx is expired.
        fail_at = max(t, deadline)
        k = min(attempt, net.max_backoff_doublings)
        retry_at = fail_at + net.backoff_base * (2 ** k)
        if net.faithful_ctx_bug:
            # Defect §2.2.7: same dead context forever; the loop never exits
            # and later neighbors are never reached from this relayer — but
            # each retry still resends (the delivery above), so a healed link
            # eventually gets the message.
            self._push_event(retry_at, ("fanout", src, message, hop, targets,
                                        idx, attempt + 1, ctx_start))
        else:
            # Fixed node: fresh context per attempt; a post-heal attempt
            # succeeds and the fan-out proceeds.
            self._push_event(retry_at, ("fanout", src, message, hop, targets,
                                        idx, attempt + 1, retry_at))

    # -- driver ----------------------------------------------------------

    def run(self, until: Optional[float] = None) -> None:
        stop = self.horizon if until is None else until
        while self._q and self._q[0][0] <= stop:
            t, _, ev = heapq.heappop(self._q)
            self.now = t
            if ev[0] == "deliver":
                self._deliver(t, *ev[1:])
            else:
                self._fanout(t, *ev[1:])

    # -- observability (the reference had none — SURVEY.md §5) -----------

    def read(self, node: int) -> List[int]:
        """The `read` handler: ordered log snapshot (main.go:123-130)."""
        return list(self.nodes[node].log)

    def delivery_count(self) -> int:
        """First-receipt count (cheap on both engines — the native core's
        ``deliveries`` property marshals full arrays)."""
        return len(self.deliveries)

    def hop_depths(self, message: int) -> Dict[int, int]:
        """Min hop over all arrivals per node (>= BFS distance; == on
        race-free graphs — see the parity-clock note in the module doc)."""
        return {nid: hop for (nid, m), hop in self._min_hop.items()
                if m == message}

    def coverage_by_hop(self, message: int, max_hops: int) -> List[float]:
        """coverage[h] = fraction of nodes holding ``message`` within h hops.

        This is the hop-depth clock on which the round-synchronous flood
        kernel is exactly comparable: flood coverage after round t == the
        BFS ball of radius t (ops/propagate.flood_gather docstring)."""
        depths = self.hop_depths(message)
        n = len(self.nodes)
        return [sum(1 for d in depths.values() if d <= h) / n
                for h in range(max_hops + 1)]

    def coverage_at(self, message: int, t: float) -> float:
        """Wall-clock coverage (Maelstrom's stable-latency view)."""
        n = len(self.nodes)
        holders = {nid for (tt, nid, m, _) in self.deliveries
                   if m == message and tt <= t}
        return len(holders) / n


def topology_from_table(topo) -> Dict[int, List[int]]:
    """Convert a padded-table Topology into the dict form the event sim (and
    the reference's `topology` message, main.go:132-149) uses."""
    import numpy as np
    nbrs = np.asarray(topo.nbrs)
    deg = np.asarray(topo.deg)
    return {i: [int(x) for x in nbrs[i, :deg[i]]] for i in range(topo.n)}
