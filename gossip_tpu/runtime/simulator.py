"""Round-batched simulation drivers (the ``jax-tpu`` backend).

Two drivers over the same round step:

  * :func:`simulate_curve` — ``lax.scan`` over a fixed number of rounds,
    recording the coverage curve + cumulative message counts.  This is the
    observability product the reference never had (SURVEY.md §5: Maelstrom
    computed everything externally).
  * :func:`simulate_until` — ``lax.while_loop`` until coverage >= target,
    for racing the wall-clock (the bench path).  No per-round host sync:
    the whole loop is one XLA program.

The Go-semantics event-driven backend (``go-native``) lives in
:mod:`gossip_tpu.runtime.gonative`; both implement "run this protocol config
to convergence", which is the Backend seam from BASELINE.json's north star.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from gossip_tpu.config import FaultConfig, ProtocolConfig, RunConfig
from gossip_tpu.models.si import coverage, make_si_round
from gossip_tpu.models.state import SimState, alive_mask, init_state
from gossip_tpu.topology.generators import Topology


@dataclasses.dataclass
class CurveResult:
    coverage: np.ndarray        # float32[T] min-over-rumors coverage after round t
    msgs: np.ndarray            # float32[T] cumulative messages after round t
    rounds_to_target: int       # first round index with coverage >= target (+1),
                                # or -1 if never reached
    final_coverage: float
    state: SimState


@dataclasses.dataclass
class UntilResult:
    rounds: int
    coverage: float
    msgs: float
    state: SimState


def _build(proto: ProtocolConfig, topo: Topology, run: RunConfig,
           fault: Optional[FaultConfig]):
    """step + its table args + init.  Tables travel as jit ARGUMENTS and the
    alive mask is rebuilt in-trace, so no O(N) buffer is inlined into the
    XLA compile request (models/swim.py doc — the axon remote-compile
    endpoint rejects oversized requests)."""
    step, tables = make_si_round(proto, topo, fault, run.origin, tabled=True)
    init = init_state(run, proto, topo.n)
    return step, tables, init


def simulate_curve(proto: ProtocolConfig, topo: Topology, run: RunConfig,
                   fault: Optional[FaultConfig] = None) -> CurveResult:
    from gossip_tpu.ops import nemesis as NE
    step, tables, init = _build(proto, topo, run, fault)
    step = NE.drop_lost(step, NE.get(fault))

    @jax.jit
    def scan(init_state_, *tbl):
        alive = NE.metric_alive(fault, topo.n, run.origin)
        def body(state, _):
            state = step(state, *tbl)
            return state, (coverage(state.seen, alive), state.msgs)
        return jax.lax.scan(body, init_state_, None, length=run.max_rounds)

    final, (covs, msgs) = scan(init, *tables)
    covs = np.asarray(covs)
    msgs = np.asarray(msgs)
    hit = np.nonzero(covs >= run.target_coverage)[0]
    return CurveResult(
        coverage=covs,
        msgs=msgs,
        rounds_to_target=int(hit[0]) + 1 if len(hit) else -1,
        final_coverage=float(covs[-1]),
        state=final,
    )


def simulate_until(proto: ProtocolConfig, topo: Topology, run: RunConfig,
                   fault: Optional[FaultConfig] = None,
                   timing: Optional[dict] = None) -> UntilResult:
    """``timing``: pass a dict to get ``compile_s``/``steady_s`` filled
    via the AOT split (utils.trace.aot_timed) instead of one fused call —
    the hardware-table contract that walls never mix compile with
    steady state."""
    from gossip_tpu.ops import nemesis as NE
    step, tables, init = _build(proto, topo, run, fault)
    step = NE.drop_lost(step, NE.get(fault))
    target = jnp.float32(run.target_coverage)
    alive = NE.metric_alive(fault, topo.n, run.origin)  # host final metric

    @jax.jit
    def loop(init_state_, *tbl):
        alive_t = NE.metric_alive(fault, topo.n, run.origin)
        def cond(state):
            return ((coverage(state.seen, alive_t) < target)
                    & (state.round < run.max_rounds))
        def body(state):
            return step(state, *tbl)
        return jax.lax.while_loop(cond, body, init_state_)

    from gossip_tpu.utils.trace import maybe_aot_timed
    final = maybe_aot_timed(loop, timing, init, *tables, label="solo")
    return UntilResult(
        rounds=int(final.round),
        coverage=float(coverage(final.seen, alive)),
        msgs=float(final.msgs),
        state=final,
    )


def _swim_recorder(proto: ProtocolConfig, n: int, n_pad: int,
                   n_shards: int):
    """In-loop metrics row for the SWIM drivers (ops/round_metrics,
    failure-detection reading of the counters): ``newly`` is newly
    CONFIRMED-DEAD (subject, observer) wire entries — the detection
    front's growth; ``front`` the per-shard fraction of observers
    holding any confirmed death; ``offered`` the dissemination upper
    bound fanout*n*S (every diss message carries the full S-subject
    wire row); ``bytes`` the pmax contribution table's per-device
    egress (``4*n_pad*S``; 0 on a single device — SWIM's only
    collective is the wire merge).  The previous confirmed count rides
    the carry as one scalar (parallel/sharded._dense_recorder
    liveness rationale)."""
    from gossip_tpu.models.swim import DEAD_WIRE
    from gossip_tpu.ops import round_metrics as RM
    s_subj = proto.swim_subjects
    offered = float(proto.fanout * n * s_subj)
    per_round_bytes = (0.0 if n_shards == 1
                       else 4.0 * n_pad * s_subj + 4.0)

    def rec(m, prev, msgs0, s1, obs_pad):
        dead_tbl = s1.wire == DEAD_WIRE
        confirmed = jnp.sum(dead_tbl & obs_pad[:, None],
                            dtype=jnp.float32)
        newly = confirmed - prev
        return RM.record(
            m, newly=newly, msgs=s1.msgs - msgs0,
            dup=RM.dup_estimate(offered, newly),
            bytes=per_round_bytes,
            front=RM.front_bool(dead_tbl, obs_pad, n_shards)), confirmed

    def init_prev(state, obs_pad):
        return jnp.sum((state.wire == DEAD_WIRE) & obs_pad[:, None],
                       dtype=jnp.float32)

    return rec, init_prev


def _swim_obs_pad(alive_obs, n: int, n_pad: int):
    """The observer mask padded to the sharded row count (padding rows
    never observe; a no-op when unsharded)."""
    if n_pad == n:
        return alive_obs
    return jnp.zeros((n_pad,), jnp.bool_).at[:n].set(alive_obs)


def simulate_swim_curve(proto: ProtocolConfig, n: int, rounds: int,
                        dead_nodes=(), fail_round: int = 0,
                        fault: Optional[FaultConfig] = None,
                        topo: Optional[Topology] = None,
                        seed: int = 0, mesh=None, timing=None):
    """SWIM detection-fraction curve over ``rounds`` (lax.scan, one XLA
    program).  With ``mesh`` the sharded twin runs instead.  Returns
    (detection[T] as numpy, final SwimState).  ``timing``: optional
    compile/steady AOT-split dict (utils/trace.maybe_aot_timed); with
    an active run ledger the scan carries a round-metrics buffer stack
    (ops/round_metrics)."""
    from gossip_tpu.models import swim as SW
    # tabled=True: topology arrays enter the jitted scan as ARGUMENTS, not
    # closure constants — a closed-over 1M-row neighbor table would be
    # serialized inline into the compile request (models/swim doc).
    if mesh is None:
        step, tables = SW.make_swim_round(proto, n, tuple(dead_nodes),
                                          fail_round, fault, topo,
                                          tabled=True, max_rounds=rounds)
        init = SW.init_swim_state(n, proto.swim_subjects, seed)
    else:
        from gossip_tpu.parallel.sharded_swim import (
            init_sharded_swim_state, make_sharded_swim_round)
        step, tables = make_sharded_swim_round(proto, n, mesh,
                                               tuple(dead_nodes),
                                               fail_round, fault, topo,
                                               tabled=True,
                                               max_rounds=rounds)
        init = init_sharded_swim_state(n, proto, mesh, seed)
    # metric targets: static scripted deaths + permanent churn deaths
    # (the kernels got the static dead_nodes only — churn die/recover
    # timing lives in the schedule, not the fail_round mask)
    dead = SW.detection_targets(dead_nodes, fault)
    rotate = proto.swim_rotate
    epoch_rounds = SW.resolve_epoch_rounds(proto, n)
    from gossip_tpu.ops import round_metrics as RM
    from gossip_tpu.utils.trace import maybe_aot_timed
    n_pad = int(init.wire.shape[0])
    n_shards = int(np.prod(list(mesh.shape.values()))) if mesh else 1
    rec, init_prev = (_swim_recorder(proto, n, n_pad, n_shards)
                      if RM.wanted() else (None, None))

    @jax.jit
    def scan(state, *tbl):
        # Observer population: nodes that stay alive after fail_round.
        # Without this mask, fault-dead observers sit in the denominator
        # and the detection fraction plateaus at the alive fraction, never
        # reaching the target.  Built in-trace: no O(N) inline constant.
        alive_obs = SW.observer_alive(n, tuple(dead_nodes), fault)
        obs_pad = _swim_obs_pad(alive_obs, n, n_pad)
        m0 = (RM.init(rounds, n_shards, "simulate_swim_curve")
              if rec else None)
        p0 = init_prev(state, obs_pad) if rec else None

        def body(carry, _):
            s0, m, prev = carry
            msgs0 = s0.msgs
            s = step(s0, *tbl)
            if m is not None:
                m, prev = rec(m, prev, msgs0, s, obs_pad)
            # observers: rows [0, n) — drops the mesh padding rows (a no-op
            # slice in the unsharded case); detection over the dead subjects
            # in the window of the round just executed (s.round - 1)
            window = SW.subject_window(s.round - 1, proto.swim_subjects, n,
                                       rotate, epoch_rounds)
            frac = SW.detection_fraction(
                SW.SwimState(s.wire[:n], s.timer[:n], s.round,
                             s.base_key, s.msgs), dead,
                alive_obs, subj_gids=window) if dead else 0.0
            return (s, m, prev), frac
        return jax.lax.scan(body, (state, m0, p0), None, length=rounds)

    (final, _, _), fracs = maybe_aot_timed(scan, timing, init, *tables,
                                           label="solo")
    return np.asarray(fracs), final


def simulate_swim_until(proto: ProtocolConfig, n: int, max_rounds: int,
                        target: float, dead_nodes=(), fail_round: int = 0,
                        fault: Optional[FaultConfig] = None,
                        topo: Optional[Topology] = None,
                        seed: int = 0, mesh=None,
                        timing: Optional[dict] = None):
    """SWIM to target detection (lax.while_loop, one XLA program) — the
    early-exit twin of :func:`simulate_swim_curve` for runs that don't
    need the curve: detection typically completes in ~40% of the curve
    driver's fixed budget, and this driver stops there.  Returns
    (rounds, detection, peak, final SwimState); rounds == final.round
    when the target was hit, max_rounds otherwise (caller compares
    detection).  ``peak`` is the best detection seen over the run — under
    a rotating subject window the final round's detection can drop back
    toward 0 after the window leaves the dead node's epoch, so the peak,
    not the final, is the rotating headline number."""
    from gossip_tpu.models import swim as SW
    if mesh is None:
        step, tables = SW.make_swim_round(proto, n, tuple(dead_nodes),
                                          fail_round, fault, topo,
                                          tabled=True, max_rounds=max_rounds)
        init = SW.init_swim_state(n, proto.swim_subjects, seed)
    else:
        from gossip_tpu.parallel.sharded_swim import (
            init_sharded_swim_state, make_sharded_swim_round)
        step, tables = make_sharded_swim_round(proto, n, mesh,
                                               tuple(dead_nodes),
                                               fail_round, fault, topo,
                                               tabled=True,
                                               max_rounds=max_rounds)
        init = init_sharded_swim_state(n, proto, mesh, seed)
    # metric targets: static scripted deaths + permanent churn deaths
    dead = SW.detection_targets(dead_nodes, fault)
    rotate = proto.swim_rotate
    epoch_rounds = SW.resolve_epoch_rounds(proto, n)
    tgt = jnp.float32(target)
    from gossip_tpu.ops import round_metrics as RM
    n_pad = int(init.wire.shape[0])
    n_shards = int(np.prod(list(mesh.shape.values()))) if mesh else 1
    rec, init_prev = (_swim_recorder(proto, n, n_pad, n_shards)
                      if RM.wanted() else (None, None))

    @jax.jit
    def loop(state, *tbl):
        alive_obs = SW.observer_alive(n, tuple(dead_nodes), fault)
        obs_pad = _swim_obs_pad(alive_obs, n, n_pad)
        m0 = (RM.init(max_rounds, n_shards, "simulate_swim_until")
              if rec else None)
        p0 = init_prev(state, obs_pad) if rec else None

        def detection(s):
            window = SW.subject_window(s.round - 1, proto.swim_subjects, n,
                                       rotate, epoch_rounds)
            return SW.detection_fraction(
                SW.SwimState(s.wire[:n], s.timer[:n], s.round,
                             s.base_key, s.msgs), dead,
                alive_obs, subj_gids=window) if dead else jnp.float32(0.0)

        def cond(carry):
            s, det, _, _, _ = carry
            return (det < tgt) & (s.round < max_rounds)

        def body(carry):
            s0, _, peak, m, prev = carry
            msgs0 = s0.msgs
            s = step(s0, *tbl)
            if m is not None:
                m, prev = rec(m, prev, msgs0, s, obs_pad)
            det = detection(s)
            return s, det, jnp.maximum(peak, det), m, prev

        return jax.lax.while_loop(
            cond, body,
            (state, jnp.float32(0.0), jnp.float32(0.0), m0, p0))

    from gossip_tpu.utils.trace import maybe_aot_timed
    final, det, peak, _, _ = maybe_aot_timed(loop, timing, init, *tables,
                                             label="solo")
    return int(final.round), float(det), float(peak), final


def checkpointed_swim(proto: ProtocolConfig, n: int, run: RunConfig,
                      path: str, every: int = 50, dead_nodes=(),
                      fail_round: int = 0,
                      fault: Optional[FaultConfig] = None,
                      topo: Optional[Topology] = None, mesh=None,
                      resume_state=None, want_curve: bool = False,
                      curve_prefix=(), extra_meta=None):
    """Fixed-budget SWIM run in compiled segments with atomic npz
    checkpoints — the failure-detection twin of the SI ``--checkpoint``
    engines (utils/checkpoint.run_with_checkpoints; the reference loses
    all state on process death, main.go:22-26).  The rotating subject
    window needs no host-side driver — ``subject_window`` is computed
    in-trace from ``state.round`` — so the generic segment runner drives
    it unchanged and resume is bitwise (tests/test_checkpoint_sharded).

    ``want_curve`` records the per-round detection fraction; the final
    detection is computed from the final state either way.  With
    ``mesh`` the node-sharded twin runs (resume re-places the padded
    rows via restore_sharded_swim_state).  Returns
    ``(final_state, detection, curve-or-None)``.

    Churn schedules (events + drop ramps; the SWIM factories reject
    partitions — membership overlay) run in the segments exactly as in
    the straight drivers: the step indexes its ABSOLUTE ``state.round``,
    which the checkpoint persists, so resume == straight run bitwise
    under an active fault program (utils/checkpoint crash contract;
    tests/test_crash_safety.py pins detection 1.0 on the scheduled
    permanent crash across a kill).  ``detection_targets`` already
    folds permanent churn deaths into the metric target set, and
    ``observer_alive`` drops them from the observer denominator.
    """
    from gossip_tpu.models import swim as SW
    from gossip_tpu.utils.checkpoint import run_with_checkpoints
    dead = tuple(dead_nodes)
    rotate = proto.swim_rotate
    epoch_rounds = SW.resolve_epoch_rounds(proto, n)
    if mesh is None:
        step, tables = SW.make_swim_round(proto, n, dead, fail_round,
                                          fault, topo, tabled=True,
                                          max_rounds=run.max_rounds)
        state = (resume_state if resume_state is not None
                 else SW.init_swim_state(n, proto.swim_subjects, run.seed))
    else:
        from gossip_tpu.parallel.sharded_swim import (
            init_sharded_swim_state, make_sharded_swim_round,
            restore_sharded_swim_state)
        step, tables = make_sharded_swim_round(proto, n, mesh, dead,
                                               fail_round, fault, topo,
                                               tabled=True,
                                               max_rounds=run.max_rounds)
        state = (restore_sharded_swim_state(resume_state, mesh)
                 if resume_state is not None
                 else init_sharded_swim_state(n, proto, mesh, run.seed))

    # metric targets: static scripted deaths + permanent churn deaths
    # (`dead` stays static-only — it scripts the kernels' fail_round mask)
    targets = SW.detection_targets(dead, fault)

    def detection(s):
        # same in-trace construction as simulate_swim_curve's body:
        # detection of the round just executed (window at s.round - 1),
        # observers sliced to the real rows
        alive_obs = SW.observer_alive(n, dead, fault)
        window = SW.subject_window(s.round - 1, proto.swim_subjects, n,
                                   rotate, epoch_rounds)
        return SW.detection_fraction(
            SW.SwimState(s.wire[:n], s.timer[:n], s.round,
                         s.base_key, s.msgs), targets,
            alive_obs, subj_gids=window) if targets else jnp.float32(0.0)

    curve_fn = detection if want_curve else None
    remaining = max(0, run.max_rounds - int(state.round))
    out = run_with_checkpoints(step, state, remaining, path, every=every,
                               step_args=tables, curve_fn=curve_fn,
                               curve_prefix=curve_prefix,
                               extra_meta=extra_meta)
    final, curve = out if want_curve else (out, None)
    if curve:
        det = float(curve[-1])    # the scan already computed it
    elif int(final.round):
        det = float(jax.jit(detection)(final))
    else:
        det = 0.0
    return final, det, curve


def compiled_until(proto: ProtocolConfig, topo: Topology, run: RunConfig,
                   fault: Optional[FaultConfig] = None):
    """Lowered/compiled while-loop runner + fresh init state, for benchmarks
    that must separate compile time from run time.  The returned loop takes
    (state, *tables); pass the returned tables through."""
    from gossip_tpu.ops import nemesis as NE
    step, tables, init = _build(proto, topo, run, fault)
    step = NE.drop_lost(step, NE.get(fault))
    target = jnp.float32(run.target_coverage)

    @partial(jax.jit, donate_argnums=0)
    def loop(state, *tbl):
        alive = NE.metric_alive(fault, topo.n, run.origin)
        def cond(s):
            return ((coverage(s.seen, alive) < target)
                    & (s.round < run.max_rounds))
        def body(s):
            return step(s, *tbl)
        return jax.lax.while_loop(cond, body, state)

    return loop, init, tables
