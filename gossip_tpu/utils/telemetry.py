"""Run-ledger telemetry: the crash-safe flight recorder every surface
writes through (docs/OBSERVABILITY.md holds the full schema).

The reference node has zero instrumentation — every number came from
the external Maelstrom checker (SURVEY.md §5) — and this repo's own
timing story was fragmented ad-hoc dicts until round 7: ``timing=``
splits in utils/trace, per-family keys in the dry run, bespoke JSON in
tools/hw_refresh.py, bench.py's probe messages printed to stderr and
lost.  The round-5 dark window (78/78 tunnel probes timed out, only
evidence a hand-rolled watchdog log) is the motivating failure: the
capture path must leave mechanically checkable evidence even when the
process is SIGKILLed mid-round.

This module is that one layer:

  * a :class:`Ledger` is a run-scoped, append-only JSONL file opened
    once per run with a **provenance** first line (run id, git commit,
    jax version, argv, timestamps);
  * a nested **span** API (``with ledger.span("compile"): ...``)
    recording monotonic walls and optional device ``memory_stats()``
    snapshots;
  * **counters/gauges** for discrete occurrences (probe timeouts,
    fallbacks);
  * **crash-safe flushing**: every event is written as one line and
    fsynced before control returns — a SIGKILLed or wedged run leaves
    a parseable partial ledger (at most one torn line per writer,
    which :func:`load_ledger` drops by contract; a new writer
    newline-heals a shared file's torn tail on open).

Zero steady-state cost: nothing here runs inside a compiled loop.
Spans wrap whole driver calls on the host; per-round coverage/msgs
stay on device (carried in the scan/while_loop, exported once), so
telemetry adds no host callbacks to steady state — the dry-run budget
guard (tools/dryrun_budgets.json) runs with telemetry enabled and
stays green.

jax is only imported lazily (``record_runtime`` / memory snapshots):
bench.py's parent process deliberately never initializes a backend —
probing happens in subprocesses — and the go-native paths must stay
runnable without jax (the utils/trace deferred-import pattern).

``GOSSIP_TELEMETRY=<path>`` is the ambient switch: :func:`from_env`
opens a ledger there (appending — multiple runs share one flight
recorder file, distinguished by the per-line ``run`` id), or returns
the no-op :class:`NullLedger` when unset and no default is given.
Render a ledger with tools/telemetry_report.py.
"""

from __future__ import annotations

import collections
import contextlib
import json
import math
import os
import subprocess
import sys
import threading
import time
import uuid
from typing import IO, Iterator, Optional

SCHEMA_VERSION = 1
ENV_VAR = "GOSSIP_TELEMETRY"

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _git_commit() -> Optional[str]:
    """HEAD of the repo this module ships in, or None (source exports
    without .git, or no git binary — provenance tolerates absence, the
    validator only requires the KEY to be present)."""
    try:
        p = subprocess.run(["git", "rev-parse", "HEAD"], cwd=_REPO,
                           capture_output=True, text=True, timeout=30)
        out = p.stdout.strip()
        return out if p.returncode == 0 and len(out) == 40 else None
    except (OSError, subprocess.SubprocessError):
        return None


def _jax_version() -> Optional[str]:
    """jax's version WITHOUT importing (and thereby initializing) it:
    importlib.metadata reads dist-info only.  Already-imported jax is
    read directly (cheaper, and correct even for editable installs)."""
    mod = sys.modules.get("jax")
    if mod is not None:
        return getattr(mod, "__version__", None)
    try:
        import importlib.metadata
        return importlib.metadata.version("jax")
    except Exception:
        return None


def provenance(argv=None) -> dict:
    """The one provenance schema every new-format artifact carries
    (tools/validate_artifacts.py contract): ``run_id``, ``git_commit``,
    ``captured`` plus toolchain/process context.  Embed this dict under
    a ``"provenance"`` key in plain-JSON artifacts; ledgers carry it as
    their first event line."""
    return {
        "run_id": uuid.uuid4().hex[:12],
        "schema": SCHEMA_VERSION,
        "git_commit": _git_commit(),
        "captured": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "argv": list(sys.argv) if argv is None else list(argv),
        "jax_version": _jax_version(),
        "python": sys.version.split()[0],
        "platform": sys.platform,
        "pid": os.getpid(),
    }


def new_trace_id() -> str:
    """A fresh request correlation id (16 hex chars) — minted ONCE per
    logical request by the outermost client (rpc/sidecar.SidecarClient)
    and carried verbatim through gRPC metadata across router dispatch,
    failover re-dispatch, and batcher admission, so every ledger event
    a request touches joins on the same id (tools/trace_report.py).
    uuid4-derived: no coordination, no clock, collision odds at any
    realistic request volume are negligible (64 bits)."""
    return uuid.uuid4().hex[:16]


def _finite(x):
    """Non-finite floats replaced by their reprs ('nan'/'inf'/'-inf'),
    recursively — the ledger must stay STRICT JSON (jq and every
    non-Python consumer reject the NaN/Infinity literals Python's json
    would otherwise emit), and a poisoned gauge must record the fact of
    the poisoning, not corrupt the file."""
    if isinstance(x, float) and not math.isfinite(x):
        return repr(x)
    if isinstance(x, dict):
        return {k: _finite(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_finite(v) for v in x]
    return x


def _dumps(obj) -> str:
    """json.dumps that never emits non-strict NaN/Infinity literals:
    the cheap strict attempt first, the :func:`_finite` rewrite only
    when a non-finite value is actually present.  ``default=str``
    catches numpy scalars — a numpy nan stringifies to "nan" there,
    consistent with the rewrite."""
    try:
        return json.dumps(obj, default=str, allow_nan=False)
    except ValueError:
        return json.dumps(_finite(obj), default=str, allow_nan=False)


class Ledger:
    """Append-only JSONL flight recorder; one instance per run.

    Every emit is one ``f.write(line)`` + flush + fsync, so a SIGKILL
    at any point leaves every prior event durable and at most the
    final line torn (:func:`load_ledger` drops a torn tail).  Lines
    all carry ``ev`` (event kind), ``ts`` (wall-clock seconds) and
    ``run`` (this run's id) — multiple runs can append to one file and
    stay separable.

    ``echo`` mirrors each line to stderr (bench.py's probe events stay
    operator-visible without a second ad-hoc print path).  ``fsync``
    can be disabled for high-rate callers that only need flush
    semantics; the default is the flight-recorder contract.
    """

    # a recording ledger: surfaces that would pay real work to PREPARE
    # an emission (round-metric device transfers — ops/round_metrics)
    # check this instead of emitting into a void
    active = True

    def __init__(self, path: str, argv=None, echo: bool = False,
                 fsync: bool = True):
        self.path = os.path.abspath(path)
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._f: Optional[IO[str]] = open(self.path, "a")
        self._echo = echo
        self._fsync = fsync
        # fsyncs actually issued: the zero-new-fsyncs-in-the-timed-path
        # claim (request tracing, docs/OBSERVABILITY.md) is verified by
        # reading this counter across a measured window, not by trust
        self.fsyncs = 0
        self._span_stack: list = []
        self._next_span = 1
        self._counters: dict = {}
        prov = provenance(argv)
        self.run_id = prov["run_id"]
        self._emit("provenance", prov)

    # -- core ----------------------------------------------------------

    def _emit(self, ev: str, fields: dict, sync: bool = True):
        if self._f is None:
            return
        obj = {"ev": ev, "ts": round(time.time(), 3), "run": self.run_id}
        # reserved keys never collide silently — a caller-supplied
        # "run"/"ts"/"ev" would break run filtering and the report's
        # timeline, so they are prefixed instead of overwriting (the
        # pre-ledger watchdog format carried its own "ts")
        fields = dict(fields)
        for k in ("ev", "ts", "run"):
            if k in fields:
                fields[f"x_{k}"] = fields.pop(k)
        obj.update(fields)
        line = _dumps(obj)
        try:
            # leading newline: every write SELF-HEALS a torn tail left
            # by any sibling writer killed mid-write on a shared file
            # (an already-open append handle would otherwise merge its
            # next event into the fragment).  Costs an occasional blank
            # line, which every reader here skips.
            self._f.write("\n" + line + "\n")
            self._f.flush()
            if self._fsync and sync:
                os.fsync(self._f.fileno())
                self.fsyncs += 1
        except OSError as e:
            # the flight recorder must never be what kills the flight
            # (disk full mid-run): warn once, stop recording
            sys.stderr.write(f"telemetry: ledger write failed, "
                             f"disabling recorder: {e}\n")
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None
            return
        if self._echo:
            sys.stderr.write(line + "\n")

    def event(self, kind: str, sync: bool = True, **fields):
        """A free-form event line (``probe``, ``family``, ``step`` ...);
        reserved kinds (``provenance``, ``span_start``, ``span_end``,
        ``counter``, ``gauge``) have dedicated emitters.

        ``sync=False`` skips the per-event fsync (flush only) — for
        emitters that run INSIDE a caller's timed window, where fsync
        latency would leak into the wall being measured
        (utils/trace.maybe_aot_timed).  Durability then arrives with
        the next fsynced event; the flushed line still survives any
        crash that isn't a whole-OS power loss."""
        self._emit(kind, fields, sync=sync)

    def counter(self, name: str, inc: int = 1):
        """Monotonic occurrence count; each update is durable, and the
        running total rides along so a partial ledger still reads the
        high-water without re-summing."""
        total = self._counters.get(name, 0) + inc
        self._counters[name] = total
        self._emit("counter", {"name": name, "inc": inc, "total": total})

    def gauge(self, name: str, value, sync: bool = True):
        """``sync=False`` is for gauges emitted from INSIDE a caller's
        timed window (the sweep's cache stats — the sweep call itself
        is what the dry run measures): flush-only, same contract as
        ``event(..., sync=False)``."""
        self._emit("gauge", {"name": name, "value": value}, sync=sync)

    # -- spans ---------------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str, memory: bool = False,
             **attrs) -> Iterator[dict]:
        """Nested wall-clock span.  Emits ``span_start`` immediately
        (durable before the work begins — a killed run still shows the
        span was entered) and ``span_end`` with the monotonic wall on
        exit; ``ok`` records whether the block raised.  Yields a dict
        the block can stuff extra fields into; they land on the end
        event.  ``memory=True`` snapshots device ``memory_stats()`` at
        exit (TPU backends report bytes_in_use/peak_bytes_in_use; CPU
        devices have none and the field is omitted).

        The ledger writes bracket the timed region — span walls never
        include the fsync cost of their own events."""
        span_id = self._next_span
        self._next_span += 1
        parent = self._span_stack[-1] if self._span_stack else None
        # structural keys win over caller attrs of the same name
        self._emit("span_start", {**attrs, "span": span_id,
                                  "parent": parent, "name": name})
        self._span_stack.append(span_id)
        extra: dict = {}
        t0 = time.perf_counter()
        ok = True
        try:
            yield extra
        except BaseException:
            ok = False
            raise
        finally:
            wall_ms = (time.perf_counter() - t0) * 1e3
            self._span_stack.pop()
            if memory:
                mem = device_memory_stats()
                if mem is not None:
                    extra.setdefault("memory", mem)
            self._emit("span_end", {**extra, "span": span_id,
                                    "parent": parent, "name": name,
                                    "wall_ms": round(wall_ms, 3),
                                    "ok": ok})

    # -- runtime context ----------------------------------------------

    def record_runtime(self):
        """Backend/platform/device-count provenance from a process that
        has already initialized jax (the dry-run body, capture tools).
        Separate from __init__ because opening a ledger must never be
        the thing that initializes a backend (a wedged tunnel hangs ANY
        jax init — the round-2/4 lesson)."""
        try:
            import jax
            devs = jax.devices()
            self._emit("runtime", {
                "backend": jax.default_backend(),
                "device_count": len(devs),
                "device_kind": (getattr(devs[0], "device_kind", None)
                                if devs else None),
                "jax_version": jax.__version__})
        except Exception as e:
            self._emit("runtime",
                       {"error": f"{type(e).__name__}: {e}"[:300]})

    def memory_snapshot(self, tag: str = ""):
        """One ``memory`` event with per-device memory_stats (no-op
        fields on backends that expose none)."""
        mem = device_memory_stats()
        if mem is not None:
            self._emit("memory", {"tag": tag, "devices": mem})

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class NullLedger:
    """No-op twin so hot surfaces can call unconditionally; the active
    ledger is a pure config choice (GOSSIP_TELEMETRY), never an
    if-tree at every call site."""

    path = None
    run_id = None
    active = False
    fsyncs = 0

    def event(self, kind, sync=True, **fields):
        pass

    def counter(self, name, inc=1):
        pass

    def gauge(self, name, value, sync=True):
        pass

    @contextlib.contextmanager
    def span(self, name, memory=False, **attrs):
        yield {}

    def record_runtime(self):
        pass

    def memory_snapshot(self, tag=""):
        pass

    def close(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class EchoLedger(NullLedger):
    """File-less ledger that still echoes events to stderr — what an
    echo-requesting surface (bench.py) gets when the operator disabled
    the file with GOSSIP_TELEMETRY="": the flight-recorder FILE is
    off, but wedge/fallback diagnostics must never go silent (the
    dark-window lesson this layer exists for)."""

    active = True

    def event(self, kind, sync=True, **fields):
        obj = {"ev": kind, "ts": round(time.time(), 3)}
        obj.update(fields)
        sys.stderr.write(json.dumps(obj, default=str) + "\n")

    def counter(self, name, inc=1):
        self.event("counter", name=name, inc=inc)

    def gauge(self, name, value, sync=True):
        self.event("gauge", name=name, value=value)


def device_memory_stats():
    """[{device, **memory_stats}] for devices that report stats, or
    None (jax absent / not initialized / CPU-only — never imports jax
    into a process that hasn't already paid for it)."""
    if "jax" not in sys.modules:
        return None
    try:
        import jax
        rows = []
        for d in jax.local_devices():
            stats = getattr(d, "memory_stats", lambda: None)()
            if stats:
                rows.append({"device": str(d),
                             **{k: stats[k] for k in
                                ("bytes_in_use", "peak_bytes_in_use",
                                 "bytes_limit") if k in stats}})
        return rows or None
    except Exception:
        return None


# -- ambient ledger ---------------------------------------------------

_CURRENT: object = NullLedger()


def current():
    """The process-ambient ledger (NullLedger unless activated) —
    utils/trace.maybe_aot_timed emits driver timing through this, so
    every sharded driver's wall decomposition reaches the flight
    recorder without threading a ledger argument through the world."""
    return _CURRENT


def activate(ledger):
    """Install ``ledger`` as the ambient one; returns the previous
    (restore it in a finally for scoped use)."""
    global _CURRENT
    prev = _CURRENT
    _CURRENT = ledger
    return prev


def from_env(default_path: Optional[str] = None, argv=None,
             echo: bool = False):
    """Ledger at $GOSSIP_TELEMETRY, else at ``default_path``, else the
    NullLedger.  GOSSIP_TELEMETRY="" explicitly disables the FILE
    (matches the GOSSIP_COMPILE_CACHE convention); an ``echo``-
    requesting caller still gets stderr diagnostics via EchoLedger —
    disabling the recorder must never silence wedge evidence."""
    path = os.environ.get(ENV_VAR)
    if path is None:
        path = default_path
    if not path:
        return EchoLedger() if echo else NullLedger()
    try:
        return Ledger(path, argv=argv, echo=echo)
    except OSError as e:
        # an unwritable ledger path must degrade, not abort the run it
        # was meant to record (bench's one-JSON-line contract survives
        # a read-only checkout)
        sys.stderr.write(f"telemetry: cannot open ledger {path!r} "
                         f"({e}); recording disabled\n")
        return EchoLedger() if echo else NullLedger()


def artifact_ledger(path: str, rewrite: bool = True,
                    fsync: bool = False, argv=None):
    """Provenance-stamped ARTIFACT ledger — the ONE stamping helper
    every committed-jsonl writer shares: tests/conftest.py's per-test
    duration ledger and gossip_tpu/analysis's staticcheck findings
    ledger both open through here, so a future writer cannot re-roll
    (and drift) the remove-then-stamp choreography.

    Differences from :func:`from_env`, which serves RUN flight
    recorders: ``rewrite=True`` truncates an existing file first — a
    committed artifact is THIS run's evidence, not an append log
    (pass False for the explicit-path append convention, e.g. a
    caller aggregating several test sessions) — and ``fsync`` defaults
    off (artifact writers run outside any crash window worth an fsync
    per line; the provenance first line still lands via Ledger's
    normal emit path).  An unwritable path degrades to the NullLedger
    with a stderr warning — a recorder must never fail the run it
    records (the from_env contract)."""
    if rewrite:
        try:
            os.remove(path)
        except FileNotFoundError:
            pass
        except OSError as e:
            sys.stderr.write(f"telemetry: cannot rewrite artifact "
                             f"ledger {path!r} ({e}); recording "
                             "disabled\n")
            return NullLedger()
    try:
        return Ledger(path, argv=argv, fsync=fsync)
    except OSError as e:
        sys.stderr.write(f"telemetry: cannot open artifact ledger "
                         f"{path!r} ({e}); recording disabled\n")
        return NullLedger()


def percentile(values, q: float) -> float:
    """Nearest-rank percentile (q in [0, 1]) of a value sequence, 0.0
    with no samples — the ONE latency-quantile definition the serving
    layer shares: the admission batcher's per-tick ``batch`` events
    (rpc/batcher wait walls) and the load harness's p50/p95/p99 gates
    (tools/load_harness) must mean the same thing by construction.
    Same nearest-rank convention as utils/trace.RoundTimer."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"q={q} outside [0, 1]")
    vals = sorted(values)
    if not vals:
        return 0.0
    # epsilon guards float artifacts like 0.95*20 -> 19.000000000000004
    rank = math.ceil(q * len(vals) - 1e-9)
    return float(vals[min(len(vals) - 1, max(0, rank - 1))])


class MetricsWindow:
    """Thread-safe rolling metrics window for the live fleet plane
    (the ``Metrics`` RPC on gossip.Simulator — rpc/sidecar serves one
    per replica, rpc/router keeps its own for dispatch latencies).

    Holds (monotonic_ts, latency_ms) samples pruned to the trailing
    ``window_s`` seconds plus named monotonic counters (sheds,
    failovers, ...).  ``snapshot()`` is the one read path: rps over
    the window, sample count, p50/p95/p99 via :func:`percentile` (the
    shared nearest-rank definition), and the counter totals.  Pure
    host-side bookkeeping — a record() is an append + occasional
    popleft under a lock, never an fsync, never a device transfer —
    so the zero-steady-state-cost contract of this module holds.
    """

    def __init__(self, window_s: float = 60.0):
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._samples: collections.deque = collections.deque()
        self._counters: dict = {}

    def record(self, latency_ms: float, now: Optional[float] = None):
        now = time.monotonic() if now is None else now
        with self._lock:
            self._samples.append((now, float(latency_ms)))
            self._prune_locked(now)

    def bump(self, name: str, inc: int = 1):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + inc

    def _prune_locked(self, now: float):
        cutoff = now - self.window_s
        while self._samples and self._samples[0][0] < cutoff:
            self._samples.popleft()

    def snapshot(self, now: Optional[float] = None) -> dict:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._prune_locked(now)
            lats = [ms for _, ms in self._samples]
            oldest = self._samples[0][0] if self._samples else now
            counters = dict(self._counters)
        # rps over the ACTUAL span covered, not the nominal window:
        # a 3-second-old process with 30 samples reports ~10 rps, not
        # the misleading 0.5 a fixed 60 s denominator would give
        if lats:
            span = min(max(now - oldest, 1e-9), self.window_s)
            rps = len(lats) / span
        else:
            rps = 0.0
        return {
            "window_s": self.window_s,
            "n": len(lats),
            "rps": round(rps, 3),
            "p50_ms": round(percentile(lats, 0.50), 3),
            "p95_ms": round(percentile(lats, 0.95), 3),
            "p99_ms": round(percentile(lats, 0.99), 3),
            **counters,
        }


# -- reading ----------------------------------------------------------

def parse_dryrun_table(text: str):
    """The last ``{"dryrun_family_ms": ...}`` JSON object line in
    ``text``, or None — the ONE parser of the dry-run stdout contract
    (teardown noise after the table never discards it).  Lives here,
    dependency-free, so tools/readme_table.py can render a MULTICHIP
    record's tail without importing anything jax-bearing;
    __graft_entry__.dryrun_multichip uses the same function on its
    subprocess stdout."""
    for line in reversed(text.splitlines()):
        if not line.strip():
            continue
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict) and "dryrun_family_ms" in parsed:
            return parsed
    return None


def load_ledger(path: str, run: Optional[str] = None,
                strict: bool = False,
                trace_id: Optional[str] = None):
    """Parse a ledger back into a list of event dicts.

    Crash contract: every fsynced line is durable, and a kill between
    write and fsync can tear at most one line per WRITER.  A
    single-writer ledger therefore tears only at the tail; a shared
    file (hw_refresh + its step subprocesses) can carry a torn line
    mid-file when a killed child's fragment is followed by the
    parent's appends (the writer heals the newline, so the fragment
    stays its own line).  The flight-recorder read-out must survive
    exactly that post-mortem, so unparseable lines are DROPPED by
    default; ``strict=True`` (single-writer files, tests) raises
    ValueError on any torn line that is not the final one.
    ``run`` filters to one run id; ``run="last"`` selects the newest
    provenance line's run.  ``trace_id`` filters to the events of one
    request trace (events carrying that ``trace_id`` field) — the
    single-trace read path tools/trace_report.py's exemplar drill-down
    and the failover-propagation tests share."""
    events = []
    with open(path) as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            events.append(json.loads(line))
        except json.JSONDecodeError:
            if strict and i != len(lines) - 1:
                raise ValueError(
                    f"{path}:{i + 1}: corrupt ledger line (not a torn "
                    f"tail): {line[:120]!r}")
            continue                       # torn line: documented drop
    if run == "last":
        provs = [e for e in events if e.get("ev") == "provenance"]
        run = provs[-1]["run"] if provs else None
    if run is not None:
        events = [e for e in events if e.get("run") == run]
    if trace_id is not None:
        events = [e for e in events if e.get("trace_id") == trace_id]
    return events
