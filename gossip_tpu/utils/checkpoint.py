"""Checkpoint / resume for round-state arrays.

The reference has NO persistence: all state is in-memory behind an RWMutex
and process death loses everything (SURVEY.md §5 "Checkpoint/resume:
None").  Here a simulation's full state is a handful of arrays (SimState /
SwimState), so checkpointing is one ``npz`` file: cheap, dependency-free,
and exact — including the typed PRNG key, serialized via
``jax.random.key_data`` and re-wrapped on load, so a resumed run continues
the *identical* trajectory (tests/test_utils.py proves resume == straight
run, bitwise).

Orbax exists in the environment but would be a dependency for no gain at
this state size; the format here is a plain ``np.savez`` with a JSON
metadata entry (state class name + field names + key dtype impl).

Crash contract (tests/test_crash_safety.py):

* Writes are atomic — ``np.savez`` lands in ``path + ".tmp"`` and
  ``os.replace`` publishes it, so a SIGKILL at any point leaves either
  the previous complete checkpoint or the new one, never a torn file.
  A kill BETWEEN the tmp write and the replace can strand the ``.tmp``
  sibling; :func:`save_state` deletes a stale one before every write
  and loads never look at it, so a stranded partial can neither grow
  forever nor be mistaken for a checkpoint.
* A checkpoint that is nonetheless unreadable (truncated by the
  filesystem, wrong format, unknown state class) raises ``ValueError``
  NAMING THE FILE from :func:`load_meta`/:func:`load_state` — never a
  raw ``KeyError``/``zipfile`` traceback — so ``--resume`` can refuse
  it with a one-line error.
* Nemesis fault programs (ops/nemesis schedules riding ``step_args``)
  are resume-safe: every round step indexes its schedule by the
  ABSOLUTE ``state.round`` its state class carries — which the
  checkpoint persists and the PRNG streams already key on — so the
  schedule lookup ``tbl[min(r, T-1)]`` lines up across segments and
  across kills; :func:`run_with_checkpoints` cross-checks its
  ``base_round`` cursor against the state's own counter so a driver
  that re-zeroed it cannot silently restart the fault program.
  Resume == straight run bitwise even when the kill lands inside an
  open partition window or mid-ramp (tools/crashloop.py is the live
  SIGKILL harness).
"""

from __future__ import annotations

import json
import os
import weakref
from typing import Union

import jax
import jax.numpy as jnp
import numpy as np

from gossip_tpu.models.rumor import RumorState
from gossip_tpu.models.state import SimState
from gossip_tpu.models.swim import SwimState
from gossip_tpu.ops.pallas_round import FusedState

# FusedState covers BOTH fused layouts: the single-device one-word-per-
# node table and the plane-sharded [W, rows, 128] stack (the plane stack
# rides in the ``table`` field) — the config fingerprint distinguishes
# the runs, the array shape distinguishes the layouts.
_STATE_TYPES = {"SimState": SimState, "SwimState": SwimState,
                "RumorState": RumorState, "FusedState": FusedState}
State = Union[SimState, SwimState, RumorState, FusedState]


def save_state(path: str, state: State, extra_meta=None) -> None:
    """Write a registered round-state to ``path`` (.npz).  Sharded
    arrays are gathered to host — checkpoint outside the hot loop.
    ``extra_meta`` (a JSON-able dict) rides in the metadata entry — e.g.
    the run's config fingerprint, so resume can refuse mismatched flags
    (:func:`load_meta`)."""
    cls = type(state).__name__
    if cls not in _STATE_TYPES:
        raise TypeError(f"unknown state type {cls}")
    fields = state._fields
    arrays = {}
    key_field = None
    for name in fields:
        val = getattr(state, name)
        if name == "base_key":
            key_field = name
            arrays[name] = np.asarray(jax.random.key_data(val))
        else:
            arrays[name] = np.asarray(val)
    meta = {"cls": cls, "fields": list(fields), "key_field": key_field}
    if key_field is not None:
        # FusedState has no traced key (the kernel seeds from scalar
        # (seed, round)); key-less states skip the impl record entirely
        meta["key_impl"] = str(jax.random.key_impl(state.base_key))
    if extra_meta is not None:
        meta["extra"] = extra_meta
    tmp = path + ".tmp"
    if os.path.exists(tmp):
        # stale partial write: a crash between the tmp write below and
        # os.replace strands the sibling; it is never a valid checkpoint
        # (loads read ``path`` only) and must not survive forever
        os.remove(tmp)
    with open(tmp, "wb") as f:
        np.savez(f, __meta__=json.dumps(meta), **arrays)
    os.replace(tmp, path)          # atomic: no torn checkpoints on crash


def _open_npz(path: str):
    """np.load with the crash contract: anything short of a readable
    zip archive (a file truncated by the filesystem under a crash, a
    non-npz imposter) is a ``ValueError`` naming the file, never a raw
    ``zipfile``/``OSError`` traceback.  A missing file stays
    ``FileNotFoundError`` — absent and corrupt are different failures
    and the CLI messages differ."""
    try:
        return np.load(path, allow_pickle=False)
    except FileNotFoundError:
        raise
    except Exception as e:
        raise ValueError(
            f"checkpoint {path} is not a readable .npz archive "
            f"(truncated or corrupted — e.g. by a crash of the "
            f"filesystem, not of the simulator: writes are atomic): "
            f"{type(e).__name__}: {e}") from e


def _meta_of(z, path: str) -> dict:
    if "__meta__" not in getattr(z, "files", ()):
        raise ValueError(
            f"checkpoint {path} has no __meta__ entry — not a "
            "gossip_tpu checkpoint (save_state writes one always)")
    try:
        return json.loads(str(z["__meta__"]))
    except Exception as e:
        raise ValueError(
            f"checkpoint {path} has an unparseable __meta__ entry: "
            f"{type(e).__name__}: {e}") from e


def load_meta(path: str) -> dict:
    """The metadata entry of a checkpoint (incl. any ``extra_meta`` under
    'extra') without loading the arrays.  Raises ``ValueError`` naming
    the file when it is not a readable checkpoint (module crash
    contract)."""
    with _open_npz(path) as z:
        return _meta_of(z, path)


def load_state(path: str) -> State:
    """Load a checkpoint written by :func:`save_state`.  Raises
    ``ValueError`` naming the file on a truncated/invalid archive or an
    unknown state class (module crash contract)."""
    with _open_npz(path) as z:
        meta = _meta_of(z, path)
        cls = _STATE_TYPES.get(meta.get("cls"))
        if cls is None:
            raise ValueError(
                f"checkpoint {path} carries unknown state class "
                f"{meta.get('cls')!r} (known: "
                f"{sorted(_STATE_TYPES)}) — written by an incompatible "
                "version?")
        # metadata keys first, with their own diagnosis — a foreign or
        # incomplete metadata dict must not be misreported as a
        # truncated ARRAY write by the member-read handler below
        fields = meta.get("fields")
        key_field = meta.get("key_field")
        key_impl = meta.get("key_impl")
        if fields is None or (key_field is not None and key_impl is None):
            raise ValueError(
                f"checkpoint {path} metadata is incomplete (needs "
                "'fields' and, for a keyed state, 'key_impl') — "
                "written by an incompatible version?")
        kwargs = {}
        try:
            for name in fields:
                if name == key_field:
                    # rewrap under the impl the checkpoint was SAVED with
                    # — the loading process may default to a different
                    # PRNG impl (e.g. rbg on TPU), which would silently
                    # change the resumed trajectory
                    kwargs[name] = jax.random.wrap_key_data(
                        jax.numpy.asarray(z[name]), impl=key_impl)
                else:
                    kwargs[name] = jax.numpy.asarray(z[name])
        except KeyError as e:
            raise ValueError(
                f"checkpoint {path} is missing array entry {e} named "
                "by its own metadata — truncated write?") from e
        except Exception as e:
            # mid-archive corruption with an intact central directory
            # (bad CRC, zlib error): np.load opened fine and __meta__
            # parsed, but a member read blew up — still the crash
            # contract's ValueError, never a raw zipfile/zlib traceback
            raise ValueError(
                f"checkpoint {path} has a corrupted array entry "
                f"({type(e).__name__}: {e}) — damaged in place after "
                "the atomic write?") from e
    return cls(**kwargs)


# One jitted fori_loop runner per (step function, lost-tracking mode),
# so repeated run_with_checkpoints calls (resume loops) reuse the
# executable.  Weak keys: a dropped step closure (and the topology
# arrays it captures) must not be pinned in memory by this cache.
# The loop counter is ignored by every body: round absoluteness lives
# in ``state.round`` (each step advances and reads its own counter —
# the module crash contract), so segment 7 of a resumed run re-enters
# the executable segment 1 compiled with nothing to rebase.
_segment_runners: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_curve_runners: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _segment_runner(step, track_lost: bool = False):
    per_step = _segment_runners.get(step)
    if per_step is None:
        per_step = {}
        _segment_runners[step] = per_step
    runner = per_step.get(track_lost)
    if runner is None:
        # the runner must NOT strongly capture ``step``: the cache value
        # referencing its own weak key would make eviction impossible and
        # pin every dropped step closure (and its captured topology
        # arrays) forever in long-lived processes (rpc sidecar).  The
        # weakref is only dereferenced while the cache entry — and hence
        # the step — is still alive.
        step_ref = weakref.ref(step)

        if track_lost:
            # churn-path steps return (state, lost) — models/si.py
            # contract; the destroyed-message count accumulates as one
            # scalar carry so the cumulative ``dropped`` observable
            # survives checkpoints (and hence kills) exactly
            @jax.jit
            def runner(s, n_steps, acc, *args):
                def body(_, carry):
                    st, a = carry
                    st2, lo = step_ref()(st, *args)
                    return st2, a + lo
                return jax.lax.fori_loop(0, n_steps, body, (s, acc))
        else:
            @jax.jit
            def runner(s, n_steps, *args):
                return jax.lax.fori_loop(
                    0, n_steps,
                    lambda _, st: step_ref()(st, *args), s)
        per_step[track_lost] = runner
    return runner


def _curve_segment_runner(step, curve_fn, track_lost: bool = False):
    """Segment runner that also records ``curve_fn(state)`` after every
    round, as one compiled ``lax.scan``.  Scan lengths are static, so a
    run compiles at most two executables per (step, curve_fn): the
    ``every``-long body and the tail.  Identical step sequence to the
    fori_loop runner — the bitwise-trajectory promise is unchanged."""
    per_step = _curve_runners.setdefault(step, weakref.WeakKeyDictionary())
    variants = per_step.get(curve_fn)
    if variants is None:
        variants = {}
        per_step[curve_fn] = variants
    runner = variants.get(track_lost)
    if runner is None:
        import functools

        # weak captures, same reason as _segment_runner: the cached
        # runner must not keep its own keys alive
        step_ref = weakref.ref(step)
        curve_ref = weakref.ref(curve_fn)

        if track_lost:
            @functools.partial(jax.jit, static_argnums=1)
            def runner(s, n_steps, acc, *args):
                def body(carry, _):
                    st, a = carry
                    st2, lo = step_ref()(st, *args)
                    return (st2, a + lo), curve_ref()(st2)
                return jax.lax.scan(body, (s, acc), None,
                                    length=n_steps)
        else:
            @functools.partial(jax.jit, static_argnums=1)
            def runner(s, n_steps, *args):
                def body(st, _):
                    st2 = step_ref()(st, *args)
                    return st2, curve_ref()(st2)
                return jax.lax.scan(body, s, None, length=n_steps)
        variants[track_lost] = runner
    return runner


def run_with_checkpoints(step, state: State, rounds: int, path: str,
                         every: int = 50, step_args=(),
                         extra_meta=None, curve_fn=None,
                         curve_prefix=(), base_round=None,
                         track_lost: bool = False,
                         lost_prefix: float = 0.0):
    """Drive ``step`` for ``rounds`` rounds, checkpointing every ``every``
    rounds (and at the end).  Resume by loading the file and calling again
    with the remaining round budget — long sweeps survive preemption.

    Each inter-checkpoint segment runs as ONE compiled ``fori_loop`` (the
    segment length is a traced argument, so the short tail segment reuses
    the same executable, as does a resume call with the same ``step``):
    the host syncs once per checkpoint, not once per round, preserving the
    while-loop fusion the round kernels are built for (tests/test_utils.py
    asserts both the bitwise trajectory and the one-trace property).

    ``step_args`` travel as traced jit ARGUMENTS into the segment runner
    — pass a tabled step's topology arrays here instead of closing over
    them, so 1M+-row tables are not inlined into the compile request
    (models/swim.py doc).

    ``curve_fn`` (state -> float scalar, or state -> dict of named float
    scalars) switches the segments to a compiled ``lax.scan`` that
    records the value(s) after every round: long runs can persist AND
    capture their convergence curve (the reference could do neither —
    SURVEY.md §5).  A dict-valued curve_fn records one list per channel
    (e.g. rumor mongering's coverage + hot-fraction pair, whose
    extinction round is only recoverable from the hot channel).  The
    curve-so-far rides in the checkpoint metadata under
    ``extra['curve']`` — a list for the scalar form, a dict of lists for
    the dict form — so a resumed run continues it seamlessly (pass the
    saved value as ``curve_prefix``).  Returns ``state`` without
    ``curve_fn``, ``(state, curve)`` with it.

    Fault programs (module crash contract): a nemesis schedule passed
    through ``step_args`` (ops/nemesis.sched_args on the factory's
    table tail) is indexed by the step's own ABSOLUTE ``state.round``
    — which this checkpoint format persists — so a resume sees the
    same lookups as a straight run with no rebasing.  ``base_round``
    is the host-side round cursor: derived from ``state.round`` and
    cross-checked against an explicit value, so a driver that rebuilt
    its state with a re-zeroed counter (which would silently restart
    the fault program while the trajectory continues) is refused; it
    also stamps ``extra['round']``.  ``track_lost=True`` declares the
    churn-step contract
    (``step(state, *args) -> (state, lost)``): the runners accumulate
    the per-round destroyed-message count on device and the cumulative
    total persists in the checkpoint metadata under
    ``extra['dropped']`` (seed a resume with the saved value via
    ``lost_prefix`` — the nemesis ``dropped`` observable then matches
    the uninterrupted run BITWISE across kills).  "Exact" here means
    exactly the straight driver's number: the carry is the same
    sequential f32 accumulation every in-loop nemesis total uses
    (ops/nemesis.lost_count, the msgs counters), so like them it
    inherits f32 integer range — totals beyond 2**24 round like any
    other f32 protocol counter.  Every checkpoint's metadata also
    records the absolute round cursor under ``extra['round']``.
    """
    if every < 1:
        raise ValueError(f"every must be >= 1, got {every}")
    state_round = getattr(state, "round", None)
    if state_round is not None:
        sr = int(state_round)
        if base_round is None:
            base_round = sr
        elif int(base_round) != sr:
            # a driver that rebuilt its state with a re-zeroed round
            # would silently restart the fault program from round 0
            # while the trajectory continues — refuse before corrupting
            raise ValueError(
                f"base_round={base_round} disagrees with the state's "
                f"own round counter {sr}; a resumed fault program must "
                "continue at the absolute round the checkpoint stopped "
                "at")
    else:
        base_round = 0 if base_round is None else int(base_round)
    curve = ({k: list(v) for k, v in curve_prefix.items()}
             if isinstance(curve_prefix, dict) else list(curve_prefix))
    dropped = float(lost_prefix)
    acc = jnp.float32(dropped) if track_lost else None

    def meta_now():
        m = dict(extra_meta or {})
        m["round"] = base_round + done
        if track_lost:
            m["dropped"] = dropped
        if curve_fn is not None:
            m["curve"] = curve
        return m

    def flight_record():
        # one ambient-ledger event per published checkpoint (fsync'd by
        # the telemetry contract): a SIGKILLed run's ledger shows the
        # exact round cursor — and under churn the exact destroyed-
        # message total — of its last durable state, which is what the
        # crashloop harness (tools/crashloop.py) stamps at every kill
        from gossip_tpu.utils import telemetry
        led = telemetry.current()
        if led.active:
            fields = {"path": path, "round": int(base_round + done)}
            if track_lost:
                fields["dropped"] = dropped
            led.event("checkpoint", **fields)

    if curve_fn is None:
        run_segment = _segment_runner(step, track_lost)
    else:
        run_segment = _curve_segment_runner(step, curve_fn, track_lost)
    done = 0
    while done < rounds:
        todo = min(every, rounds - done)
        if curve_fn is None:
            if track_lost:
                state, acc = run_segment(state, todo, acc, *step_args)
            else:
                state = run_segment(state, todo, *step_args)
        else:
            if track_lost:
                (state, acc), seg = run_segment(state, todo, acc,
                                                *step_args)
            else:
                state, seg = run_segment(state, todo, *step_args)
            if isinstance(seg, dict):
                if not isinstance(curve, dict):
                    if curve:      # scalar prefix + dict curve_fn
                        raise TypeError(
                            "curve_prefix is a flat list but curve_fn "
                            "records named channels; pass the saved "
                            "dict-of-lists instead")
                    curve = {k: [] for k in seg}
                for k, v in seg.items():
                    curve[k].extend(float(x) for x in np.asarray(v))
            else:
                if isinstance(curve, dict):
                    raise TypeError(
                        "curve_prefix carries named channels but "
                        "curve_fn records a flat scalar; pass the "
                        "matching channel list (or the dict-recording "
                        "curve_fn the checkpoint was written with)")
                curve.extend(float(x) for x in np.asarray(seg))
        done += todo
        jax.block_until_ready(jax.tree_util.tree_leaves(state)[0])
        if track_lost:
            # one scalar sync per checkpoint (we already sync the state
            # above); float64(float32) and its JSON repr round-trip
            # exactly, so the resumed accumulator is the bitwise carry
            dropped = float(acc)
        save_state(path, state, meta_now())
        flight_record()
    if rounds <= 0:
        if curve_fn is not None and not isinstance(curve, dict) and not curve:
            # zero segments ran, so the dict-vs-scalar branch above never
            # told us curve_fn's shape: a dict-valued curve_fn must still
            # return a dict of channels, not a bare empty list, or
            # downstream channel extraction (e.g. the CLI's hot_curve)
            # silently loses the names (ADVICE r4).  eval_shape reads the
            # channel keys without running any compute.
            shape = jax.eval_shape(curve_fn, state)
            if isinstance(shape, dict):
                curve = {k: [] for k in shape}
        save_state(path, state, meta_now())
        flight_record()
    if curve_fn is None:
        return state
    return state, curve
