"""Checkpoint / resume for round-state arrays.

The reference has NO persistence: all state is in-memory behind an RWMutex
and process death loses everything (SURVEY.md §5 "Checkpoint/resume:
None").  Here a simulation's full state is a handful of arrays (SimState /
SwimState), so checkpointing is one ``npz`` file: cheap, dependency-free,
and exact — including the typed PRNG key, serialized via
``jax.random.key_data`` and re-wrapped on load, so a resumed run continues
the *identical* trajectory (tests/test_utils.py proves resume == straight
run, bitwise).

Orbax exists in the environment but would be a dependency for no gain at
this state size; the format here is a plain ``np.savez`` with a JSON
metadata entry (state class name + field names + key dtype impl).
"""

from __future__ import annotations

import json
import os
import weakref
from typing import Union

import jax
import numpy as np

from gossip_tpu.models.rumor import RumorState
from gossip_tpu.models.state import SimState
from gossip_tpu.models.swim import SwimState
from gossip_tpu.ops.pallas_round import FusedState

# FusedState covers BOTH fused layouts: the single-device one-word-per-
# node table and the plane-sharded [W, rows, 128] stack (the plane stack
# rides in the ``table`` field) — the config fingerprint distinguishes
# the runs, the array shape distinguishes the layouts.
_STATE_TYPES = {"SimState": SimState, "SwimState": SwimState,
                "RumorState": RumorState, "FusedState": FusedState}
State = Union[SimState, SwimState, RumorState, FusedState]


def save_state(path: str, state: State, extra_meta=None) -> None:
    """Write a registered round-state to ``path`` (.npz).  Sharded
    arrays are gathered to host — checkpoint outside the hot loop.
    ``extra_meta`` (a JSON-able dict) rides in the metadata entry — e.g.
    the run's config fingerprint, so resume can refuse mismatched flags
    (:func:`load_meta`)."""
    cls = type(state).__name__
    if cls not in _STATE_TYPES:
        raise TypeError(f"unknown state type {cls}")
    fields = state._fields
    arrays = {}
    key_field = None
    for name in fields:
        val = getattr(state, name)
        if name == "base_key":
            key_field = name
            arrays[name] = np.asarray(jax.random.key_data(val))
        else:
            arrays[name] = np.asarray(val)
    meta = {"cls": cls, "fields": list(fields), "key_field": key_field}
    if key_field is not None:
        # FusedState has no traced key (the kernel seeds from scalar
        # (seed, round)); key-less states skip the impl record entirely
        meta["key_impl"] = str(jax.random.key_impl(state.base_key))
    if extra_meta is not None:
        meta["extra"] = extra_meta
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, __meta__=json.dumps(meta), **arrays)
    os.replace(tmp, path)          # atomic: no torn checkpoints on crash


def load_meta(path: str) -> dict:
    """The metadata entry of a checkpoint (incl. any ``extra_meta`` under
    'extra') without loading the arrays."""
    with np.load(path, allow_pickle=False) as z:
        return json.loads(str(z["__meta__"]))


def load_state(path: str) -> State:
    """Load a checkpoint written by :func:`save_state`."""
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        cls = _STATE_TYPES[meta["cls"]]
        kwargs = {}
        for name in meta["fields"]:
            if name == meta["key_field"]:
                # rewrap under the impl the checkpoint was SAVED with — the
                # loading process may default to a different PRNG impl
                # (e.g. rbg on TPU), which would silently change the
                # resumed trajectory
                kwargs[name] = jax.random.wrap_key_data(
                    jax.numpy.asarray(z[name]), impl=meta["key_impl"])
            else:
                kwargs[name] = jax.numpy.asarray(z[name])
    return cls(**kwargs)


# One jitted fori_loop runner per step function, so repeated
# run_with_checkpoints calls (resume loops) reuse the executable.  Weak
# keys: a dropped step closure (and the topology arrays it captures) must
# not be pinned in memory by this cache.
_segment_runners: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_curve_runners: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _segment_runner(step):
    runner = _segment_runners.get(step)
    if runner is None:
        # the runner must NOT strongly capture ``step``: the cache value
        # referencing its own weak key would make eviction impossible and
        # pin every dropped step closure (and its captured topology
        # arrays) forever in long-lived processes (rpc sidecar).  The
        # weakref is only dereferenced while the cache entry — and hence
        # the step — is still alive.
        step_ref = weakref.ref(step)

        @jax.jit
        def runner(s, n_steps, *args):
            return jax.lax.fori_loop(0, n_steps,
                                     lambda _, st: step_ref()(st, *args), s)
        _segment_runners[step] = runner
    return runner


def _curve_segment_runner(step, curve_fn):
    """Segment runner that also records ``curve_fn(state)`` after every
    round, as one compiled ``lax.scan``.  Scan lengths are static, so a
    run compiles at most two executables per (step, curve_fn): the
    ``every``-long body and the tail.  Identical step sequence to the
    fori_loop runner — the bitwise-trajectory promise is unchanged."""
    per_step = _curve_runners.setdefault(step, weakref.WeakKeyDictionary())
    runner = per_step.get(curve_fn)
    if runner is None:
        import functools

        # weak captures, same reason as _segment_runner: the cached
        # runner must not keep its own keys alive
        step_ref = weakref.ref(step)
        curve_ref = weakref.ref(curve_fn)

        @functools.partial(jax.jit, static_argnums=1)
        def runner(s, n_steps, *args):
            def body(st, _):
                st2 = step_ref()(st, *args)
                return st2, curve_ref()(st2)
            return jax.lax.scan(body, s, None, length=n_steps)
        per_step[curve_fn] = runner
    return runner


def run_with_checkpoints(step, state: State, rounds: int, path: str,
                         every: int = 50, step_args=(),
                         extra_meta=None, curve_fn=None,
                         curve_prefix=()):
    """Drive ``step`` for ``rounds`` rounds, checkpointing every ``every``
    rounds (and at the end).  Resume by loading the file and calling again
    with the remaining round budget — long sweeps survive preemption.

    Each inter-checkpoint segment runs as ONE compiled ``fori_loop`` (the
    segment length is a traced argument, so the short tail segment reuses
    the same executable, as does a resume call with the same ``step``):
    the host syncs once per checkpoint, not once per round, preserving the
    while-loop fusion the round kernels are built for (tests/test_utils.py
    asserts both the bitwise trajectory and the one-trace property).

    ``step_args`` travel as traced jit ARGUMENTS into the segment runner
    — pass a tabled step's topology arrays here instead of closing over
    them, so 1M+-row tables are not inlined into the compile request
    (models/swim.py doc).

    ``curve_fn`` (state -> float scalar, or state -> dict of named float
    scalars) switches the segments to a compiled ``lax.scan`` that
    records the value(s) after every round: long runs can persist AND
    capture their convergence curve (the reference could do neither —
    SURVEY.md §5).  A dict-valued curve_fn records one list per channel
    (e.g. rumor mongering's coverage + hot-fraction pair, whose
    extinction round is only recoverable from the hot channel).  The
    curve-so-far rides in the checkpoint metadata under
    ``extra['curve']`` — a list for the scalar form, a dict of lists for
    the dict form — so a resumed run continues it seamlessly (pass the
    saved value as ``curve_prefix``).  Returns ``state`` without
    ``curve_fn``, ``(state, curve)`` with it.
    """
    if every < 1:
        raise ValueError(f"every must be >= 1, got {every}")
    curve = ({k: list(v) for k, v in curve_prefix.items()}
             if isinstance(curve_prefix, dict) else list(curve_prefix))

    def meta_now():
        if curve_fn is None:
            return extra_meta
        m = dict(extra_meta or {})
        m["curve"] = curve
        return m

    if curve_fn is None:
        run_segment = _segment_runner(step)
    else:
        run_segment = _curve_segment_runner(step, curve_fn)
    done = 0
    while done < rounds:
        todo = min(every, rounds - done)
        if curve_fn is None:
            state = run_segment(state, todo, *step_args)
        else:
            state, seg = run_segment(state, todo, *step_args)
            if isinstance(seg, dict):
                if not isinstance(curve, dict):
                    if curve:      # scalar prefix + dict curve_fn
                        raise TypeError(
                            "curve_prefix is a flat list but curve_fn "
                            "records named channels; pass the saved "
                            "dict-of-lists instead")
                    curve = {k: [] for k in seg}
                for k, v in seg.items():
                    curve[k].extend(float(x) for x in np.asarray(v))
            else:
                if isinstance(curve, dict):
                    raise TypeError(
                        "curve_prefix carries named channels but "
                        "curve_fn records a flat scalar; pass the "
                        "matching channel list (or the dict-recording "
                        "curve_fn the checkpoint was written with)")
                curve.extend(float(x) for x in np.asarray(seg))
        done += todo
        jax.block_until_ready(jax.tree_util.tree_leaves(state)[0])
        save_state(path, state, meta_now())
    if rounds <= 0:
        if curve_fn is not None and not isinstance(curve, dict) and not curve:
            # zero segments ran, so the dict-vs-scalar branch above never
            # told us curve_fn's shape: a dict-valued curve_fn must still
            # return a dict of channels, not a bare empty list, or
            # downstream channel extraction (e.g. the CLI's hot_curve)
            # silently loses the names (ADVICE r4).  eval_shape reads the
            # channel keys without running any compute.
            shape = jax.eval_shape(curve_fn, state)
            if isinstance(shape, dict):
                curve = {k: [] for k in shape}
        save_state(path, state, meta_now())
    if curve_fn is None:
        return state
    return state, curve
