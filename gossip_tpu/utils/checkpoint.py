"""Checkpoint / resume for round-state arrays.

The reference has NO persistence: all state is in-memory behind an RWMutex
and process death loses everything (SURVEY.md §5 "Checkpoint/resume:
None").  Here a simulation's full state is a handful of arrays (SimState /
SwimState), so checkpointing is one ``npz`` file: cheap, dependency-free,
and exact — including the typed PRNG key, serialized via
``jax.random.key_data`` and re-wrapped on load, so a resumed run continues
the *identical* trajectory (tests/test_utils.py proves resume == straight
run, bitwise).

Orbax exists in the environment but would be a dependency for no gain at
this state size; the format here is a plain ``np.savez`` with a JSON
metadata entry (state class name + field names + key dtype impl).
"""

from __future__ import annotations

import json
import os
import weakref
from typing import Union

import jax
import numpy as np

from gossip_tpu.models.rumor import RumorState
from gossip_tpu.models.state import SimState
from gossip_tpu.models.swim import SwimState

_STATE_TYPES = {"SimState": SimState, "SwimState": SwimState,
                "RumorState": RumorState}
State = Union[SimState, SwimState, RumorState]


def save_state(path: str, state: State, extra_meta=None) -> None:
    """Write a SimState/SwimState/RumorState to ``path`` (.npz).  Sharded
    arrays are gathered to host — checkpoint outside the hot loop.
    ``extra_meta`` (a JSON-able dict) rides in the metadata entry — e.g.
    the run's config fingerprint, so resume can refuse mismatched flags
    (:func:`load_meta`)."""
    cls = type(state).__name__
    if cls not in _STATE_TYPES:
        raise TypeError(f"unknown state type {cls}")
    fields = state._fields
    arrays = {}
    key_field = None
    for name in fields:
        val = getattr(state, name)
        if name == "base_key":
            key_field = name
            arrays[name] = np.asarray(jax.random.key_data(val))
        else:
            arrays[name] = np.asarray(val)
    meta = {"cls": cls, "fields": list(fields), "key_field": key_field,
            "key_impl": str(jax.random.key_impl(state.base_key))}
    if extra_meta is not None:
        meta["extra"] = extra_meta
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, __meta__=json.dumps(meta), **arrays)
    os.replace(tmp, path)          # atomic: no torn checkpoints on crash


def load_meta(path: str) -> dict:
    """The metadata entry of a checkpoint (incl. any ``extra_meta`` under
    'extra') without loading the arrays."""
    with np.load(path, allow_pickle=False) as z:
        return json.loads(str(z["__meta__"]))


def load_state(path: str) -> State:
    """Load a checkpoint written by :func:`save_state`."""
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        cls = _STATE_TYPES[meta["cls"]]
        kwargs = {}
        for name in meta["fields"]:
            if name == meta["key_field"]:
                # rewrap under the impl the checkpoint was SAVED with — the
                # loading process may default to a different PRNG impl
                # (e.g. rbg on TPU), which would silently change the
                # resumed trajectory
                kwargs[name] = jax.random.wrap_key_data(
                    jax.numpy.asarray(z[name]), impl=meta["key_impl"])
            else:
                kwargs[name] = jax.numpy.asarray(z[name])
    return cls(**kwargs)


# One jitted fori_loop runner per step function, so repeated
# run_with_checkpoints calls (resume loops) reuse the executable.  Weak
# keys: a dropped step closure (and the topology arrays it captures) must
# not be pinned in memory by this cache.
_segment_runners: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _segment_runner(step):
    runner = _segment_runners.get(step)
    if runner is None:
        @jax.jit
        def runner(s, n_steps, *args):
            return jax.lax.fori_loop(0, n_steps,
                                     lambda _, st: step(st, *args), s)
        _segment_runners[step] = runner
    return runner


def run_with_checkpoints(step, state: State, rounds: int, path: str,
                         every: int = 50, step_args=(),
                         extra_meta=None) -> State:
    """Drive ``step`` for ``rounds`` rounds, checkpointing every ``every``
    rounds (and at the end).  Resume by loading the file and calling again
    with the remaining round budget — long sweeps survive preemption.

    Each inter-checkpoint segment runs as ONE compiled ``fori_loop`` (the
    segment length is a traced argument, so the short tail segment reuses
    the same executable, as does a resume call with the same ``step``):
    the host syncs once per checkpoint, not once per round, preserving the
    while-loop fusion the round kernels are built for (tests/test_utils.py
    asserts both the bitwise trajectory and the one-trace property).

    ``step_args`` travel as traced jit ARGUMENTS into the segment runner
    — pass a tabled step's topology arrays here instead of closing over
    them, so 1M+-row tables are not inlined into the compile request
    (models/swim.py doc)."""
    if every < 1:
        raise ValueError(f"every must be >= 1, got {every}")
    run_segment = _segment_runner(step)
    done = 0
    while done < rounds:
        todo = min(every, rounds - done)
        state = run_segment(state, todo, *step_args)
        done += todo
        jax.block_until_ready(state.seen if hasattr(state, "seen")
                              else state.wire)
        save_state(path, state, extra_meta)
    if rounds <= 0:
        save_state(path, state, extra_meta)
    return state
