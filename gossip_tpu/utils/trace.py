"""Profiling hooks: jax.profiler wrappers for round-level tracing.

The reference has no tracing at all (SURVEY.md §5).  These helpers wrap
``jax.profiler`` so any driver can capture an XLA trace viewable in
TensorBoard / Perfetto (`trace(...)`) or annotate host-side phases
(`annotate(...)`) without importing profiler plumbing everywhere.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

# jax is imported lazily inside the helpers: cli.cmd_run imports this
# module unconditionally, and the go-native/native-router paths must
# stay runnable without ever touching jax (deferred-import pattern of
# backend.py/cli.py).


@contextlib.contextmanager
def trace(logdir: Optional[str]) -> Iterator[None]:
    """Capture a jax.profiler trace of the enclosed block into ``logdir``
    (TensorBoard's profile plugin / Perfetto read it).  ``None``/empty
    is a no-op (matching callers' ``if args.profile`` truthiness gates),
    so callers can wrap unconditionally: ``with trace(args.profile):``."""
    if not logdir:
        yield
        return
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region inside an active trace (host + device timeline)."""
    import jax
    with jax.profiler.TraceAnnotation(name):
        yield


def aot_timed(jitted, *args):
    """(out, compile_s, steady_s): compile the jitted callable for these
    arguments ahead of time, then time the execution alone.

    The hardware-table contract (round-2 verdict): reported walls must
    not mix one-off compile cost with steady-state throughput — the
    64-node sweep row's "11.6 s" was ~all compile.  ``compile_s`` covers
    trace+lower+compile; ``steady_s`` is the device execution of one
    call."""
    import jax
    t0 = time.perf_counter()
    compiled = jitted.lower(*args).compile()
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = compiled(*args)
    jax.block_until_ready(out)
    steady_s = time.perf_counter() - t0
    return out, compile_s, steady_s


def steady_timed(jitted, *args):
    """(out, steady_s): time ONE plain call of an already-jitted
    callable — an executable-cache hit when the caller warmed it, so
    the number is steady-state execution, not compile.  The cached-loop
    twin of :func:`aot_timed` (whose lower+compile deliberately
    bypasses the executable cache to measure a real compile)."""
    import jax
    t0 = time.perf_counter()
    out = jitted(*args)
    jax.block_until_ready(out)
    return out, time.perf_counter() - t0


def maybe_aot_timed(jitted, timing, *args):
    """:func:`aot_timed` when the caller passed a ``timing`` dict (fills
    ``compile_s``/``steady_s``), a plain call otherwise — the one place
    the drivers' optional-timing branch and its key names live.

    ``timing={"aot": False}`` opts into :func:`steady_timed` instead:
    ``steady_s`` is the cached-executable execution and ``compile_s``
    reports 0.0 (nothing compiled) — for callers probing a memoized
    driver's steady state, where an AOT lower+compile would measure a
    recompile the real re-entry never pays."""
    if timing is None:
        return jitted(*args)
    if timing.get("aot", True) is False:
        out, timing["steady_s"] = steady_timed(jitted, *args)
        timing.setdefault("compile_s", 0.0)
        return out
    out, timing["compile_s"], timing["steady_s"] = aot_timed(jitted, *args)
    return out


class RoundTimer:
    """Wall-clock per-round timing for python-driven loops (the scan/while
    drivers time whole programs instead — this is for stepwise drivers like
    utils/checkpoint.run_with_checkpoints)."""

    def __init__(self):
        self.times: list = []
        self._t0: Optional[float] = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.times.append(time.perf_counter() - self._t0)
        return False

    @property
    def mean_ms(self) -> float:
        return 1e3 * sum(self.times) / max(1, len(self.times))
