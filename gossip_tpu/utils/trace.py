"""Profiling hooks: jax.profiler wrappers for round-level tracing.

The reference has no tracing at all (SURVEY.md §5).  These helpers wrap
``jax.profiler`` so any driver can capture an XLA trace viewable in
TensorBoard / Perfetto (`trace(...)`) or annotate host-side phases
(`annotate(...)`) without importing profiler plumbing everywhere.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Iterator, Optional

# jax is imported lazily inside the helpers: cli.cmd_run imports this
# module unconditionally, and the go-native/native-router paths must
# stay runnable without ever touching jax (deferred-import pattern of
# backend.py/cli.py).

PROFILE_ENV = "GOSSIP_PROFILE"


def profile_dir() -> Optional[str]:
    """$GOSSIP_PROFILE — the ambient profiler capture directory, or
    None (unset/empty = profiling off, the GOSSIP_TELEMETRY
    convention)."""
    return os.environ.get(PROFILE_ENV) or None


@contextlib.contextmanager
def trace(logdir: Optional[str]) -> Iterator[None]:
    """Capture a jax.profiler trace of the enclosed block into ``logdir``
    (TensorBoard's profile plugin / Perfetto read it).  ``None``/empty
    is a no-op (matching callers' ``if args.profile`` truthiness gates),
    so callers can wrap unconditionally: ``with trace(args.profile):``."""
    if not logdir:
        yield
        return
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region inside an active trace (host + device timeline);
    probed via compat so a jax without TraceAnnotation degrades to a
    plain block instead of crashing the run it was meant to observe."""
    from gossip_tpu import compat
    with compat.trace_annotation(name):
        yield


@contextlib.contextmanager
def profile(tag: Optional[str] = None) -> Iterator[None]:
    """The $GOSSIP_PROFILE hook: capture a jax.profiler trace of the
    enclosed block into the ambient directory, with an optional named
    annotation around the whole block.  A no-op (zero jax import) when
    GOSSIP_PROFILE is unset, and a plain block when this jax lacks the
    profiler API (compat.profiler_trace_fns probe) — the profiled
    surfaces (dry-run families, bench legs) wrap unconditionally.

    One capture per ``profile()`` block: jax traces do not nest, so the
    callers wrap the OUTER program (the dry-run body, one bench leg)
    and mark inner phases with :func:`annotate`."""
    logdir = profile_dir()
    if not logdir:
        yield
        return
    from gossip_tpu import compat
    fns = compat.profiler_trace_fns()
    if fns is None:
        yield
        return
    start, stop = fns
    start(logdir)
    try:
        with annotate(tag) if tag else contextlib.nullcontext():
            yield
    finally:
        stop()


def aot_timed(jitted, *args, label=None):
    """(out, compile_s, steady_s, cache): obtain the executable for
    these arguments ahead of time, then time the execution alone.
    ``label`` is the caller's driver label for the chokepoint's
    ``xla_compile`` attribution event (utils/compile_cache) — the
    per-engine name a cost report groups by.

    The hardware-table contract (round-2 verdict): reported walls must
    not mix one-off compile cost with steady-state throughput — the
    64-node sweep row's "11.6 s" was ~all compile.  ``compile_s``
    covers trace+lower+ACQUIRE; since the compile-once PR, acquisition
    goes through the ONE chokepoint ``utils/compile_cache
    .load_or_compile`` — a real XLA compile on a cache miss (or with
    the cache disabled: bitwise the old behavior), a deserialization
    of the stored executable on a hit — and ``cache`` says which
    (``hit|miss|disabled``), so a warm compile_s can never masquerade
    as a cold one in an artifact.  ``steady_s`` is the device
    execution of one call, identical either way (warm-vs-cold output
    equality is pinned in tests/test_compile_cache.py)."""
    import jax

    from gossip_tpu.utils import compile_cache
    t0 = time.perf_counter()
    compiled, cache = compile_cache.load_or_compile(jitted, *args,
                                                    label=label)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = compiled(*args)
    jax.block_until_ready(out)
    steady_s = time.perf_counter() - t0
    return out, compile_s, steady_s, cache


def steady_timed(jitted, *args):
    """(out, steady_s): time ONE plain call of an already-jitted
    callable — an executable-cache hit when the caller warmed it, so
    the number is steady-state execution, not compile.  The cached-loop
    twin of :func:`aot_timed` (whose lower+compile deliberately
    bypasses the executable cache to measure a real compile)."""
    import jax
    t0 = time.perf_counter()
    out = jitted(*args)
    jax.block_until_ready(out)
    return out, time.perf_counter() - t0


def maybe_aot_timed(jitted, timing, *args, label=None):
    """:func:`aot_timed` when the caller passed a ``timing`` dict (fills
    ``compile_s``/``steady_s``), a plain call otherwise — the one place
    the drivers' optional-timing branch and its key names live.
    ``label`` names the calling driver for compile attribution
    (:func:`aot_timed`); it also rides the ``driver_timing`` event so
    walls and costs join on the same engine name.

    ``timing={"aot": False}`` opts into :func:`steady_timed` instead:
    ``steady_s`` is the cached-executable execution and ``compile_s``
    reports 0.0 (nothing compiled) — for callers probing a memoized
    driver's steady state, where an AOT lower+compile would measure a
    recompile the real re-entry never pays.

    On the AOT path ``timing["compile_cache"]`` records the executable
    store's verdict (``hit|miss|disabled`` — utils/compile_cache):
    this is the chokepoint every sharded driver's compile goes
    through, so enabling GOSSIP_COMPILE_CACHE warms them all with no
    per-driver plumbing."""
    fn_name = getattr(jitted, "__name__", None) or type(jitted).__name__
    if timing is None:
        out = jitted(*args)
        _emit_round_metrics(out, fn_name)
        return out
    if timing.get("aot", True) is False:
        out, timing["steady_s"] = steady_timed(jitted, *args)
        timing.setdefault("compile_s", 0.0)
    else:
        (out, timing["compile_s"], timing["steady_s"],
         timing["compile_cache"]) = aot_timed(jitted, *args, label=label)
    # every driver's wall decomposition reaches the ambient run ledger
    # (utils/telemetry) with no per-driver plumbing; a NullLedger makes
    # this a no-op.  The emit happens AFTER this call's own timed
    # region, but the CALLER may be timing us (the dry run's family
    # windows) — so sync=False: flush-only, no fsync latency inside
    # anyone's measured wall
    from gossip_tpu.utils import telemetry
    telemetry.current().event(
        "driver_timing", sync=False,
        fn=fn_name,
        label=label,
        cache=timing.get("compile_cache"),
        # walls only: the bool "aot" control flag is an int subclass
        # and must not masquerade as a timing field
        **{k: v for k, v in timing.items()
           if isinstance(v, (int, float)) and not isinstance(v, bool)})
    _emit_round_metrics(out, fn_name)
    return out


def _emit_round_metrics(out, fn_name: str):
    """The round-metrics flush half of the chokepoint: any
    :class:`~gossip_tpu.ops.round_metrics.RoundMetrics` stacks an
    instrumented driver carried through its loop are transferred to the
    host ONCE here — after the timed region, outside the compiled
    program — and ledgered as ``round_metrics`` events.  Gated on an
    ACTIVE ambient ledger so un-ledgered callers pay neither the
    device-to-host copy nor the ops import (and the go-native paths
    never touch jax)."""
    from gossip_tpu.utils import telemetry
    led = telemetry.current()
    if not getattr(led, "active", False):
        return
    from gossip_tpu.ops import round_metrics
    round_metrics.emit(out, led, fn=fn_name)


class RoundTimer:
    """Wall-clock per-round timing for python-driven loops (the scan/while
    drivers time whole programs instead — this is for stepwise drivers like
    utils/checkpoint.run_with_checkpoints)."""

    def __init__(self):
        self.times: list = []
        self._t0: Optional[float] = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.times.append(time.perf_counter() - self._t0)
        return False

    @property
    def mean_ms(self) -> float:
        return 1e3 * sum(self.times) / max(1, len(self.times))

    def percentile_ms(self, q: float) -> float:
        """Nearest-rank percentile (q in [0, 1]) of the recorded round
        walls, in ms; 0.0 with no samples (mean_ms convention).
        Delegates to the ONE quantile definition
        (utils/telemetry.percentile — shared with the serving layer's
        batch events and load-harness gates)."""
        from gossip_tpu.utils.telemetry import percentile
        return 1e3 * percentile(self.times, q)

    @property
    def p50_ms(self) -> float:
        return self.percentile_ms(0.50)

    @property
    def p95_ms(self) -> float:
        """Stepwise drivers report means that hide stragglers (a single
        wedged round disappears into 100 fast ones); the tail
        percentile is the straggler detector."""
        return self.percentile_ms(0.95)
