"""Compile-once runtime: persistent XLA cache + AOT executable store.

PR 1 made steady-state rounds device-resident, which left first-round
compiles as the dominant cost: 559-5201 ms per dry-run family (~27 s
aggregate per process, `artifacts/dryrun_steady_budget_r06.json`
`first_ms`) and the same wall eats most of the tier-1 budget.  A
serving system cannot pay full XLA compilation on every process start,
so this module makes the SECOND process (and every later one) reuse
executables instead of recompiling.  Two layers, one env var:

  1. **JAX's persistent compilation cache** (``enable_persistent``):
     ``jax_compilation_cache_dir`` pointed at the shared directory, so
     every plain ``jit`` first call — the dry-run families, CLI runs,
     tests — consults the on-disk cache before invoking XLA.  The
     knobs are PROBED through ``compat.persistent_cache_knobs`` (they
     moved across jax lines; CPU-backend caching was once gated behind
     the enable-xla-caches flag) and a missing knob degrades to "no
     cache", never to a crash or a silently-warm "cold" measurement.

  2. **An own-layer AOT store** (``load_or_compile``): explicit
     ``lower().compile()`` callers — every sharded driver's
     ``timing=`` path, through the ONE chokepoint in
     ``utils/trace.aot_timed`` — serialize the compiled executable
     (``jax.experimental.serialize_executable``) into
     ``<dir>/aot/<key>``.  A later process lowers, matches the key,
     and DESERIALIZES instead of compiling: warm cost is
     trace+lower+load.  The key is the sha256 of the **lowered HLO
     text** plus jax version / backend / device count — shapes,
     dtypes, mesh/axis specs, donation, and closed-over constants are
     all part of the HLO by construction, so a hit can never pair a
     stale executable with changed program semantics (warm-vs-cold
     bitwise equality is pinned in tests/test_compile_cache.py,
     including cross-process).

``GOSSIP_COMPILE_CACHE=<dir>`` is the ambient switch for both layers;
``GOSSIP_COMPILE_CACHE=""`` explicitly disables them (bench's honest
cold-compile policy; the same convention as GOSSIP_TELEMETRY).  Every
compile through the chokepoint emits a telemetry ``compile`` span with
``cache: hit|miss|disabled`` and bumps a ``compile_cache_<status>``
counter, so a run ledger shows exactly which process paid which
compile (tools/telemetry_report.py renders the table).

Trust note: the AOT store deserializes pickled executables from the
cache directory — the same trust domain as the persistent XLA cache
directory and the checkpoint files (a hostile cache dir is a hostile
filesystem).  Corrupt or stale entries are treated as misses and
overwritten, never raised to the driver.

Toolchain caveat (measured on jax 0.4.37 / XLA CPU, and the reason
every failure path here is non-fatal): the two layers interfere
in-process.  An executable that was itself LOADED from the persistent
XLA cache serializes WITHOUT its object files, and — worse — after a
process has taken even one persistent-cache hit, every subsequent
``deserialize_and_load`` in that process fails with "Symbols not
found", even for freshly compiled unrelated programs.  The store
therefore (a) verifies each blob round-trips before publishing it
(:func:`_try_store`), (b) treats load failures as non-destructive
misses (:func:`_try_load`), and (c) is at full strength exactly where
it matters: a fresh process's warm start, before any persistent-cache
hit has poisoned deserialization (the cross-process test in
tests/test_compile_cache.py pins this).
"""

from __future__ import annotations

import hashlib
import os
import pickle
import sys
import time
from typing import Optional, Tuple

ENV_VAR = "GOSSIP_COMPILE_CACHE"

_AOT_SUBDIR = "aot"
# bumped when the store's on-disk format changes; part of every key so
# old entries become misses instead of unpickle errors
_STORE_VERSION = 1


def cache_dir_from_env(default_path: Optional[str] = None) -> Optional[str]:
    """The active cache directory: $GOSSIP_COMPILE_CACHE, else
    ``default_path``, else None.  An empty-string env var explicitly
    DISABLES the cache (overriding any default) — the GOSSIP_TELEMETRY
    convention."""
    path = os.environ.get(ENV_VAR)
    if path is None:
        path = default_path
    return path or None


def enable_persistent(path: Optional[str],
                      min_compile_time_secs: float = 0.0,
                      min_entry_size_bytes: int = -1) -> dict:
    """Point jax's persistent compilation cache at ``path`` (None/""
    disables it, also overriding any ambient JAX_COMPILATION_CACHE_DIR
    — an explicit disable must mean honestly-cold compiles).  Returns
    a status dict — ``{"dir", "persistent", "knobs"}`` — that callers
    ledger verbatim, so every artifact says whether its compiles could
    have been warm.

    ``min_compile_time_secs=0.0`` caches everything by default: the
    dry-run families compile in 0.5-5 s each and the disk round-trip
    is microseconds by comparison; the CLI keeps its own 2 s threshold
    for operator ~/.cache hygiene.  Both knobs (and the dir itself)
    are set through ``compat.set_cache_knob`` — absent knobs on other
    jax lines are recorded in ``knobs`` and skipped, never raised."""
    from gossip_tpu import compat
    status = {"dir": None, "persistent": False,
              "knobs": compat.persistent_cache_knobs()}
    if not path:
        compat.set_cache_knob("jax_compilation_cache_dir", None)
        return status
    path = os.path.abspath(path)
    try:
        os.makedirs(path, exist_ok=True)
    except OSError as e:
        # read-only checkout / sandbox: run uncached, never abort the
        # run the cache was meant to speed up
        sys.stderr.write(f"compile_cache: cannot create {path!r} ({e}); "
                         "persistent cache disabled\n")
        compat.set_cache_knob("jax_compilation_cache_dir", None)
        return status
    ok = compat.set_cache_knob("jax_compilation_cache_dir", path)
    # the master enable defaults True on every line that has it, but a
    # caller (or sitecustomize) may have flipped it — "dir set" must
    # mean "cache on", not "cache on unless someone disabled it
    # upstream", or status would claim warm-capability for cold walls.
    # A line WITHOUT the knob has no off state to reset, so its absence
    # must not veto ``persistent`` (the dir knob alone enables there)
    if ok:
        compat.set_cache_knob("jax_enable_compilation_cache", True)
    compat.set_cache_knob("jax_persistent_cache_min_compile_time_secs",
                          min_compile_time_secs)
    compat.set_cache_knob("jax_persistent_cache_min_entry_size_bytes",
                          min_entry_size_bytes)
    # jax_persistent_cache_enable_xla_caches is probed (reported in
    # status["knobs"]) but left at its default: CPU-backend caching on
    # this 0.4.37 container works without it (measured cross-process),
    # and the knob's value vocabulary differs across lines — forcing a
    # guess could disable a working cache.  The expect_warm dry-run
    # guard is the end-to-end check that warmth actually happens.
    status["dir"] = path
    status["persistent"] = ok
    return status


def enable_from_env(default_path: Optional[str] = None,
                    min_compile_time_secs: float = 0.0) -> dict:
    """``enable_persistent`` at the ambient dir (env over default) —
    the one call a process makes at startup to become warm-startable.
    The returned status should be ledgered (the dry-run body does)."""
    return enable_persistent(cache_dir_from_env(default_path),
                             min_compile_time_secs=min_compile_time_secs)


# -- the AOT executable store -----------------------------------------

def _fingerprint(hlo_text: str) -> str:
    """Store key: lowered-HLO hash + toolchain/topology context.  The
    HLO carries shapes, dtypes, sharding/mesh specs, donation and
    every closed-over constant; version/backend/device-count guard the
    executable format itself (a serialized CPU executable must never
    load into a TPU process or a different device count)."""
    import jax
    h = hashlib.sha256()
    h.update(hlo_text.encode())
    h.update(f"|v{_STORE_VERSION}|{jax.__version__}"
             f"|{jax.default_backend()}|{jax.device_count()}".encode())
    return h.hexdigest()[:40]


def _entry_path(cache_dir: str, key: str) -> str:
    return os.path.join(cache_dir, _AOT_SUBDIR, key + ".xbin")


def _try_load(path: str, fns):
    """Deserialized executable, or None — a miss, by contract, never an
    error.  A pickle-corrupt file (torn write from a pre-atomic-rename
    crash, disk damage) is deleted; an entry that unpickles but will
    not LOAD here is KEPT — loadability is process-state-dependent
    (another process may load it fine) and the writer verified it once
    (:func:`_try_store`), so deleting would let one odd process evict
    everyone's warm start."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        # missing entry, or a TRANSIENT read failure (EMFILE, EIO,
        # permissions): a miss either way, and never grounds to evict
        # an entry other processes may be warm-starting from
        return None
    try:
        payload, in_tree, out_tree = pickle.loads(data)
    except Exception as e:
        sys.stderr.write(f"compile_cache: dropping corrupt AOT entry "
                         f"{os.path.basename(path)} "
                         f"({type(e).__name__}: {e})\n")
        try:
            os.remove(path)
        except OSError:
            pass
        return None
    try:
        return fns[1](payload, in_tree, out_tree)
    except Exception as e:
        sys.stderr.write(f"compile_cache: AOT entry "
                         f"{os.path.basename(path)} did not load in "
                         f"this process ({type(e).__name__}); "
                         "recompiling\n")
        return None


def _try_store(path: str, compiled, fns) -> None:
    """Serialize ``compiled`` to ``path`` atomically (tmp + rename, so
    a killed writer can never leave a torn entry a sibling process
    would then deserialize).  The blob is VERIFIED by deserializing it
    before the rename: an executable that was itself loaded from the
    XLA persistent cache serializes to a truncated payload missing its
    object files ("Symbols not found" on load — measured on jax
    0.4.37/CPU), and the store must never publish an entry its own
    writer cannot read back.  Failures degrade to "not cached" (the
    persistent-cache layer still serves the program)."""
    try:
        payload, in_tree, out_tree = fns[0](compiled)
        fns[1](payload, in_tree, out_tree)          # verify round-trip
    except Exception as e:
        sys.stderr.write(f"compile_cache: executable does not "
                         f"round-trip ({type(e).__name__}); not "
                         "storing (persistent-cache-loaded executables "
                         "cannot be re-serialized)\n")
        return
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            pickle.dump((payload, in_tree, out_tree), f)
        os.replace(tmp, path)
    except Exception as e:
        sys.stderr.write(f"compile_cache: could not store AOT entry "
                         f"({type(e).__name__}: {e})\n")


# -- XLA cost & memory attribution (the observability PR) -------------

# every xla_compile event carries ALL of these keys, populated or
# explicit-null (record-never-gate): a consumer joins on schema, not on
# backend luck.  peak_bytes is argument+output+temp — the same closed
# form the PR 15 scale gate measured against budget.py's prediction.
ATTRIBUTION_FIELDS = ("flops", "bytes_accessed", "argument_bytes",
                      "output_bytes", "temp_bytes", "peak_bytes")

_LAST_COMPILE: Optional[dict] = None


def last_compile() -> Optional[dict]:
    """The most recent chokepoint compile's attribution record (the
    ``xla_compile`` event fields), or None when this process has not
    compiled through the chokepoint yet — the sidecar's ``Metrics``
    reply reads this so a steady-state fleet that compiles shows WHAT
    compiled (absent-not-wrong: no compile means no field, never a
    fabricated one)."""
    return _LAST_COMPILE


def xla_attribution(compiled) -> dict:
    """``cost_analysis()`` flops/bytes-accessed and
    ``memory_analysis()`` argument/output/temp/peak bytes of a compiled
    executable — every field explicit None when the backend/object
    cannot report it (older jax lines return no analyses; interpret
    stubs have neither method).  Never raises: attribution is evidence
    about the run, not a gate on it."""
    out = {k: None for k in ATTRIBUTION_FIELDS}
    try:
        cost = compiled.cost_analysis()
        # this jax line returns [per-computation dict]; others a dict
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        if cost.get("flops") is not None:
            out["flops"] = float(cost["flops"])
        if cost.get("bytes accessed") is not None:
            out["bytes_accessed"] = float(cost["bytes accessed"])
    except Exception:
        pass
    try:
        mem = compiled.memory_analysis()
        for field, attr in (("argument_bytes", "argument_size_in_bytes"),
                            ("output_bytes", "output_size_in_bytes"),
                            ("temp_bytes", "temp_size_in_bytes")):
            v = getattr(mem, attr, None)
            if v is not None:
                out[field] = int(v)
        if None not in (out["argument_bytes"], out["output_bytes"],
                        out["temp_bytes"]):
            out["peak_bytes"] = (out["argument_bytes"]
                                 + out["output_bytes"]
                                 + out["temp_bytes"])
    except Exception:
        pass
    return out


def _default_label(jitted, name: str) -> str:
    """Fallback driver label when the caller supplies none: the wrapped
    function's defining module tail (``parallel.sharded`` → the engine
    family), else the function name — so even an unlabeled compile is
    attributable to SOME surface."""
    mod = getattr(jitted, "__module__", None)
    if mod and mod.startswith("gossip_tpu."):
        return mod[len("gossip_tpu."):]
    return mod or name


def load_or_compile(jitted, *args, cache_dir: Optional[str] = None,
                    label: Optional[str] = None) -> Tuple[object, str]:
    """(compiled, status): the AOT chokepoint.  Lower ``jitted`` for
    ``args``, then either deserialize a stored executable (``"hit"``)
    or compile and store it (``"miss"``); ``"disabled"`` when no cache
    dir is active or this jax cannot serialize executables.  The whole
    operation is one telemetry ``compile`` span carrying ``cache``/
    ``fn``/``key`` — a run killed mid-compile shows WHERE in the span
    tree, and the ledger's span walls decompose warm vs cold without
    any driver plumbing (utils/trace.aot_timed is the one caller the
    sharded drivers go through).

    The lowering runs unconditionally: it IS the key (module doc), so
    a warm process still pays trace+lower — that residual is exactly
    what the dry run's ``first_warm_ms`` budgets bound.

    Every acquisition here additionally emits one ``xla_compile``
    event (sync=False — this runs inside callers' timed windows) with
    the caller's driver ``label``, the store ``key``, the acquire
    wall, the cache verdict, and the executable's own cost/memory
    attribution (:func:`xla_attribution`, explicit nulls on backends
    without the analyses) — the self-attribution plane
    docs/OBSERVABILITY.md "XLA cost & memory attribution" documents;
    :func:`last_compile` keeps the most recent record for the live
    Metrics surface."""
    global _LAST_COMPILE
    from gossip_tpu import compat
    from gossip_tpu.utils import telemetry
    if cache_dir is None:
        cache_dir = cache_dir_from_env()
    fns = compat.serialize_executable_fns()
    led = telemetry.current()
    name = getattr(jitted, "__name__", None) or type(jitted).__name__
    key = None
    t0 = time.perf_counter()
    with led.span("compile", fn=name) as ext:
        # on the END event too: the report's cache table reads rows
        # from span_end lines (span_start attrs don't ride along)
        ext["fn"] = name
        lowered = jitted.lower(*args)
        if not cache_dir or fns is None:
            compiled = lowered.compile()
            status = "disabled"
        else:
            key = _fingerprint(lowered.as_text())
            path = _entry_path(cache_dir, key)
            compiled = _try_load(path, fns)
            if compiled is not None:
                status = "hit"
            else:
                compiled = lowered.compile()
                _try_store(path, compiled, fns)
                status = "miss"
            ext["key"] = key
        ext["cache"] = status
    wall_ms = (time.perf_counter() - t0) * 1e3
    led.counter(f"compile_cache_{status}")
    record = {"label": label or _default_label(jitted, name),
              "fn": name, "key": key, "cache": status,
              "compile_ms": round(wall_ms, 3),
              **xla_attribution(compiled)}
    _LAST_COMPILE = dict(record)
    led.event("xla_compile", sync=False, **record)
    return compiled, status


# -- plain-jit compile accounting -------------------------------------

class JitCompileMonitor:
    """Counts XLA persistent-cache hits/misses for PLAIN jit calls —
    the compiles that never pass through :func:`load_or_compile`
    because nothing lowers them explicitly (the dry-run families'
    first calls).  jax.monitoring emits one event per compile request;
    deltas around a timed window classify it as warm or cold, so the
    dry run can ledger a ``compile`` event per family with the same
    ``cache: hit|miss|disabled`` vocabulary as the chokepoint.

    Since the traced-operand PR the monitor also counts REAL backend
    compiles (``backend_compiles``: jax's per-compile
    ``/jax/core/compile/backend_compile_duration`` event, which fires
    whether or not a persistent cache is configured) — the delta probe
    behind the ``assert_compiles`` test fixture (tests/conftest.py):
    "K nemesis scenarios, ONE compile" is an assertion on this counter.

    Listener registration is process-global and permanent (jax offers
    no unregister on this line) — instantiate once per process, as the
    dry-run body does."""

    HIT = "/jax/compilation_cache/cache_hits"
    MISS = "/jax/compilation_cache/cache_misses"
    BACKEND = "/jax/core/compile/backend_compile_duration"

    def __init__(self):
        self.hits = 0
        self.misses = 0
        self.backend_compiles = 0
        self.available = False
        self.durations_available = False
        try:
            from jax import monitoring
            monitoring.register_event_listener(self._on_event)
            self.available = True
        except Exception as e:
            sys.stderr.write("compile_cache: jax.monitoring unavailable "
                             f"({type(e).__name__}: {e}); plain-jit "
                             "cache accounting disabled\n")
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(
                self._on_duration)
            self.durations_available = True
        except Exception:
            pass        # older jax: backend-compile counting degrades

    def _on_event(self, name, **kw):
        if name == self.HIT:
            self.hits += 1
        elif name == self.MISS:
            self.misses += 1

    def _on_duration(self, name, dur, **kw):
        if name == self.BACKEND:
            self.backend_compiles += 1

    def snapshot(self) -> Tuple[int, int]:
        return self.hits, self.misses

    def classify(self, before: Tuple[int, int],
                 cache_enabled: bool) -> dict:
        """{cache, hits, misses} for the window since ``before``.
        ``miss`` wins when a window holds both (ONE cold sub-compile
        means the process paid a real compile)."""
        dh, dm = self.hits - before[0], self.misses - before[1]
        if not cache_enabled or not self.available:
            cache = "disabled"
        elif dm > 0:
            cache = "miss"
        elif dh > 0:
            cache = "hit"
        else:
            # no persistent-cache traffic at all: an in-memory
            # executable reuse (steady calls) — not a compile event
            cache = "none"
        return {"cache": cache, "hits": dh, "misses": dm}


def entry_count(cache_dir: Optional[str]) -> Optional[int]:
    """Number of files in the cache dir tree (both layers), or None
    when disabled/absent — a cheap cross-check the dry run ledgers
    alongside the monitor's counters."""
    if not cache_dir or not os.path.isdir(cache_dir):
        return None
    total = 0
    for _, _, files in os.walk(cache_dir):
        total += len(files)
    return total


def timed_split(jitted, *args, cache_dir: str):
    """(compiled, cold_s, warm_s, (status0, status1)): one honest cold
    compile into a fresh store, then the SAME lower+compile warm from
    it, with jax's in-memory caches cleared in between so the warm
    number measures the store (trace+lower+deserialize), not a
    Python-side memo.  The bench's reproducible CPU-side compile-split
    signal; also a convenient self-test that a store round-trips on
    this toolchain.  The statuses travel WITH the walls — a pair other
    than ("miss", "hit") means warm_s is not a store round-trip (a
    write/load failure made it a second full compile) and the consumer
    must say so rather than publish it as warm; with the store
    unavailable entirely the warm leg is SKIPPED (warm_s None,
    statuses ("disabled", "skipped")) instead of paying a meaningless
    second compile.

    jax's PERSISTENT cache is suspended for the duration (config
    saved/restored): with it active the cold compile could be served
    warm — and a persistent-cache-loaded executable cannot even enter
    the store (_try_store's round-trip verify) — so the split would
    silently measure nothing."""
    import jax

    from gossip_tpu import compat
    prev = getattr(jax.config, "jax_compilation_cache_dir", None)
    compat.set_cache_knob("jax_compilation_cache_dir", None)
    try:
        t0 = time.perf_counter()
        compiled, status0 = load_or_compile(jitted, *args,
                                            cache_dir=cache_dir)
        cold_s = time.perf_counter() - t0
        if status0 == "disabled":
            # no store on this toolchain/dir: a second compile would
            # measure nothing but another cold compile (minutes for
            # the big programs) — report the warm leg as absent
            return compiled, cold_s, None, (status0, "skipped")
        jax.clear_caches()
        t0 = time.perf_counter()
        compiled, status1 = load_or_compile(jitted, *args,
                                            cache_dir=cache_dir)
        warm_s = time.perf_counter() - t0
    finally:
        compat.set_cache_knob("jax_compilation_cache_dir", prev)
    if (status0, status1) != ("miss", "hit"):
        # a dirty dir (cold was already warm) or a store failure (warm
        # recompiled) silently corrupts the split — report it instead
        sys.stderr.write(f"compile_cache: timed_split statuses "
                         f"({status0}, {status1}) != (miss, hit); "
                         "walls may not be a true cold/warm pair\n")
    return compiled, cold_s, warm_s, (status0, status1)
