"""Metrics and observability: what the reference never had.

The reference's only instrumentation is ``log.Fatal`` on exit; every
latency/msgs-per-op number came from the external Maelstrom checker
(SURVEY.md §5).  This module makes the framework's own metrics first-class
(BASELINE.md tracked metrics):

  * rounds-to-target-coverage,
  * simulated node-rounds/sec/chip,
  * messages-per-round / messages-per-op,
  * convergence-curve artifacts (JSONL dumps, curve-gap comparison — the
    parity deliverable between backends).
"""

from __future__ import annotations

import dataclasses
import json
from typing import List, Optional, Sequence


@dataclasses.dataclass
class ConvergenceMetrics:
    """Summary of one run's coverage curve."""

    rounds_to_target: int          # -1 if never reached
    final_coverage: float
    auc: float                     # mean coverage over rounds (higher=faster)
    msgs_total: float
    msgs_per_node_per_round: float
    node_rounds_per_sec: Optional[float] = None   # None without timing

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def summarize_curve(coverage: Sequence[float], msgs: Sequence[float],
                    n: int, target: float = 0.99,
                    wall_s: Optional[float] = None,
                    n_chips: int = 1) -> ConvergenceMetrics:
    cov = list(map(float, coverage))
    rounds = len(cov)
    hit = next((i + 1 for i, c in enumerate(cov) if c >= target), -1)
    msgs_total = float(msgs[-1]) if len(msgs) else 0.0
    rate = None
    if wall_s and wall_s > 0:
        rate = n * rounds / wall_s / n_chips
    return ConvergenceMetrics(
        rounds_to_target=hit,
        final_coverage=cov[-1] if cov else 0.0,
        auc=sum(cov) / rounds if rounds else 0.0,
        msgs_total=msgs_total,
        msgs_per_node_per_round=(msgs_total / (n * rounds)) if rounds else 0.0,
        node_rounds_per_sec=rate,
    )


def curve_gap(a: Sequence[float], b: Sequence[float]) -> float:
    """Max absolute coverage gap between two curves (padded with their
    final values) — the backend-parity artifact: the jax-tpu flood curve vs
    the go-native hop curve should gap to ~0 on race-free graphs
    (runtime/gonative.py parity contract)."""
    la, lb = list(map(float, a)), list(map(float, b))
    m = max(len(la), len(lb))
    la += [la[-1]] * (m - len(la)) if la else [0.0] * m
    lb += [lb[-1]] * (m - len(lb)) if lb else [0.0] * m
    return max(abs(x - y) for x, y in zip(la, lb))


def dump_curve_jsonl(path: str, coverage: Sequence[float],
                     msgs: Optional[Sequence[float]] = None,
                     meta: Optional[dict] = None) -> None:
    """One JSON object per round: {round, coverage, msgs?} with an optional
    leading meta line ({"meta": ...}) — trivially greppable/plottable.

    A msgs series of the wrong length is rejected BEFORE the file is
    opened: the old behavior raised IndexError mid-write, leaving a
    torn artifact on disk that silently parsed as a shorter run."""
    if msgs is not None and len(msgs) != len(coverage):
        raise ValueError(
            f"len(msgs)={len(msgs)} != len(coverage)={len(coverage)}; "
            "each round needs both series (pass msgs=None to omit)")
    with open(path, "w") as f:
        if meta is not None:
            f.write(json.dumps({"meta": meta}) + "\n")
        for i, c in enumerate(coverage):
            row = {"round": i + 1, "coverage": float(c)}
            if msgs is not None:
                row["msgs"] = float(msgs[i])
            f.write(json.dumps(row) + "\n")


def load_curve_jsonl(path: str) -> List[dict]:
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]
