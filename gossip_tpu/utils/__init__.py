"""Metrics, checkpointing, and tracing (SURVEY.md §5 auxiliary subsystems —
all absent from the reference, all first-class here)."""
