"""Command-line interface: pick a backend, run, sweep, serve.

The reference has no flags, no env vars, no config of any kind — its only
runtime configuration is the ``topology`` message (reference main.go:132-149,
SURVEY.md §5).  This CLI makes every implicit constant explicit and
sweepable, and selects the engine at runtime through the Backend seam
(BASELINE.json north star):

    python -m gossip_tpu run --backend jax-tpu --mode pushpull --n 100000
    python -m gossip_tpu run --backend go-native --mode flood --n 1024 \
        --family ring --curve
    python -m gossip_tpu sweep --scale 0.01          # the 5 BASELINE configs
    python -m gossip_tpu serve --port 50051          # gRPC sidecar
    python -m gossip_tpu maelstrom                   # protocol node on stdio

Output is JSON lines (one report per line) so harnesses can consume it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from gossip_tpu.config import (FaultConfig, MeshConfig, ProtocolConfig,
                               RunConfig, TopologyConfig)


_CACHE_DEFAULT = os.environ.get(
    "GOSSIP_COMPILE_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "gossip_tpu", "xla"))


def _add_cache_flags(p: argparse.ArgumentParser) -> None:
    """JAX persistent compilation cache (default ON for every jax-driven
    subcommand).  Rationale: the SWIM-1M BASELINE row's wall is ~88%
    XLA compile (127.7 s of 145.5 s, artifacts/baseline_sweep_r04b.jsonl)
    and the r04 ablation (artifacts/swim_compile_ablation_r04.json,
    tools/swim_compile_ablation.py) showed that cost is structural —
    spread across the whole 1M-row program (every component stub is
    within the +-4 s repeat-compile noise; compile scales with n, see
    the artifact's scaling_compile_s_by_n: 28.5 s at 100k -> ~120 s at
    1M) — so the fix is to pay it once per shape EVER, not once per
    process."""
    p.add_argument("--compile-cache", default=_CACHE_DEFAULT, metavar="DIR",
                   help="persistent XLA compilation cache directory "
                        "(env GOSSIP_COMPILE_CACHE overrides the "
                        "default; repeated runs of the same shapes skip "
                        "recompilation)")
    p.add_argument("--no-compile-cache", action="store_true",
                   help="disable the persistent compilation cache (e.g. "
                        "to measure cold compile_s)")


def _enable_compile_cache(a) -> None:
    """One definition of "the cache is on": utils/compile_cache, which
    also probes the knob set (compat.persistent_cache_knobs) so a jax
    line missing a knob degrades instead of crashing.  An explicit
    disable must also override a JAX_COMPILATION_CACHE_DIR env var, or
    the documented "honest cold compile" measurement could silently
    hit that cache."""
    if not hasattr(a, "no_compile_cache"):   # subcommand without the flags
        return
    from gossip_tpu.utils import compile_cache
    if a.no_compile_cache or not a.compile_cache:
        compile_cache.enable_persistent(None)
        # the AOT executable store reads GOSSIP_COMPILE_CACHE directly
        # (trace.aot_timed chokepoint) — an explicit disable must shut
        # BOTH layers, or the store serves a warm compile_s that
        # _cache_stamp then records as cold
        os.environ[compile_cache.ENV_VAR] = ""
        return
    # cache anything that took >2 s to compile; below that the disk
    # round-trip costs more than the recompile (operator ~/.cache
    # hygiene — the dry run's own dir caches everything instead)
    status = compile_cache.enable_persistent(a.compile_cache,
                                             min_compile_time_secs=2.0)
    if not status["persistent"]:   # read-only HOME / sandbox: uncached
        a.no_compile_cache = True  # keep _cache_stamp honest
        os.environ[compile_cache.ENV_VAR] = ""
        return
    # both layers on one dir: the AOT store lands beside the XLA cache
    os.environ[compile_cache.ENV_VAR] = a.compile_cache


def _cache_stamp(a):
    """What a report row records about the compile cache, so warm-cache
    compile_s can never masquerade as a cold measurement in an artifact."""
    if not hasattr(a, "no_compile_cache") or a.no_compile_cache or \
            not a.compile_cache:
        return None
    return a.compile_cache


def _add_run_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--backend", default="jax-tpu",
                   choices=("jax-tpu", "go-native"))
    p.add_argument("--mode", default="push",
                   choices=("push", "pull", "pushpull", "flood",
                            "antientropy", "swim", "rumor"))
    p.add_argument("--rumor-k", type=int, default=2,
                   help="rumor mongering: remove a rumor after this many "
                        "unnecessary (feedback) or total (blind) pushes")
    p.add_argument("--rumor-variant", default="feedback",
                   choices=("feedback", "blind"))
    p.add_argument("--n", type=int, default=1024)
    p.add_argument("--fanout", type=int, default=1)
    p.add_argument("--rumors", type=int, default=1)
    p.add_argument("--period", type=int, default=1,
                   help="anti-entropy exchange period (rounds)")
    p.add_argument("--family", default="complete",
                   choices=("complete", "ring", "grid", "erdos_renyi",
                            "watts_strogatz", "power_law"))
    p.add_argument("--k", type=int, default=4,
                   help="ring/WS neighbors; BA attachment edges")
    p.add_argument("--p", type=float, default=0.01,
                   help="ER edge prob / WS rewire prob")
    p.add_argument("--degree-cap", type=int, default=None)
    p.add_argument("--target", type=float, default=0.99)
    p.add_argument("--max-rounds", type=int, default=256)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--origin", type=int, default=0)
    p.add_argument("--drop", type=float, default=0.0,
                   help="per-message drop probability per round")
    p.add_argument("--death", type=float, default=0.0,
                   help="fraction of nodes statically dead")
    p.add_argument("--devices", type=int, default=1,
                   help="mesh size for node-dim sharding (jax-tpu)")
    p.add_argument("--exchange", default="dense",
                   choices=("dense", "sparse", "halo"),
                   help="cross-shard pattern: dense all_gather (any), "
                        "sparse all_to_all (complete topology, "
                        "pull/antientropy, O(messages)), halo ppermute "
                        "(band-limited topologies, O(band))")
    p.add_argument("--engine", default="auto",
                   choices=("auto", "fused", "xla", "native"),
                   help="round kernel: auto = best eligible (fused Pallas "
                        "on TPU for single-device pull on the complete "
                        "graph — static fault masks and --curve "
                        "included since round 4 — bit-packed XLA "
                        "otherwise); fused "
                        "= force the Pallas kernel (TPU, pull, complete "
                        "graph; <= 32 rumors on one device, rumor planes "
                        "sharded zero-ICI with --devices beyond that); "
                        "xla = force the XLA kernels (the threefry stream "
                        "that matches the sharded paths bitwise); native "
                        "= go-native backend only: force the C++ event "
                        "core and raise the node cap to 1M")
    p.add_argument("--curve", action="store_true",
                   help="include the per-round coverage curve")
    p.add_argument("--parity-check", action="store_true",
                   help="flood only: run the SAME topology through both "
                        "backends (jax-tpu rounds vs go-native hop "
                        "depths — the C++ event core above 20k nodes) "
                        "and report the parity-contract checks: "
                        "curve_gap (~0 on race-free graphs), "
                        "hop_bound_violation (~0 always: races only "
                        "slow the event sim), fixed_point_gap (~0 "
                        "always: identical final coverage) — the "
                        "backend-parity artifact at any n up to 1M")
    p.add_argument("--profile", default=None, metavar="LOGDIR",
                   help="capture a jax.profiler trace of the run into "
                        "LOGDIR (TensorBoard profile plugin / Perfetto)")
    p.add_argument("--checkpoint", default=None, metavar="PATH",
                   help="checkpointed driver (SI single-device, sharded "
                        "packed via --devices, --engine fused planes, "
                        "swim, or rumor — the last two single-device or "
                        "sharded): "
                        "run max_rounds rounds saving an atomic npz every "
                        "--checkpoint-every rounds; with --resume, "
                        "continue a previous run from PATH (bitwise "
                        "continuation incl. the PRNG key); composes with "
                        "--curve/--save-curve (curve persists in the "
                        "checkpoint and resumes seamlessly)")
    p.add_argument("--checkpoint-every", type=int, default=50)
    p.add_argument("--resume", action="store_true",
                   help="load --checkpoint PATH and continue to "
                        "max_rounds total rounds")
    p.add_argument("--plan", default=None, metavar="FILE",
                   help="execute a ScalePlan (from `gossip_tpu plan`) "
                        "through the streamed word-plane tile driver "
                        "instead of the flag-configured run; composes "
                        "with --checkpoint/--resume (the plan carries "
                        "n/rumors/fanout/faults/segments — "
                        "docs/SCALING.md)")
    p.add_argument("--save-curve", default=None, metavar="PATH",
                   help="write the coverage curve as JSONL (implies --curve)")
    p.add_argument("--ensemble", type=int, default=0, metavar="S",
                   help="run S seeds as one vmapped batch and report "
                        "ensemble statistics (jax-tpu; for swim this "
                        "is the detection-latency distribution of one "
                        "failure scenario across seeds; --devices "
                        "shards the SEED axis over a mesh)")
    p.add_argument("--swim-subjects", type=int, default=8)
    p.add_argument("--swim-proxies", type=int, default=3)
    p.add_argument("--swim-suspect-rounds", type=int, default=0,
                   help="0 = use suggested_suspect_rounds(n)")
    p.add_argument("--swim-rotate", action="store_true",
                   help="rotate the subject window over all n nodes "
                        "(full-membership failure detection)")
    p.add_argument("--swim-epoch-rounds", type=int, default=0,
                   help="rounds per rotating-window epoch (0 = auto)")
    p.add_argument("--swim-diss", choices=("scatter", "sort", "pack"),
                   default="sort",
                   help="dissemination reduce lowering (all bitwise-"
                        "identical): 'sort' = sort-by-receiver + "
                        "segment-max (default; 2.2x faster on TPU, "
                        "artifacts/swim_ab_r04.json); 'scatter' = "
                        "duplicate-index scatter-max control; 'pack' = "
                        "sort with the row gather on 8/16-bit packed "
                        "codes — needs --max-rounds to prove its lane "
                        "bound, silently falls back to sort without it")
    p.add_argument("--swim-rng", choices=("split", "packed"),
                   default="split",
                   help="per-round randomness lowering: 'split' = one "
                        "independent threefry chain per quantity (the "
                        "original contract); 'packed' = one key chain + "
                        "one multi-word draw per node, fields split by "
                        "bits (opt-in statistical contract — different "
                        "trajectories, uniform marginals up to a "
                        "documented <= m/2^32 modulo bias, mesh-"
                        "invariant; models/swim.packed_round_draws)")
    p.add_argument("--dead-nodes", nargs="*", type=int, default=None,
                   metavar="ID",
                   help="node ids that fail at --fail-round (swim scenario; "
                        "default: node 1%%S fails at round 2)")
    p.add_argument("--fail-round", type=int, default=0)
    # time-varying nemesis schedule (ChurnConfig -> ops/nemesis,
    # compiled into the round loops; docs/ROBUSTNESS.md)
    p.add_argument("--churn-event", action="append", default=None,
                   metavar="NODE:DIE[:REC]",
                   help="scripted crash/recover churn: NODE dies at round "
                        "DIE and recovers at round REC (omit REC or pass "
                        "-1 for a permanent crash); repeatable")
    p.add_argument("--partition", action="append", default=None,
                   metavar="START:END:CUT",
                   help="network partition window: for rounds [START, END) "
                        "every message crossing node-id CUT is lost; "
                        "repeatable, windows must not overlap")
    p.add_argument("--drop-ramp", default=None, metavar="START:END:P0:P1",
                   help="drop-rate ramp: link drop probability moves "
                        "linearly P0 -> P1 over rounds [START, END), then "
                        "holds P1")


def _parse_churn(a):
    """--churn-event/--partition/--drop-ramp -> ChurnConfig or None.
    Field validation (ranges, overlap) lives in ChurnConfig itself —
    this only parses the colon syntax."""
    def ints(s, what, lens):
        parts = s.split(":")
        if len(parts) not in lens:
            raise ValueError(
                f"--{what} takes {'|'.join(map(str, sorted(lens)))} "
                f"colon-separated fields, got {s!r}")
        return parts

    events = []
    for s in (getattr(a, "churn_event", None) or ()):
        parts = ints(s, "churn-event", {2, 3})
        if len(parts) == 2:
            parts.append("-1")
        events.append(tuple(int(x) for x in parts))
    partitions = []
    for s in (getattr(a, "partition", None) or ()):
        partitions.append(tuple(int(x) for x in ints(s, "partition", {3})))
    ramp = None
    if getattr(a, "drop_ramp", None):
        f = ints(a.drop_ramp, "drop-ramp", {4})
        ramp = (int(f[0]), int(f[1]), float(f[2]), float(f[3]))
    if not (events or partitions or ramp):
        return None
    from gossip_tpu.config import ChurnConfig
    return ChurnConfig(events=tuple(events),
                       partitions=tuple(partitions), ramp=ramp)


def _parse_byz(a):
    """--byz NODE:ROUND:KIND[:ARG] (+ --byz-quorum) -> ByzConfig or
    None.  Field validation (known kinds, one action per node, quorum
    range) lives in ByzConfig itself — this only parses the colon
    syntax, the _parse_churn discipline."""
    specs = getattr(a, "byz", None) or ()
    if not specs:
        return None
    liars = []
    for s in specs:
        p = s.split(":")
        if len(p) not in (3, 4):
            raise ValueError("--byz takes NODE:ROUND:KIND[:ARG] "
                             f"colon-separated fields, got {s!r}")
        liars.append((int(p[0]), int(p[1]), p[2],
                      int(p[3]) if len(p) == 4 else 0))
    from gossip_tpu.config import ByzConfig
    return ByzConfig(liars=tuple(liars),
                     quorum=getattr(a, "byz_quorum", 2))


def _args_to_configs(a):
    t = a.swim_suspect_rounds
    if not t and a.mode == "swim":    # import only when needed: pulls in jax
        from gossip_tpu.models.swim import suggested_suspect_rounds
        t = suggested_suspect_rounds(a.n, a.fanout)
    t = t or 4
    proto = ProtocolConfig(mode=a.mode, fanout=a.fanout, rumors=a.rumors,
                           period=a.period, swim_subjects=a.swim_subjects,
                           swim_proxies=a.swim_proxies,
                           swim_suspect_rounds=t,
                           swim_rotate=a.swim_rotate,
                           swim_epoch_rounds=a.swim_epoch_rounds,
                           swim_diss=a.swim_diss,
                           swim_rng=a.swim_rng,
                           rumor_k=a.rumor_k,
                           rumor_variant=a.rumor_variant)
    tc = TopologyConfig(family=a.family, n=a.n, k=a.k, p=a.p,
                        degree_cap=a.degree_cap, seed=a.seed)
    run = RunConfig(target_coverage=a.target, max_rounds=a.max_rounds,
                    seed=a.seed, origin=a.origin,
                    engine=getattr(a, "engine", "auto"))
    fault = None
    churn = _parse_churn(a)
    if a.drop > 0 or a.death > 0 or a.dead_nodes or churn is not None:
        fault = FaultConfig(node_death_rate=a.death, drop_prob=a.drop,
                            seed=a.seed,
                            dead_nodes=tuple(a.dead_nodes or ()),
                            fail_round=a.fail_round, churn=churn)
    mesh = (MeshConfig(n_devices=a.devices, exchange=a.exchange)
            if a.devices > 1 else None)
    return proto, tc, run, fault, mesh


def cmd_run(a) -> int:
    from gossip_tpu.backend import run_simulation
    from gossip_tpu.utils.trace import trace   # trace(None) is a no-op
    if a.plan:
        # a plan file IS the run configuration (n/mode/rumors/faults/
        # segments all come from it); any run-shape flag changed from
        # its parser default would be silently discarded, so it is
        # refused instead (no-silent-drop policy).  The default map is
        # read from the live parser at registration time
        # (_PLAN_GUARDED_RUN_FLAGS in main), so this check cannot
        # drift from the real defaults.
        changed = [f"--{k.replace('_', '-')}"
                   for k, d in a.plan_guard_defaults.items()
                   if getattr(a, k) != d]
        if a.ensemble > 1 or a.parity_check or a.curve or a.save_curve:
            print("error: --plan executes the streamed scale driver; "
                  "drop --ensemble/--parity-check/--curve/--save-curve",
                  file=sys.stderr)
            return 2
        if changed:
            print("error: --plan takes the run shape from the plan "
                  f"file; drop {' '.join(sorted(changed))} (regenerate "
                  "the plan with `gossip_tpu plan` to change them)",
                  file=sys.stderr)
            return 2
        return _run_plan_file(a.plan, checkpoint=a.checkpoint,
                              resume=a.resume)
    proto, tc, run, fault, mesh = _args_to_configs(a)
    if a.parity_check and a.ensemble > 1:
        # the ensemble branch would otherwise win and silently discard
        # the parity request (no-silent-drop policy)
        print("error: --parity-check and --ensemble are separate run "
              "shapes; pick one", file=sys.stderr)
        return 2
    if a.ensemble > 1:
        if a.backend != "jax-tpu":
            print("error: --ensemble needs the jax-tpu backend",
                  file=sys.stderr)
            return 2
        from gossip_tpu.backend import run_ensemble
        ens_mesh = None
        if a.devices > 1:
            if a.exchange != "dense":
                # the seed-axis mesh has no cross-shard exchange to
                # route; a requested pattern must not be silently
                # dropped (no-silent-drop policy)
                print("error: --ensemble shards the SEED axis; "
                      "--exchange does not apply (drop it)",
                      file=sys.stderr)
                return 2
            # the SEED axis shards over the mesh (embarrassingly
            # parallel, value-invariant; seeds must divide devices)
            from gossip_tpu.parallel.sharded import make_mesh
            ens_mesh = make_mesh(a.devices, axis_name="seed")
        with trace(a.profile):
            # mode dispatch (SI / rumor / swim-scenario) lives in
            # backend.run_ensemble, shared with the sidecar's Ensemble
            # RPC so the two surfaces cannot drift
            # run_ensemble owns the seed default, the engine guard,
            # and the mode dispatch (shared with the Ensemble RPC)
            ens, out_extra = run_ensemble(proto, tc, run, fault,
                                          count=a.ensemble, mesh=ens_mesh)
        out = {"ensemble": ens.summary(), "mode": a.mode, "n": tc.n,
               "backend": a.backend, **out_extra}
        if a.profile:
            out["profile_logdir"] = a.profile
        if a.save_curve:
            # per-round ensemble band: mean / min / max over seeds
            from gossip_tpu.utils.metrics import dump_curve_jsonl
            import numpy as np
            dump_curve_jsonl(a.save_curve, ens.curves.mean(axis=0),
                             meta={**out, "band_min":
                                   np.round(ens.curves.min(axis=0), 6
                                            ).tolist(),
                                   "band_max":
                                   np.round(ens.curves.max(axis=0), 6
                                            ).tolist()})
        if a.curve:
            out["curve_mean"] = [float(c) for c in ens.curves.mean(axis=0)]
        print(json.dumps(out))
        return 0
    if a.parity_check:
        # large-N backend parity spot check (VERDICT r2 item 8): both
        # backends on one explicit topology, gap of the coverage curves
        # on the flood clock mapping (one jax round == one hop depth)
        if a.mode != "flood" or a.backend != "jax-tpu":
            print("error: --parity-check compares the jax-tpu flood "
                  "rounds against go-native hop depths; use --backend "
                  "jax-tpu --mode flood", file=sys.stderr)
            return 2
        if fault is not None:
            print("error: --parity-check needs a fault-free run "
                  "(go-native takes no FaultConfig)", file=sys.stderr)
            return 2
        if a.curve or a.save_curve or a.checkpoint:
            # never silently discard a requested output shape (the
            # repo's incompatible-flag policy)
            print("error: --parity-check is a self-contained artifact "
                  "run; drop --curve/--save-curve/--checkpoint",
                  file=sys.stderr)
            return 2
        import dataclasses as _dc
        from gossip_tpu.backend import _GONATIVE_MAX_NODES
        from gossip_tpu.utils.metrics import curve_gap
        with trace(a.profile):
            rep = run_simulation(a.backend, proto, tc, run, None, mesh,
                                 want_curve=True)
            # the C++ event core above the Python engine's cap
            ref_run = _dc.replace(
                run,
                engine="native" if tc.n > _GONATIVE_MAX_NODES else "auto")
            ref = run_simulation("go-native", proto, tc, ref_run,
                                 want_curve=True)
        if rep.rounds < 0:
            # the event sim always runs to quiescence; a jax run cut off
            # by --max-rounds would report a bogus fixed_point_gap that
            # reads as backend divergence
            print("error: the jax flood run did not reach --target "
                  f"within --max-rounds={run.max_rounds}; raise "
                  "--max-rounds past the graph diameter so the parity "
                  "fixed point is the converged state", file=sys.stderr)
            return 2
        # The parity contract (tests/test_gonative.py): the flood kernel
        # is the exact BFS ball per round; event-order races can only
        # SLOW the event sim's hop curve (never push it above the
        # kernel's), and both backends converge to the identical fixed
        # point.  curve_gap therefore reads ~0 only on race-free
        # graphs (ring k=2); on racy graphs the contract is the bound +
        # the fixed point, reported separately.
        m = min(len(rep.curve), len(ref.curve))
        bound = max((ref.curve[t] - rep.curve[t] for t in range(m)),
                    default=0.0)
        out = {"curve_gap": curve_gap(rep.curve, ref.curve),
               "hop_bound_violation": max(0.0, bound),
               "fixed_point_gap": abs(rep.coverage - ref.coverage),
               "n": tc.n, "family": a.family,
               "compile_cache": _cache_stamp(a),
               "jax": {**rep.to_dict(), "curve": None},
               "gonative": {**ref.to_dict(), "curve": None}}
        if a.profile:
            out["profile_logdir"] = a.profile
        print(json.dumps(out))
        return 0
    if a.resume and not a.checkpoint:
        print("error: --resume needs --checkpoint PATH (the file to "
              "continue from)", file=sys.stderr)
        return 2
    if a.checkpoint:
        with trace(a.profile):
            return _cmd_run_checkpointed(a, proto, tc, run, fault, mesh)
    want_curve = a.curve or bool(a.save_curve)
    with trace(a.profile):
        report = run_simulation(a.backend, proto, tc, run, fault, mesh,
                                want_curve=want_curve)
    out = report.to_dict()
    out["compile_cache"] = _cache_stamp(a)
    if a.profile:
        out["profile_logdir"] = a.profile
    if a.save_curve:
        from gossip_tpu.utils.metrics import dump_curve_jsonl
        meta = dict(out)
        curve = meta.pop("curve")
        dump_curve_jsonl(a.save_curve, curve, meta=meta)
        if not a.curve:          # curve went to the file, not the report
            out["curve"] = None
    print(json.dumps(out))
    return 0


def _cmd_run_checkpointed(a, proto, tc, run, fault, mesh) -> int:
    """--checkpoint driver: fixed-round run in compiled segments with an
    atomic npz every --checkpoint-every rounds; --resume continues a
    saved run to max_rounds TOTAL rounds, bitwise identical to an
    uninterrupted run (tests/test_utils.py, test_checkpoint_sharded.py).

    Five engines (round-4; the reference loses all state on process
    death, main.go:22-26):

    * single device, engine auto/xla  — the SI XLA kernels;
    * --devices > 1, dense exchange   — the node-sharded packed engine
      (pull/antientropy);
    * --engine fused                  — the rumor-plane fused engine
      (any --devices; the checkpoint carries the plane stack);
    * --mode swim                     — failure detection, single-device
      or node-sharded (runtime/simulator.checkpointed_swim; the
      rotating window is in-trace, so resume is bitwise);
    * --mode rumor                    — SIR rumor mongering, single-
      device or node-sharded (models/rumor.checkpointed_rumor; fixed
      segments, no extinction early-exit — the extinct state is
      absorbing).

    --curve/--save-curve compose with all of them: segments run as a
    compiled scan recording per-round coverage (SWIM: detection
    fraction; rumor: coverage + hot-fraction channels, extinction being
    recoverable only from the hot channel), and the curve-so-far is
    persisted in the checkpoint so --resume continues it seamlessly.

    Nemesis fault programs compose too (crash-safety round): each
    engine runs every schedule feature its straight twin honors, the
    checkpoint stamps the fault-program fingerprint + absolute round
    cursor + exact dropped total, and --resume continues the SAME
    program bitwise or refuses loudly (docs/ROBUSTNESS.md "Crash
    safety"; tools/crashloop.py is the live SIGKILL harness)."""
    import os

    n_dev = 1 if mesh is None else mesh.n_devices
    exchange = "dense" if mesh is None else mesh.exchange
    want_curve = a.curve or bool(a.save_curve)
    if a.backend != "jax-tpu":
        print("error: --checkpoint drives the jax-tpu engines only",
              file=sys.stderr)
        return 2
    fused = run.engine == "fused"
    if fused:
        from gossip_tpu.backend import _fused_ineligible_reason
        # plane_stack: the checkpointed fused driver is ALWAYS the
        # plane-sharded engine (make_plane_mesh, any n_dev), which runs
        # churn events as alive-word operands
        reason = _fused_ineligible_reason(proto, tc, fault, n_dev,
                                          plane_stack=True)
        if reason is not None:
            print(f"error: {reason}", file=sys.stderr)
            return 2
    elif n_dev > 1 and a.mode not in ("swim", "rumor"):
        # swim/rumor shard through their own engines; this check guards
        # the packed SI exchange only
        from gossip_tpu.parallel.sharded_packed import (
            sharded_checkpoint_ineligible_reason)
        reason = sharded_checkpoint_ineligible_reason(proto, exchange)
        if reason is not None:
            print(f"error: {reason}", file=sys.stderr)
            return 2
    import dataclasses

    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.topology import generators as G
    from gossip_tpu.utils.checkpoint import load_meta, load_state

    # Config fingerprint stored with every checkpoint: resume refuses
    # mismatched flags instead of silently continuing a DIFFERENT run
    # (the bitwise-continuation promise is per-config; devices is part
    # of it — mesh padding and plane layout depend on the mesh shape).
    fingerprint = {"proto": dataclasses.asdict(proto),
                   "tc": dataclasses.asdict(tc),
                   "fault": None if fault is None
                   else dataclasses.asdict(fault),
                   "seed": run.seed, "origin": run.origin,
                   "devices": n_dev, "exchange": exchange,
                   "engine": "fused" if fused else "xla"}
    # Fault-program fingerprint: a digest of the BUILT nemesis schedule
    # content + the eventual-alive denominator (ops/nemesis
    # .schedule_fingerprint) — semantic, where the config fingerprint
    # above is syntactic.  Resume refuses a missing fingerprint loudly
    # (a checkpoint that cannot prove which schedule produced it — e.g.
    # a pre-crash-safety build's — must not be continued under one);
    # the digest-mismatch branch below is today shadowed by the config
    # fingerprint (churn is inside it) and stands as the semantic
    # backstop should a refactor ever move the schedule out of the
    # syntactic fingerprint.
    fault_fp = NE.schedule_fingerprint(fault, tc.n, run.origin)
    ch = NE.get(fault)
    resumed = False
    resume_state = None
    curve_prefix = ()
    lost_prefix = 0.0
    if a.resume:
        if not os.path.exists(a.checkpoint):
            print(f"error: --resume: no checkpoint at {a.checkpoint}",
                  file=sys.stderr)
            return 2
        try:
            meta = load_meta(a.checkpoint)
        except ValueError as e:
            # corrupt/truncated/foreign file: the module crash contract
            # (utils/checkpoint) turns it into one ValueError naming the
            # file — surface it as a clean CLI error, never a traceback
            print(f"error: --resume: {e}", file=sys.stderr)
            return 2
        saved = meta.get("extra", {}).get("config")
        if saved is not None:
            # pre-round-4 checkpoints lack the devices/exchange/engine
            # keys; they were all written by the single-device XLA
            # driver, so defaulting preserves their resumability
            saved = {"devices": 1, "exchange": "dense", "engine": "xla",
                     **saved}
        if saved is not None and saved != json.loads(
                json.dumps(fingerprint)):
            diff = [k for k in fingerprint
                    if json.loads(json.dumps(fingerprint[k]))
                    != saved.get(k)]
            print("error: --resume config mismatch vs the checkpoint "
                  f"(differs in: {', '.join(diff)}); rerun with the "
                  "flags the checkpoint was written with",
                  file=sys.stderr)
            return 2
        saved_fp = meta.get("extra", {}).get("fault_program")
        if fault_fp is not None and saved_fp is None:
            print("error: --resume under a fault program, but the "
                  "checkpoint carries no fault-program fingerprint (it "
                  "was written without a churn schedule, or by a "
                  "pre-crash-safety build); a resumed run cannot prove "
                  "it continues the SAME schedule — restart without "
                  "--resume or drop the churn flags", file=sys.stderr)
            return 2
        if saved_fp is not None and fault_fp is None:
            print("error: the checkpoint was written under a fault "
                  "program but this resume scripts none; rerun with "
                  "the churn flags the checkpoint was written with",
                  file=sys.stderr)
            return 2
        if fault_fp is not None and saved_fp != fault_fp:
            print("error: --resume fault-program mismatch vs the "
                  "checkpoint (schedule digest "
                  f"{saved_fp[:12]}... != {fault_fp[:12]}...); a "
                  "different churn/partition/ramp program would fork "
                  "the trajectory — rerun with the schedule the "
                  "checkpoint was written with", file=sys.stderr)
            return 2
        lost_prefix = float(meta.get("extra", {}).get("dropped", 0.0))
        saved_curve = meta.get("extra", {}).get("curve")
        # curve history must match the request, both ways — a silently
        # truncated or silently dropped curve is worse than an error
        # (the repo's incompatible-flag policy)
        if want_curve and saved_curve is None:
            print("error: --resume with --curve/--save-curve, but the "
                  "checkpoint has no curve history (it was written "
                  "without curve capture); drop the curve flags or "
                  "restart without --resume", file=sys.stderr)
            return 2
        if saved_curve is not None and not want_curve:
            print("error: the checkpoint carries a curve history; add "
                  "--curve or --save-curve to continue it (refusing to "
                  "silently drop it)", file=sys.stderr)
            return 2
        # rumor checkpoints carry named channels (dict of lists); the
        # scalar engines carry one flat list
        curve_prefix = (saved_curve if isinstance(saved_curve, dict)
                        else tuple(saved_curve or ()))
        try:
            resume_state = load_state(a.checkpoint)
        except ValueError as e:
            # meta parsed but the arrays are torn/missing (module crash
            # contract): same clean refusal as the load_meta path above
            print(f"error: --resume: {e}", file=sys.stderr)
            return 2
        resumed = True

    extra = {"config": fingerprint}
    if fault_fp is not None:
        extra["fault_program"] = fault_fp
    out_extra = {}
    if a.mode == "swim":
        from gossip_tpu.backend import swim_scenario
        from gossip_tpu.runtime.simulator import checkpointed_swim
        dead, fail_round, default_scenario = swim_scenario(proto, tc.n,
                                                           fault)
        swim_topo = None if tc.family == "complete" else G.build(tc)
        mesh_obj = None
        if n_dev > 1:
            from gossip_tpu.parallel.sharded import make_mesh
            mesh_obj = make_mesh(n_dev)
        final, cov, curve = checkpointed_swim(
            proto, tc.n, run, a.checkpoint, every=a.checkpoint_every,
            dead_nodes=dead, fail_round=fail_round, fault=fault,
            topo=swim_topo, mesh=mesh_obj, resume_state=resume_state,
            want_curve=want_curve, curve_prefix=curve_prefix,
            extra_meta=extra)
        out_extra["metric"] = "detection_fraction"
        out_extra["default_scenario"] = default_scenario
        if proto.swim_rotate and curve:
            # rotation: the window can leave the dead node's epoch, so
            # the headline is the best in-window detection (exact only
            # with curve capture; without it only the final is known)
            out_extra["peak_detection"] = float(max(curve))
        engine_label = "swim-sharded" if n_dev > 1 else "swim-xla"
    elif a.mode == "rumor":
        import numpy as _np

        from gossip_tpu.models.rumor import checkpointed_rumor
        mesh_obj = None
        if n_dev > 1:
            from gossip_tpu.parallel.sharded import make_mesh
            mesh_obj = make_mesh(n_dev)
        final, cov, residue, curve = checkpointed_rumor(
            proto, G.build(tc), run, a.checkpoint,
            every=a.checkpoint_every, fault=fault, mesh=mesh_obj,
            resume_state=resume_state, want_curve=want_curve,
            curve_prefix=curve_prefix, extra_meta=extra,
            lost_prefix=lost_prefix)
        out_extra["residue"] = residue
        out_extra["extinct"] = not bool(_np.any(_np.asarray(final.hot)))
        if curve:
            dead_at = _np.nonzero(_np.asarray(curve["hot"]) == 0.0)[0]
            out_extra["extinction_round"] = (int(dead_at[0]) + 1
                                             if len(dead_at) else -1)
        engine_label = "rumor-sharded" if n_dev > 1 else "rumor-xla"
    elif fused:
        from gossip_tpu.parallel.sharded_fused import (
            checkpointed_fused_planes, make_plane_mesh)
        final, cov, curve = checkpointed_fused_planes(
            tc.n, proto.rumors, run, make_plane_mesh(n_dev), a.checkpoint,
            every=a.checkpoint_every, fanout=proto.fanout,
            resume_state=resume_state, want_curve=want_curve,
            curve_prefix=curve_prefix, extra_meta=extra, fault=fault)
        engine_label = "fused-pallas-planes"
    elif n_dev > 1:
        from gossip_tpu.parallel.sharded import make_mesh
        from gossip_tpu.parallel.sharded_packed import (
            checkpointed_packed_sharded)
        final, cov, curve = checkpointed_packed_sharded(
            proto, G.build(tc), run, make_mesh(n_dev), a.checkpoint,
            every=a.checkpoint_every, fault=fault,
            resume_state=resume_state, want_curve=want_curve,
            curve_prefix=curve_prefix, extra_meta=extra,
            lost_prefix=lost_prefix)
        engine_label = "sharded-packed"
    else:
        from gossip_tpu.models.si import coverage, make_si_round
        from gossip_tpu.models.state import init_state
        from gossip_tpu.utils.checkpoint import run_with_checkpoints
        topo = G.build(tc)
        # churn runs in the segments exactly as in the straight driver:
        # the step indexes its ABSOLUTE state.round, which the
        # checkpoint persists, so resume == straight run bitwise under
        # the fault program (utils/checkpoint crash contract); the
        # metric denominator is the eventual alive set (metric_alive
        # falls back to the static mask without churn)
        step, tables = make_si_round(proto, topo, fault, run.origin,
                                     tabled=True)
        state = resume_state if resumed else init_state(run, proto, tc.n)
        curve_fn = None
        if want_curve:
            def curve_fn(s):
                return coverage(s.seen, NE.metric_alive(fault, tc.n,
                                                        run.origin))
        remaining = max(0, run.max_rounds - int(state.round))
        out_state = run_with_checkpoints(step, state, remaining,
                                         a.checkpoint,
                                         every=a.checkpoint_every,
                                         step_args=tables,
                                         curve_fn=curve_fn,
                                         curve_prefix=curve_prefix,
                                         extra_meta=extra,
                                         track_lost=ch is not None,
                                         lost_prefix=lost_prefix)
        final, curve = (out_state if want_curve else (out_state, None))
        cov = float(coverage(final.seen,
                             NE.metric_alive(fault, tc.n, run.origin)))
        engine_label = "si-xla"
    out = {"backend": a.backend, "mode": a.mode, "n": tc.n,
           "rounds": int(final.round), "coverage": cov,
           "msgs": float(final.msgs), "checkpoint": a.checkpoint,
           "checkpoint_every": a.checkpoint_every, "resumed": resumed,
           "engine": engine_label, "devices": n_dev,
           "compile_cache": _cache_stamp(a)}
    if ch is not None:
        # the nemesis observables of the run as persisted: the exact
        # destroyed-message total accumulated across every segment AND
        # every kill/resume (engines that track it — run_with_checkpoints
        # track_lost), and the fault-program fingerprint the checkpoint
        # refuses mismatched resumes against
        final_meta = load_meta(a.checkpoint).get("extra", {})
        if "dropped" in final_meta:
            out["dropped"] = final_meta["dropped"]
        out["fault_program"] = fault_fp
    out.update(out_extra)
    if a.profile:
        out["profile_logdir"] = a.profile
    # rumor curves carry named channels; the headline curve is coverage
    # (the hot channel rides alongside under its own key — in the
    # save-curve artifact's meta line too, because extinction is only
    # recoverable from it and a silently dropped channel violates the
    # curve-history policy above)
    curve_list = curve["coverage"] if isinstance(curve, dict) else curve
    if a.save_curve:
        from gossip_tpu.utils.metrics import dump_curve_jsonl
        save_meta = dict(out)
        if isinstance(curve, dict):
            save_meta["hot_curve"] = list(curve["hot"])
        dump_curve_jsonl(a.save_curve, list(curve_list), meta=save_meta)
    if a.curve:
        out["curve"] = list(curve_list)
        if isinstance(curve, dict):
            out["hot_curve"] = list(curve["hot"])
    print(json.dumps(out))
    return 0


# The five BASELINE.json benchmark configs, scalable for CPU smoke runs.
def baseline_configs(scale: float, devices: int):
    def sn(n):                       # scaled node count
        return max(64, int(n * scale))
    n2 = sn(10_000)
    n3 = sn(100_000)
    n4 = sn(1_000_000)
    n5 = sn(10_000_000)
    return [
        dict(name="push-complete-64-goref", backend="jax-tpu",
             proto=ProtocolConfig(mode="push", fanout=1),
             tc=TopologyConfig(family="complete", n=64),
             run=RunConfig(max_rounds=64), compare_gonative=True),
        dict(name="pushpull-er-10k", backend="jax-tpu",
             proto=ProtocolConfig(mode="pushpull", fanout=1),
             tc=TopologyConfig(family="erdos_renyi", n=n2,
                               p=min(1.0, 0.01 * 10_000 / n2)),
             run=RunConfig(max_rounds=64)),
        dict(name="antientropy-ws-100k", backend="jax-tpu",
             proto=ProtocolConfig(mode="antientropy", fanout=1, period=2),
             tc=TopologyConfig(family="watts_strogatz", n=n3, k=6, p=0.1),
             run=RunConfig(max_rounds=256)),
        dict(name="swim-powerlaw-1m", backend="jax-tpu",
             proto=ProtocolConfig(mode="swim", fanout=2, swim_proxies=3,
                                  swim_subjects=8, swim_suspect_rounds=24),
             tc=TopologyConfig(family="power_law", n=n4, k=3,
                               degree_cap=256),
             run=RunConfig(max_rounds=80)),
        # BASELINE.json configs[4]: "10M-node multi-rumor broadcast,
        # node-dim sharded".  Mode pull: on a multi-chip mesh the node
        # dimension shards across devices; on one chip engine='auto'
        # routes to the fused Pallas multi-rumor kernel.  revision=2
        # records the round-2 mode change (pushpull -> pull) so old and
        # new sweep artifacts are machine-distinguishable (ADVICE r2).
        dict(name="multirumor-10m-sharded", backend="jax-tpu",
             proto=ProtocolConfig(mode="pull", fanout=1, rumors=8),
             tc=TopologyConfig(family="complete", n=n5),
             run=RunConfig(max_rounds=64),
             mesh=MeshConfig(n_devices=devices), revision=2),
    ]


def cmd_sweep(a) -> int:
    from gossip_tpu.backend import run_simulation
    import jax
    devices = a.devices or len(jax.devices())
    configs = baseline_configs(a.scale, devices)
    if a.only:
        configs = [c for c in configs if c["name"] in a.only]
    if a.swim_diss:
        import dataclasses as _dc
        configs = [dict(cfg, proto=_dc.replace(cfg["proto"],
                                               swim_diss=a.swim_diss))
                   if cfg["proto"].mode == "swim" else cfg
                   for cfg in configs]
    import time as _time
    for cfg in configs:
        t0_row = _time.perf_counter()
        report = run_simulation(cfg["backend"], cfg["proto"], cfg["tc"],
                                cfg["run"], None, cfg.get("mesh"),
                                want_curve=a.curve)
        out = report.to_dict()
        out["config"] = cfg["name"]
        # bump a config's revision whenever its workload definition
        # changes so sweep artifacts from different definitions can never
        # be compared as if they measured the same thing
        out["config_revision"] = cfg.get("revision", 1)
        # same principle for timings: a warm-cache compile_s must be
        # distinguishable from a cold one in the artifact itself
        out["compile_cache"] = _cache_stamp(a)
        if cfg.get("compare_gonative"):
            ref = run_simulation("go-native",
                                 ProtocolConfig(mode="flood"), cfg["tc"],
                                 cfg["run"], want_curve=a.curve)
            out["gonative_ref"] = ref.to_dict()
        # row-level reconciliation (VERDICT r4 task 5): the ROW wall is
        # everything this config cost — engine wall + topo build + the
        # go-native reference run + residual host overhead — so
        # row_wall_s ~= wall_s + meta.topo_build_s +
        # gonative_ref.wall_s + row_overhead_s by construction, and the
        # r04 table's ~10 s of unattributed first-row time can never
        # recur unexplained
        row_wall = _time.perf_counter() - t0_row
        parts = (out["wall_s"]
                 + (out.get("meta") or {}).get("topo_build_s", 0.0)
                 + (out.get("gonative_ref") or {}).get("wall_s", 0.0))
        out["row_wall_s"] = round(row_wall, 4)
        out["row_overhead_s"] = round(max(0.0, row_wall - parts), 4)
        print(json.dumps(out), flush=True)
    return 0


def cmd_grid(a) -> int:
    """Batched config sweep: the cartesian product of --modes/--fanouts/
    --drops/--periods/--seeds — and, with --families, topology families —
    runs as ONE compiled XLA program (the north-star "sweep fanout, mode,
    and graph topology across a pod" sentence —
    parallel/sweep.config_sweep_curves).  --devices shards the config axis
    over a mesh; --pod-mesh S N runs the full 2-D (configs x node-shards)
    shard_map program, families included."""
    from gossip_tpu.parallel.sweep import (SweepPoint, config_sweep_curves,
                                           config_sweep_curves_2d)
    from gossip_tpu.topology import generators as G
    if any(r < 1 for r in a.rumors):
        # 0 is SweepPoint's internal batch-default sentinel; letting it
        # through would run 1 rumor while the summary prints 0
        print("error: --rumors values must be >= 1", file=sys.stderr)
        return 2
    families = a.families or [a.family]
    ns = a.ns or [a.n]
    run = RunConfig(target_coverage=a.target, max_rounds=a.max_rounds,
                    seed=a.seed)
    fault = (FaultConfig(node_death_rate=a.death, seed=a.seed)
             if a.death > 0 else None)
    # the topology stack enumerates (family, n) pairs; topo_idx t maps
    # back as family t // len(ns), size t % len(ns)
    fam_n = [(f, n) for f in families for n in ns]
    points = [
        SweepPoint(mode=m, fanout=f, drop_prob=d,
                   period=(p if m == "antientropy" else 1), seed=s,
                   topo_idx=t, rumors=r)
        for t in range(len(fam_n))
        for m in a.modes for f in a.fanouts for d in a.drops
        for p in (a.periods if 'antientropy' in a.modes else [1])
        for s in a.seeds for r in a.rumors]
    # periods multiply only anti-entropy points; dedupe the rest
    points = list(dict.fromkeys(points))
    topos = [G.build(TopologyConfig(family=f, n=n, k=a.k, p=a.p,
                                    degree_cap=a.degree_cap, seed=a.seed))
             for f, n in fam_n]
    topo_arg = topos if len(topos) > 1 else topos[0]
    if a.pod_mesh:
        # DCN-aware: configs (communication-free) ride the outer/slice
        # axis, node shards (O(N) collectives) stay intra-slice on ICI.
        from gossip_tpu.parallel.multislice import make_hybrid_mesh
        s, nd = a.pod_mesh
        mesh2d = make_hybrid_mesh(s, nd, axis_names=("sweep", "nodes"))
        res = config_sweep_curves_2d(points, topo_arg, run, mesh2d,
                                     fault=fault)
    elif a.devices > 1:
        from gossip_tpu.parallel.sharded import make_mesh
        res = config_sweep_curves(points, topo_arg, run, fault=fault,
                                  mesh=make_mesh(a.devices,
                                                 axis_name="sweep"))
    else:
        # single-device grids partition by mode bucket so pure buckets
        # never pay the masked other half (falls through to the plain
        # batch when the grid is single-bucket)
        from gossip_tpu.parallel.sweep import config_sweep_curves_partitioned
        res = config_sweep_curves_partitioned(points, topo_arg, run,
                                              fault=fault)
    for i, summary in enumerate(res.summaries()):
        fam, n = fam_n[points[i].topo_idx]
        summary["n"] = n
        summary["family"] = fam
        if a.curve:
            summary["curve"] = [float(c) for c in res.curves[i]]
        print(json.dumps(summary), flush=True)
    return 0


def _parse_scenario(spec: str):
    """One ``--scenario`` spec -> ChurnConfig: ';'-separated
    ``event=NODE:DIE[:REC]`` / ``partition=START:END:CUT`` /
    ``ramp=START:END:P0:P1`` items (the colon syntax of the run
    command's --churn-event/--partition/--drop-ramp, reused via
    _parse_churn so the two surfaces cannot drift)."""
    events, partitions, ramp = [], [], None
    for item in filter(None, (s.strip() for s in spec.split(";"))):
        key, _, val = item.partition("=")
        if key == "event":
            events.append(val)
        elif key == "partition":
            partitions.append(val)
        elif key == "ramp":
            if ramp is not None:
                raise ValueError(
                    f"scenario {spec!r} has more than one ramp")
            ramp = val
        else:
            raise ValueError(
                f"unknown scenario field {key!r} in {spec!r} "
                "(use event= / partition= / ramp=)")
    ch = _parse_churn(argparse.Namespace(
        churn_event=events or None, partition=partitions or None,
        drop_ramp=ramp))
    if ch is None:
        raise ValueError(f"scenario {spec!r} scripts no faults")
    return ch


def cmd_churn_sweep(a) -> int:
    """K nemesis scenarios — distinct churn/partition/drop-ramp fault
    programs over ONE protocol config — for the cost of ONE compile.
    --engine xla (default): the schedule stack rides ONE compiled
    vmapped loop as a runtime operand (parallel/sweep
    .churn_sweep_curves); per-scenario trajectories are bitwise the
    solo ``run`` command's, and --devices shards the scenario axis.
    --engine fused: the plane-sharded fused Pallas engine runs the K
    scenarios serially through ONE memoized compiled loop — schedule
    content (alive words, partition cut masks, the 20-bit drop
    threshold) is all runtime operands since the fused-operand PR, so
    scenarios 1..K-1 re-enter scenario 0's executable
    (parallel/sweep.fused_churn_sweep_curves); --devices shards the
    rumor-plane axis and per-scenario trajectories are bitwise the
    solo fused curve driver's."""
    from gossip_tpu.topology import generators as G
    scens = [_parse_scenario(s) for s in a.scenario]
    proto = ProtocolConfig(mode=a.mode, fanout=a.fanout, rumors=a.rumors,
                           period=a.period)
    tc = TopologyConfig(family=a.family, n=a.n, k=a.k, p=a.p,
                        seed=a.seed)
    run = RunConfig(target_coverage=a.target, max_rounds=a.max_rounds,
                    seed=a.seed)
    faults = [FaultConfig(node_death_rate=a.death, drop_prob=a.drop,
                          seed=a.seed, churn=ch) for ch in scens]
    if a.engine == "fused":
        from gossip_tpu.backend import _fused_ineligible_reason
        from gossip_tpu.parallel.sharded_fused import make_plane_mesh
        from gossip_tpu.parallel.sweep import fused_churn_sweep_curves
        reason = _fused_ineligible_reason(proto, tc, faults[0],
                                          a.devices, plane_stack=True)
        if reason is not None:
            print(f"error: {reason}", file=sys.stderr)
            return 2
        res = fused_churn_sweep_curves(
            tc.n, proto.rumors, run, faults,
            make_plane_mesh(a.devices), fanout=proto.fanout)
    else:
        from gossip_tpu.parallel.sweep import churn_sweep_curves
        mesh = None
        if a.devices > 1:
            if len(faults) % a.devices:
                print(f"error: {len(faults)} scenarios do not divide "
                      f"over {a.devices} devices", file=sys.stderr)
                return 2
            from gossip_tpu.parallel.sharded import make_mesh
            mesh = make_mesh(a.devices, axis_name="scenario")
        res = churn_sweep_curves(proto, G.build(tc), run, faults,
                                 mesh=mesh)
    out = {"churn_sweep": res.summaries(), "n": tc.n, "mode": a.mode,
           "engine": a.engine,
           "scenarios": len(faults), "target": run.target_coverage}
    if a.curve:
        out["curves"] = [[round(float(c), 6) for c in row]
                         for row in res.curves]
    print(json.dumps(out))
    return 0


def _parse_crdt_injections(a):
    """--add NODE:ROUND:AMOUNT / --set-add ELEM:ROUND / --set-remove
    ELEM:ROUND -> CrdtConfig kwargs (field validation lives in
    CrdtConfig itself — this only parses the colon syntax, the
    _parse_churn discipline)."""
    def parts(s, what, arity):
        p = s.split(":")
        if len(p) != arity:
            raise ValueError(f"--{what} takes {arity} colon-separated "
                             f"fields, got {s!r}")
        return tuple(int(x) for x in p)

    return dict(
        adds=tuple(parts(s, "add", 3) for s in (a.add or ())),
        set_adds=tuple(parts(s, "set-add", 2)
                       for s in (a.set_add or ())),
        set_removes=tuple(parts(s, "set-remove", 2)
                          for s in (a.set_remove or ())))


def cmd_crdt(a) -> int:
    """CRDT gossip run: a commutative-merge payload (Gossip Glomers
    counter/set workloads) on the pull exchange fabric, value
    convergence judged integer-exact against the ground-truth merge on
    the eventual-alive set (docs/WORKLOADS.md)."""
    from gossip_tpu.config import CrdtConfig
    from gossip_tpu.topology import generators as G
    cfg = CrdtConfig(kind=a.type, elements=a.elements,
                     **_parse_crdt_injections(a))
    proto = ProtocolConfig(mode="pull", fanout=a.fanout)
    tc = TopologyConfig(family=a.family, n=a.n, k=a.k, p=a.p,
                        seed=a.seed)
    run = RunConfig(target_coverage=a.target, max_rounds=a.max_rounds,
                    seed=a.seed, origin=a.origin)
    churn = _parse_churn(a)
    byz = _parse_byz(a)
    fault = None
    if (a.drop > 0 or a.death > 0 or churn is not None
            or byz is not None):
        fault = FaultConfig(node_death_rate=a.death, drop_prob=a.drop,
                            seed=a.seed, churn=churn, byz=byz)
    topo = G.build(tc)
    want_curve = a.curve or bool(a.save_curve)
    import time as _time
    t0 = _time.perf_counter()
    if a.devices > 1:
        from gossip_tpu.parallel.sharded import make_mesh
        from gossip_tpu.parallel.sharded_crdt import (
            simulate_curve_crdt_sharded, simulate_until_crdt_sharded)
        mesh = make_mesh(a.devices)
        if want_curve:
            conv, msgs, final, truth = simulate_curve_crdt_sharded(
                cfg, proto, topo, run, mesh, fault, defend=a.defend)
        else:
            rounds, vc, msgs_f, final, truth = (
                simulate_until_crdt_sharded(cfg, proto, topo, run,
                                            mesh, fault,
                                            defend=a.defend))
        engine = "crdt-sharded"
    else:
        from gossip_tpu.models.crdt import (simulate_curve_crdt,
                                            simulate_until_crdt)
        if want_curve:
            conv, msgs, final, truth = simulate_curve_crdt(
                cfg, proto, topo, run, fault, defend=a.defend)
        else:
            rounds, vc, msgs_f, final, truth = simulate_until_crdt(
                cfg, proto, topo, run, fault, defend=a.defend)
        engine = "crdt-xla"
    wall = _time.perf_counter() - t0
    if want_curve:
        hit = [i for i, c in enumerate(conv) if c >= a.target]
        rounds = (hit[0] + 1) if hit else -1
        vc, msgs_f = float(conv[-1]), float(msgs[-1])
    out = {"backend": "jax-tpu", "mode": "crdt", "type": a.type,
           "n": a.n, "rounds": rounds, "value_conv": vc,
           "converged": vc >= a.target, "truth_value": truth,
           "msgs": msgs_f, "wall_s": round(wall, 4),
           "devices": a.devices, "engine": engine,
           "compile_cache": _cache_stamp(a)}
    if churn is not None:
        out["fault_program"] = True
    if byz is not None:
        out["byz_program"] = True
        out["defended"] = bool(a.defend)
    if a.save_curve:
        from gossip_tpu.utils.metrics import dump_curve_jsonl
        dump_curve_jsonl(a.save_curve, [float(c) for c in conv],
                         meta=dict(out))
    if a.curve:
        out["curve"] = [float(c) for c in conv]
    print(json.dumps(out))
    return 0


def _parse_log_injections(a):
    """--send NODE:KEY:ROUND:VALUE / --commit NODE:KEY:ROUND:UPTO ->
    LogConfig kwargs (field validation lives in LogConfig itself —
    the _parse_crdt_injections discipline)."""
    def parts(s, what):
        p = s.split(":")
        if len(p) != 4:
            raise ValueError(f"--{what} takes 4 colon-separated "
                             f"fields, got {s!r}")
        return tuple(int(x) for x in p)

    return dict(
        sends=tuple(parts(s, "send") for s in (a.send or ())),
        commits=tuple(parts(s, "commit") for s in (a.commit or ())))


def cmd_log(a) -> int:
    """Replicated kafka-style log run: ordered per-key offset payloads
    on the pull exchange fabric, convergence judged integer-exact
    against the acked-appends ground truth on the eventual-alive set
    (docs/WORKLOADS.md "Replicated logs")."""
    from gossip_tpu.config import LogConfig
    from gossip_tpu.topology import generators as G
    cfg = LogConfig(keys=a.keys, capacity=a.capacity,
                    **_parse_log_injections(a))
    proto = ProtocolConfig(mode="pull", fanout=a.fanout)
    tc = TopologyConfig(family=a.family, n=a.n, k=a.k, p=a.p,
                        seed=a.seed)
    run = RunConfig(target_coverage=a.target, max_rounds=a.max_rounds,
                    seed=a.seed, origin=a.origin)
    churn = _parse_churn(a)
    fault = None
    if a.drop > 0 or a.death > 0 or churn is not None:
        fault = FaultConfig(node_death_rate=a.death, drop_prob=a.drop,
                            seed=a.seed, churn=churn)
    topo = G.build(tc)
    want_curve = a.curve or bool(a.save_curve)
    import time as _time
    t0 = _time.perf_counter()
    if a.devices > 1:
        from gossip_tpu.parallel.sharded import make_mesh
        from gossip_tpu.parallel.sharded_log import (
            simulate_curve_log_sharded, simulate_until_log_sharded)
        mesh = make_mesh(a.devices)
        if want_curve:
            conv, msgs, final, truth = simulate_curve_log_sharded(
                cfg, proto, topo, run, mesh, fault)
        else:
            rounds, lc, msgs_f, final, truth = (
                simulate_until_log_sharded(cfg, proto, topo, run,
                                           mesh, fault))
        engine = "log-sharded"
    else:
        from gossip_tpu.models.log import (simulate_curve_log,
                                           simulate_until_log)
        if want_curve:
            conv, msgs, final, truth = simulate_curve_log(
                cfg, proto, topo, run, fault)
        else:
            rounds, lc, msgs_f, final, truth = simulate_until_log(
                cfg, proto, topo, run, fault)
        engine = "log-xla"
    wall = _time.perf_counter() - t0
    if want_curve:
        hit = [i for i, c in enumerate(conv) if c >= a.target]
        rounds = (hit[0] + 1) if hit else -1
        lc, msgs_f = float(conv[-1]), float(msgs[-1])
    out = {"backend": "jax-tpu", "mode": "log", "n": a.n,
           "keys": a.keys, "capacity": a.capacity, "rounds": rounds,
           "log_conv": lc, "converged": lc >= a.target,
           "truth": truth, "msgs": msgs_f, "wall_s": round(wall, 4),
           "devices": a.devices, "engine": engine,
           "compile_cache": _cache_stamp(a)}
    if churn is not None:
        out["fault_program"] = True
    if a.save_curve:
        from gossip_tpu.utils.metrics import dump_curve_jsonl
        dump_curve_jsonl(a.save_curve, [float(c) for c in conv],
                         meta=dict(out))
    if a.curve:
        out["curve"] = [float(c) for c in conv]
    print(json.dumps(out))
    return 0


def _parse_txn_writes(a):
    """--write NODE:KEY:ROUND:VALUE -> TxnConfig kwargs (field
    validation lives in TxnConfig itself — the _parse_log_injections
    discipline)."""
    def parts(s):
        p = s.split(":")
        if len(p) != 4:
            raise ValueError("--write takes 4 colon-separated fields, "
                             f"got {s!r}")
        return tuple(int(x) for x in p)

    return dict(writes=tuple(parts(s) for s in (a.write or ())))


def cmd_txn(a) -> int:
    """LWW-register transaction run: totally-available multi-key
    writes on the pull exchange fabric, convergence judged
    integer-exact against the acked-writes LWW ground truth on the
    eventual-alive set (docs/WORKLOADS.md "Transactions")."""
    from gossip_tpu.config import TxnConfig
    from gossip_tpu.topology import generators as G
    cfg = TxnConfig(keys=a.keys, txns=a.txns, zipf_alpha=a.zipf_alpha,
                    hot_key=a.hot_key, load=a.load,
                    spread_rounds=a.spread, **_parse_txn_writes(a))
    proto = ProtocolConfig(mode="pull", fanout=a.fanout)
    tc = TopologyConfig(family=a.family, n=a.n, k=a.k, p=a.p,
                        seed=a.seed)
    run = RunConfig(target_coverage=a.target, max_rounds=a.max_rounds,
                    seed=a.seed, origin=a.origin)
    churn = _parse_churn(a)
    byz = _parse_byz(a)
    fault = None
    if (a.drop > 0 or a.death > 0 or churn is not None
            or byz is not None):
        fault = FaultConfig(node_death_rate=a.death, drop_prob=a.drop,
                            seed=a.seed, churn=churn, byz=byz)
    topo = G.build(tc)
    want_curve = a.curve or bool(a.save_curve)
    import time as _time
    t0 = _time.perf_counter()
    if a.devices > 1:
        from gossip_tpu.parallel.sharded import make_mesh
        from gossip_tpu.parallel.sharded_register import (
            simulate_curve_txn_sharded, simulate_until_txn_sharded)
        mesh = make_mesh(a.devices)
        if want_curve:
            conv, msgs, final, truth = simulate_curve_txn_sharded(
                cfg, proto, topo, run, mesh, fault, defend=a.defend)
        else:
            rounds, tcv, msgs_f, final, truth = (
                simulate_until_txn_sharded(cfg, proto, topo, run,
                                           mesh, fault,
                                           defend=a.defend))
        engine = "txn-sharded"
    else:
        from gossip_tpu.models.register import (simulate_curve_txn,
                                                simulate_until_txn)
        if want_curve:
            conv, msgs, final, truth = simulate_curve_txn(
                cfg, proto, topo, run, fault, defend=a.defend)
        else:
            rounds, tcv, msgs_f, final, truth = simulate_until_txn(
                cfg, proto, topo, run, fault, defend=a.defend)
        engine = "txn-xla"
    wall = _time.perf_counter() - t0
    if want_curve:
        hit = [i for i, c in enumerate(conv) if c >= a.target]
        rounds = (hit[0] + 1) if hit else -1
        tcv, msgs_f = float(conv[-1]), float(msgs[-1])
    out = {"backend": "jax-tpu", "mode": "txn", "n": a.n,
           "keys": a.keys, "rounds": rounds, "txn_conv": tcv,
           "converged": tcv >= a.target, "truth": truth,
           "msgs": msgs_f, "wall_s": round(wall, 4),
           "devices": a.devices, "engine": engine,
           "zipf_alpha": a.zipf_alpha, "hot_key": a.hot_key,
           "load": a.load, "compile_cache": _cache_stamp(a)}
    if churn is not None:
        out["fault_program"] = True
    if byz is not None:
        out["byz_program"] = True
        out["defended"] = bool(a.defend)
    if a.save_curve:
        from gossip_tpu.utils.metrics import dump_curve_jsonl
        dump_curve_jsonl(a.save_curve, [float(c) for c in conv],
                         meta=dict(out))
    if a.curve:
        out["curve"] = [float(c) for c in conv]
    print(json.dumps(out))
    return 0


def cmd_serve(a) -> int:
    from gossip_tpu.config import ServingConfig
    from gossip_tpu.rpc.sidecar import serve
    from gossip_tpu.utils import telemetry
    # the replica's flight recorder: GOSSIP_TELEMETRY in the child env
    # (tools/trace_capture.py points every replica at ONE shared file —
    # the multi-writer torn-line contract) or the NullLedger; without
    # this activation a replica's batch/request_trace events would
    # vanish and no cross-ledger waterfall could ever join
    telemetry.activate(telemetry.from_env(argv=sys.argv))
    batching = None
    if not a.no_batching:
        try:
            batching = ServingConfig(tick_ms=a.batch_tick_ms,
                                     max_batch=a.batch_max,
                                     max_queue=a.batch_queue,
                                     devices=a.devices,
                                     coordinator=a.coordinator,
                                     num_processes=a.num_processes,
                                     process_id=a.process_id)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    try:
        server, port = serve(a.port, a.workers, batching=batching)
    except ValueError as e:
        # the mesh refusal (fewer devices than --devices) must be a
        # clean CLI error, not a traceback — the fleet's spawn gate
        # reads the child's stderr tail
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(json.dumps({"serving": True, "port": port,
                      "batching": batching is not None,
                      "devices": (batching.devices
                                  if batching is not None else 1)}),
          flush=True)
    server.wait_for_termination()
    return 0


def cmd_route(a) -> int:
    """Spawn N sidecar replicas and front them with the health-gated
    failover router (rpc/router, docs/SERVING.md "Fleet")."""
    from gossip_tpu.config import FleetConfig
    from gossip_tpu.rpc.router import Fleet, fleet_env
    try:
        cfg = FleetConfig(replicas=a.replicas,
                          probe_interval_ms=a.probe_interval_ms,
                          down_after=a.down_after, up_after=a.up_after,
                          max_inflight=a.max_inflight,
                          devices_per_replica=a.devices_per_replica)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    replica_argv = []
    if a.no_batching:
        if cfg.devices_per_replica > 1:
            print("error: --devices-per-replica needs batching "
                  "replicas (the mesh shards the admission megabatch); "
                  "drop --no-batching", file=sys.stderr)
            return 2
        replica_argv.append("--no-batching")
    if cfg.devices_per_replica > 1:
        # BOTH halves of the mesh contract: the child's ServingConfig
        # width (--devices) AND the host-device-count env (fleet_env
        # devices=) — either alone silently degrades, which the
        # post-spawn serving_devices gate then refuses
        replica_argv += ["--devices", str(cfg.devices_per_replica)]
    fleet = Fleet(cfg=cfg, port=a.port, max_workers=a.workers,
                  replica_argv=replica_argv,
                  env=fleet_env(platform=a.replica_platform or None,
                                devices=cfg.devices_per_replica))
    try:
        if not fleet.router.wait_healthy(a.replicas, timeout_s=60):
            # a fleet that never admitted all replicas must not print
            # a success-looking status line and serve only sheds
            print(f"error: only {fleet.router.healthy_count()}/"
                  f"{a.replicas} replicas admitted within 60s (see "
                  f"the replica logs under {fleet.workdir})",
                  file=sys.stderr)
            return 1
        print(json.dumps({
            "routing": True, "port": fleet.port,
            "replicas": [r.address for r in fleet.router.replicas],
            "healthy": fleet.router.healthy_count()}), flush=True)
        fleet.server.wait_for_termination()
    except KeyboardInterrupt:
        pass
    finally:
        fleet.close()
    return 0


def _fleet_degraded(m: dict) -> List[str]:
    """Degradation reasons from one Metrics reply (empty = healthy).
    One definition for the CLI exit code, the --json document, and the
    --out artifact — fleet-status cannot disagree with itself."""
    reasons = []
    if m.get("router"):
        if m.get("healthy", 0) < m.get("replicas", 0):
            reasons.append(f"{m.get('healthy', 0)}/"
                           f"{m.get('replicas', 0)} replicas healthy")
        for row in m.get("fleet", ()):
            if not row.get("healthy"):
                reasons.append(f"replica {row.get('replica')} "
                               f"{(row.get('state') or 'down')}")
            elif "error" in row:
                reasons.append(f"replica {row.get('replica')} metrics "
                               f"unreachable: {row['error']}")
    elif not m.get("ok"):
        reasons.append("replica reports not ok")
    return reasons


def _render_fleet_status(m: dict) -> str:
    """The human fleet table (one poll).  A router reply renders the
    fleet; a bare replica reply renders its own window."""
    if not m.get("router"):
        w = m.get("window", {})
        line = (f"replica | rps {w.get('rps', 0)} "
                f"p50 {w.get('p50_ms', 0)}ms p99 {w.get('p99_ms', 0)}ms"
                f" | inflight {m.get('inflight', 0)} compiles "
                f"{m.get('compiles_total')} (+{m.get('compiles_delta')})"
                f" devices {m.get('serving_devices')}")
        lc = m.get("last_compile")
        if lc:
            line += (f" | last compile {lc.get('label')} "
                     f"[{lc.get('cache')}]")
        return line
    w = m.get("window", {})
    c = m.get("counters", {})
    lines = [f"fleet {m.get('healthy', 0)}/{m.get('replicas', 0)} "
             f"healthy | rps {w.get('rps', 0)} p50 {w.get('p50_ms', 0)}"
             f"ms p99 {w.get('p99_ms', 0)}ms | dispatched "
             f"{c.get('dispatched', 0)} failovers "
             f"{c.get('failovers', 0)} sheds {c.get('sheds', 0)}"]
    for row in m.get("fleet", ()):
        state = "up" if row.get("healthy") \
            else (row.get("state") or "down").upper()
        line = (f"  r{row.get('replica')} {row.get('address', ''):<21}"
                f" {state:<5} epoch {row.get('epoch')} "
                f"inflight {row.get('inflight')}")
        rm = row.get("metrics")
        if rm:
            rw = rm.get("window", {})
            line += (f" | rps {rw.get('rps', 0)} "
                     f"p50 {rw.get('p50_ms', 0)}ms "
                     f"p99 {rw.get('p99_ms', 0)}ms | compiles "
                     f"{rm.get('compiles_total')} "
                     f"(+{rm.get('compiles_delta')}) devices "
                     f"{rm.get('serving_devices')}")
            lc = rm.get("last_compile")
            if lc:
                line += (f" | last compile {lc.get('label')} "
                         f"[{lc.get('cache')}]")
        elif "error" in row:
            line += f" | error: {row['error']}"
        lines.append(line)
    return "\n".join(lines)


def cmd_fleet_status(a) -> int:
    """Live fleet health over the Metrics RPC (docs/OBSERVABILITY.md
    "Live fleet metrics").  Exit codes: 0 = every replica healthy and
    reporting, 1 = degraded (a down replica, an unreachable metrics
    leaf, or healthy < replicas), 2 = the target itself unreachable —
    a rollout gate can `fleet-status && proceed` directly."""
    import time as _time

    import grpc

    from gossip_tpu.rpc.sidecar import SidecarClient
    from gossip_tpu.utils import telemetry
    client = SidecarClient(a.address, max_attempts=1)
    rc = 2
    try:
        while True:
            try:
                m = client.metrics(timeout=a.timeout_s)
            except (grpc.RpcError, ValueError) as e:
                code = e.code() if callable(getattr(e, "code", None)) \
                    else None
                print(f"error: {a.address} unreachable "
                      f"({code or type(e).__name__})", file=sys.stderr)
                rc = 2
                m = None
            if m is not None:
                reasons = _fleet_degraded(m)
                rc = 1 if reasons else 0
                if a.as_json:
                    print(json.dumps({"degraded": bool(reasons),
                                      "reasons": reasons,
                                      "metrics": m}), flush=True)
                else:
                    print(_render_fleet_status(m), flush=True)
                    for reason in reasons:
                        print(f"  DEGRADED: {reason}", flush=True)
                if a.out:
                    # *fleet_status* artifacts are provenance-required
                    # (tools/validate_artifacts.py, never grandfathered)
                    with open(a.out, "w") as f:
                        json.dump({"provenance": telemetry.provenance(),
                                   "degraded": bool(reasons),
                                   "reasons": reasons, "metrics": m},
                                  f, indent=1)
            if not a.watch:
                return rc
            _time.sleep(a.interval_s)
    except KeyboardInterrupt:
        return rc
    finally:
        client.close()


def _device_spec_from_flags(a):
    from gossip_tpu.planner.budget import DeviceSpec
    return DeviceSpec(
        chips=a.chips,
        hbm_bytes_per_chip=int(a.hbm_gb * 1024**3),
        slices=a.slices,
        host_ram_bytes=int(a.host_ram_gb * 1024**3))


def _plan_fault_from_flags(a):
    ch = _parse_scenario(a.scenario) if a.scenario else None
    if ch is None and a.death == 0.0 and a.drop == 0.0:
        return None
    return FaultConfig(node_death_rate=a.death, drop_prob=a.drop,
                       seed=a.fault_seed, churn=ch)


def cmd_plan(a) -> int:
    """Capacity planning without a device: print (or validate) a
    ScalePlan as JSON — what word-plane tiling / segment schedule /
    mesh shape fits N on the given topology, or a LOUD refusal naming
    the binding constraint (planner/budget, docs/SCALING.md).  Pure
    host arithmetic; runs on a wedged-tunnel box."""
    from gossip_tpu.planner import budget as PB
    if a.validate:
        try:
            with open(a.validate) as f:
                doc = json.load(f)
            plan = PB.plan_from_dict(doc)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(json.dumps({"plan_valid": True, "n": plan.n,
                          "tiles": plan.tiles,
                          "bucket_words": plan.bucket_words,
                          "fingerprint": PB.plan_fingerprint(
                              plan.to_dict())}))
        return 0
    try:
        fault = _plan_fault_from_flags(a)
        reserve = (PB.DEFAULT_RESERVE_FRAC if a.reserve is None
                   else a.reserve)
        plan = PB.plan_scale(
            a.n, rumors=a.rumors, device=_device_spec_from_flags(a),
            engine=a.engine, fanout=a.fanout, max_rounds=a.max_rounds,
            seed=a.seed, origin=a.origin, fault=fault,
            segment_every=a.segment_every, reserve_frac=reserve)
    except PB.InfeasiblePlanError as e:
        # the refusal IS the product here: one line, constraint named
        print(f"error: {e}", file=sys.stderr)
        return 2
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    text = plan.to_json()
    if a.out:
        with open(a.out, "w") as f:
            f.write(text + "\n")
        print(json.dumps({"plan_written": a.out, "n": plan.n,
                          "tiles": plan.tiles,
                          "bucket_words": plan.bucket_words,
                          "predicted_peak_device_bytes":
                          plan.predicted_peak_device_bytes,
                          "binding": plan.binding}))
    else:
        print(text)
    return 0


def _run_plan_file(path: str, *, checkpoint=None, resume=False,
                   check_bitwise=False, measure_memory=False,
                   overlap=True) -> int:
    """Load a plan file and execute it through the streamed driver —
    shared by ``scale-run`` and ``run --plan`` so the two surfaces
    cannot drift."""
    from gossip_tpu.planner import budget as PB
    from gossip_tpu.planner.stream import run_at_scale
    try:
        with open(path) as f:
            doc = json.load(f)
        plan = PB.plan_from_dict(doc)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if resume and not checkpoint:
        print("error: --resume needs --checkpoint PATH",
              file=sys.stderr)
        return 2
    try:
        res = run_at_scale(plan, checkpoint_path=checkpoint,
                           resume=resume, check_bitwise=check_bitwise,
                           measure_memory=measure_memory,
                           overlap=overlap)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    out = res.to_dict()
    out["plan_fingerprint"] = PB.plan_fingerprint(plan.to_dict())
    print(json.dumps(out))
    if check_bitwise and res.bitwise_equal is not True:
        return 1
    return 0


def cmd_scale_run(a) -> int:
    """Execute a ScalePlan: stream word-plane tiles through the packed
    engine per checkpoint segment (planner/stream, docs/SCALING.md)."""
    return _run_plan_file(a.plan, checkpoint=a.checkpoint,
                          resume=a.resume,
                          check_bitwise=a.check_bitwise,
                          measure_memory=a.measure_memory,
                          overlap=not a.no_overlap)


def cmd_staticcheck(a) -> int:
    """AST invariant analyzer over the repo's own source (pure stdlib
    — never initializes jax, so it runs on a wedged-tunnel box):
    recompile-hazard lint for the serving/sweep paths, lock discipline
    for rpc/, convention gates, and the suppression-baseline
    discipline (docs/STATIC_ANALYSIS.md)."""
    from gossip_tpu.analysis import runner
    argv = []
    if a.root is not None:
        argv += ["--root", a.root]
    if a.baseline is not None:
        argv += ["--baseline", a.baseline]
    if a.ledger:
        argv += ["--ledger", a.ledger]
    if a.json_summary:
        argv += ["--json"]
    return runner.main(argv)


def cmd_maelstrom(a) -> int:
    from gossip_tpu.runtime.maelstrom_node import main as node_main
    node_main(["--gossip-interval", str(a.gossip_interval),
               "--workload", a.workload])
    return 0


def _node_argv(gossip_interval: float, workload: str = "broadcast"):
    """Node command for the harnesses; None keeps their default (the
    immediate-relay broadcast node) so the reference-shaped path stays
    the default."""
    if gossip_interval <= 0 and workload == "broadcast":
        return None
    argv = [sys.executable, "-u", "-m",
            "gossip_tpu.runtime.maelstrom_node",
            "--workload", workload]
    if gossip_interval > 0:
        argv += ["--gossip-interval", str(gossip_interval)]
    return argv


def cmd_maelstrom_check(a) -> int:
    argv = _node_argv(a.gossip_interval, a.workload)
    if a.workload == "kafka":
        if a.router == "native":
            print("error: the kafka workload runs on the python "
                  "router (the C++ router speaks the broadcast "
                  "envelope set only)", file=sys.stderr)
            return 2
        import asyncio

        from gossip_tpu.runtime.maelstrom_harness import (
            run_kafka_workload)
        stats = asyncio.run(run_kafka_workload(
            a.n, a.ops, rate=a.rate, latency=a.latency,
            topology=a.topology, partition_mid=a.partition, seed=a.seed,
            argv=argv))
    elif a.workload == "txn":
        if a.router == "native":
            print("error: the txn workload runs on the python "
                  "router (the C++ router speaks the broadcast "
                  "envelope set only)", file=sys.stderr)
            return 2
        import asyncio

        from gossip_tpu.runtime.maelstrom_harness import (
            run_txn_workload)
        stats = asyncio.run(run_txn_workload(
            a.n, a.ops, rate=a.rate, latency=a.latency,
            topology=a.topology, partition_mid=a.partition, seed=a.seed,
            argv=argv))
    elif a.workload == "counter":
        if a.router == "native":
            print("error: the counter workload runs on the python "
                  "router (the C++ router speaks the broadcast "
                  "envelope set only)", file=sys.stderr)
            return 2
        import asyncio

        from gossip_tpu.runtime.maelstrom_harness import (
            run_counter_workload)
        stats = asyncio.run(run_counter_workload(
            a.n, a.ops, rate=a.rate, latency=a.latency,
            topology=a.topology, partition_mid=a.partition, seed=a.seed,
            argv=argv))
    elif a.router == "native":
        from gossip_tpu.runtime.native_router import run_native_workload
        stats = run_native_workload(
            a.n, a.ops, rate=a.rate, latency=a.latency,
            topology=a.topology, partition_mid=a.partition, seed=a.seed,
            argv=argv)
    else:
        import asyncio

        from gossip_tpu.runtime.maelstrom_harness import (
            run_broadcast_workload)
        stats = asyncio.run(run_broadcast_workload(
            a.n, a.ops, rate=a.rate, latency=a.latency,
            topology=a.topology, partition_mid=a.partition, seed=a.seed,
            argv=argv))
    stats["workload"] = a.workload
    stats["gossip_interval"] = a.gossip_interval
    ok = stats["invariant_ok"]
    if a.assert_msgs_per_op is not None:
        # Glomers-style efficiency gate: the report carries the target
        # and the verdict, and the exit code enforces it
        stats["msgs_per_op_target"] = a.assert_msgs_per_op
        stats["msgs_per_op_ok"] = (stats["msgs_per_op"]
                                   <= a.assert_msgs_per_op)
        ok = ok and stats["msgs_per_op_ok"]
    if a.assert_latency_ms is not None:
        stats["op_latency_target_ms"] = a.assert_latency_ms
        stats["op_latency_ok"] = (stats["op_latency_ms"]["max"]
                                  <= a.assert_latency_ms)
        ok = ok and stats["op_latency_ok"]
    print(json.dumps(stats))
    return 0 if ok else 1


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="gossip_tpu",
        description="TPU-native gossip simulation framework")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("run", help="run one simulation")
    _add_run_flags(p)
    _add_cache_flags(p)
    # Flags that COMPOSE with --plan (everything else is run-shape the
    # plan file carries, and cmd_run refuses it when changed from its
    # default — no-silent-drop).  The guarded set is EVERY other run
    # flag, derived from the live parser's own defaults via
    # parse_args([]), so a future _add_run_flags addition is guarded
    # automatically instead of silently discarded; the four
    # output-shape flags get their own earlier refusal message.
    _PLAN_COMPOSABLE_FLAGS = {
        "plan", "checkpoint", "resume", "compile_cache",
        "no_compile_cache", "ensemble", "parity_check", "curve",
        "save_curve"}
    _run_defaults = {k: v for k, v in vars(p.parse_args([])).items()
                     if k not in _PLAN_COMPOSABLE_FLAGS}
    p.set_defaults(fn=cmd_run, plan_guard_defaults=_run_defaults)

    p = sub.add_parser("sweep", help="run the 5 BASELINE benchmark configs")
    p.add_argument("--scale", type=float, default=1.0,
                   help="node-count scale factor (CPU smoke: 0.01)")
    p.add_argument("--devices", type=int, default=0,
                   help="mesh size for the sharded config (0 = all)")
    p.add_argument("--only", nargs="*", default=None,
                   help="subset of config names")
    p.add_argument("--curve", action="store_true")
    p.add_argument("--swim-diss", choices=("scatter", "sort", "pack"),
                   default=None,
                   help="override the SWIM config's dissemination "
                        "lowering (bitwise-identical trajectories; lets "
                        "the hardware capture re-measure the SWIM row "
                        "under an A/B-arbitrated winner without a code "
                        "change — tools/hw_refresh.py)")
    _add_cache_flags(p)
    p.set_defaults(fn=cmd_sweep)

    p = sub.add_parser("grid", help="batched config sweep: cartesian "
                       "product of modes/fanouts/drops/seeds in ONE "
                       "compiled program")
    p.add_argument("--modes", nargs="+", default=["push", "pull", "pushpull"],
                   choices=("push", "pull", "pushpull", "antientropy"))
    p.add_argument("--fanouts", nargs="+", type=int, default=[1, 2])
    p.add_argument("--drops", nargs="+", type=float, default=[0.0])
    p.add_argument("--periods", nargs="+", type=int, default=[2],
                   help="anti-entropy cadences (ignored for other modes)")
    p.add_argument("--seeds", nargs="+", type=int, default=[0])
    p.add_argument("--n", type=int, default=4096)
    p.add_argument("--ns", nargs="+", type=int, default=None,
                   help="sweep MULTIPLE graph sizes in the same program "
                        "(overrides --n; smaller graphs pad with inert "
                        "phantom rows — or, on the implicit complete "
                        "graph, bound each point's partner draw by its "
                        "own traced n — and each point's coverage uses "
                        "its own n)")
    p.add_argument("--rumors", nargs="+", type=int, default=[1],
                   help="rumor counts to sweep; multiple values batch "
                        "into the same program (the rumor axis pads to "
                        "the max with inert all-false phantom columns, "
                        "masked out of each point's coverage; 1-D grids "
                        "only — the pod mesh takes one value)")
    p.add_argument("--family", default="complete",
                   choices=("complete", "ring", "grid", "erdos_renyi",
                            "watts_strogatz", "power_law"))
    p.add_argument("--families", nargs="+", default=None,
                   choices=("ring", "grid", "erdos_renyi",
                            "watts_strogatz", "power_law"),
                   help="sweep MULTIPLE same-n explicit families as one "
                        "stacked table operand (overrides --family; the "
                        "implicit complete graph has no table to stack)")
    p.add_argument("--k", type=int, default=4)
    p.add_argument("--p", type=float, default=0.01)
    p.add_argument("--degree-cap", type=int, default=None)
    p.add_argument("--target", type=float, default=0.99)
    p.add_argument("--max-rounds", type=int, default=64)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--death", type=float, default=0.0)
    p.add_argument("--curve", action="store_true")
    p.add_argument("--devices", type=int, default=1,
                   help="shard the config axis over this many devices")
    p.add_argument("--pod-mesh", nargs=2, type=int, default=None,
                   metavar=("SWEEP", "NODES"),
                   help="2-D mesh: configs sharded over SWEEP devices, "
                        "each config's nodes over NODES devices")
    _add_cache_flags(p)
    p.set_defaults(fn=cmd_grid)

    p = sub.add_parser("churn-sweep",
                       help="run K nemesis scenarios (churn/partition/"
                            "drop-ramp fault programs) through ONE "
                            "compiled loop and report per-scenario "
                            "convergence + exact dropped totals")
    p.add_argument("--scenario", action="append", required=True,
                   metavar="SPEC",
                   help="one fault program: ';'-separated "
                        "event=NODE:DIE[:REC] / partition=START:END:CUT "
                        "/ ramp=START:END:P0:P1 items; repeat the flag "
                        "per scenario")
    p.add_argument("--n", type=int, default=4096)
    p.add_argument("--family", default="complete",
                   choices=("complete", "ring", "grid", "erdos_renyi",
                            "watts_strogatz", "power_law"))
    p.add_argument("--k", type=int, default=4)
    p.add_argument("--p", type=float, default=0.01)
    p.add_argument("--mode", default="pushpull",
                   choices=("push", "pull", "pushpull", "flood",
                            "antientropy"))
    p.add_argument("--fanout", type=int, default=2)
    p.add_argument("--rumors", type=int, default=1)
    p.add_argument("--period", type=int, default=1)
    p.add_argument("--target", type=float, default=0.99)
    p.add_argument("--max-rounds", type=int, default=64)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--drop", type=float, default=0.0,
                   help="base link drop probability (the drop table "
                        "outside any ramp; may differ per run, not per "
                        "scenario)")
    p.add_argument("--death", type=float, default=0.0,
                   help="static death rate (shared by every scenario — "
                        "the one compiled step bakes the static mask)")
    p.add_argument("--curve", action="store_true")
    p.add_argument("--devices", type=int, default=1,
                   help="shard the scenario axis (xla) or the "
                        "rumor-plane axis (fused) over this many "
                        "devices")
    p.add_argument("--engine", default="xla", choices=("xla", "fused"),
                   help="xla: K scenarios as ONE vmapped program; "
                        "fused: the plane-sharded Pallas engine, K "
                        "scenarios re-entering ONE memoized compiled "
                        "loop (--mode pull, complete family, TPU)")
    _add_cache_flags(p)
    p.set_defaults(fn=cmd_churn_sweep)

    p = sub.add_parser("crdt",
                       help="run a commutative-merge CRDT payload "
                            "(Gossip Glomers counter/set workloads) on "
                            "the pull exchange fabric with optional "
                            "nemesis fault programs; value convergence "
                            "is integer-exact against the ground-truth "
                            "merge on the eventual-alive set")
    p.add_argument("--type", default="gcounter",
                   choices=("gcounter", "pncounter", "gset", "orset"),
                   help="payload kind (ops/crdt.py): grow-only / PN "
                        "counter shards (merge = per-column max) or "
                        "packed set bit-planes (merge = OR)")
    p.add_argument("--n", type=int, default=1024)
    p.add_argument("--fanout", type=int, default=2)
    p.add_argument("--family", default="complete",
                   choices=("complete", "ring", "grid", "erdos_renyi",
                            "watts_strogatz", "power_law"))
    p.add_argument("--k", type=int, default=4)
    p.add_argument("--p", type=float, default=0.01)
    p.add_argument("--target", type=float, default=1.0,
                   help="value-convergence target (default 1.0: EVERY "
                        "eventual-alive node equals the ground truth "
                        "exactly — the Gossip Glomers invariant)")
    p.add_argument("--max-rounds", type=int, default=64)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--origin", type=int, default=0,
                   help="set-element owner rotation origin (element e "
                        "injects at node (origin + e) %% n)")
    p.add_argument("--devices", type=int, default=1,
                   help="node-dim mesh size (sharded pull exchange)")
    p.add_argument("--drop", type=float, default=0.0)
    p.add_argument("--death", type=float, default=0.0)
    p.add_argument("--add", action="append", default=None,
                   metavar="NODE:ROUND:AMOUNT",
                   help="scripted counter add (repeatable; negative "
                        "amounts decrement a pncounter; default "
                        "program: node j adds 1 + j%%7 at round 0)")
    p.add_argument("--set-add", action="append", default=None,
                   metavar="ELEM:ROUND",
                   help="scripted set add at the element's owner node "
                        "(repeatable; default: every element at "
                        "round 0)")
    p.add_argument("--set-remove", action="append", default=None,
                   metavar="ELEM:ROUND",
                   help="scripted orset remove (tombstone; repeatable)")
    p.add_argument("--elements", type=int, default=64,
                   help="set element universe size E (packed to "
                        "ceil(E/32) uint32 words per plane)")
    p.add_argument("--churn-event", action="append", default=None,
                   metavar="NODE:DIE[:REC]",
                   help="nemesis crash/recover churn (the run "
                        "command's syntax; repeatable)")
    p.add_argument("--partition", action="append", default=None,
                   metavar="START:END:CUT",
                   help="nemesis partition window (repeatable)")
    p.add_argument("--drop-ramp", default=None,
                   metavar="START:END:P0:P1",
                   help="nemesis drop-rate ramp")
    p.add_argument("--byz", action="append", default=None,
                   metavar="NODE:ROUND:KIND[:ARG]",
                   help="scripted byzantine liar: from ROUND on, NODE "
                        "serves forged state of KIND (corrupt | replay "
                        "| equivocate | inflate), ARG the kind-specific "
                        "payload knob; repeatable, one action per node "
                        "(docs/ROBUSTNESS.md \"Byzantine adversaries\")")
    p.add_argument("--byz-quorum", type=int, default=2,
                   help="independent-witness count q for defended set "
                        "bit admission (1-3; needs fanout >= q)")
    p.add_argument("--defend", action="store_true",
                   help="enable the array-form defenses (owner-column "
                        "guards, monotonicity clamps, quorum echo); "
                        "off = the undefended control arm")
    p.add_argument("--curve", action="store_true",
                   help="include the per-round value-convergence curve")
    p.add_argument("--save-curve", default=None, metavar="PATH",
                   help="write the value-convergence curve as JSONL")
    _add_cache_flags(p)
    p.set_defaults(fn=cmd_crdt)

    p = sub.add_parser("log",
                       help="run a replicated kafka-style log "
                            "(ordered per-key offset payloads with "
                            "committed offsets) on the pull exchange "
                            "fabric with optional nemesis fault "
                            "programs; convergence is integer-exact "
                            "against the acked-appends ground truth "
                            "on the eventual-alive set")
    p.add_argument("--n", type=int, default=1024)
    p.add_argument("--keys", type=int, default=4,
                   help="number of per-key logs K (ops/logs.py)")
    p.add_argument("--capacity", type=int, default=16,
                   help="ring slots per key C (at most C sends per "
                        "key — a wrap would alias offsets and is "
                        "rejected loudly)")
    p.add_argument("--fanout", type=int, default=2)
    p.add_argument("--family", default="complete",
                   choices=("complete", "ring", "grid", "erdos_renyi",
                            "watts_strogatz", "power_law"))
    p.add_argument("--k", type=int, default=4)
    p.add_argument("--p", type=float, default=0.01)
    p.add_argument("--target", type=float, default=1.0,
                   help="log-convergence target (default 1.0: EVERY "
                        "eventual-alive node holds the exact acked "
                        "log + committed offsets — the Gossip "
                        "Glomers invariant)")
    p.add_argument("--max-rounds", type=int, default=64)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--origin", type=int, default=0)
    p.add_argument("--devices", type=int, default=1,
                   help="node-dim mesh size (sharded pull exchange)")
    p.add_argument("--drop", type=float, default=0.0)
    p.add_argument("--death", type=float, default=0.0)
    p.add_argument("--send", action="append", default=None,
                   metavar="NODE:KEY:ROUND:VALUE",
                   help="scripted append (repeatable; values >= 1; "
                        "per-key rounds must be nondecreasing — "
                        "offset order is time order; default "
                        "program: 4 sends per key, rounds 0-3)")
    p.add_argument("--commit", action="append", default=None,
                   metavar="NODE:KEY:ROUND:UPTO",
                   help="scripted commit (repeatable; commits "
                        "min(upto, acked_len) — clamped to the "
                        "eventually-acked log length; default: one "
                        "commit per key at round 4)")
    p.add_argument("--churn-event", action="append", default=None,
                   metavar="NODE:DIE[:REC]",
                   help="nemesis crash/recover churn (repeatable)")
    p.add_argument("--partition", action="append", default=None,
                   metavar="START:END:CUT",
                   help="nemesis partition window (repeatable)")
    p.add_argument("--drop-ramp", default=None,
                   metavar="START:END:P0:P1",
                   help="nemesis drop-rate ramp")
    p.add_argument("--curve", action="store_true",
                   help="include the per-round log-convergence curve")
    p.add_argument("--save-curve", default=None, metavar="PATH",
                   help="write the log-convergence curve as JSONL")
    _add_cache_flags(p)
    p.set_defaults(fn=cmd_log)

    p = sub.add_parser("txn",
                       help="run totally-available transactions over "
                            "LWW registers (the Maelstrom "
                            "txn-rw-register shape) on the pull "
                            "exchange fabric with optional nemesis "
                            "fault programs; convergence is "
                            "integer-exact against the acked-writes "
                            "LWW ground truth on the eventual-alive "
                            "set")
    p.add_argument("--n", type=int, default=1024)
    p.add_argument("--keys", type=int, default=8,
                   help="register universe K (ops/registers.py)")
    p.add_argument("--txns", type=int, default=16,
                   help="default-program write count T (the skewed "
                        "closed-form traffic generator)")
    p.add_argument("--zipf-alpha", type=float, default=1.1,
                   help="key-popularity skew (> 0; 1.0 = classic "
                        "zipf, larger = more skewed)")
    p.add_argument("--hot-key", type=float, default=0.0,
                   help="hot-key storm: probability mass redirected "
                        "onto key 0 during the middle third of the "
                        "write program")
    p.add_argument("--load", default="uniform",
                   choices=("uniform", "diurnal"),
                   help="writes-over-rounds shape: uniform, or "
                        "diurnal (1 + sin density, one peak "
                        "mid-window)")
    p.add_argument("--spread", type=int, default=8,
                   help="rounds the default write program spans")
    p.add_argument("--fanout", type=int, default=2)
    p.add_argument("--family", default="complete",
                   choices=("complete", "ring", "grid", "erdos_renyi",
                            "watts_strogatz", "power_law"))
    p.add_argument("--k", type=int, default=4)
    p.add_argument("--p", type=float, default=0.01)
    p.add_argument("--target", type=float, default=1.0,
                   help="txn-convergence target (default 1.0: EVERY "
                        "eventual-alive node holds the exact LWW "
                        "winner + timestamp per key — the "
                        "total-availability convergence invariant)")
    p.add_argument("--max-rounds", type=int, default=64)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--origin", type=int, default=0)
    p.add_argument("--devices", type=int, default=1,
                   help="node-dim mesh size (sharded pull exchange)")
    p.add_argument("--drop", type=float, default=0.0)
    p.add_argument("--death", type=float, default=0.0)
    p.add_argument("--write", action="append", default=None,
                   metavar="NODE:KEY:ROUND:VALUE",
                   help="scripted write micro-op (repeatable; values "
                        ">= 1; at most one write per (key, round, "
                        "node) — the unique-timestamp contract; "
                        "overrides the skewed default program)")
    p.add_argument("--churn-event", action="append", default=None,
                   metavar="NODE:DIE[:REC]",
                   help="nemesis crash/recover churn (repeatable)")
    p.add_argument("--partition", action="append", default=None,
                   metavar="START:END:CUT",
                   help="nemesis partition window (repeatable)")
    p.add_argument("--drop-ramp", default=None,
                   metavar="START:END:P0:P1",
                   help="nemesis drop-rate ramp")
    p.add_argument("--byz", action="append", default=None,
                   metavar="NODE:ROUND:KIND[:ARG]",
                   help="scripted byzantine liar: from ROUND on, NODE "
                        "serves forged register state of KIND (corrupt "
                        "| replay | equivocate | inflate), ARG the "
                        "kind-specific payload knob; repeatable "
                        "(docs/ROBUSTNESS.md \"Byzantine adversaries\")")
    p.add_argument("--byz-quorum", type=int, default=2,
                   help="independent-witness count q (register defense "
                        "is owner-provenance, q applies to set planes)")
    p.add_argument("--defend", action="store_true",
                   help="enable the array-form defenses (owner-"
                        "provenance admission); off = the undefended "
                        "control arm")
    p.add_argument("--curve", action="store_true",
                   help="include the per-round txn-convergence curve")
    p.add_argument("--save-curve", default=None, metavar="PATH",
                   help="write the txn-convergence curve as JSONL")
    _add_cache_flags(p)
    p.set_defaults(fn=cmd_txn)

    p = sub.add_parser("serve", help="start the gRPC sidecar")
    p.add_argument("--port", type=int, default=50051)
    p.add_argument("--workers", type=int, default=16)
    p.add_argument("--no-batching", action="store_true",
                   help="disable the admission-batching serving layer "
                        "(per-request solo dispatch, the pre-serving "
                        "behavior)")
    p.add_argument("--batch-tick-ms", type=float, default=20.0,
                   help="admission collector cadence (docs/SERVING.md)")
    p.add_argument("--batch-max", type=int, default=64,
                   help="per-tick per-key megabatch lane cap")
    p.add_argument("--batch-queue", type=int, default=256,
                   help="backpressure cap: admissions past this depth "
                        "get RESOURCE_EXHAUSTED")
    p.add_argument("--devices", type=int, default=1,
                   help="megabatch mesh width (power of two): shard "
                        "each tick's megabatch over the first K JAX "
                        "devices; refuses at startup when the process "
                        "has fewer (docs/SERVING.md \"Mesh-sharded "
                        "replicas\")")
    p.add_argument("--coordinator", default=None,
                   metavar="HOST:PORT",
                   help="jax.distributed coordinator address when one "
                        "logical replica spans processes")
    p.add_argument("--num-processes", type=int, default=1,
                   help="process count of the jax.distributed "
                        "topology (1 = the degenerate single-process "
                        "case, no initialization)")
    p.add_argument("--process-id", type=int, default=0,
                   help="this process's rank in [0, num-processes)")
    _add_cache_flags(p)
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("route",
                       help="front N sidecar replicas with the "
                            "health-gated failover router "
                            "(docs/SERVING.md \"Fleet\")")
    p.add_argument("--replicas", type=int, default=2,
                   help="sidecar replica processes to spawn")
    p.add_argument("--port", type=int, default=50051,
                   help="router port (replicas pick free ports)")
    p.add_argument("--workers", type=int, default=16)
    p.add_argument("--probe-interval-ms", type=float, default=250.0,
                   help="health-probe cadence per replica")
    p.add_argument("--down-after", type=int, default=2,
                   help="consecutive probe failures before a replica "
                        "leaves rotation")
    p.add_argument("--up-after", type=int, default=3,
                   help="consecutive healthy probes before a downed "
                        "replica re-enters rotation (flap hysteresis)")
    p.add_argument("--max-inflight", type=int, default=8,
                   help="per-replica in-flight cap; past it the "
                        "router sheds with RESOURCE_EXHAUSTED")
    p.add_argument("--no-batching", action="store_true",
                   help="disable admission batching in the replicas")
    p.add_argument("--devices-per-replica", type=int, default=1,
                   help="megabatch mesh width per replica (power of "
                        "two): children get XLA_FLAGS=--xla_force_"
                        "host_platform_device_count=K and serve "
                        "--devices K; the fleet refuses loudly if a "
                        "child reports fewer serving devices")
    p.add_argument("--replica-platform", default="cpu",
                   help="JAX_PLATFORMS pin for replica children "
                        "(default cpu: N processes cannot share one "
                        "TPU; '' inherits the ambient platform)")
    p.set_defaults(fn=cmd_route)

    p = sub.add_parser(
        "fleet-status",
        help="live fleet metrics table over the Metrics RPC; exits "
             "nonzero on a degraded replica (docs/OBSERVABILITY.md "
             "\"Live fleet metrics\")")
    p.add_argument("address", metavar="HOST:PORT",
                   help="router address (renders the whole fleet) or "
                        "a single replica address (renders its window)")
    p.add_argument("--watch", action="store_true",
                   help="re-render every --interval seconds until ^C "
                        "(exit code reflects the LAST poll)")
    p.add_argument("--interval", dest="interval_s", type=float,
                   default=2.0, help="--watch poll cadence, seconds")
    p.add_argument("--timeout", dest="timeout_s", type=float,
                   default=10.0, help="per-poll Metrics RPC timeout")
    p.add_argument("--json", dest="as_json", action="store_true",
                   help="one JSON document per poll instead of the "
                        "table")
    p.add_argument("--out", default=None, metavar="PATH",
                   help="also write the latest poll as a provenance-"
                        "stamped fleet_status JSON artifact")
    p.set_defaults(fn=cmd_fleet_status)

    p = sub.add_parser(
        "plan",
        help="HBM budget model: what word-plane tiling fits N on this "
             "topology? (prints a ScalePlan as JSON, or refuses "
             "naming the binding constraint; pure host arithmetic — "
             "docs/SCALING.md)")
    p.add_argument("--n", type=int, default=100_000_000,
                   help="target node count")
    p.add_argument("--rumors", type=int, default=64)
    p.add_argument("--fanout", type=int, default=1)
    p.add_argument("--engine", default="packed",
                   choices=("packed", "dense", "fused"),
                   help="engine byte model (only 'packed' is "
                        "executable by scale-run)")
    p.add_argument("--max-rounds", type=int, default=64)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--origin", type=int, default=0)
    p.add_argument("--chips", type=int, default=1,
                   help="total chip count")
    p.add_argument("--hbm-gb", type=float, default=16.0,
                   help="HBM per chip (GiB); fractional values allowed "
                        "(the dry-run family plans against artificial "
                        "budgets)")
    p.add_argument("--slices", type=int, default=1,
                   help="DCN slices (chips/slices = the ICI inner "
                        "axis; >1 emits the hybrid mesh)")
    p.add_argument("--host-ram-gb", type=float, default=64.0)
    p.add_argument("--segment-every", type=int, default=None,
                   help="checkpoint segment length in rounds")
    p.add_argument("--reserve", type=float, default=None,
                   help="HBM fraction held back from the plan "
                        "(default: planner/budget"
                        ".DEFAULT_RESERVE_FRAC, 0.08)")
    p.add_argument("--death", type=float, default=0.0)
    p.add_argument("--drop", type=float, default=0.0)
    p.add_argument("--fault-seed", type=int, default=0)
    p.add_argument("--scenario", default=None,
                   help="fault program spec, the churn-sweep syntax: "
                        "'event=N:D[:R];partition=S:E:C;ramp=S:E:P0:P1'")
    p.add_argument("--out", default=None, metavar="FILE",
                   help="write the plan JSON here instead of stdout")
    p.add_argument("--validate", default=None, metavar="FILE",
                   help="validate an existing plan file instead of "
                        "planning")
    p.set_defaults(fn=cmd_plan)

    p = sub.add_parser(
        "scale-run",
        help="execute a ScalePlan: stream word-plane tiles through "
             "the packed engine per checkpoint segment "
             "(docs/SCALING.md)")
    p.add_argument("--plan", required=True, metavar="FILE",
                   help="plan JSON from `gossip_tpu plan`")
    p.add_argument("--checkpoint", default=None, metavar="PATH",
                   help="publish an atomic npz checkpoint per segment")
    p.add_argument("--resume", action="store_true",
                   help="continue from --checkpoint (refuses a "
                        "mismatched plan or fault-program fingerprint)")
    p.add_argument("--check-bitwise", action="store_true",
                   help="also run the untiled in-memory reference and "
                        "gate byte equality (exit 1 on mismatch)")
    p.add_argument("--measure-memory", action="store_true",
                   help="AOT memory analysis of the tile loop "
                        "(one extra compile)")
    p.add_argument("--no-overlap", action="store_true",
                   help="drain each tile synchronously instead of "
                        "running the three-stage fetch pipeline — the "
                        "serial A/B leg for overlap capture "
                        "(trajectories are bitwise identical either "
                        "way; docs/SCALING.md)")
    # the same cache + multi-host init the equivalent `run --plan`
    # path gets (main()'s dispatch list includes scale-run): a big-N
    # tile loop's compile is exactly what the persistent cache exists
    # to amortize
    _add_cache_flags(p)
    p.set_defaults(fn=cmd_scale_run)

    p = sub.add_parser(
        "staticcheck",
        help="AST invariant analyzer over the repo source: "
             "recompile-hazard lint (serving/sweep), rpc lock "
             "discipline, convention gates; exit 1 on findings "
             "(docs/STATIC_ANALYSIS.md)")
    p.add_argument("--root", default=None, metavar="DIR",
                   help="tree to analyze (default: this repo)")
    p.add_argument("--baseline", default=None, metavar="JSON",
                   help="suppression baseline (default: tools/"
                        "staticcheck_baseline.json; '' disables)")
    p.add_argument("--ledger", default=None, metavar="PATH",
                   help="write the provenance-stamped findings ledger")
    p.add_argument("--json", dest="json_summary", action="store_true",
                   help="one summary JSON line instead of per-finding "
                        "text")
    p.set_defaults(fn=cmd_staticcheck)

    p = sub.add_parser("maelstrom",
                       help="run the Maelstrom protocol node on stdio")
    p.add_argument("--gossip-interval", type=float, default=0.0,
                   help="batch relays per neighbor every INTERVAL "
                        "seconds (0 = immediate per-message fan-out)")
    p.add_argument("--workload", default="broadcast",
                   choices=("broadcast", "counter", "kafka", "txn"),
                   help="node personality: broadcast log (the "
                        "reference), Gossip Glomers counter (CRDT "
                        "shards, merge = per-key max), the "
                        "replicated kafka-style log (owner-assigned "
                        "offsets, committed-offset max merge), or "
                        "txn-rw-register (totally-available "
                        "transactions over LWW registers)")
    p.set_defaults(fn=cmd_maelstrom)

    p = sub.add_parser("maelstrom-check",
                       help="run the Maelstrom broadcast workload against "
                            "N real node processes and check the "
                            "eventual-delivery invariant (the external "
                            "harness the reference was tested with, "
                            "in-repo)")
    p.add_argument("--n", type=int, default=5)
    p.add_argument("--ops", type=int, default=20)
    p.add_argument("--rate", type=float, default=50.0, help="ops/sec")
    p.add_argument("--latency", type=float, default=0.002,
                   help="simulated link latency (s)")
    p.add_argument("--topology", default="line", choices=("line", "grid"))
    p.add_argument("--partition", action="store_true",
                   help="cut a mid-cluster link for the middle third of "
                        "the run (fault-tolerance variant)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--router", default="python",
                   choices=("python", "native"),
                   help="harness engine: the asyncio router or the C++ "
                        "poll()-loop router (native/router.cpp, built on "
                        "demand)")
    p.add_argument("--workload", default="broadcast",
                   choices=("broadcast", "counter", "kafka", "txn"),
                   help="broadcast (every value in every read), the "
                        "Gossip Glomers counter (every node's final "
                        "read == the sum of acked adds, through a "
                        "--partition), kafka (acked sends exactly "
                        "once per key in offset order, monotone "
                        "committed offsets, gapless polls — through "
                        "a --partition), or txn (txn-rw-register: "
                        "no G0/G1a weak-isolation anomalies + "
                        "cross-node LWW convergence — through a "
                        "--partition)")
    p.add_argument("--gossip-interval", type=float, default=0.0,
                   help="run the nodes with interval-batched relays "
                        "(seconds; 0 = the reference's immediate "
                        "per-message fan-out)")
    p.add_argument("--assert-msgs-per-op", type=float, default=None,
                   metavar="T",
                   help="Glomers-style efficiency gate: fail (exit 1) if "
                        "msgs_per_op exceeds T; the report records the "
                        "target and verdict")
    p.add_argument("--assert-latency-ms", type=float, default=None,
                   metavar="MS",
                   help="fail if the max client-op latency exceeds MS")
    p.set_defaults(fn=cmd_maelstrom_check)

    a = ap.parse_args(argv)
    try:
        if a.cmd in ("run", "sweep", "grid", "churn-sweep", "crdt",
                     "log", "txn", "serve", "scale-run"):
            # multi-host pods: one jax.distributed.initialize() per host
            # before any jax API (no-op without the coordinator env vars)
            from gossip_tpu.parallel.multislice import maybe_init_distributed
            maybe_init_distributed()
            _enable_compile_cache(a)
        return a.fn(a)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
