"""Rumor-plane sharding for the fused Pallas pull kernel: scale RUMORS,
not traffic.

The sharded SI kernels scale the NODE dimension and pay ICI for it every
round (all_gather / all_to_all of digest state — parallel/sharded.py,
sharded_sparse.py).  For massive multi-rumor broadcast the TPU-native
layout is the transpose: shard the RUMOR dimension.  SI pull semantics
(models/si.py, after the reference's whole-log exchange, main.go:126) give
every node ONE partner per round, and the partner's *entire* digest rides
that exchange — rumors never influence partner choice.  So the state
``uint32[W, rows, 128]`` (W word-planes of the one-word-per-node layout,
plane p holding rumors 32p..32p+31) can shard plane-wise across the mesh:
every device runs the SAME fused VMEM kernel (ops/pallas_round.py) on its
local planes, seeded identically, so the hardware PRNG reproduces the SAME
partner draw on every device — one global partner per node per round,
whole digest exchanged, and the merge needs **zero ICI traffic**.  The
only cross-device communication in the whole simulation is the scalar
coverage reduction in the loop condition.

This is the engine for the 10M-node multi-rumor flagship: 32 rumors per
chip-plane, R = 32*W rumors total.  Planes that fit the VMEM envelope run
the whole-table value kernel; bigger planes (N=10M is a 38 MiB table,
~4x that in live windows) route through the staged big-table path of
ops/pallas_round.py (XLA rotation + grid-blocked gather) — same math,
block-sized VMEM, no upper bound on n.  Node-dim sharding of the same
workload would all_gather O(N*W) words per round; here the per-round ICI
cost is a float.

Rumor padding: planes are always full 32-bit words; rumor columns beyond
``rumors`` (and whole planes beyond ``ceil(rumors/32)``, when W is padded
up to the mesh size) are initialized ALL-ONES for real nodes, so their
per-rumor coverage is 1.0 from round 0 and the min-over-rumors metric is
untouched.  Phantom *nodes* stay zero (kernel contract).

Testing: the kernel's inject path (tests-only explicit bit operands)
makes the sharded round bitwise-checkable on the 8-device CPU mesh —
every plane must equal the single-device multi-rumor kernel run with the
same bits (tests/test_sharded_fused.py).  The hw-PRNG path additionally
requires every device to draw the same stream — an EXECUTED assertion,
not an argument: :func:`assert_prng_invariant` runs one identically-
seeded round on one identical plane per device, all_gathers a
(popcount, weighted-mix) digest of each device's output, and requires
all rows equal (tests/test_sharded_fused.py TPU tier; also a
tools/hw_refresh.py step and part of the dryrun program).  The CPU
interpreter stubs the hardware PRNG, so off-TPU the check only proves
the program/collective plumbing; the invariant itself is a TPU artifact.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gossip_tpu.compat import shard_map
from gossip_tpu.config import RunConfig
from gossip_tpu.ops.pallas_round import (
    BITS, LANES, coverage_words, coverage_words_alive, drop_threshold_for,
    fault_masks_word, fused_multirumor_pull_round, mr_rows, word_pack)

AXIS = "planes"


def make_plane_mesh(n_devices: int) -> Mesh:
    """1-D mesh over the rumor-plane axis."""
    devs = jax.devices()[:n_devices]
    if len(devs) < n_devices:
        raise ValueError(f"need {n_devices} devices, have {len(devs)}")
    return Mesh(devs, (AXIS,))


def plane_count(rumors: int, n_devices: int) -> int:
    """Planes covering ``rumors``, padded up to a multiple of the mesh."""
    w = -(-rumors // BITS)
    return -(-w // n_devices) * n_devices


@functools.lru_cache(maxsize=32)
def _cached_plane_init(n: int, rumors: int, origin: int, mesh: Mesh):
    """Jitted builder of the initial plane stack, memoized per statics.

    The per-plane Python loop below runs ONCE at trace time; every later
    call is an executable-cache hit producing a fresh (donation-safe)
    device buffer under the plane sharding.  Before this, the dry run's
    steady re-entry rebuilt the stack with ~6 eager dispatches per plane
    per call — host-side driver overhead the device-resident loop then
    sat waiting on."""
    w_total = plane_count(rumors, mesh.shape[AXIS])

    def build():
        planes = []
        for p in range(w_total):
            lo = p * BITS
            real = max(0, min(rumors - lo, BITS))
            seen = jnp.concatenate(
                [jnp.zeros((n, real), jnp.bool_),
                 jnp.ones((n, BITS - real), jnp.bool_)], axis=1)
            if real:
                origins = (origin + lo + jnp.arange(real)) % n
                seen = seen.at[origins, jnp.arange(real)].set(True)
            planes.append(word_pack(seen))
        return jnp.stack(planes)

    return jax.jit(build,
                   out_shardings=NamedSharding(mesh, P(AXIS, None, None)))


def init_plane_state(n: int, rumors: int, mesh: Mesh,
                     origin: int = 0) -> jax.Array:
    """uint32[W, rows, 128] plane-sharded state; rumor r starts at node
    (origin + r) % n (models/state.init_state contract); padding rumor
    columns/planes are all-ones (coverage 1.0, inert under OR-merge)."""
    if not 0 <= origin < n:
        raise ValueError(f"origin {origin} out of range for n={n}")
    return _cached_plane_init(n, rumors, origin, mesh)()


def coverage_planes(planes: jax.Array, n: int) -> jax.Array:
    """Min-over-rumors infected fraction across every plane and bit.
    Padding rumors are all-ones (coverage 1.0) so they never win the min."""
    per_plane = jax.vmap(lambda t: coverage_words(t, n, BITS))(planes)
    return jnp.min(per_plane)


def coverage_planes_masked(planes: jax.Array, n: int,
                           alive_words=None) -> jax.Array:
    """The ONE plane-coverage body: plain min-over-rumors fraction, or
    the alive-weighted twin when a death mask rides along (padding
    rumors stay 1.0 under the weighting: every alive node holds their
    all-ones bits).  ``alive_words`` is a runtime OPERAND — the compiled
    drivers share one executable across fault configurations."""
    if alive_words is None:
        return coverage_planes(planes, n)
    per_plane = jax.vmap(
        lambda t: coverage_words_alive(t, alive_words, BITS))(planes)
    return jnp.min(per_plane)


@functools.lru_cache(maxsize=32)
def _cached_alive_words(fault, n: int, origin: int):
    """Jitted builder of the plane engine's alive mask (fault_masks_word
    rendering) — the steady-state twin of :func:`_cached_plane_init`:
    re-entering a faulted driver re-executes a cached program instead of
    dispatching the O(n) mask build eagerly per call."""
    return jax.jit(lambda: fault_masks_word(fault, n, origin)[0])


@functools.lru_cache(maxsize=32)
def _cached_churn_masks(fault, n: int, origin: int):
    """The churn-path mask operands, built ONCE per fault and cached as
    VALUES: ``(cov_words, base_words, die_words, rec_words, cut_tbl,
    thr_tbl)`` — the EVENTUAL alive words the cond/coverage compare
    against (ops/nemesis.fused_eventual_words: permanent churn deaths
    out of the denominator, transient ones in — the heal-convergence
    contract), the static base mask, the die/recover round tables, and
    (since the operand PR) the per-round partition-cut and 20-bit
    drop-threshold tables (ops/nemesis.fused_sched_tables) the
    compiled loop indexes by its round counter.  All runtime OPERANDS:
    a churn sweep over schedules — events, partition windows, AND
    drop-rate ramps — shares one compiled loop (the alive-mask
    runtime-operand trick, extended to cut words and the drop coin).

    Deliberately EAGER, not a per-fault ``jax.jit(build)`` closure: a
    fresh jit per fault bakes the schedule content as trace constants
    and pays one backend compile per SCENARIO — exactly the recompile
    class this PR deletes (the K-scenario compile-count pin in
    tests/test_sharded_fused.py counts it).  Eager builds dispatch
    shape-keyed primitive programs shared across every fault of the
    same shape class, and the lru_cache makes steady re-entry free.
    Caching device buffers is donation-safe here: the compiled loops
    donate only the plane stack, never the mask operands."""
    from gossip_tpu.ops import nemesis as NE
    cut_np, thr_np = NE.fused_sched_tables(fault, n)
    base = NE.fused_base_words(fault, n, origin)
    die_w, rec_w = NE.fused_word_tables(fault, n)
    return (NE.fused_eventual_words(base, die_w, rec_w), base,
            die_w, rec_w, jnp.asarray(cut_np, jnp.int32),
            jnp.asarray(thr_np, jnp.int32))


def fused_planes_cov_fn(n: int, fault=None, origin: int = 0):
    """``planes -> coverage`` — alive-weighted iff the fault draws
    deaths (cf. ops/pallas_round.fused_cov_fn); a fault-binding wrapper
    around :func:`coverage_planes_masked`, which the compiled drivers
    call directly with the mask as an operand.  Under a churn schedule
    the denominator is the EVENTUAL alive words (permanent churn deaths
    out, transient ones in — the heal-convergence contract the compiled
    churn loops already apply via :func:`_cached_churn_masks`)."""
    from gossip_tpu.ops import nemesis as NE
    if NE.get(fault) is not None:
        def cov_churn(p):
            eventual = _cached_churn_masks(fault, n, origin)[0]
            return coverage_planes_masked(p, n, eventual)
        return cov_churn
    if fault is None or not fault.node_death_rate:
        return lambda p: coverage_planes_masked(p, n)

    def cov(p):
        alive_words, _ = fault_masks_word(fault, n, origin)
        return coverage_planes_masked(p, n, alive_words)
    return cov


def make_sharded_fused_round_masked(n: int, mesh: Mesh, fanout: int = 1,
                                    interpret: bool = False,
                                    inject_bits=None,
                                    has_alive: bool = False,
                                    has_cut: bool = False):
    """The masked core of :func:`make_sharded_fused_round`:
    ``round_fn(planes, seed, round_, alive_words=None,
    drop_threshold=0, cut_words=None)`` with EVERY fault input as a
    runtime OPERAND (replicated over the mesh) instead of a
    trace-baked constant — the death mask, the 20-bit drop threshold
    (an SMEM scalar inside the kernel since the operand PR, so
    drop-rate sweeps and RAMPS re-enter one executable), and, with
    ``has_cut``, the partition side-word mask
    (ops/pallas_round.render_cut_words).  The compiled drivers built
    on this share one executable across every fault configuration of
    the same operand structure — a fault sweep over death rates,
    seeds, drop rates, ramps, or partition windows re-enters one
    cached program per shape instead of recompiling the whole
    shard_map loop per point.  Same values as the baked form: the
    masks are pure functions of the fault config over the REPLICATED
    node dimension, and they consume no hardware PRNG (the drop coin
    rides free bits of the existing partner draw; the side compare
    rides the partner rotation) — the zero-ICI same-stream invariant
    is untouched."""
    n_dev = mesh.shape[AXIS]

    def local_round(planes_l, seed, round_, thr, *masks):
        alive_words = masks[0] if has_alive else None
        cut_words = masks[1 if has_alive else 0] if has_cut else None
        w_local = planes_l.shape[0]
        outs = [fused_multirumor_pull_round(
                    planes_l[i], seed, round_, n, fanout, interpret,
                    inject_bits=inject_bits,
                    drop_threshold=thr,
                    alive_words=alive_words,
                    cut_words=cut_words)
                for i in range(w_local)]
        return jnp.stack(outs)

    in_specs = (P(AXIS, None, None), P(), P(), P())
    if has_alive:
        in_specs += (P(None, None),)
    if has_cut:
        in_specs += (P(None, None),)
    # check_vma=False: pallas_call's out_shape carries no varying-mesh-axes
    # annotation, which the default shard_map VMA check rejects
    mapped = shard_map(
        local_round, mesh=mesh, in_specs=in_specs,
        out_specs=P(AXIS, None, None), check_vma=False)

    def round_fn(planes, seed, round_, alive_words=None,
                 drop_threshold=0, cut_words=None):
        if planes.shape[0] % n_dev:
            raise ValueError(f"{planes.shape[0]} planes do not divide "
                             f"over {n_dev} devices")
        if (alive_words is not None) != has_alive:
            raise ValueError("alive_words must be passed exactly when the "
                             "round was built with has_alive=True")
        if (cut_words is not None) != has_cut:
            raise ValueError("cut_words must be passed exactly when the "
                             "round was built with has_cut=True")
        masks = (alive_words,) if has_alive else ()
        if has_cut:
            masks += (cut_words,)
        return mapped(planes, jnp.asarray(seed, jnp.int32),
                      jnp.asarray(round_, jnp.int32),
                      jnp.asarray(drop_threshold, jnp.int32), *masks)

    return round_fn


def make_sharded_fused_round(n: int, mesh: Mesh, fanout: int = 1,
                             interpret: bool = False, inject_bits=None,
                             fault=None, origin: int = 0):
    """shard_map'd round: each device advances its local planes with the
    identically-seeded fused kernel — same partner draw on every device,
    zero ICI.  ``inject_bits`` (tests) is one (sbits, rbits) pair reused
    for every plane, which IS the semantic: one shared partner stream.

    ``fault`` threads the fault operands into every plane's kernel call
    — a fault-binding wrapper around
    :func:`make_sharded_fused_round_masked` that rebuilds the masks
    in-trace per call (loop-invariant or round-indexed, hoisted by
    jitted callers).  Under a churn schedule the FULL nemesis runs:
    events render the alive words per round from the die/recover word
    tables, partition windows render per-round side-word cut masks
    (ops/pallas_round.render_cut_words), and drop-rate ramps index the
    20-bit threshold table — all from the state's ABSOLUTE round
    counter, so checkpointed resume stays bitwise
    (ops/nemesis.fused_sched_tables; the two check_supported rejection
    rows this engine used to carry are deleted)."""
    from gossip_tpu.ops import nemesis as NE
    NE.check_supported(fault, engine="fused-planes")
    static_thr = drop_threshold_for(fault)
    has_churn = NE.get(fault) is not None
    has_alive = (fault is not None
                 and bool(fault.node_death_rate)) or has_churn
    core = make_sharded_fused_round_masked(
        n, mesh, fanout, interpret, inject_bits=inject_bits,
        has_alive=has_alive, has_cut=has_churn)
    if has_churn:
        # loop-invariant closure constants: converted ONCE here, not
        # per round_fn call (eager stepwise callers pay one transfer)
        cut_np, thr_np = NE.fused_sched_tables(fault, n)
        cut_tbl = jnp.asarray(cut_np, jnp.int32)
        thr_tbl = jnp.asarray(thr_np, jnp.int32)

    def round_fn(planes, seed, round_):
        from gossip_tpu.ops.pallas_round import render_cut_words
        if has_churn:
            base = NE.fused_base_words(fault, n, origin)
            die_w, rec_w = NE.fused_word_tables(fault, n)
            alive_words = NE.fused_alive_words_at(base, die_w, rec_w,
                                                  round_)
            # the ONE clamped steady-row lookup (ops/nemesis._idx)
            return core(planes, seed, round_, alive_words,
                        NE._idx(thr_tbl, round_),
                        render_cut_words(NE._idx(cut_tbl, round_), n))
        if has_alive:
            alive_words = fault_masks_word(fault, n, origin)[0]
            return core(planes, seed, round_, alive_words, static_thr)
        return core(planes, seed, round_, drop_threshold=static_thr)

    return round_fn


def prng_invariant_digests(n: int, mesh: Mesh, seed: int = 0,
                           round_: int = 1, fanout: int = 1,
                           interpret: bool = False) -> jax.Array:
    """Digest of one identically-seeded fused round per device.

    Every device builds the SAME deterministic non-trivial input plane,
    runs the SAME fused kernel with the SAME seed scalars, and digests
    its output as (total popcount, index-weighted mix) — two uint32s
    whose collision probability for diverged PRNG streams is ~2^-64.
    The digests ride one all_gather; equal rows == the zero-ICI
    same-stream invariant held on this mesh.  Returns uint32[n_dev, 2].
    """
    rows = mr_rows(n)

    def local(_dummy):
        i = jax.lax.broadcasted_iota(jnp.uint32, (rows, LANES), 0)
        j = jax.lax.broadcasted_iota(jnp.uint32, (rows, LANES), 1)
        table = ((i * jnp.uint32(2654435761)) ^ (j * jnp.uint32(40503))
                 ) | jnp.uint32(1)
        out = fused_multirumor_pull_round(
            table, jnp.int32(seed), jnp.int32(round_), n, fanout,
            interpret)
        pop = jnp.sum(jax.lax.population_count(out), dtype=jnp.uint32)
        # distinct odd weight per position (2x+1, not x|1 — OR-ing maps
        # even/odd lane pairs to the SAME weight, and a weight collision
        # plus permutation-invariant popcount would let a lane-pair swap
        # between diverged streams slip through)
        w = jnp.uint32(2) * (i * jnp.uint32(LANES) + j) + jnp.uint32(1)
        mix = jnp.sum(out * w, dtype=jnp.uint32)
        return jax.lax.all_gather(jnp.stack([pop, mix]), AXIS)

    mapped = shard_map(
        local, mesh=mesh, in_specs=(P(AXIS),), out_specs=P(None, None),
        check_vma=False)
    return mapped(jnp.zeros((mesh.shape[AXIS],), jnp.int32))


def assert_prng_invariant(n: int, mesh: Mesh, seed: int = 0,
                          round_: int = 1, fanout: int = 1,
                          interpret: bool = False):
    """Raise unless every device drew the identical partner stream.
    Returns the digest table on success (an artifact to record)."""
    import numpy as np
    d = np.asarray(prng_invariant_digests(n, mesh, seed, round_, fanout,
                                          interpret))
    if not (d == d[0]).all():
        raise AssertionError(
            "zero-ICI plane-sharding PRNG invariant VIOLATED: devices "
            f"drew different partner streams; digests per device:\n{d}")
    if int(d[0, 0]) == 0:
        raise AssertionError(
            "degenerate digest (popcount 0) — the check input never "
            "reached the kernel")
    return d


def restore_plane_state(planes, mesh: Mesh):
    """Re-place host-loaded checkpoint planes under the plane sharding.
    The stack is already padded to the mesh (init_plane_state contract),
    so a same-mesh-shape resume is bitwise exact; the CLI fingerprint
    refuses a different device count."""
    return jax.device_put(jnp.asarray(planes),
                          NamedSharding(mesh, P(AXIS, None, None)))


def checkpointed_fused_planes(n: int, rumors: int, run: RunConfig,
                              mesh: Mesh, path: str, every: int = 50,
                              fanout: int = 1,
                              resume_state=None, want_curve: bool = False,
                              interpret: bool = False,
                              curve_prefix=(), extra_meta=None,
                              fault=None):
    """Fixed-budget plane-sharded fused run in compiled segments with
    atomic npz checkpoints — persistence for the flagship multi-rumor
    runs, the one scale long enough to need it (the reference loses all
    state on process death, main.go:22-26).  The checkpoint state is a
    :class:`~gossip_tpu.ops.pallas_round.FusedState` whose ``table``
    field carries the [W, rows, 128] plane stack; there is no PRNG key
    to persist — the kernel's hardware PRNG streams are a pure function
    of (seed, round), both in the config fingerprint / round counter.

    With ``want_curve`` the segments run as a scan recording
    min-over-rumors coverage per round (alive-weighted under a fault,
    like the non-checkpoint scan twins).  ``interpret`` is the
    CPU-interpreter path for tests (deterministic stubbed PRNG: resume
    bitwise-equality is still meaningful off-TPU).

    Returns ``(final_state, coverage, curve-or-None)``.
    """
    from gossip_tpu.ops.pallas_round import FusedState
    from gossip_tpu.utils.checkpoint import run_with_checkpoints
    # the FULL churn schedule — events, partition windows, drop-rate
    # ramps — runs in the segments exactly as in the straight fused
    # drivers: the round closure renders the alive words, per-round cut
    # mask, and drop threshold from the state's ABSOLUTE round counter,
    # which the checkpoint persists, so resume == straight run bitwise
    # (utils/checkpoint crash contract); the coverage denominator under
    # churn is the eventual alive words (fused_planes_cov_fn)
    round_fn = make_sharded_fused_round(n, mesh, fanout, interpret,
                                        fault=fault, origin=run.origin)
    cov_planes = fused_planes_cov_fn(n, fault, run.origin)

    def step(st: FusedState) -> FusedState:
        return FusedState(table=round_fn(st.table, run.seed, st.round),
                          round=st.round + 1,
                          msgs=st.msgs + 2.0 * fanout * n)

    if resume_state is None:
        state = FusedState(table=init_plane_state(n, rumors, mesh,
                                                  run.origin),
                           round=jnp.int32(0), msgs=jnp.float32(0.0))
    else:
        state = resume_state._replace(
            table=restore_plane_state(resume_state.table, mesh))

    curve_fn = None
    if want_curve:
        def curve_fn(s):
            return cov_planes(s.table)

    remaining = max(0, run.max_rounds - int(state.round))
    out = run_with_checkpoints(step, state, remaining, path, every=every,
                               curve_fn=curve_fn,
                               curve_prefix=curve_prefix,
                               extra_meta=extra_meta)
    final, curve = out if want_curve else (out, None)
    cov = float(cov_planes(final.table))
    return final, cov, curve


def _plane_recorder(n: int, fanout: int, mesh: Mesh):
    """In-loop metrics row for the plane-sharded fused drivers
    (ops/round_metrics).  ``msgs`` is the driver's own accounting
    (2*fanout*n transmissions per round, all W word-planes riding one
    exchange); ``offered`` counts every delivered digest bit including
    the all-ones rumor padding (an upper bound, consistent with the
    module contract); ``bytes`` is 4.0 — the scalar coverage reduction
    is the ONLY cross-device traffic, which is exactly the zero-ICI
    claim this plane makes checkable per round.  The previous round's
    bit count rides the carry as ONE scalar — re-reading the pre-step
    plane stack after the kernel call would extend its liveness across
    the aliased pallas_call and resurrect the copy-insertion full-table
    copy the donation contract exists to kill."""
    from gossip_tpu.ops import round_metrics as RM
    n_shards = mesh.shape[AXIS]

    def rec(m, prev_count, planes1):
        count = RM.count_planes(planes1)
        newly = count - prev_count
        offered = (jnp.float32(fanout * n)
                   * jnp.float32(planes1.shape[0] * BITS))
        return RM.record(
            m, newly=newly, msgs=2.0 * fanout * n,
            dup=RM.dup_estimate(offered, newly), bytes=4.0,
            front=RM.front_planes(planes1, n, n_shards)), count

    return rec


@functools.lru_cache(maxsize=32)
def _cached_curve_scan(n: int, seed: int, max_rounds: int, mesh: Mesh,
                       fanout: int, interpret: bool,
                       has_alive: bool, metrics: bool = False,
                       has_churn: bool = False):
    """The compiled curve-scan driver, memoized by EXACTLY the statics
    its trace bakes in (seed and max_rounds are closed-over literals) —
    not the whole RunConfig, whose unused fields (engine, checkpoint
    knobs) would fragment the cache, and NOT the fault config at all:
    the alive mask, the 20-bit drop threshold (per-round table under
    churn — so RAMPS ride free), and the partition cut table are all
    runtime OPERANDS (``*masks``), so a fault sweep over death rates,
    seeds, drop rates, ramps, or partition windows shares ONE compiled
    loop per operand structure instead of recompiling per point (the
    operand PR: only the two structure booleans below remain — they
    change the operand COUNT, never carry content).  Every argument is
    hashable (Mesh hashes structurally).  Re-entering the driver with
    the same statics — a sweep server, the RPC sidecar, the multichip
    dryrun's steady pass — reuses the jitted callable instead of
    retracing the whole shard_map program per call (VERDICT r4 task 7:
    driver-level steady timings must be executable-cache hits like
    every other family's).  The plane state is a runtime ARGUMENT, so
    different ``rumors`` shapes share one entry via jit's own cache.
    Convergence/coverage is computed ON DEVICE inside the scan — the
    steady path does no per-round host round-trip.  ``metrics`` bakes
    the round-metrics buffer carry into the program (ops/round_metrics
    — part of the memo key: the instrumented and bare loops are
    different executables).  Mask layouts: churn-free passes
    ``(thr,)`` (plus ``(thr, cov_words)`` under static deaths);
    ``has_churn`` switches to the ``(cov_words, base, die, rec,
    cut_tbl, thr_tbl)`` six-tuple of :func:`_cached_churn_masks` — the
    loop indexes the die/recover/cut/threshold tables by its own
    counter and renders the per-round side-word cut mask in-trace
    (render_cut_words, the alive-word trick extended to cut words),
    while the cond/coverage compare against the EVENTUAL alive
    words."""
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.ops import round_metrics as RM
    from gossip_tpu.ops.pallas_round import render_cut_words
    step = make_sharded_fused_round_masked(
        n, mesh, fanout, interpret,
        has_alive=has_alive or has_churn, has_cut=has_churn)
    rec = _plane_recorder(n, fanout, mesh) if metrics else None

    @functools.partial(jax.jit, donate_argnums=0)
    def scan(planes, *masks):
        if has_churn:
            cov_words, base_w, die_w, rec_w, cut_tbl, thr_tbl = masks
        else:
            thr0 = masks[0]
            cov_words = masks[1] if has_alive else None
        m0 = (RM.init(max_rounds, mesh.shape[AXIS],
                      "simulate_curve_sharded_fused") if rec else None)
        c0 = RM.count_planes(planes) if rec else None

        def body(c, _):
            planes_c, round_c, m, cnt = c
            if has_churn:
                aw = NE.fused_alive_words_at(base_w, die_w, rec_w,
                                             round_c)
                # the ONE clamped steady-row lookup (ops/nemesis._idx)
                planes_n = step(planes_c, seed, round_c, aw,
                                NE._idx(thr_tbl, round_c),
                                render_cut_words(
                                    NE._idx(cut_tbl, round_c), n))
            else:
                planes_n = step(planes_c, seed, round_c, cov_words,
                                thr0)
            if m is not None:
                m, cnt = rec(m, cnt, planes_n)
            return ((planes_n, round_c + 1, m, cnt),
                    coverage_planes_masked(planes_n, n, cov_words))
        (final, _, m, _), covs = jax.lax.scan(
            body, (planes, jnp.int32(0), m0, c0), None,
            length=max_rounds)
        return final, covs, m

    return scan


def _init_and_masks(n: int, rumors: int, run: RunConfig, mesh: Mesh,
                    fault, has_alive: bool, timing,
                    has_churn: bool = False):
    """(init_planes, masks): the cached-jitted state/mask builders shared
    by both simulate drivers.  With a ``timing`` dict the build is
    blocked-on and recorded as ``init_build_s`` — the driver-side
    component of the wall decomposition (backend._timing_meta folds it
    into ``driver_overhead_s``; the dry run reports it per family).
    ``has_churn`` builds the churn mask quadruple instead
    (:func:`_cached_churn_masks`)."""
    t0 = time.perf_counter()
    init = init_plane_state(n, rumors, mesh, run.origin)
    if has_churn:
        masks = _cached_churn_masks(fault, n, run.origin)
    elif has_alive:
        masks = (jnp.asarray(drop_threshold_for(fault), jnp.int32),
                 _cached_alive_words(fault, n, run.origin)())
    else:
        masks = (jnp.asarray(drop_threshold_for(fault), jnp.int32),)
    if timing is not None:
        jax.block_until_ready((init,) + masks)
        timing["init_build_s"] = time.perf_counter() - t0
    return init, masks


def simulate_curve_sharded_fused(n: int, rumors: int, run: RunConfig,
                                 mesh: Mesh, fanout: int = 1,
                                 interpret: bool = False, fault=None,
                                 timing=None):
    """(covs[max_rounds], final_planes): fixed-length scan over the
    plane-sharded round recording per-round min-over-rumors coverage —
    the curve twin of :func:`simulate_until_sharded_fused` (no early
    exit; the caller derives rounds-to-target from the curve).
    ``timing``: optional wall-decomposition dict (utils/trace
    maybe_aot_timed contract — AOT compile/steady split by default,
    ``{"aot": False}`` for a steady-only probe on the cached
    executable; plus ``init_build_s``, see :func:`_init_and_masks`)."""
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.ops import round_metrics as RM
    from gossip_tpu.utils.trace import maybe_aot_timed
    NE.check_supported(fault, engine="fused-planes")
    has_alive = fault is not None and bool(fault.node_death_rate)
    has_churn = NE.get(fault) is not None
    scan = _cached_curve_scan(n, run.seed, run.max_rounds, mesh, fanout,
                              interpret,
                              has_alive, RM.wanted(), has_churn)
    init, masks = _init_and_masks(n, rumors, run, mesh, fault, has_alive,
                                  timing, has_churn)
    final, covs, _ = maybe_aot_timed(scan, timing, init, *masks, label="fused")
    return covs, final


@functools.lru_cache(maxsize=32)
def _cached_until_loop(n: int, seed: int, max_rounds: int,
                       target_coverage: float, mesh: Mesh,
                       fanout: int, interpret: bool,
                       has_alive: bool, metrics: bool = False,
                       has_churn: bool = False):
    """The compiled until-target driver, memoized like
    :func:`_cached_curve_scan` (same key contract and rationale —
    fault content all operands, no fault config in the key — plus
    the target the cond compares against).  Returns ``loop(planes,
    *masks) -> (final_planes, rounds, coverage)`` — the reported
    coverage is computed INSIDE the program through the SAME chooser
    the cond used (one chooser for both, and one executable dispatch
    per steady call instead of loop + separate coverage).  The
    convergence check runs on device inside the while_loop cond; steady
    state does no per-round host round-trip.  ``metrics`` bakes the
    round-metrics buffer carry into the program (part of the memo
    key, as in :func:`_cached_curve_scan`, which also documents
    ``has_churn`` and the mask layouts)."""
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.ops import round_metrics as RM
    from gossip_tpu.ops.pallas_round import render_cut_words
    step = make_sharded_fused_round_masked(
        n, mesh, fanout, interpret,
        has_alive=has_alive or has_churn, has_cut=has_churn)
    target = jnp.float32(target_coverage)
    rec = _plane_recorder(n, fanout, mesh) if metrics else None

    @functools.partial(jax.jit, donate_argnums=0)
    def loop(planes, *masks):
        if has_churn:
            cov_words, base_w, die_w, rec_w, cut_tbl, thr_tbl = masks
        else:
            thr0 = masks[0]
            cov_words = masks[1] if has_alive else None
        m0 = (RM.init(max_rounds, mesh.shape[AXIS],
                      "simulate_until_sharded_fused") if rec else None)
        c0 = RM.count_planes(planes) if rec else None

        def cond(c):
            planes_c, round_c, _, _ = c
            return ((coverage_planes_masked(planes_c, n, cov_words)
                     < target)
                    & (round_c < max_rounds))

        def body(c):
            planes_c, round_c, m, cnt = c
            if has_churn:
                aw = NE.fused_alive_words_at(base_w, die_w, rec_w,
                                             round_c)
                # the ONE clamped steady-row lookup (ops/nemesis._idx)
                planes_n = step(planes_c, seed, round_c, aw,
                                NE._idx(thr_tbl, round_c),
                                render_cut_words(
                                    NE._idx(cut_tbl, round_c), n))
            else:
                planes_n = step(planes_c, seed, round_c, cov_words,
                                thr0)
            if m is not None:
                m, cnt = rec(m, cnt, planes_n)
            return planes_n, round_c + 1, m, cnt

        final, rounds, m, _ = jax.lax.while_loop(
            cond, body, (planes, jnp.int32(0), m0, c0))
        return (final, rounds,
                coverage_planes_masked(final, n, cov_words), m)

    return loop


def simulate_until_sharded_fused(n: int, rumors: int, run: RunConfig,
                                 mesh: Mesh, fanout: int = 1,
                                 interpret: bool = False, fault=None,
                                 timing=None):
    """(rounds, coverage, msgs, final_planes): compiled while_loop to
    min-over-rumors target coverage on the plane-sharded state.

    msgs counts transmissions (request + whole-digest response per
    partner draw, all W words riding one exchange): 2*fanout*n/round.
    ``fault`` threads the static fault masks into every plane's kernel;
    the cond and the reported coverage switch to the alive-weighted
    metric (coverage_planes_masked — one chooser for both).  ``timing``:
    optional wall-decomposition dict (see the curve twin)."""
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.ops import round_metrics as RM
    from gossip_tpu.utils.trace import maybe_aot_timed
    NE.check_supported(fault, engine="fused-planes")
    has_alive = fault is not None and bool(fault.node_death_rate)
    has_churn = NE.get(fault) is not None
    loop = _cached_until_loop(n, run.seed, run.max_rounds,
                              run.target_coverage, mesh, fanout,
                              interpret,
                              has_alive, RM.wanted(), has_churn)
    init, masks = _init_and_masks(n, rumors, run, mesh, fault, has_alive,
                                  timing, has_churn)
    final, rounds, cov, _ = maybe_aot_timed(loop, timing, init, *masks,
                                            label="fused")
    rounds = int(rounds)
    cov = float(cov)
    msgs = 2.0 * fanout * n * rounds
    return rounds, cov, msgs, final
