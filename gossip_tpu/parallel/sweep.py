"""Ensemble sweeps: the data-parallel axis (SURVEY.md §2.3 "DP").

The reference runs one stochastic trajectory per process launch; asking
"how many rounds does this protocol *typically* take?" means re-running the
binary N times.  Here the trajectory ensemble is one ``vmap`` axis: S seeds
run the same jitted round step as a single batched XLA program, so ensemble
statistics (median/quantiles of rounds-to-target, curve bands) cost one
compile and one device pass.  On a mesh this is the second axis of the
north star ("multi-config sweep on a second mesh axis"); single-device it
is plain vmap.

Scope: seed ensembles share one (protocol, topology, fault) config — the
round step is closed over statics, so sweeping *structural* config (mode,
topology family) stays a python loop over compiles (see cli.cmd_sweep).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from gossip_tpu.config import FaultConfig, ProtocolConfig, RunConfig
from gossip_tpu.models.si import coverage, make_si_round
from gossip_tpu.models.state import SimState, alive_mask, init_state
from gossip_tpu.topology.generators import Topology


@dataclasses.dataclass
class EnsembleResult:
    curves: np.ndarray          # float32[S, T] coverage per seed per round
    msgs: np.ndarray            # float32[S, T]
    rounds_to_target: np.ndarray  # int[S], -1 where never reached
    target: float

    @property
    def converged(self) -> np.ndarray:
        return self.rounds_to_target >= 0

    def summary(self) -> dict:
        r = self.rounds_to_target[self.converged]
        return {
            "seeds": int(len(self.rounds_to_target)),
            "converged": int(self.converged.sum()),
            "rounds_mean": float(r.mean()) if len(r) else None,
            "rounds_std": float(r.std()) if len(r) else None,
            "rounds_p50": float(np.median(r)) if len(r) else None,
            "rounds_p95": float(np.percentile(r, 95)) if len(r) else None,
            "final_coverage_mean": float(self.curves[:, -1].mean()),
            "msgs_mean": float(self.msgs[:, -1].mean()),
            "target": self.target,
        }


def ensemble_curves(proto: ProtocolConfig, topo: Topology, run: RunConfig,
                    seeds: Sequence[int],
                    fault: Optional[FaultConfig] = None) -> EnsembleResult:
    """Run |seeds| independent trajectories as ONE batched XLA program."""
    step = make_si_round(proto, topo, fault, run.origin)
    alive = alive_mask(fault, topo.n, run.origin)
    base = init_state(run, proto, topo.n)
    keys = jax.vmap(jax.random.key)(jnp.asarray(list(seeds), jnp.uint32))
    s = len(seeds)
    init = SimState(
        seen=jnp.broadcast_to(base.seen, (s,) + base.seen.shape),
        round=jnp.zeros((s,), jnp.int32),
        base_key=keys,
        msgs=jnp.zeros((s,), jnp.float32),
    )

    @jax.jit
    def scan(states):
        def body(st, _):
            st = jax.vmap(step)(st)
            covs = jax.vmap(lambda x: coverage(x.seen, alive))(st)
            return st, (covs, st.msgs)
        return jax.lax.scan(body, states, None, length=run.max_rounds)

    _, (covs, msgs) = scan(init)
    curves = np.asarray(covs).T          # [S, T]
    msgs_t = np.asarray(msgs).T
    hit = np.full(s, -1, np.int64)
    reached = curves >= run.target_coverage
    any_hit = reached.any(axis=1)
    hit[any_hit] = reached[any_hit].argmax(axis=1) + 1
    return EnsembleResult(curves=curves, msgs=msgs_t,
                          rounds_to_target=hit,
                          target=run.target_coverage)
