"""Ensemble sweeps: the data-parallel axis (SURVEY.md §2.3 "DP").

The reference runs one stochastic trajectory per process launch; asking
"how many rounds does this protocol *typically* take?" means re-running the
binary N times.  Here the trajectory ensemble is one ``vmap`` axis: S seeds
run the same jitted round step as a single batched XLA program, so ensemble
statistics (median/quantiles of rounds-to-target, curve bands) cost one
compile and one device pass.  On a mesh this is the second axis of the
north star ("multi-config sweep on a second mesh axis"); single-device it
is plain vmap.

Two batching axes live here:

* :func:`ensemble_curves` — S seeds of ONE config as a vmap batch (round 1).
* :func:`config_sweep_curves` — a batch of DISTINCT configs in one XLA
  program (round 2, VERDICT item 4): everything that does not change array
  shapes is a traced per-config scalar — (do_push, do_pull) mode flags,
  fanout (as a column mask under a shared k_max draw width), drop_prob,
  anti-entropy period, and seed.  push+pull are both computed and masked by
  the flags, so a mixed-mode batch costs one push-pull round per config —
  the price of one program instead of C compiles.  Only topology family/n,
  rumor count, and death masks stay structural (they change shapes or
  tables).

Round 3 added the TOPOLOGY axis (VERDICT r2 item 6): same-n explicit
families stack into one ``int32[F, n, D_max]`` traced table operand and
each point's ``topo_idx`` dynamic-slices its family — completing the
north star's "sweep fanout, mode, and graph topology" sentence in one
XLA program.

Round 4 batched the N axis too (VERDICT r3 item 6): different-n explicit
entries pad to ``n_max`` with PHANTOM rows (degree 0, sentinel
neighbors, masked out of liveness and coverage), so a families x sizes
grid is ONE program — `grid --family ring --ns 1000 10000` compiles
once (explicit families only — see _stack_topologies).  A point's
curve equals its solo run bitwise on the real prefix (per-node draws
are keyed by global id).

Later in round 4 the RUMOR axis joined them: per-point rumor counts
(``SweepPoint.rumors``) pad the state's R axis to the batch max with
ALL-FALSE phantom columns — never seeded, so they scatter nothing,
gather nothing, and flip no ``sender_active`` bit (msgs and the real
prefix stay bitwise equal to the solo run) — and the coverage min
masks them out per point.  `grid --rumors 1 4` is one program.

Finally, mixed-n IMPLICIT (complete-graph) batches joined too: a
complete graph has no table to stack, so each point's uniform partner
draw is bounded by its own n as a TRACED operand
(ops/sampling.sample_peers_complete) — randint's draw depends only on
the bound's value, so the solo static-bound trajectory reproduces
bitwise.  The one structural split left is implicit-vs-explicit:
stacked tables and traced bounds are different programs, so a batch
must be one kind or the other (each batches fully within its kind).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from gossip_tpu.compat import shard_map
from gossip_tpu import config as C
from gossip_tpu.config import FaultConfig, ProtocolConfig, RunConfig
from gossip_tpu.models import si as si_mod
from gossip_tpu.models.si import coverage, make_si_round
from gossip_tpu.models.state import SimState, alive_mask, init_state
from gossip_tpu.ops import nemesis as NE
from gossip_tpu.ops.propagate import pull_merge, push_counts
from gossip_tpu.ops.sampling import (drop_mask, sample_peers,
                                     sample_peers_complete)
from gossip_tpu.topology.generators import Topology


@dataclasses.dataclass
class EnsembleResult:
    curves: np.ndarray          # float32[S, T] coverage per seed per round
    msgs: np.ndarray            # float32[S, T]
    rounds_to_target: np.ndarray  # int[S], -1 where never reached
    target: float

    @property
    def converged(self) -> np.ndarray:
        return self.rounds_to_target >= 0

    def summary(self) -> dict:
        r = self.rounds_to_target[self.converged]
        return {
            "seeds": int(len(self.rounds_to_target)),
            "converged": int(self.converged.sum()),
            "rounds_mean": float(r.mean()) if len(r) else None,
            "rounds_std": float(r.std()) if len(r) else None,
            "rounds_p50": float(np.median(r)) if len(r) else None,
            "rounds_p95": float(np.percentile(r, 95)) if len(r) else None,
            "final_coverage_mean": float(self.curves[:, -1].mean()),
            "msgs_mean": float(self.msgs[:, -1].mean()),
            "target": self.target,
        }




def _shard_ensemble(init, mesh, axis_name: str, n_seeds: int):
    """Place a stacked ensemble state under a 1-D seed-axis mesh (the
    batch is embarrassingly parallel, like the config sweep's mesh:
    sharding never changes values — pinned in tests).  Scalars-per-seed
    shard on the axis; per-seed arrays shard on their leading dim."""
    if mesh is None:
        return init
    from jax.sharding import NamedSharding, PartitionSpec as P
    if n_seeds % mesh.shape[axis_name] != 0:
        raise ValueError(
            f"{n_seeds} seeds do not divide over the {axis_name} mesh "
            f"axis of size {mesh.shape[axis_name]}; pad the seed list "
            "or change the mesh")
    def place(x):
        spec = P(axis_name, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))
    return jax.tree_util.tree_map(place, init)

def ensemble_curves(proto: ProtocolConfig, topo: Topology, run: RunConfig,
                    seeds: Sequence[int],
                    fault: Optional[FaultConfig] = None, mesh=None,
                    axis_name: str = "seed") -> EnsembleResult:
    """Run |seeds| independent trajectories as ONE batched XLA program.
    ``mesh``: a 1-D device mesh shards the SEED axis (value-invariant,
    embarrassingly parallel — _shard_ensemble).  The SCENARIO-batched
    twin — one seed, K nemesis schedules vmapped through one compiled
    loop — is :func:`churn_sweep_curves`."""
    # tables as jit ARGUMENTS + liveness in-trace: no O(N) closure
    # constants in the compile request (models/swim.py doc)
    step, tables = make_si_round(proto, topo, fault, run.origin, tabled=True)
    # churn-path steps return (state, lost); the ensemble records no
    # per-round observables, so drop the lost count (ops/nemesis)
    step = NE.drop_lost(step, NE.get(fault))
    base = init_state(run, proto, topo.n)
    keys = jax.vmap(jax.random.key)(jnp.asarray(list(seeds), jnp.uint32))
    s = len(seeds)
    init = SimState(
        seen=jnp.broadcast_to(base.seen, (s,) + base.seen.shape),
        round=jnp.zeros((s,), jnp.int32),
        base_key=keys,
        msgs=jnp.zeros((s,), jnp.float32),
    )
    init = _shard_ensemble(init, mesh, axis_name, s)

    @jax.jit
    def scan(states, *tbl):
        # eventual alive set under churn (heal-convergence denominator)
        alive = NE.metric_alive(fault, topo.n, run.origin)
        def body(st, _):
            st = jax.vmap(lambda x: step(x, *tbl))(st)
            covs = jax.vmap(lambda x: coverage(x.seen, alive))(st)
            return st, (covs, st.msgs)
        return jax.lax.scan(body, states, None, length=run.max_rounds)

    _, (covs, msgs) = scan(init, *tables)
    curves = np.asarray(covs).T          # [S, T]
    return EnsembleResult(curves=curves, msgs=np.asarray(msgs).T,
                          rounds_to_target=_rounds_to_target(
                              curves, run.target_coverage),
                          target=run.target_coverage)


@dataclasses.dataclass
class ChurnSweepResult:
    """K nemesis scenarios through ONE compiled loop
    (:func:`churn_sweep_curves`).  ``curves``/``msgs`` are per-scenario
    per-round; ``dropped`` is the kernels' EXACT per-round destroyed-
    message count (drop coins + open cut) — the per-scenario nemesis
    observable the ledger records."""
    faults: tuple                 # the FaultConfigs, batch order
    curves: np.ndarray            # float32[K, T]
    msgs: np.ndarray              # float32[K, T]
    dropped: np.ndarray           # float32[K, T]
    rounds_to_target: np.ndarray  # int[K], -1 where never reached
    target: float

    def summaries(self):
        out = []
        for i, f in enumerate(self.faults):
            ch = f.churn
            out.append({
                "scenario": {"events": list(map(list, ch.events)),
                             "partitions": list(map(list,
                                                    ch.partitions)),
                             "ramp": (list(ch.ramp)
                                      if ch.ramp else None),
                             "drop_prob": f.drop_prob},
                "rounds_to_target": int(self.rounds_to_target[i]),
                "converged": bool(self.rounds_to_target[i] >= 0),
                "final_coverage": float(self.curves[i, -1]),
                "msgs_total": float(self.msgs[i, -1]),
                "dropped_total": float(self.dropped[i].sum()),
            })
        return out


@functools.lru_cache(maxsize=16)
def _cached_churn_sweep_scan(proto: ProtocolConfig, n: int,
                             have_table: bool,
                             fault_static: FaultConfig, origin: int,
                             max_rounds: int):
    """The scenario-batched churn sweep's compiled scan, memoized by
    EXACTLY the statics its trace bakes — schedule CONTENT is a runtime
    operand (ops/nemesis module doc), so every K-scenario family with
    the same static structure re-enters ONE compiled program, and even
    a DIFFERENT scenario stack of the same shapes is an in-process
    executable-cache hit (the _cached_pod_sweep_scan memo discipline).

    The returned callable takes ``(states, alive_stack, *tables)``:
    K-stacked SimState, the per-scenario EVENTUAL-alive coverage
    denominators ``bool[K, n]`` (a function of which churn deaths are
    permanent — content, so an operand), the (unstacked) topology
    tables, and the four stacked schedule operands of
    ``nemesis.build_stack``.  vmap maps the scenario axis through the
    one step; per-scenario trajectories are BITWISE the solo runs
    (same keys — pinned in tests/test_nemesis.py)."""
    rep_fault, topo_ph = NE.placeholder_trace_inputs(fault_static, n,
                                                     have_table)
    step, _ = make_si_round(proto, topo_ph, rep_fault, origin,
                            tabled=True)
    n_topo = 0 if topo_ph.implicit else 2

    def one(st, die, rec_, cut, drop, topo_tbl):
        return step(st, *topo_tbl, die, rec_, cut, drop)

    @jax.jit
    def scan(states, alive_stack, *tbl):
        topo_tbl, sched_tail = tbl[:n_topo], tbl[n_topo:]

        def body(sts, _):
            sts, lost = jax.vmap(
                lambda st, d, r, c, p: one(st, d, r, c, p, topo_tbl)
            )(sts, *sched_tail)
            # the coverage READOUT leaves the device as an EXACT
            # integer: min-over-rumors alive-entry count per scenario
            # (integer sums are order-exact in any lowering, unlike the
            # final division, which XLA fuses to a recip-mul in some
            # contexts and true division in others — a 1-ulp lottery).
            # The driver divides ONCE on the host in float32, which is
            # IEEE true division — bitwise the solo coverage() path.
            cnt = jax.vmap(
                lambda x, al: jnp.min(jnp.sum(
                    x & al[:, None], axis=0, dtype=jnp.int32)))(
                sts.seen, alive_stack)
            return sts, (cnt, sts.msgs, lost)
        return jax.lax.scan(body, states, None, length=max_rounds)
    return scan


def churn_sweep_curves(proto: ProtocolConfig, topo: Topology,
                       run: RunConfig, faults, mesh=None,
                       axis_name: str = "scenario",
                       timing=None) -> ChurnSweepResult:
    """Run K nemesis SCENARIOS — distinct churn/partition/ramp fault
    programs over one protocol config — as ONE batched XLA program:
    the schedule stack (ops/nemesis.build_stack) vmaps through the one
    compiled round loop as a ``[K, ...]`` runtime operand, so the whole
    scenario family costs one compile (and re-entering with a NEW
    family of the same shapes costs none: _cached_churn_sweep_scan).
    This is the Maelstrom move — one binary, every nemesis — for the
    batched simulator.

    Every fault must carry a churn schedule; the STATIC fault structure
    (death mask draw, scripted dead_nodes) must match across the stack
    because the step bakes it — ``drop_prob`` may vary freely (it only
    feeds the per-scenario drop table).  Scenario k's curve equals the
    solo ``simulate_curve(..., fault=faults[k])`` run BITWISE (same
    threefry keys; coverage over the scenario's own eventual-alive
    denominator).

    ``mesh``: a 1-D device mesh shards the SCENARIO axis (value-
    invariant, embarrassingly parallel — _shard_ensemble).  ``timing``:
    optional compile/steady AOT-split dict (utils/trace contract).
    Returns :class:`ChurnSweepResult` (curves / msgs / exact per-round
    ``dropped`` per scenario)."""
    faults = tuple(faults)
    if not faults:
        raise ValueError("need at least one churn FaultConfig")
    statics = {dataclasses.replace(f, churn=None, drop_prob=0.0)
               for f in faults}
    if len(statics) > 1:
        raise ValueError(
            "churn sweep scenarios must share the STATIC fault "
            "structure (node_death_rate/seed/dead_nodes are baked into "
            "the one compiled step); vary the churn schedule and "
            "drop_prob only")
    stack = NE.build_stack(faults, topo.n)       # validates churn too
    k = len(faults)
    # drop_prob is stripped from the memo key like the schedule: it
    # only feeds the per-scenario drop_tbl operand, never the trace
    scan = _cached_churn_sweep_scan(
        proto, topo.n, not topo.implicit,
        dataclasses.replace(faults[0], churn=None, drop_prob=0.0),
        run.origin, run.max_rounds)
    alive_stack = jnp.stack(
        [NE.eventual_alive(f, topo.n, run.origin) for f in faults])
    base = init_state(run, proto, topo.n)
    keys = jax.vmap(jax.random.key)(
        jnp.full((k,), run.seed, jnp.uint32))
    init = SimState(
        seen=jnp.broadcast_to(base.seen, (k,) + base.seen.shape),
        round=jnp.zeros((k,), jnp.int32),
        base_key=keys,
        msgs=jnp.zeros((k,), jnp.float32),
    )
    init = _shard_ensemble(init, mesh, axis_name, k)
    sched_ops = NE.sched_args(stack)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        put = lambda x: jax.device_put(                   # noqa: E731
            x, NamedSharding(mesh, P(axis_name,
                                     *([None] * (x.ndim - 1)))))
        alive_stack = put(alive_stack)
        sched_ops = tuple(put(x) for x in sched_ops)
    topo_tbl = () if topo.implicit else (topo.nbrs, topo.deg)
    from gossip_tpu.utils.trace import maybe_aot_timed
    _, (cnts, msgs, lost) = maybe_aot_timed(
        scan, timing, init, alive_stack, *topo_tbl, *sched_ops, label="sweep")
    # one true f32 division per cell (the scan emits exact integer
    # counts — see _cached_churn_sweep_scan's readout comment)
    denom = np.asarray(alive_stack.sum(axis=1)).astype(np.float32)
    curves = (np.asarray(cnts).T.astype(np.float32)
              / np.maximum(denom, 1.0)[:, None])
    return ChurnSweepResult(faults=faults, curves=curves,
                            msgs=np.asarray(msgs).T,
                            dropped=np.asarray(lost).T,
                            rounds_to_target=_rounds_to_target(
                                curves, run.target_coverage),
                            target=run.target_coverage)


@dataclasses.dataclass
class FusedChurnSweepResult:
    """K nemesis scenarios through the plane-sharded FUSED engine
    (:func:`fused_churn_sweep_curves`).  ``msgs`` is the fused
    accounting's closed form (2*fanout*n per round, every scenario —
    request+digest transmissions, dropped and dead-partner pulls
    counted like the solo fused drivers); there is no ``dropped``
    column because the fused kernels do not materialize per-round
    destroyed-message counts (the drop coin is resolved inside the
    kernel) — an honest absence, not a zero."""
    faults: tuple                 # the FaultConfigs, batch order
    curves: np.ndarray            # float32[K, T]
    msgs: np.ndarray              # float32[K, T]
    rounds_to_target: np.ndarray  # int[K], -1 where never reached
    target: float

    def summaries(self):
        out = []
        for i, f in enumerate(self.faults):
            ch = f.churn
            out.append({
                "scenario": {"events": list(map(list, ch.events)),
                             "partitions": list(map(list,
                                                    ch.partitions)),
                             "ramp": (list(ch.ramp)
                                      if ch.ramp else None),
                             "drop_prob": f.drop_prob},
                "rounds_to_target": int(self.rounds_to_target[i]),
                "converged": bool(self.rounds_to_target[i] >= 0),
                "final_coverage": float(self.curves[i, -1]),
                "msgs_total": float(self.msgs[i, -1]),
            })
        return out


def fused_churn_sweep_curves(n: int, rumors: int, run: RunConfig,
                             faults, mesh, fanout: int = 1,
                             interpret: bool = False,
                             timing=None) -> FusedChurnSweepResult:
    """Run K nemesis SCENARIOS — distinct churn/partition/ramp fault
    programs — through the plane-sharded FUSED Pallas engine for the
    cost of ONE compile.  The fused scenario batch amortizes by
    EXECUTABLE REUSE, not vmap: the memoized fused curve scan
    (parallel/sharded_fused._cached_curve_scan) keys WITHOUT the fault
    config — every scenario's schedule lowers to runtime operands (the
    per-round alive words, the partition cut table rendered to
    side-word masks in-trace, and the 20-bit drop-threshold table the
    SMEM scalar is indexed from) — so scenario 0 compiles the loop and
    scenarios 1..K-1 re-enter the same executable (compile-count
    pinned in tests/test_sharded_fused.py; a vmapped scenario axis is
    not a lowering the plane-sharded pallas_call program has, and the
    plane axis already occupies the mesh).

    Every fault must carry a churn schedule and the STATIC fault
    structure must match across the stack (the churn_sweep_curves
    contract: ``drop_prob`` may vary freely — it only moves the
    threshold table).  Scenario k's curve IS the solo
    ``simulate_curve_sharded_fused(..., fault=faults[k])`` run — the
    sweep calls exactly that driver, so per-scenario bitwise solo
    parity holds by construction (still pinned in tests, against
    drift).  ``timing`` (utils/trace contract) decomposes scenario 0
    only — the compile-bearing entry; later scenarios are steady
    re-entries by definition."""
    from gossip_tpu.parallel.sharded_fused import (
        simulate_curve_sharded_fused)
    faults = tuple(faults)
    if not faults:
        raise ValueError("need at least one churn FaultConfig")
    for f in faults:
        if NE.get(f) is None:
            raise ValueError(
                "fused churn sweep scenarios must each carry a churn "
                "schedule (static-only faults run the plain fused "
                "curve driver)")
        NE.check_supported(f, engine="fused-planes")
    statics = {dataclasses.replace(f, churn=None, drop_prob=0.0)
               for f in faults}
    if len(statics) > 1:
        raise ValueError(
            "churn sweep scenarios must share the STATIC fault "
            "structure (node_death_rate/seed/dead_nodes select the "
            "mask operand layout); vary the churn schedule and "
            "drop_prob only")
    curves = []
    for i, f in enumerate(faults):
        covs, _ = simulate_curve_sharded_fused(
            n, rumors, run, mesh, fanout=fanout, fault=f,
            interpret=interpret, timing=timing if i == 0 else None)
        curves.append(np.asarray(covs))
    curves = np.stack(curves)
    per_round = 2.0 * fanout * n
    msgs = np.broadcast_to(
        per_round * np.arange(1, run.max_rounds + 1, dtype=np.float32),
        curves.shape).copy()
    return FusedChurnSweepResult(
        faults=faults, curves=curves, msgs=msgs,
        rounds_to_target=_rounds_to_target(curves,
                                           run.target_coverage),
        target=run.target_coverage)


# ---------------------------------------------------------------------------
# Request-batched serving (the admission batcher's megabatch driver,
# rpc/batcher): K heterogeneous REQUESTS — distinct (mode, fanout-shared,
# drop, period, seed, origin, target, n-within-bucket, rumors, static
# fault, churn schedule) — through ONE compiled scan.  This generalizes
# churn_sweep_curves (one proto, K schedules) to per-request protocol
# operands, and config_sweep_curves (K protos, no schedules) to
# per-request nemesis schedule stacks.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RequestSpec:
    """One serving request's simulation config, megabatch-shaped.

    The batch-key contract (rpc/batcher module doc): everything in here
    EXCEPT ``proto.fanout``, ``proto.exclude_self``, ``run.max_rounds``
    and the topology/n-bucket is a runtime OPERAND of the one compiled
    scan — mode flags, period, seed, origin, target, drop probability,
    the static death mask, and the whole churn schedule all vary freely
    within a batch without retracing.  ``fanout`` is the shared draw
    width because trajectories are a function of (config, draw width):
    only fanout == k reproduces the solo run bitwise (the
    config_sweep_curves k_max contract), and serving promises bitwise
    solo parity."""
    proto: ProtocolConfig
    run: RunConfig
    fault: Optional[FaultConfig]
    n: int

    def __post_init__(self):
        if self.proto.mode not in _MODE_FLAGS:
            raise ValueError(
                f"request batching supports {sorted(_MODE_FLAGS)}; got "
                f"{self.proto.mode!r} (flood/swim/rumor change the round "
                "structure — dispatch them solo)")
        if not self.proto.exclude_self:
            raise ValueError("request batching samples with the shared "
                             "exclude_self=True contract")
        if self.proto.period > 1 and self.proto.mode != C.ANTI_ENTROPY:
            raise ValueError("period > 1 is the anti-entropy cadence")
        if self.n < 2:
            raise ValueError("request batching needs n >= 2 (the traced "
                             "peer bound's self-exclusion shift)")


@dataclasses.dataclass
class RequestSweepResult:
    """K requests through one compiled scan: stacked per-round buffers
    plus the per-request readouts split back out of them
    (:meth:`metrics_rows`).  ``curves``/``msgs``/``dropped`` are
    [K, T]; ``state_digests`` are sha256 hexes of each request's final
    ``seen`` block truncated to its OWN (n, rumors) — bitwise the solo
    run's final state (pinned in tests/test_serving.py)."""
    specs: tuple
    curves: np.ndarray            # float32[K, T]
    msgs: np.ndarray              # float32[K, T]
    dropped: np.ndarray           # float32[K, T]
    rounds_to_target: np.ndarray  # int[K], -1 where never reached
    state_digests: tuple          # str[K]

    def metrics_rows(self):
        """Per-request round-metrics rows split out of the stacked
        buffers — the serving reply's observability payload (coverage
        curve, cumulative msgs, exact per-round destroyed-message
        counts) in ledger-friendly plain lists."""
        out = []
        for i, spec in enumerate(self.specs):
            out.append({
                "mode": spec.proto.mode, "n": spec.n,
                "rounds": int(self.curves.shape[1]),
                "coverage": [float(c) for c in self.curves[i]],
                "msgs": [float(m) for m in self.msgs[i]],
                "dropped": [float(d) for d in self.dropped[i]],
                "dropped_total": float(self.dropped[i].sum()),
                "rounds_to_target": int(self.rounds_to_target[i]),
            })
        return out


def _pow2_at_least(x: int, lo: int = 1) -> int:
    """The smallest power of two >= max(x, lo) — the serving bucket
    function (n-bucket, rumor bucket, batch-lane bucket)."""
    x = max(int(x), lo)
    return 1 << (x - 1).bit_length()


@functools.lru_cache(maxsize=16)
def _cached_request_sweep_scan(n_pad: int, k: int, r_max: int,
                               have_table: bool, need_push: bool,
                               need_pull: bool, have_ae: bool,
                               max_rounds: int):
    """The request megabatch's compiled scan, memoized by EXACTLY the
    statics its trace bakes: the pow2 n-bucket, the shared draw width
    ``k``, the rumor bucket, implicit-vs-table, the batch's
    half-elision switches, and the scan length.  Everything
    request-specific — mode flags, period, seed keys, per-request n
    and rumor count, static alive masks, metric denominators, and the
    four stacked nemesis schedule tables — arrives as runtime
    operands, so K compatible requests compile ONCE and every later
    same-bucket batch re-enters the executable (compile-count pinned
    in tests/test_serving.py, the _cached_churn_sweep_scan memo
    discipline).

    The callable takes ``(seen0, keys, msgs0, do_push, do_pull, do_ae,
    period, n_pt, r_pt, base_alive, metric_alive, die, rec, cut_tbl,
    drop_tbl, *topo_tables)`` — all leading-[K] stacks except the
    shared topology tables — and returns ``(final_seen, counts, msgs,
    lost)`` with [T, K] per-round buffers.  The coverage readout
    leaves the device as an EXACT integer count per request (the
    _cached_churn_sweep_scan rationale: integer sums are order-exact;
    the one division happens per request on the host, emulating the
    solo path's own lowering — see request_sweep_curves)."""
    if have_table:
        topo_ph = Topology(nbrs=jnp.zeros((0, 0), jnp.int32),
                           deg=jnp.zeros((0,), jnp.int32), n=n_pad,
                           family="placeholder")
    else:
        topo_ph = Topology(nbrs=None, deg=None, n=n_pad,
                           family="complete")
    colr = jnp.arange(r_max, dtype=jnp.int32)

    def one_req(seen, round_, base_key, msgs, do_push, do_pull, do_ae,
                period, n_pt, r_pt, base_alive, metric_alive,
                die, rec_, cut_row, drop_row, topo_tbl):
        nbrs, deg = topo_tbl if topo_tbl else (None, None)
        gids = jnp.arange(n_pad, dtype=jnp.int32)
        r = jnp.asarray(round_, jnp.int32)
        # per-round liveness / cut / drop from the request's OWN
        # schedule operands — the clamped steady-row lookup
        # (ops/nemesis._idx semantics, inlined over the [K, T] stack)
        down = (die <= r) & (r < rec_)
        alive = base_alive & ~down
        idx = jnp.minimum(jnp.maximum(r, 0), cut_row.shape[0] - 1)
        cut = cut_row[idx]
        dp = drop_row[idx]
        rkey = jax.random.fold_in(base_key, r)
        visible = seen & alive[:, None]
        delta, msgs_r, lost = _sweep_round_delta(
            rkey, r, gids, visible, alive, topo_ph, k, nbrs, deg,
            do_push, do_pull, do_ae, jnp.int32(k), dp, period, have_ae,
            scatter_n=n_pad, count_reduce=lambda c: c,
            gather=lambda v: v, need_push=need_push,
            need_pull=need_pull,
            peer_bound=(None if have_table else n_pt),
            cut=cut, want_lost=True)
        seen = seen | delta
        # integer coverage count: min over the request's REAL rumor
        # columns of its metric-alive entry count (phantom columns are
        # all-false and would win an unmasked min)
        cnt_r = jnp.sum(seen & metric_alive[:, None], axis=0,
                        dtype=jnp.int32)
        cnt = jnp.min(jnp.where(colr < r_pt, cnt_r,
                                jnp.int32(n_pad + 1)))
        return seen, msgs + msgs_r, cnt, lost

    @jax.jit
    def scan(seen0, seeds, msgs0, do_push, do_pull, do_ae, period,
             n_pt, r_pt, base_alive, metric_alive, die, rec_, cut_tbl,
             drop_tbl, *table):
        # key derivation INSIDE the compiled program: a host-side
        # vmapped jax.random.key over K seeds would be a fresh tiny
        # XLA program per distinct K — serving ticks vary K, and
        # steady-state serving must never compile.  Same key values as
        # the solo init_state (jax.random.key(seed)) by construction.
        keys = jax.vmap(jax.random.key)(seeds)

        def body(carry, round_):
            seen, msgs = carry
            seen, msgs, cnts, lost = jax.vmap(
                lambda s, key, m, a, b, c, p, npt, rpt, ba, ma, di, re,
                cu, dr: one_req(s, round_, key, m, a, b, c, p, npt,
                                rpt, ba, ma, di, re, cu, dr, table)
            )(seen, keys, msgs, do_push, do_pull,
              do_ae, period, n_pt, r_pt, base_alive, metric_alive,
              die, rec_, cut_tbl, drop_tbl)
            return (seen, msgs), (cnts, msgs, lost)
        (seen_f, _), out = jax.lax.scan(
            body, (seen0, msgs0),
            jnp.arange(max_rounds, dtype=jnp.int32))
        return (seen_f,) + out
    return scan


def request_sweep_curves(specs, topo: Optional[Topology] = None,
                         n_pad: Optional[int] = None, mesh=None,
                         axis_name: str = "request", lanes=None,
                         full: bool = False,
                         timing=None) -> RequestSweepResult:
    """Run K heterogeneous serving REQUESTS as ONE batched XLA program
    — the megabatch the admission batcher (rpc/batcher) dispatches per
    tick.  Every request's (mode, drop, period, seed, origin, target,
    static fault, churn schedule, n-within-bucket, rumors-within-
    bucket) is a runtime operand; the compiled scan is shared by the
    whole bucket (see :func:`_cached_request_sweep_scan` for the
    memo-key vs operand split, and docs/SERVING.md for the table).

    Bitwise contract (pinned in tests/test_serving.py): request i's
    coverage curve, cumulative msgs, rounds-to-target, and final seen
    state equal its SOLO ``runtime/simulator.simulate_curve`` dispatch
    byte for byte — same threefry streams (draws keyed by global id,
    so pow2 row padding is inert), same drop/cut order, and a host
    readout that emulates the solo coverage division exactly (the
    no-fault solo path lowers mean() as a recip-mul; the
    fault/churn-weighted path as a true division — both measured on
    this toolchain and reproduced per request below).

    ``topo``: None = the implicit complete family (requests may differ
    in n within the pow2 ``n_pad`` bucket — phantom rows are inert by
    the config_sweep ragged contract); a Topology = one shared
    explicit table (every request's n must equal it).  ``lanes`` pads
    the batch to a pow2 lane count with inert all-masked dummies so
    every batch size in a bucket shares one executable.  ``mesh``: an
    optional 1-D mesh shards the request axis (value-invariant,
    embarrassingly parallel — _shard_ensemble)."""
    specs = tuple(specs)
    if not specs:
        raise ValueError("need at least one RequestSpec")
    kset = {sp.proto.fanout for sp in specs}
    if len(kset) > 1:
        raise ValueError(
            f"request batch mixes fanouts {sorted(kset)}: the draw "
            "width is the one static the solo-bitwise contract pins "
            "(group by fanout in the batch key)")
    k = kset.pop()
    mrset = {sp.run.max_rounds for sp in specs}
    if len(mrset) > 1:
        raise ValueError(
            f"request batch mixes max_rounds {sorted(mrset)}: the scan "
            "length is static (group by max_rounds in the batch key)")
    max_rounds = mrset.pop()
    have_table = topo is not None
    if have_table:
        bad = [sp.n for sp in specs if sp.n != topo.n]
        if bad:
            raise ValueError(
                f"explicit-table requests must match the shared "
                f"topology's n={topo.n}; got {bad}")
        if n_pad is not None and n_pad != topo.n:
            raise ValueError("explicit-table batches keep n_pad == n")
        n_pad = topo.n
    else:
        want = _pow2_at_least(max(sp.n for sp in specs), 2)
        n_pad = want if n_pad is None else n_pad
        if n_pad < want:
            raise ValueError(f"n_pad={n_pad} below the batch's pow2 "
                             f"bucket {want}")
    r_max = _pow2_at_least(max(sp.proto.rumors for sp in specs))
    kN = len(specs)
    lanes = _pow2_at_least(kN) if lanes is None else lanes
    if lanes < kN:
        raise ValueError(f"lanes={lanes} below the batch size {kN}")
    # half-elision switches are batch-COMPOSITION statics; ``full=True``
    # (the serving batcher) pins all three ON so every tick of a bucket
    # shares ONE executable regardless of which modes happened to
    # coalesce — a masked absent half is bitwise inert (the disjoint-
    # RNG-tag elision contract in _sweep_round_delta), and steady-state
    # serving must never compile because a mode combination was new
    need_push = full or any(_MODE_FLAGS[sp.proto.mode][0]
                            for sp in specs)
    need_pull = full or any(_MODE_FLAGS[sp.proto.mode][1]
                            for sp in specs)
    have_ae = full or any(sp.proto.mode == C.ANTI_ENTROPY
                          for sp in specs)

    # -- per-request operand stacks (host-side; all CONTENT) ----------
    seen0 = np.zeros((lanes, n_pad, r_max), np.bool_)
    base_alive = np.zeros((lanes, n_pad), np.bool_)
    metric_alive = np.zeros((lanes, n_pad), np.bool_)
    weighted = []
    denoms = []
    from gossip_tpu.models.state import alive_mask
    for i, sp in enumerate(specs):
        # models/state.init_state's seeding formula (rumor r starts at
        # (origin + r) % n) in numpy — a jitted init per distinct
        # origin would be a tiny compile per request content
        cols = np.arange(sp.proto.rumors)
        seen0[i, (sp.run.origin + cols) % sp.n, cols] = True
        # fault-free requests (the common serving case) assemble their
        # masks with ZERO jax work — a jnp.ones per new n-within-bucket
        # would compile inside the serving window.  Fault-bearing masks
        # stay jax-side on purpose: the bernoulli death draw IS the
        # value the bitwise contract pins, and its tiny programs are
        # shape-keyed (warmed by the mix's first occurrence).
        am = alive_mask(sp.fault, sp.n, sp.run.origin)
        base_alive[i, :sp.n] = True if am is None else np.asarray(am)
        ma = NE.metric_alive(sp.fault, sp.n, sp.run.origin)
        weighted.append(ma is not None)
        if ma is None:
            metric_alive[i, :sp.n] = True
            denoms.append(float(sp.n))
        else:
            ma = np.asarray(ma)
            metric_alive[i, :sp.n] = ma
            denoms.append(float(ma.sum()))
    sched = NE.build_request_stack(
        [sp.fault for sp in specs], [sp.n for sp in specs], n_pad)
    # all remaining operand assembly is NUMPY by design: the lane
    # count varies tick to tick in serving, and any jnp op over a
    # K-sized input is a fresh tiny XLA program per distinct K —
    # steady-state serving assembles content with ZERO compiles (the
    # load-harness all-warm gate; only the memoized scan itself is a
    # compiled program, shared per bucket)
    pad = lanes - kN
    if pad:
        sched = NE.Schedule(
            die=np.concatenate([sched.die, np.full(
                (pad, n_pad), NE.NEVER, np.int32)]),
            rec=np.concatenate([sched.rec, np.full(
                (pad, n_pad), NE.NEVER, np.int32)]),
            cut_tbl=np.concatenate([sched.cut_tbl, np.full(
                (pad, sched.cut_tbl.shape[1]), -1, np.int32)]),
            drop_tbl=np.concatenate([sched.drop_tbl, np.zeros(
                (pad, sched.drop_tbl.shape[1]), np.float32)]))
    seeds = np.asarray([sp.run.seed for sp in specs] + [0] * pad,
                       np.uint32)

    def vec(fn, dtype, dummy):
        return np.asarray([fn(sp) for sp in specs] + [dummy] * pad,
                          dtype)

    # dummy lanes are fully inert: no half enabled, all-dead masks —
    # their draws exist but their deltas/counts are discarded
    do_push = vec(lambda sp: _MODE_FLAGS[sp.proto.mode][0], np.bool_,
                  False)
    do_pull = vec(lambda sp: _MODE_FLAGS[sp.proto.mode][1], np.bool_,
                  False)
    do_ae = vec(lambda sp: sp.proto.mode == C.ANTI_ENTROPY, np.bool_,
                False)
    period = vec(lambda sp: sp.proto.period, np.int32, 1)
    n_pt = vec(lambda sp: sp.n, np.int32, 2)
    r_pt = vec(lambda sp: sp.proto.rumors, np.int32, 1)

    scan = _cached_request_sweep_scan(n_pad, k, r_max, have_table,
                                      need_push, need_pull, have_ae,
                                      max_rounds)
    ops = [seen0, seeds,
           np.zeros((lanes,), np.float32), do_push, do_pull, do_ae,
           period, n_pt, r_pt, base_alive,
           metric_alive] + list(NE.sched_args(sched))
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        if lanes % mesh.shape[axis_name] != 0:
            raise ValueError(
                f"{lanes} request lanes do not divide over the "
                f"{axis_name} mesh axis of size "
                f"{mesh.shape[axis_name]}")
        ops = [jax.device_put(x, NamedSharding(
            mesh, P(axis_name, *([None] * (x.ndim - 1))))) for x in ops]
    topo_tbl = (topo.nbrs, topo.deg) if have_table else ()
    from gossip_tpu.utils.trace import maybe_aot_timed
    seen_f, cnts, msgs, lost = maybe_aot_timed(scan, timing, *ops,
                                               *topo_tbl, label="sweep")

    # -- per-request readouts split back out of the stacked buffers --
    cnts = np.asarray(cnts).T[:kN]       # [K, T] exact integers
    msgs = np.asarray(msgs).T[:kN]
    lost = np.asarray(lost).T[:kN]
    seen_f = np.asarray(seen_f)
    curves = np.empty_like(cnts, dtype=np.float32)
    rtt = np.full(kN, -1, np.int64)
    digests = []
    import hashlib
    for i, sp in enumerate(specs):
        c = cnts[i].astype(np.float32)
        if weighted[i]:
            # the solo weighted readout is a true f32 division
            # (coverage()'s sum/w.sum() — measured lowering)
            curves[i] = c / np.float32(denoms[i])
        else:
            # the solo no-fault readout is jnp.mean, which lowers as a
            # reciprocal MULTIPLY (measured; true division differs by
            # 1 ulp on some counts) — emulate it exactly
            curves[i] = c * (np.float32(1.0) / np.float32(denoms[i]))
        hit = np.nonzero(curves[i] >= sp.run.target_coverage)[0]
        rtt[i] = int(hit[0]) + 1 if len(hit) else -1
        block = np.ascontiguousarray(
            seen_f[i, :sp.n, :sp.proto.rumors])
        digests.append(hashlib.sha256(block.tobytes()).hexdigest())
    return RequestSweepResult(specs=specs, curves=curves, msgs=msgs,
                              dropped=lost, rounds_to_target=rtt,
                              state_digests=tuple(digests))


@functools.lru_cache(maxsize=16)
def _cached_pod_sweep_scan(n: int, n_pad: int, nl: int, k_max: int,
                           have_ae: bool, need_push: bool, need_pull: bool,
                           multi: bool, have_table: bool, max_rounds: int,
                           origin: int, mesh, fault_static,
                           sweep_axis: str, node_axis: str):
    """The 2-D pod sweep's compiled scan, memoized by EXACTLY the
    statics its trace bakes in — max_rounds and origin, not the whole
    RunConfig, whose unused fields (seed: the sweep's seeds are
    per-point runtime operands) would fragment the cache (VERDICT r4
    task 7: re-entering the driver must be an executable-cache hit,
    not a whole-program retrace).

    Every array the trajectories depend on — seen blocks, seeds, the
    per-point flag vectors, and the (possibly family-stacked) topology
    tables — flows through the returned callable as a runtime ARGUMENT;
    the only topology facts baked into the trace are ``n`` and
    implicit-vs-table, which are part of this key.  The table branch
    gets a shape-empty placeholder whose ``.implicit`` is False so
    ``sample_peers`` dispatches to the table path (its row data always
    comes from the ``local_nbrs``/``local_deg`` arguments)."""
    from jax.sharding import PartitionSpec as P

    from gossip_tpu.parallel.sharded import sharded_alive
    if have_table:
        topo_ph = Topology(nbrs=jnp.zeros((0, 0), jnp.int32),
                           deg=jnp.zeros((0,), jnp.int32), n=n,
                           family="placeholder")
    else:
        topo_ph = Topology(nbrs=None, deg=None, n=n, family="complete")

    def one_cfg_round(seen_l, round_, base_key, msgs,
                      do_push, do_pull, do_ae, fanout, dropp, period,
                      tidx, nbrs_l, deg_l):
        """One config's round on this node shard ([nl, R] rows)."""
        if multi:
            # per-config family slice of the node-sharded stack
            nbrs_l, deg_l = nbrs_l[tidx], deg_l[tidx]
        shard = jax.lax.axis_index(node_axis)
        gids = shard * nl + jnp.arange(nl, dtype=jnp.int32)
        # fault_static by name: the grid sweeps reject churn schedules
        # upstream (check_supported events=False), so this key carries
        # no schedule content — the staticcheck content-in-memo-key
        # naming contract (gossip_tpu/analysis/recompile.py)
        alive_l = sharded_alive(fault_static, n, n_pad, origin)[gids]
        rkey = jax.random.fold_in(base_key, round_)
        visible = seen_l & alive_l[:, None]

        def count_reduce(counts):
            # psum + own slice rather than psum_scatter: this runs under
            # vmap over the local configs
            full = jax.lax.psum(counts, node_axis)
            return jax.lax.dynamic_slice_in_dim(full, shard * nl, nl, 0)

        delta, msgs_round = _sweep_round_delta(
            rkey, round_, gids, visible, alive_l, topo_ph, k_max,
            nbrs_l, deg_l, do_push, do_pull, do_ae, fanout, dropp, period,
            have_ae, scatter_n=n_pad, count_reduce=count_reduce,
            gather=lambda v: jax.lax.all_gather(v, node_axis, tiled=True),
            need_push=need_push, need_pull=need_pull)
        seen_new = seen_l | delta
        msgs_new = msgs + jax.lax.psum(msgs_round, node_axis)

        # coverage on-device (min over rumors of alive-weighted fraction)
        w = alive_l.astype(jnp.float32)
        cnt = jax.lax.psum(jnp.sum(seen_new * w[:, None], axis=0),
                           node_axis)                           # [R]
        denom = jax.lax.psum(jnp.sum(w), node_axis)
        cov = jnp.min(cnt / jnp.maximum(denom, 1.0))
        return seen_new, msgs_new, cov

    def local_block(seen_b, round_, keys_b, msgs_b,
                    dpush_b, dpull_b, dae_b, fan_b, drop_b, per_b, tidx_b,
                    *table):
        nbrs_l, deg_l = table if table else (None, None)
        return jax.vmap(
            lambda s, key, m, a, b, c, f, d, p, t: one_cfg_round(
                s, round_, key, m, a, b, c, f, d, p, t, nbrs_l, deg_l)
        )(seen_b, keys_b, msgs_b, dpush_b, dpull_b, dae_b, fan_b, drop_b,
          per_b, tidx_b)

    sw = P(sweep_axis)
    in_specs = [P(sweep_axis, node_axis, None), P(), sw, sw,
                sw, sw, sw, sw, sw, sw, sw]
    if multi:
        in_specs += [P(None, node_axis, None), P(None, node_axis)]
    elif have_table:
        in_specs += [P(node_axis, None), P(node_axis)]
    mapped = shard_map(local_block, mesh=mesh,
                           in_specs=tuple(in_specs),
                           out_specs=(P(sweep_axis, node_axis, None), sw,
                                      sw))

    @jax.jit
    def scan(seen, keys, msgs, *args):
        flags_, tbl = args[:7], args[7:]
        def body(carry, round_):
            seen, msgs = carry
            seen, msgs, covs = mapped(seen, round_, keys, msgs, *flags_,
                                      *tbl)
            return (seen, msgs), (covs, msgs)
        return jax.lax.scan(body, (seen, msgs),
                            jnp.arange(max_rounds, dtype=jnp.int32))

    return scan


def _pod_sweep_cache_stats(info, before=None) -> tuple:
    """(gauges, evicting) from ``lru_cache.cache_info()`` snapshots:
    the telemetry view of the pod-sweep scan memo.  ``evicting`` is
    the per-call thrash signature — THIS call missed (``misses`` grew
    past ``before``'s) while the memo was already full, so lru_cache
    evicted an entry to admit the new scan and some earlier shape's
    re-entry will now recompile the whole shard_map program.  Judged
    from the delta, not cumulative totals: a process that has seen 17
    distinct shapes over its lifetime is not thrashing when a later
    memo-hit sweep runs.  Pure function of the info tuples so the
    predicate is unit-testable without 17 real compiles."""
    gauges = {"pod_sweep_scan_cache_hits": info.hits,
              "pod_sweep_scan_cache_misses": info.misses,
              "pod_sweep_scan_cache_size": info.currsize,
              "pod_sweep_scan_cache_maxsize": info.maxsize}
    evicting = (before is not None
                and info.maxsize is not None
                and info.misses > before.misses
                and before.currsize >= info.maxsize)
    return gauges, evicting


def _emit_pod_sweep_cache_telemetry(before) -> None:
    """Sweep-end cache telemetry (the compile-once PR): gauges for the
    memoized scan's hit/miss/size, and a ``sweep_cache_eviction``
    warning event when this sweep's scan displaced a cached one — a
    grid of more than the memo's 16 distinct shape keys used to thrash
    and recompile silently.  ``before`` is the cache_info snapshot the
    sweep took before building its scan."""
    from gossip_tpu.utils import telemetry
    led = telemetry.current()
    gauges, evicting = _pod_sweep_cache_stats(
        _cached_pod_sweep_scan.cache_info(), before)
    # sync=False throughout: this emitter runs INSIDE whatever wall
    # the caller is timing around the sweep (the dry run's
    # hybrid_2d_sweep windows) — flush-only, no fsync latency in a
    # measured steady_ms (the driver_timing contract, utils/trace)
    for name, value in gauges.items():
        led.gauge(name, value, sync=False)
    if evicting:
        led.event(
            "sweep_cache_eviction", sync=False,
            **gauges,
            note="grid exceeds the pod-sweep scan memo (maxsize=16 "
                 "distinct shape keys): some re-entries recompile the "
                 "whole shard_map program; split the grid by shape or "
                 "raise _cached_pod_sweep_scan's maxsize")


def config_sweep_curves_2d(points, topo, run: RunConfig,
                           mesh, fault: Optional[FaultConfig] = None,
                           k_max: Optional[int] = None, rumors: int = 1,
                           sweep_axis: str = "sweep",
                           node_axis: str = "nodes",
                           timing=None) -> ConfigSweepResult:
    """The north star's full 2-D pod sweep: distinct configs sharded over
    ``sweep_axis`` AND every config's node dimension sharded over
    ``node_axis`` — one ``shard_map`` over a 2-D mesh, one XLA program.

    The config axis is embarrassingly parallel; the node axis uses the
    dense collectives of parallel/sharded.py (``psum`` count reduction,
    ``all_gather`` pull digests) *under vmap* — each device holds a
    ``[C_local, nl, R]`` block and the collectives batch over its local
    configs.  Same trajectory definition as :func:`config_sweep_curves`
    (same RNG keying by global node id, same shared-``k_max`` draw widths),
    so results are identical to the 1-D batch for any mesh shape.

    ``topo`` may be a SEQUENCE of same-n explicit topologies, exactly as
    in :func:`config_sweep_curves`: families stack into one
    ``int32[F, n_pad, D_max]`` operand whose ROWS shard over
    ``node_axis``, and each point's ``topo_idx`` dynamic-slices its
    family — the complete "sweep fanout, mode, and graph topology across
    a TPU pod" program.

    ``timing``: optional wall-decomposition dict (utils/trace
    .maybe_aot_timed contract) — the AOT path additionally routes the
    scan's compile through the GOSSIP_COMPILE_CACHE executable store
    (``timing["compile_cache"]`` records hit|miss|disabled), making
    the pod sweep warm-startable across processes like the other
    sharded drivers.  Sweep-end telemetry always reports the scan
    memo's hit/miss gauges and warns when the grid exceeded its 16
    shape keys (:func:`_emit_pod_sweep_cache_telemetry`).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P
    from gossip_tpu.parallel.sharded import _pad_rows, pad_to_mesh
    points = tuple(points)
    if not points:
        raise ValueError("need at least one SweepPoint")
    if fault is not None and fault.drop_prob > 0.0:
        raise ValueError("per-config loss goes through SweepPoint.drop_prob;"
                         " FaultConfig.drop_prob would be ambiguous here")
    # the grid round body is its own lowering (no churn path yet):
    # reject a schedule loudly rather than silently running static-only
    NE.check_supported(fault, engine="config-sweep", events=False,
                       partitions=False, ramp=False)
    topos, multi, topo0 = _normalize_topos(topo, points)
    if multi and any(t.n != topo0.n for t in topos):
        raise ValueError(
            "the 2-D pod sweep shards ONE node dimension; mixed-n "
            "phantom batching is the 1-D config_sweep_curves path — "
            "run the pod sweep per n")
    eff_rumors_2d = {pt.rumors or rumors for pt in points}
    if len(eff_rumors_2d) > 1:
        raise ValueError(
            "the 2-D pod sweep carries ONE rumor axis; mixed-rumor "
            "phantom batching is the 1-D config_sweep_curves path — "
            "run the pod sweep per rumor count")
    rumors = eff_rumors_2d.pop()
    cN = len(points)
    p_sweep = mesh.shape[sweep_axis]
    if cN % p_sweep != 0:
        raise ValueError(f"{cN} configs do not divide over the "
                         f"{sweep_axis} axis of size {p_sweep}")
    n = topo0.n
    n_pad = pad_to_mesh(n, mesh, node_axis)
    nl = n_pad // mesh.shape[node_axis]
    k_max = k_max or max(pt.fanout for pt in points)
    if any(pt.fanout > k_max for pt in points):
        raise ValueError("k_max smaller than a point's fanout")
    have_ae = any(pt.mode == C.ANTI_ENTROPY for pt in points)
    # same static half-elision as config_sweep_curves (VERDICT r2 item 7)
    need_push = any(_MODE_FLAGS[pt.mode][0] for pt in points)
    need_pull = any(_MODE_FLAGS[pt.mode][1] for pt in points)
    have_table = not topo0.implicit
    if multi:
        nbrs_stack, deg_stack = _stack_topologies(topos)
        # family stack rows pad to the node mesh (sentinel n rows,
        # degree 0 — permanently dark, same as the single-family pad;
        # a zero-width pad is a no-op)
        tables = (jnp.pad(nbrs_stack, ((0, 0), (0, n_pad - n), (0, 0)),
                          constant_values=n),
                  jnp.pad(deg_stack, ((0, 0), (0, n_pad - n))))
    elif have_table:
        tables = (_pad_rows(topo0.nbrs, n_pad, n),
                  _pad_rows(topo0.deg, n_pad, 0))
    else:
        tables = ()

    cache_before = _cached_pod_sweep_scan.cache_info()
    scan = _cached_pod_sweep_scan(n, n_pad, nl, k_max, have_ae, need_push,
                                  need_pull, multi, have_table,
                                  run.max_rounds, run.origin, mesh,
                                  fault, sweep_axis, node_axis)

    proto_like = ProtocolConfig(mode=C.PUSH, fanout=k_max, rumors=rumors)
    base = init_state(run, proto_like, n)
    seen0 = _pad_rows(base.seen, n_pad, False)
    init_seen = jnp.broadcast_to(seen0, (cN,) + seen0.shape)
    keys = jax.vmap(jax.random.key)(
        jnp.asarray([pt.seed for pt in points], jnp.uint32))
    flags = [jnp.asarray([_MODE_FLAGS[pt.mode][0] for pt in points]),
             jnp.asarray([_MODE_FLAGS[pt.mode][1] for pt in points]),
             jnp.asarray([pt.mode == C.ANTI_ENTROPY for pt in points]),
             jnp.asarray([pt.fanout for pt in points], jnp.int32),
             jnp.asarray([pt.drop_prob for pt in points], jnp.float32),
             jnp.asarray([pt.period for pt in points], jnp.int32),
             jnp.asarray([pt.topo_idx for pt in points], jnp.int32)]
    init_seen = jax.device_put(
        init_seen, NamedSharding(mesh, P(sweep_axis, node_axis, None)))
    row = NamedSharding(mesh, P(sweep_axis))
    keys = jax.device_put(keys, row)
    flags = [jax.device_put(f, row) for f in flags]

    from gossip_tpu.utils.trace import maybe_aot_timed
    _, (covs, msgs) = maybe_aot_timed(scan, timing, init_seen, keys,
                                      jnp.zeros((cN,), jnp.float32),
                                      *flags, *tables, label="sweep")
    _emit_pod_sweep_cache_telemetry(cache_before)
    curves = np.asarray(covs).T
    return ConfigSweepResult(points=points, curves=curves,
                             msgs=np.asarray(msgs).T,
                             rounds_to_target=_rounds_to_target(
                                 curves, run.target_coverage),
                             target=run.target_coverage)


def _rounds_to_target(curves: np.ndarray, target: float) -> np.ndarray:
    """First 1-based round index reaching target per row; -1 if never."""
    hit = np.full(curves.shape[0], -1, np.int64)
    reached = curves >= target
    any_hit = reached.any(axis=1)
    hit[any_hit] = reached[any_hit].argmax(axis=1) + 1
    return hit


# ---------------------------------------------------------------------------
# Config sweep: distinct (mode, fanout, drop, period, seed) points batched
# into one compiled program.
# ---------------------------------------------------------------------------

# mode -> (do_push, do_pull); anti-entropy is a period-gated bidirectional
# exchange (pull + reverse delta, models/si.py semantics).
_MODE_FLAGS = {C.PUSH: (True, False), C.PULL: (False, True),
               C.PUSH_PULL: (True, True), C.ANTI_ENTROPY: (False, True)}


@dataclasses.dataclass(frozen=True)
class SweepPoint:
    """One shape-invariant config point of a batched sweep.

    ``topo_idx`` selects the point's topology from the family stack when
    :func:`config_sweep_curves` is given a SEQUENCE of same-n explicit
    topologies (the north star's "sweep fanout, mode, and graph topology"
    axis — VERDICT r2 item 6); with a single topology it must stay 0."""
    mode: str = C.PUSH
    fanout: int = 1
    drop_prob: float = 0.0
    period: int = 1          # anti-entropy cadence (1 = every round)
    seed: int = 0
    topo_idx: int = 0
    rumors: int = 0          # 0 = the batch-level default (round 4:
    #                          mixed rumor counts batch by padding to
    #                          the max with inert all-false phantom
    #                          columns, masked out of the coverage min)

    def __post_init__(self):
        if self.mode not in _MODE_FLAGS:
            raise ValueError(
                f"config sweep supports {sorted(_MODE_FLAGS)}; got "
                f"{self.mode!r} (flood/swim change the round structure)")
        if self.fanout < 1:
            raise ValueError("fanout must be >= 1")
        if self.period < 1:
            raise ValueError("period must be >= 1")
        if self.period > 1 and self.mode != C.ANTI_ENTROPY:
            raise ValueError("period > 1 is the anti-entropy cadence; solo "
                             f"{self.mode!r} rounds ignore period, so a "
                             "batched point must not silently differ")
        if self.topo_idx < 0:
            raise ValueError("topo_idx must be >= 0")
        if self.rumors < 0:
            raise ValueError("rumors must be >= 0 (0 = batch default)")


@dataclasses.dataclass
class ConfigSweepResult:
    points: tuple                 # the SweepPoints, batch order
    curves: np.ndarray            # float32[C, T]
    msgs: np.ndarray              # float32[C, T]
    rounds_to_target: np.ndarray  # int[C], -1 where never reached
    target: float

    def summaries(self):
        out = []
        for i, pt in enumerate(self.points):
            out.append({
                "point": dataclasses.asdict(pt),
                "rounds_to_target": int(self.rounds_to_target[i]),
                "converged": bool(self.rounds_to_target[i] >= 0),
                "final_coverage": float(self.curves[i, -1]),
                "msgs_total": float(self.msgs[i, -1]),
            })
        return out


def _drop_targets(rkey, tag, gids, targets, drop_prob, sentinel):
    """apply_drop with a *traced* drop probability (always draws; a literal
    0.0 probability yields an all-False mask, so the where is a no-op and
    the result is bitwise identical to not drawing at all)."""
    dropped = drop_mask(rkey, tag, gids, targets.shape[1], drop_prob)
    return jnp.where(dropped, jnp.int32(sentinel), targets)


def _sweep_round_delta(rkey, round_, gids, visible, alive_l, topo, k_max,
                       nbrs, deg, do_push, do_pull, do_ae, fanout, dropp,
                       period, have_ae, scatter_n, count_reduce, gather,
                       need_push=True, need_pull=True, peer_bound=None,
                       cut=None, want_lost=False):
    """The ONE per-config sweep round body — shared by the single-device
    batch, the 2-D pod sweep, and the request-batched serving driver,
    which differ only in how scatter counts reduce (``count_reduce``),
    how the digest table is assembled (``gather``), and the scatter
    sentinel (``scatter_n``).  Returns (delta, msgs_this_round) for
    this row block — plus the nemesis ``lost`` count with
    ``want_lost=True``.

    ``need_push``/``need_pull`` are STATIC elision switches (VERDICT r2
    item 7): when no point in the batch pushes (resp. pulls), the whole
    half — its sampling, scatter/gather, and reduction — is never built,
    instead of being computed and masked.  Eliding a half cannot change
    the other half's trajectory: the halves draw from disjoint RNG tags
    (PUSH_TAG/PUSH_DROP_TAG vs PULL_TAG/PULL_DROP_TAG), same pattern as
    the ``have_ae`` elision of the reverse delta.

    ``peer_bound`` (mixed-n IMPLICIT batches): the point's own n as a
    traced scalar, bounding its uniform partner draw on the complete
    graph — randint with a traced bound reproduces the solo static-n
    draw bitwise (sample_peers_complete).  None keeps the static
    ``topo.n`` path, byte-identical to the pre-round-4 lowering.

    ``cut`` (the request-batched serving path): a traced per-round
    partition cut (ops/nemesis cut_tbl lookup, -1 = closed) applied
    AFTER the drop coins, in exactly models/si.make_si_round's churn
    order, so a batched request's trajectory stays bitwise the solo
    churn run.  ``want_lost=True`` additionally returns the kernels'
    EXACT destroyed-message count (drop coins + open cut) as a third
    output, gated per config by the same do_push/on masks as msgs."""
    n = topo.n
    col = jnp.arange(k_max, dtype=jnp.int32)[None, :]
    delta = jnp.zeros_like(visible)
    msgs = jnp.float32(0.0)
    lost = jnp.float32(0.0)

    def _peers(key):
        if peer_bound is not None:
            return sample_peers_complete(key, gids, peer_bound, k_max, True)
        return sample_peers(key, gids, topo, k_max, True,
                            local_nbrs=nbrs, local_deg=deg)

    def _cut(targets):
        # closed-cut rounds (cut = -1) are a bitwise no-op, so the
        # no-churn solo trajectory is reproduced exactly (ops/nemesis
        # same_side contract)
        if cut is None:
            return targets
        return NE.partition_targets(cut, gids, targets, n)

    if need_push:
        # push half (masked by do_push for non-push configs in the batch)
        pkey = jax.random.fold_in(rkey, si_mod.PUSH_TAG)
        targets0 = _peers(pkey)
        targets0 = jnp.where(col < fanout, targets0, jnp.int32(n))
        targets = _drop_targets(rkey, si_mod.PUSH_DROP_TAG, gids, targets0,
                                dropp, n)
        targets = _cut(targets)
        sender_active = jnp.any(visible, axis=1)
        valid = (targets < n) & sender_active[:, None]
        counts = push_counts(scatter_n,
                             jnp.where(valid, targets, scatter_n), visible)
        delta = (count_reduce(counts) > 0) & do_push
        msgs = jnp.where(do_push, jnp.sum(valid).astype(jnp.float32), 0.0)
        if want_lost:
            lost = lost + jnp.where(
                do_push,
                NE.lost_count(targets0, targets, sender_active, n), 0.0)

    if need_pull:
        # pull half (anti-entropy = bidirectional exchange gated by period)
        seen_all = gather(visible)
        qkey = jax.random.fold_in(rkey, si_mod.PULL_TAG)
        partners0 = _peers(qkey)
        partners0 = jnp.where(col < fanout, partners0, jnp.int32(n))
        partners = _drop_targets(rkey, si_mod.PULL_DROP_TAG, gids,
                                 partners0, dropp, n)
        partners = _cut(partners)
        pulled = pull_merge(seen_all, partners, n)
        partners = jnp.where(alive_l[:, None], partners, n)
        n_req = jnp.sum(partners < n).astype(jnp.float32)
        on = do_pull & ((round_ % period) == 0)
        if want_lost:
            # post-alive-mask partners, alive requesters: a dead row's
            # slot carried no request to lose, and a quiescent AE round
            # sends nothing (`on` covers both; period == 1 keeps plain
            # pull always-on) — models/si.py's exact churn accounting
            lost = lost + jnp.where(
                on, NE.lost_count(partners0, partners, alive_l, n), 0.0)
        delta = delta | (pulled & on)
        if have_ae:
            # anti-entropy reverse delta: the initiator's state scatters
            # back into the partner's row (models/si.py) — built only
            # when the batch has an AE point
            bcounts = push_counts(
                scatter_n, jnp.where(partners < n, partners, scatter_n),
                visible)
            delta = delta | ((count_reduce(bcounts) > 0) & (on & do_ae))
        mfac = jnp.where(do_ae, 3.0, 2.0)
        msgs = msgs + jnp.where(on, mfac * n_req, 0.0)
    out = delta & alive_l[:, None]
    return (out, msgs, lost) if want_lost else (out, msgs)


def _normalize_topos(topo, points):
    """(topos, multi, topo0) from a Topology-or-sequence argument, with
    the ONE topo_idx range check both sweep entry points share."""
    topos = tuple(topo) if isinstance(topo, (list, tuple)) else (topo,)
    if any(pt.topo_idx >= len(topos) for pt in points):
        raise ValueError(
            f"a point's topo_idx is past the {len(topos)} supplied "
            "topolog(ies)")
    return topos, len(topos) > 1, topos[0]


def _stack_topologies(topos):
    """Explicit topologies -> (nbrs_stack[F, n_max, D_max],
    deg_stack[F, n_max]), neighbor columns padded with the shared
    sentinel ``n_max``.  The sentinel columns sit past every row's
    degree, so sampling (which draws indices < deg) can never touch them
    — a point's trajectory is independent of the OTHER entries in the
    stack.

    Entries may differ in ``n`` (round 4, VERDICT r3 item 6): smaller
    graphs pad to ``n_max`` with PHANTOM rows (degree 0, sentinel
    neighbors).  Phantoms are inert end to end: degree-0 sampling emits
    the sentinel, no real row's table contains a phantom id, and the
    sweep masks them out of liveness and coverage — so a point's
    trajectory on its real prefix is BITWISE the solo run at its own n
    (per-node draws are keyed by global id, the sharding-invariance
    contract in ops/sampling)."""
    n_max = max(t.n for t in topos)
    for t in topos:
        if t.implicit:
            raise ValueError(
                "a topology sweep needs explicit neighbor tables for "
                "every entry (the implicit complete graph has no table "
                "to stack, and its partner draw is bounded by a static "
                "n); sweep it as its own batch")
    d_max = max(t.width for t in topos)
    nbrs = jnp.stack([
        jnp.pad(t.nbrs, ((0, n_max - t.n), (0, d_max - t.width)),
                constant_values=n_max)
        for t in topos])
    deg = jnp.stack([jnp.pad(t.deg, (0, n_max - t.n)) for t in topos])
    return nbrs, deg


def config_sweep_curves(points, topo, run: RunConfig,
                        fault: Optional[FaultConfig] = None,
                        k_max: Optional[int] = None,
                        rumors: int = 1, mesh=None,
                        axis_name: str = "sweep",
                        _force_both: bool = False) -> ConfigSweepResult:
    """Run C distinct config points as ONE batched XLA program.

    ``topo`` is one Topology, or a SEQUENCE of explicit topologies — the
    topology axis of the north star's "sweep fanout, mode, and graph
    topology" sentence (VERDICT r2 item 6).  With a sequence, each
    point's ``topo_idx`` picks its entry from a stacked
    ``int32[F, n_max, D_max]`` table operand; one compile covers the
    whole families x modes x fanouts grid.  Entries may differ in n
    (round 4): smaller graphs pad with inert phantom rows and the
    point's coverage/liveness use its OWN n — so a families x sizes
    grid is one program too (mixed-n batches take no FaultConfig and
    need origin + rumors within the smallest n; see the errors below).
    A point's trajectory equals the solo single-topology batch BITWISE
    on its real prefix (same keys; the stack pads neighbor columns with
    the sentinel past each row's degree, which sampling never draws).

    ``fault`` contributes only the static death mask (shared structure);
    per-config loss goes through ``SweepPoint.drop_prob`` — a FaultConfig
    with drop_prob set here is rejected to keep the two channels distinct.

    ``k_max`` is the shared sampling width (default: max fanout in the
    batch).  Trajectories are a function of (point, k_max): a point whose
    fanout equals k_max reproduces the solo make_si_round trajectory
    BITWISE (same keys, same draw shapes); batch composition never changes
    results (tested in tests/test_config_sweep.py).

    ``mesh``: a 1-D device mesh shards the CONFIG axis — the north star's
    "sweep fanout, mode, topology across a TPU pod" DP axis.  Configs are
    independent, so the batch is embarrassingly parallel: the batched
    arrays are placed with a ``P(axis_name)`` sharding and XLA partitions
    the whole scan with zero cross-device traffic.  Results are the same
    trajectories in the same order (sharding never changes values).
    """
    points = tuple(points)
    if not points:
        raise ValueError("need at least one SweepPoint")
    if fault is not None and fault.drop_prob > 0.0:
        raise ValueError("per-config loss goes through SweepPoint.drop_prob;"
                         " FaultConfig.drop_prob would be ambiguous here")
    # the grid round body is its own lowering (no churn path yet):
    # reject a schedule loudly rather than silently running static-only
    NE.check_supported(fault, engine="config-sweep", events=False,
                       partitions=False, ramp=False)
    if mesh is not None and len(points) % mesh.shape[axis_name] != 0:
        raise ValueError(
            f"{len(points)} configs do not divide over the {axis_name} "
            f"mesh axis of size {mesh.shape[axis_name]}; pad the batch "
            "(duplicate a point) or change the mesh")
    topos, multi, topo0 = _normalize_topos(topo, points)
    all_implicit = all(t.implicit for t in topos)
    if multi and not all_implicit and any(t.implicit for t in topos):
        raise ValueError(
            "a topology batch mixes implicit (complete) and explicit "
            "entries; the stacked-table operand and the traced-bound "
            "draw are different programs — batch them separately")
    n = max(t.n for t in topos)
    ragged = multi and any(t.n != n for t in topos)
    if ragged:
        # phantom-row batching (VERDICT r3 item 6): different-n entries
        # share one program.  The two channels that are seeded at a
        # point's own n in a solo run must be unambiguous here:
        if fault is not None:
            raise ValueError(
                "a mixed-n sweep takes no FaultConfig: the static death "
                "draw is shaped by each point's own n in a solo run, so "
                "a shared draw would silently change trajectories; run "
                "faulted points as a same-n batch")
        min_n = min(t.n for t in topos)
        worst_r = max((pt.rumors or rumors) for pt in points)
        if run.origin + worst_r > min_n:
            raise ValueError(
                f"origin {run.origin} + rumors {worst_r} exceeds the "
                f"smallest n ({min_n}) in the batch: rumor r seeds node "
                "(origin + r) % n, which would differ from the solo run "
                "on the smaller graphs")
    if multi:
        # the sweep's scatter sentinel and partner-validity bound is the
        # PADDED n; same-n stacks keep n == every entry's n (no change)
        topo0 = dataclasses.replace(topo0, n=n)
    k_max = k_max or max(pt.fanout for pt in points)
    if any(pt.fanout > k_max for pt in points):
        raise ValueError("k_max smaller than a point's fanout")
    cN = len(points)
    # Per-point rumor counts (round 4): pad the rumor axis to the batch
    # max; a point's phantom columns are ALL-FALSE forever (no origin
    # seed, so they never scatter, never gather, never flip a
    # sender_active bit — msgs and the real prefix stay bitwise equal
    # to the solo run) and are masked out of the coverage min (an inert
    # all-true column would instead cap reported coverage at
    # n*(1/n) != 1.0 in f32 on non-dyadic n).
    eff_rumors = [pt.rumors or rumors for pt in points]
    r_max = max(eff_rumors)
    mixed_rumors = len(set(eff_rumors)) > 1
    proto_like = ProtocolConfig(mode=C.PUSH, fanout=k_max, rumors=r_max)
    if multi and not all_implicit:
        tables = _stack_topologies(topos)
    elif topo0.implicit:
        # mixed-n COMPLETE graphs (round 4, the last structural axis):
        # no table to stack — each point's uniform draw is bounded by
        # its own n as a traced operand (sample_peers_complete)
        tables = ()
        if ragged and min(t.n for t in topos) < 2:
            raise ValueError("mixed-n complete batches need every "
                             "n >= 2 (the traced self-exclusion bound)")
    else:
        tables = (topo0.nbrs, topo0.deg)
    have_ae = any(pt.mode == C.ANTI_ENTROPY for pt in points)
    # static half-elision (VERDICT r2 item 7): a pure-push (resp. pure-
    # pull) batch never builds the other half.  _force_both is a
    # benchmarking hook proving the elision's win (tests only).
    need_push = _force_both or any(_MODE_FLAGS[pt.mode][0]
                                   for pt in points)
    need_pull = _force_both or any(_MODE_FLAGS[pt.mode][1]
                                   for pt in points)

    def one_round(seen, round_, base_key, msgs,
                  do_push, do_pull, do_ae, fanout, dropp, period, tidx,
                  n_pt, *tbl):
        if multi and tbl:
            # per-config family: one dynamic slice out of the stacked
            # table operand (tables are jit arguments — DESIGN.md §6)
            nbrs, deg = tbl[0][tidx], tbl[1][tidx]
        else:
            nbrs, deg = tbl if tbl else (None, None)
        # O(N) buffers in-trace: no inline constants in the compile request
        gids = jnp.arange(n, dtype=jnp.int32)
        alive = alive_mask(fault, n, run.origin)
        alive_b = jnp.ones((n,), jnp.bool_) if alive is None else alive
        if ragged:
            # phantom rows past this point's own n are never alive —
            # they cannot send, receive, or count.  For explicit tables
            # this is the second lock (their rows are already degree-0/
            # sentinel); for the tableless implicit case it is the ONLY
            # lock — the traced-bound draw targets [0, n_pt) but phantom
            # SENDERS exist, and this mask is what silences them.
            alive_b = alive_b & (gids < n_pt)
        rkey = jax.random.fold_in(base_key, round_)
        visible = seen & alive_b[:, None]
        delta, msgs_round = _sweep_round_delta(
            rkey, round_, gids, visible, alive_b, topo0, k_max, nbrs, deg,
            do_push, do_pull, do_ae, fanout, dropp, period, have_ae,
            scatter_n=n, count_reduce=lambda c: c, gather=lambda v: v,
            need_push=need_push, need_pull=need_pull,
            peer_bound=(n_pt if (ragged and topo0.implicit) else None))
        return seen | delta, round_ + 1, msgs + msgs_round

    batched = jax.vmap(one_round,
                       in_axes=(0,) * 12 + (None,) * len(tables))

    base = init_state(run, proto_like, n)
    if mixed_rumors:
        # zero the phantom columns per point in ONE broadcasted where
        # (base seeds all r_max origins; a point with fewer rumors must
        # not seed the rest)
        colr = jnp.arange(r_max)[None, None, :]
        ers = jnp.asarray(eff_rumors, jnp.int32)[:, None, None]
        init_seen = jnp.where(colr < ers, base.seen[None], False)
    else:
        init_seen = jnp.broadcast_to(base.seen, (cN,) + base.seen.shape)
    keys = jax.vmap(jax.random.key)(
        jnp.asarray([pt.seed for pt in points], jnp.uint32))
    do_push = jnp.asarray([_MODE_FLAGS[pt.mode][0] for pt in points])
    do_pull = jnp.asarray([_MODE_FLAGS[pt.mode][1] for pt in points])
    do_ae = jnp.asarray([pt.mode == C.ANTI_ENTROPY for pt in points])
    fanouts = jnp.asarray([pt.fanout for pt in points], jnp.int32)
    drops = jnp.asarray([pt.drop_prob for pt in points], jnp.float32)
    periods = jnp.asarray([pt.period for pt in points], jnp.int32)
    tidxs = jnp.asarray([pt.topo_idx for pt in points], jnp.int32)
    n_pts = jnp.asarray([topos[pt.topo_idx].n for pt in points], jnp.int32)
    rum_pts = jnp.asarray(eff_rumors, jnp.int32)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        row = NamedSharding(mesh, P(axis_name))
        init_seen = jax.device_put(
            init_seen, NamedSharding(mesh, P(axis_name, None, None)))
        keys = jax.device_put(keys, row)
        (do_push, do_pull, do_ae, fanouts, drops, periods, tidxs, n_pts,
         rum_pts) = (
            jax.device_put(x, row)
            for x in (do_push, do_pull, do_ae, fanouts, drops, periods,
                      tidxs, n_pts, rum_pts))

    @jax.jit
    def scan(seen, rounds, keys, msgs, *tbl):
        alive = alive_mask(fault, n, run.origin)
        colr = jnp.arange(r_max)

        def cov_fn(x, n_pt, r_pt):
            # One coverage body for every batching shape, ops chosen to
            # reproduce the solo paths BIT FOR BIT (tests assert curve
            # equality with solo runs):
            #  * ragged n — per-point divisor via recip-MUL, matching
            #    jnp.mean's lowering (true division differs by 1 ulp);
            #  * uniform n — models/si.coverage's exact expressions;
            #  * mixed rumors — phantom columns masked out of the min
            #    (they are all-false, so unmasked they would win it).
            if ragged:
                gids = jnp.arange(n, dtype=jnp.int32)
                w = (gids < n_pt).astype(jnp.float32)
                counts = jnp.sum(x.astype(jnp.float32) * w[:, None],
                                 axis=0)
                vals = counts * (1.0 / n_pt.astype(jnp.float32))
            elif alive is None:
                vals = jnp.mean(x.astype(jnp.float32), axis=0)
            else:
                w = alive.astype(jnp.float32)
                vals = (x.astype(jnp.float32) * w[:, None]).sum(0) / w.sum()
            if mixed_rumors:
                vals = jnp.where(colr < r_pt, vals, 2.0)
            return jnp.min(vals)

        cov_all = jax.vmap(cov_fn)

        def body(carry, _):
            seen, rounds, msgs = carry
            seen, rounds, msgs = batched(seen, rounds, keys, msgs, do_push,
                                         do_pull, do_ae, fanouts, drops,
                                         periods, tidxs, n_pts, *tbl)
            covs = cov_all(seen, n_pts, rum_pts)
            return (seen, rounds, msgs), (covs, msgs)
        return jax.lax.scan(body, (seen, rounds, msgs), None,
                            length=run.max_rounds)

    _, (covs, msgs) = scan(init_seen, jnp.zeros((cN,), jnp.int32), keys,
                           jnp.zeros((cN,), jnp.float32), *tables)
    curves = np.asarray(covs).T
    return ConfigSweepResult(points=points, curves=curves,
                             msgs=np.asarray(msgs).T,
                             rounds_to_target=_rounds_to_target(
                                 curves, run.target_coverage),
                             target=run.target_coverage)


def config_sweep_curves_partitioned(points, topo, run: RunConfig,
                                    fault: Optional[FaultConfig] = None,
                                    k_max: Optional[int] = None,
                                    rumors: int = 1) -> ConfigSweepResult:
    """Mode-partitioned sweep execution (VERDICT r2 item 7): split a
    MIXED grid into push-only / pull-only / push+pull buckets and batch
    each separately, so the pure buckets never build (or pay per round
    for) the other half.  Trajectories are IDENTICAL to the single batch:
    one shared ``k_max`` across buckets (trajectories are a function of
    (point, k_max)) and disjoint RNG tags between the halves.  Results
    are merged back in the caller's point order.

    Single-bucket grids fall through to :func:`config_sweep_curves`
    directly (whose static elision already skips the absent half).  A
    config-axis mesh is not supported here — bucket sizes rarely divide
    a mesh; shard the unpartitioned batch instead (elision still applies
    when the WHOLE grid is pure)."""
    points = tuple(points)
    if not points:
        raise ValueError("need at least one SweepPoint")
    k_max = k_max or max(pt.fanout for pt in points)

    buckets: dict = {}
    for i, pt in enumerate(points):
        buckets.setdefault(_MODE_FLAGS[pt.mode], []).append(i)
    if len(buckets) == 1:
        return config_sweep_curves(points, topo, run, fault, k_max, rumors)

    curves = np.zeros((len(points), run.max_rounds), np.float32)
    msgs = np.zeros_like(curves)
    for idxs in buckets.values():
        sub = config_sweep_curves([points[i] for i in idxs], topo, run,
                                  fault, k_max, rumors)
        curves[idxs] = sub.curves
        msgs[idxs] = sub.msgs
    return ConfigSweepResult(points=points, curves=curves, msgs=msgs,
                             rounds_to_target=_rounds_to_target(
                                 curves, run.target_coverage),
                             target=run.target_coverage)


# -- SIR rumor-mongering ensembles -----------------------------------------
#
# The classic rumor-mongering results (Demers et al. §1.4's tables) are
# DISTRIBUTIONS: residue and extinction time vary seed-to-seed because the
# whole process is a branching process near its critical point early on.
# One vmapped scan = |seeds| independent SIR trajectories in one XLA
# program, same shape as ensemble_curves but carrying the SIR state.

@dataclasses.dataclass
class RumorEnsembleResult:
    curves: np.ndarray             # float32[S, T] coverage per seed/round
    hot: np.ndarray                # float32[S, T] infective fraction
    msgs: np.ndarray               # float32[S, T]
    target: float

    @property
    def extinction_rounds(self) -> np.ndarray:
        """int[S]: first round with no hot pair (+1), -1 if none."""
        out = np.full(self.hot.shape[0], -1, np.int64)
        for i, h in enumerate(self.hot):
            idx = np.nonzero(h == 0.0)[0]
            if len(idx):
                out[i] = idx[0] + 1
        return out

    @property
    def residues(self) -> np.ndarray:
        return 1.0 - self.curves[:, -1]

    def summary(self) -> dict:
        ext = self.extinction_rounds
        done = ext >= 0
        # residue is an AT-EXTINCTION statistic: truncated (still-hot at
        # max_rounds) seeds would contribute transient not-yet-informed
        # mass and inflate the distribution, so they are excluded here —
        # like the extinction stats; raise max_rounds if terminated <
        # seeds
        res = self.residues[done]
        return {
            "seeds": int(len(ext)),
            "terminated": int(done.sum()),
            "extinction_rounds_mean": (float(ext[done].mean())
                                       if done.any() else None),
            "extinction_rounds_p95": (float(np.percentile(ext[done], 95))
                                      if done.any() else None),
            "residue_mean": float(res.mean()) if len(res) else None,
            "residue_p50": float(np.median(res)) if len(res) else None,
            "residue_p95": (float(np.percentile(res, 95))
                            if len(res) else None),
            "residue_max": float(res.max()) if len(res) else None,
            "coverage_mean": float(self.curves[:, -1].mean()),
            "msgs_mean": float(self.msgs[:, -1].mean()),
            "target": self.target,
        }


def ensemble_swim_curves(proto: ProtocolConfig, n: int, run: RunConfig,
                         seeds: Sequence[int], dead_nodes=(),
                         fail_round: int = 0,
                         fault: Optional[FaultConfig] = None,
                         topo: Optional[Topology] = None, mesh=None,
                         axis_name: str = "seed") -> EnsembleResult:
    """|seeds| independent SWIM failure-detection trajectories as ONE
    batched XLA program — the detection-LATENCY distribution for a fixed
    failure scenario across PRNG seeds (probe targets, proxy choices,
    and dissemination fan-outs all redraw per seed), which is the
    operational question SWIM answers ("how long until the cluster
    knows?").  Per-seed curves are bitwise identical to solo
    runtime/simulator.simulate_swim_curve runs with the same seed
    (tested); ``curves`` carries the per-round detection fraction, so
    ``rounds_to_target`` is rounds-to-detection."""
    from gossip_tpu.models import swim as SW
    dead = tuple(dead_nodes)
    step, tables = SW.make_swim_round(proto, n, dead, fail_round, fault,
                                      topo, tabled=True,
                                      max_rounds=run.max_rounds)
    base = SW.init_swim_state(n, proto.swim_subjects, 0)
    keys = jax.vmap(jax.random.key)(jnp.asarray(list(seeds), jnp.uint32))
    s = len(seeds)
    init = SW.SwimState(
        wire=jnp.broadcast_to(base.wire, (s,) + base.wire.shape),
        timer=jnp.broadcast_to(base.timer, (s,) + base.timer.shape),
        round=jnp.zeros((s,), jnp.int32),
        base_key=keys,
        msgs=jnp.zeros((s,), jnp.float32),
    )
    init = _shard_ensemble(init, mesh, axis_name, s)
    rotate = proto.swim_rotate
    epoch_rounds = SW.resolve_epoch_rounds(proto, n)

    @jax.jit
    def scan(states, *tbl):
        # observer denominator: base mask minus PERMANENT churn deaths
        # (matches simulate_swim_curve/until — a forever-down node
        # cannot observe; a recovering node stays in the denominator)
        alive_obs = SW.observer_alive(n, dead, fault)

        # metric targets: static scripted deaths + permanent churn
        # deaths (`dead` stays static-only for the kernel factory)
        targets = SW.detection_targets(dead, fault)

        def detection(st):
            window = SW.subject_window(st.round - 1, proto.swim_subjects,
                                       n, rotate, epoch_rounds)
            return SW.detection_fraction(
                SW.SwimState(st.wire[:n], st.timer[:n], st.round,
                             st.base_key, st.msgs), targets,
                alive_obs, subj_gids=window
            ) if targets else jnp.float32(0.0)

        def body(st, _):
            st = jax.vmap(lambda x: step(x, *tbl))(st)
            return st, (jax.vmap(detection)(st), st.msgs)
        return jax.lax.scan(body, states, None, length=run.max_rounds)

    _, (dets, msgs) = scan(init, *tables)
    curves = np.asarray(dets).T
    return EnsembleResult(curves=curves, msgs=np.asarray(msgs).T,
                          rounds_to_target=_rounds_to_target(
                              curves, run.target_coverage),
                          target=run.target_coverage)


def ensemble_rumor_curves(proto: ProtocolConfig, topo: Topology,
                          run: RunConfig, seeds: Sequence[int],
                          fault: Optional[FaultConfig] = None, mesh=None,
                          axis_name: str = "seed"
                          ) -> RumorEnsembleResult:
    """|seeds| independent SIR trajectories as ONE batched XLA program.
    Per-seed trajectories are bitwise identical to solo
    models/rumor.simulate_curve_rumor runs with the same seed (tested)."""
    from gossip_tpu.models.rumor import (RumorState, init_rumor_state,
                                         make_rumor_round, rumor_coverage)
    step, tables = make_rumor_round(proto, topo, fault, run.origin,
                                    tabled=True)
    step = NE.drop_lost(step, NE.get(fault))
    base = init_rumor_state(run, proto, topo.n)
    keys = jax.vmap(jax.random.key)(jnp.asarray(list(seeds), jnp.uint32))
    s = len(seeds)
    init = RumorState(
        seen=jnp.broadcast_to(base.seen, (s,) + base.seen.shape),
        hot=jnp.broadcast_to(base.hot, (s,) + base.hot.shape),
        cnt=jnp.broadcast_to(base.cnt, (s,) + base.cnt.shape),
        round=jnp.zeros((s,), jnp.int32),
        base_key=keys,
        msgs=jnp.zeros((s,), jnp.float32),
    )
    init = _shard_ensemble(init, mesh, axis_name, s)

    @jax.jit
    def scan(states, *tbl):
        # eventual alive set under churn — matches the solo
        # simulate_curve_rumor weighting (bitwise-parity contract)
        alive = NE.metric_alive(fault, topo.n, run.origin)
        hot_w = (None if alive is None else alive.astype(jnp.float32))

        def one_metrics(st):
            hot_any = jnp.any(st.hot, axis=1).astype(jnp.float32)
            frac = (jnp.mean(hot_any) if hot_w is None
                    else jnp.sum(hot_any * hot_w) / jnp.sum(hot_w))
            return rumor_coverage(st.seen, alive), frac, st.msgs

        def body(st, _):
            st = jax.vmap(lambda x: step(x, *tbl))(st)
            covs, hots, msgs = jax.vmap(one_metrics)(st)
            return st, (covs, hots, msgs)
        return jax.lax.scan(body, states, None, length=run.max_rounds)

    _, (covs, hots, msgs) = scan(init, *tables)
    return RumorEnsembleResult(curves=np.asarray(covs).T,
                               hot=np.asarray(hots).T,
                               msgs=np.asarray(msgs).T,
                               target=run.target_coverage)
