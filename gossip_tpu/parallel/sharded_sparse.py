"""Sparse cross-shard digest exchange: all_to_all request/response rounds.

Every sharded round in parallel/sharded*.py moves O(N) bytes per round over
ICI (`all_gather` of the whole digest table / `psum_scatter` of a full
count table) no matter how many messages the protocol actually sends.  At
10M nodes x 256 rumors that is ~320 MB/round.  This module is the
O(messages) alternative the SURVEY (§2.4, §7 "Cross-shard randomness +
exchange at 10M nodes") and round-1 VERDICT call for: the batched analog of
the reference's *point-to-point* ``SyncRPC`` (/root/reference/main.go:81)
— each pull request travels to exactly one peer shard and comes back as one
digest, instead of every shard broadcasting everything.

How static shapes are squared with sparse traffic
-------------------------------------------------
XLA collectives move fixed-size buffers, so "send only what you sampled"
needs per-(src,dst) message counts known at compile time.  Uniform iid
partner sampling gives Binomial counts — worst case nl*k, which would
erase the savings.  Instead the partner draw is **stratified over shards**:

  * each shard's ``nl*k`` request slots are split round-robin into P
    balanced groups of ``cap = nl*k/P`` (group of local slot ``t`` is
    ``(t + o_r) mod P``, with a fresh random offset ``o_r`` each round);
  * a fresh uniform random permutation ``pi_r`` of the P shards (shared by
    all shards, derived from the round key) maps groups to partner shards;
  * the partner *row within* the shard is drawn uniformly per slot, keyed
    by the slot's global id.

Every slot's partner is therefore EXACTLY uniform over all ``n_pad`` rows
(``pi_r[(t + o_r) mod P]`` is uniform over shards for any fixed ``t``; the
row draw is uniform within the shard), while per-(src,dst) counts are the
constant ``cap`` — the all_to_all buffers are ``[P, cap]`` requests out,
``[P, cap, W]`` digest words back.  What differs from iid sampling is only
the joint distribution (slots of one shard are spread round-robin over
partner shards instead of binomially); the per-node marginal — which
drives the epidemic recurrence — is untouched.  Same design move as the
fused Pallas kernel's lane/row factoring (ops/pallas_round.py).

Traffic accounting (returned as :class:`SparseMeta`): per device per round
the sparse exchange moves ``P*cap*4`` request bytes + ``P*cap*4W`` response
bytes = ``nl*k*(4 + 4W)``, vs ``n_pad*4W`` for the dense all_gather — an
O(N) -> O(messages) drop whenever ``k << P`` rumor words would have been
broadcast wastefully (at N=10M, P=8, W=8, k=1: 45 MB vs 320 MB per round).

Bitwise parity: :func:`sparse_pull_round_reference` computes the identical
trajectory on one device (same RNG keying by global slot id, same pi_r/o_r)
— tests/test_sharded_sparse.py checks equality on the 8-device CPU mesh.
The stratification parameter P is part of the trajectory definition, so the
reference takes it explicitly.
"""

from __future__ import annotations

import math
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gossip_tpu.compat import pvary, shard_map
from gossip_tpu import config as C
from gossip_tpu.config import FaultConfig, ProtocolConfig, RunConfig
from gossip_tpu.models.state import SimState
from gossip_tpu.ops.bitpack import coverage_packed, n_words, pack, unpack
from gossip_tpu.parallel.sharded import (_pad_rows, pad_to_mesh,
                                         sharded_alive)

# RNG tags (disjoint from models/si.py's 1..5)
SPARSE_PERM_TAG = 101
SPARSE_OFFSET_TAG = 102
SPARSE_ROW_TAG = 103
SPARSE_DROP_TAG = 104
TOPO_NBR_TAG = 105


class SparseMeta(NamedTuple):
    """Per-round ICI traffic of the sparse exchange vs the dense path.

    For anti-entropy with period > 1 the kernels cond-skip the ENTIRE
    exchange — request, response, and reverse collectives alike — on
    quiescent rounds, so every byte figure here is per EXCHANGE round
    and the steady per-round average is ``sparse_bytes / period``.
    Pull (and period == 1) exchanges every round, so the figures are
    then plain per-round numbers."""
    p: int                    # shards
    cap: int                  # requests per (src, dst) pair
    request_bytes: int        # per device per EXCHANGE round
    response_bytes: int       # per device per EXCHANGE round
    dense_bytes: int          # per device per round, all_gather equivalent
    # anti-entropy reverse-delta payload (0 = pull)
    reverse_bytes: int = 0

    @property
    def sparse_bytes(self) -> int:
        return self.request_bytes + self.response_bytes + self.reverse_bytes


def sparse_meta(n_pad: int, p: int, k: int, w: int,
                bidirectional: bool = False) -> SparseMeta:
    nl = n_pad // p
    cap = (nl * k) // p
    return SparseMeta(p=p, cap=cap,
                      request_bytes=p * cap * 4,
                      response_bytes=p * cap * 4 * w,
                      dense_bytes=n_pad * 4 * w,
                      reverse_bytes=p * cap * 4 * w if bidirectional else 0)


def _validate(n_pad: int, p: int, k: int) -> int:
    nl = n_pad // p
    if n_pad % p:
        raise ValueError(f"n_pad={n_pad} not divisible by mesh size {p}")
    if (nl * k) % p:
        raise ValueError(
            f"slots per shard ({nl}*{k}) must divide by mesh size {p} for "
            "balanced stratification; pad n or adjust fanout")
    return nl


def _round_draws(rkey: jax.Array, p: int):
    """(pi_r, o_r): the round's shard permutation + group offset.

    Replicated computation — every shard derives the same values."""
    pi = jax.random.permutation(jax.random.fold_in(rkey, SPARSE_PERM_TAG),
                                jnp.arange(p, dtype=jnp.int32))
    o = jax.random.randint(jax.random.fold_in(rkey, SPARSE_OFFSET_TAG),
                           (), 0, p, dtype=jnp.int32)
    return pi, o


def _slot_rows(rkey: jax.Array, slot_gids: jax.Array, nl: int) -> jax.Array:
    """Uniform partner row in [0, nl) per slot, keyed by global slot id."""
    base = jax.random.fold_in(rkey, SPARSE_ROW_TAG)
    keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(base, slot_gids)
    return jax.vmap(
        lambda kk: jax.random.randint(kk, (), 0, nl, dtype=jnp.int32))(keys)


def _slot_valid(rkey: jax.Array, slot_gids: jax.Array, drop_prob,
                alive_rows: jax.Array, k: int,
                force: bool = False) -> jax.Array:
    """Which slots issue a request: requester alive and link not dropped.
    ``force=True`` always draws the drop coins so ``drop_prob`` may be a
    TRACED per-round scalar (the ops/nemesis drop-ramp path; a p=0
    round draws all-False, bitwise a no-op on the trajectory)."""
    valid = jnp.repeat(alive_rows, k)
    if force or drop_prob > 0.0:
        base = jax.random.fold_in(rkey, SPARSE_DROP_TAG)
        keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(base,
                                                               slot_gids)
        dropped = jax.vmap(
            lambda kk: jax.random.bernoulli(kk, drop_prob))(keys)
        valid = valid & ~dropped
    return valid


def _or_reduce_k(flat: jax.Array, nl: int, k: int) -> jax.Array:
    """uint32[nl*k, W] -> OR over the k slots of each row -> uint32[nl, W]."""
    g = flat.reshape(nl, k, -1)
    out = g[:, 0, :]
    for j in range(1, k):
        out = out | g[:, j, :]
    return out


def _scatter_merge_digests(ok: jax.Array, recv: jax.Array,
                           recv_d: jax.Array, nl: int, rumors: int,
                           w: int) -> jax.Array:
    """Responder-side anti-entropy reverse merge, the ONE canonical
    implementation both mesh kernels share: OR the received requester
    digests (``recv_d`` [p, cap, W]) into the locally-requested rows
    (``recv`` [p, cap]; invalid slots carry the sentinel and drop)."""
    rows_in = jnp.where(ok, recv, nl).reshape(-1)
    contrib = unpack(recv_d.reshape(-1, w), rumors)
    cnt = jnp.zeros((nl, rumors), jnp.int32).at[rows_in].add(
        contrib.astype(jnp.int32), mode="drop")
    return pack(cnt > 0)


def make_sparse_pull_round(
        proto: ProtocolConfig, n: int, mesh: Mesh,
        fault: Optional[FaultConfig] = None, origin: int = 0,
        axis_name: str = "nodes", tabled: bool = False):
    """Sharded packed pull round with sparse all_to_all digest exchange.

    Implicit complete topology only (the 10M-node scale path — explicit
    neighbor tables keep the dense kernels of parallel/sharded_packed.py).
    State is rumor-packed ``uint32[n_pad, W]`` as in models/si_packed.

    ``proto.exclude_self`` is NOT honored (unlike ops/sampling): the
    stratified draw is uniform over all rows including the requester, so a
    slot self-pulls with probability 1/n_pad — a no-op for SI state, same
    treatment as the fused kernel's phantom pulls (ops/pallas_round.py).
    Exact self-exclusion would make the within-shard row distribution
    non-uniform across shards; not worth the bias for a 1/n effect.

    ``tabled=True`` returns ``(step, tables)`` where ``tables`` is the
    schedule-operand tail (``NE.sched_args``; empty without churn) and
    ``step(state, *tables)`` takes it as ARGUMENTS — the churn drivers
    thread it through their jitted loops so the compiled program holds
    no schedule content (ops/nemesis module doc).  The default closure
    form stays for small callers (content closure-baked, still exact).
    """
    if proto.mode not in (C.PULL, C.ANTI_ENTROPY):
        raise ValueError("sparse exchange is a pull/anti-entropy path; "
                         f"got mode {proto.mode!r}")
    p = mesh.shape[axis_name]
    k = proto.fanout
    n_pad = pad_to_mesh(n, mesh, axis_name)
    nl = _validate(n_pad, p, k)
    cap = (nl * k) // p
    w = n_words(proto.rumors)
    drop_prob = 0.0 if fault is None else fault.drop_prob
    alive_pad = sharded_alive(fault, n, n_pad, origin)
    from gossip_tpu.ops import nemesis as NE
    ch = NE.get(fault)

    def local_round(seen_l, round_, base_key, msgs, alive_l,
                    *sched_tail):
        _, sched = NE.split_tables(ch, sched_tail)
        shard = jax.lax.axis_index(axis_name)
        rkey = jax.random.fold_in(base_key, round_)
        row_gids = shard * nl + jnp.arange(nl, dtype=jnp.int32)
        if ch is not None:
            # churn path: the alive operand stays the STATIC mask; the
            # schedule OPERANDS' down-window subtracts per round
            alive_l = alive_l & ~((sched.die[row_gids] <= round_)
                                  & (round_ < sched.rec[row_gids]))
            dp = NE.drop_at(sched, round_)
            cut = NE.cut_at(sched, round_)
        else:
            dp, cut = drop_prob, None
        visible = jnp.where(alive_l[:, None], seen_l, jnp.uint32(0))

        def exchange(_):
            """The whole round's sampling + collectives.  For
            anti-entropy with period > 1 a lax.cond skips this ENTIRELY
            on quiescent rounds — forward and reverse bytes both (draws
            are keyed by (round, slot id), so skipped rounds never
            perturb later ones; the reference twin computes-and-zeroes
            to the identical state)."""
            pi, o = _round_draws(rkey, p)
            inv_pi = jnp.argsort(pi).astype(jnp.int32)

            slot_gids = shard * (nl * k) + jnp.arange(nl * k,
                                                      dtype=jnp.int32)
            rows_req = _slot_rows(rkey, slot_gids, nl)        # [nl*k]
            valid = _slot_valid(rkey, slot_gids, dp, alive_l, k,
                                force=ch is not None)
            if ch is not None:
                # cross-cut requests are lost for this round only (the
                # dense kernels' partition_targets semantics, slot form)
                local_slot = jnp.arange(nl * k, dtype=jnp.int32)
                partner_shard = jnp.take(pi, (local_slot + o) % p)
                partner_gid = partner_shard * nl + rows_req
                req_gid = slot_gids // k
                would = jnp.repeat(alive_l, k)
                valid = valid & NE.same_side(cut, req_gid, partner_gid)
                lost = jnp.sum(would & ~valid, dtype=jnp.float32)
            else:
                # must carry the varying-manual-axes type: this is a
                # cond-branch output matched against the quiescent
                # branch's pvary'd zf when period > 1
                lost = pvary(jnp.float32(0.0), (axis_name,))
            rows_req = jnp.where(valid, rows_req, jnp.int32(-1))

            # Column c of the [cap, p] slot view holds group (c + o) % p;
            # the shard receiving column c is pi[(c + o) % p].  Reorder
            # columns so send[d] is the block destined to shard d.
            A = rows_req.reshape(cap, p)                      # [cap, p]
            cols_for_dst = (inv_pi - o) % p                   # [p]
            send = jnp.take(A.T, cols_for_dst, axis=0)        # [p, cap]

            recv = jax.lax.all_to_all(send, axis_name, 0, 0, tiled=False)
            # recv[s, :] = rows requested by shard s from THIS shard.
            ok = recv >= 0
            resp = visible[jnp.clip(recv, 0, nl - 1)]         # [p, cap, W]
            resp = jnp.where(ok[:, :, None], resp, jnp.uint32(0))
            back = jax.lax.all_to_all(resp, axis_name, 0, 0, tiled=False)

            # back[d] answers the column we sent to shard d; undo the
            # reorder.
            dst_for_col = jnp.take(pi, (jnp.arange(p, dtype=jnp.int32)
                                        + o) % p)
            R_cols = jnp.take(back, dst_for_col, axis=0)   # [p(col),cap,W]
            flat = jnp.transpose(R_cols, (1, 0, 2)).reshape(nl * k, w)
            pulled = _or_reduce_k(flat, nl, k)

            if proto.mode == C.ANTI_ENTROPY:
                # Bidirectional reconciliation: the requester's own
                # digest rides ALONG with the request (one extra
                # [p, cap, W] all_to_all) and the responder merges it
                # locally — the partner pair converges to the union in
                # one exchange, still O(messages) traffic
                # (SparseMeta.reverse_bytes).
                req_digest = visible[
                    jnp.arange(nl * k, dtype=jnp.int32) // k]
                req_digest = jnp.where(valid[:, None], req_digest,
                                       jnp.uint32(0))
                D = req_digest.reshape(cap, p, w)             # [cap, p, W]
                send_d = jnp.take(jnp.transpose(D, (1, 0, 2)),
                                  cols_for_dst, axis=0)       # [p, cap, W]
                recv_d = jax.lax.all_to_all(send_d, axis_name, 0, 0,
                                            tiled=False)
                pulled = pulled | _scatter_merge_digests(
                    ok, recv, recv_d, nl, proto.rumors, w)
            return pulled, jnp.sum(valid).astype(jnp.float32), lost

        if proto.mode == C.ANTI_ENTROPY and proto.period > 1:
            on = (round_ % proto.period) == 0
            # the quiescent branch's constants must carry the same
            # varying-manual-axes type as the exchange outputs
            zf = pvary(jnp.float32(0.0), (axis_name,))
            quiet = (jnp.zeros_like(seen_l), zf, zf)
            pulled, n_req, lost_r = jax.lax.cond(on, exchange,
                                                 lambda _: quiet, None)
        else:
            pulled, n_req, lost_r = exchange(None)
        mfac = 3.0 if proto.mode == C.ANTI_ENTROPY else 2.0
        pulled = jnp.where(alive_l[:, None], pulled, jnp.uint32(0))
        msgs_new = msgs + jax.lax.psum(mfac * n_req, axis_name)
        if ch is not None:
            return (seen_l | pulled, msgs_new,
                    jax.lax.psum(lost_r, axis_name))
        return seen_l | pulled, msgs_new

    sh, sh2, rep = P(axis_name), P(axis_name, None), P()
    out_specs = (sh2, rep, rep) if ch is not None else (sh2, rep)
    in_specs = (sh2, rep, rep, rep, sh)
    tables = ()
    if ch is not None:
        in_specs += (rep,) * NE.N_SCHED_OPERANDS
        tables = NE.sched_args(NE.build(fault, n, n_pad))
    mapped = shard_map(local_round, mesh=mesh,
                           in_specs=in_specs,
                           out_specs=out_specs)

    def step_tabled(state: SimState, *tbl):
        out = mapped(state.seen, state.round, state.base_key,
                     state.msgs, alive_pad, *tbl)
        new = SimState(seen=out[0], round=state.round + 1,
                       base_key=state.base_key, msgs=out[1])
        # churn path returns (state, lost) — the models/si.py contract
        return (new, out[2]) if ch is not None else new

    if tabled:
        return step_tabled, tables

    def step(state: SimState):
        return step_tabled(state, *tables)

    return step


def sparse_pull_round_reference(
        proto: ProtocolConfig, n: int, p: int,
        fault: Optional[FaultConfig] = None,
        origin: int = 0, tabled: bool = False):
    """Single-device twin of :func:`make_sparse_pull_round` — identical
    trajectory for the same stratification parameter ``p`` (the parity
    oracle; collectives only move data).  ``tabled=True`` returns the
    ``(step, schedule-operand-tables)`` pair like the mesh kernel."""
    k = proto.fanout
    n_pad = math.ceil(n / p) * p
    nl = _validate(n_pad, p, k)
    drop_prob = 0.0 if fault is None else fault.drop_prob
    alive_pad = sharded_alive(fault, n, n_pad, origin)
    from gossip_tpu.ops import nemesis as NE
    ch = NE.get(fault)
    tables = (() if ch is None
              else NE.sched_args(NE.build(fault, n, n_pad)))

    def step_tabled(state: SimState, *tbl):
        _, sched = NE.split_tables(ch, tbl)
        seen, round_ = state.seen, state.round
        rkey = jax.random.fold_in(state.base_key, round_)
        pi, o = _round_draws(rkey, p)

        slot_gids = jnp.arange(n_pad * k, dtype=jnp.int32)
        local_slot = slot_gids % (nl * k)
        group = (local_slot + o) % p
        partner_shard = jnp.take(pi, group)
        rows = _slot_rows(rkey, slot_gids, nl)
        gids = partner_shard * nl + rows
        if ch is not None:
            alive_now = NE.alive_rows(sched, alive_pad, round_)
            dp = NE.drop_at(sched, round_)
            cut = NE.cut_at(sched, round_)
            valid = _slot_valid(rkey, slot_gids, dp, alive_now, k,
                                force=True)
            valid = valid & NE.same_side(cut, slot_gids // k, gids)
            lost = jnp.sum(jnp.repeat(alive_now, k) & ~valid,
                           dtype=jnp.float32)
        else:
            alive_now = alive_pad
            valid = _slot_valid(rkey, slot_gids, drop_prob, alive_pad, k)
            lost = jnp.float32(0.0)

        visible = jnp.where(alive_now[:, None], seen, jnp.uint32(0))
        got = visible[gids]                                   # [n_pad*k, W]
        got = jnp.where(valid[:, None], got, jnp.uint32(0))
        pulled = _or_reduce_k(got, n_pad, k)

        n_req = jnp.sum(valid).astype(jnp.float32)
        back = None
        if proto.mode == C.ANTI_ENTROPY:
            # reverse delta: the requester's digest merges into the partner
            # (single-device twin of the mesh kernel's piggybacked digest)
            req_digest = visible[slot_gids // k]              # [n_pad*k, W]
            req_digest = jnp.where(valid[:, None], req_digest,
                                   jnp.uint32(0))
            tgt = jnp.where(valid, gids, n_pad)
            cnt = jnp.zeros((n_pad, proto.rumors), jnp.int32
                            ).at[tgt].add(
                unpack(req_digest, proto.rumors).astype(jnp.int32),
                mode="drop")
            back = pack(cnt > 0)
        if proto.mode == C.ANTI_ENTROPY and proto.period > 1:
            on = (round_ % proto.period) == 0
            pulled = jnp.where(on, pulled, jnp.uint32(0))
            back = jnp.where(on, back, jnp.uint32(0))
            n_req = jnp.where(on, n_req, 0.0)
        if proto.period > 1 and proto.mode == C.ANTI_ENTROPY:
            # quiescent rounds send nothing, so nothing is lost (the
            # mesh kernel cond-skips the whole exchange)
            lost = jnp.where((round_ % proto.period) == 0, lost, 0.0)
        if back is not None:
            pulled = pulled | back
        mfac = 3.0 if proto.mode == C.ANTI_ENTROPY else 2.0
        pulled = jnp.where(alive_now[:, None], pulled, jnp.uint32(0))
        new = SimState(seen=seen | pulled, round=round_ + 1,
                       base_key=state.base_key,
                       msgs=state.msgs + mfac * n_req)
        return (new, lost) if ch is not None else new

    if tabled:
        return step_tabled, tables

    def step(state: SimState):
        return step_tabled(state, *tables)

    return step


def init_sparse_state(run: RunConfig, proto: ProtocolConfig, n: int,
                      mesh: Optional[Mesh] = None,
                      axis_name: str = "nodes",
                      p: Optional[int] = None) -> SimState:
    """Packed state padded to the mesh — or, for the single-device parity
    reference, to ``p`` stratification shards — origin rumors seeded as in
    models/state.init_state."""
    from gossip_tpu.models.si_packed import init_packed_state
    if mesh is not None:
        p = mesh.shape[axis_name]
    elif p is None:
        p = 1
    st = init_packed_state(run, proto, n)
    n_pad = math.ceil(n / p) * p
    seen = _pad_rows(st.seen, n_pad, jnp.uint32(0))
    if mesh is not None:
        seen = jax.device_put(seen,
                              NamedSharding(mesh, P(axis_name, None)))
    return SimState(seen=seen, round=st.round, base_key=st.base_key,
                    msgs=st.msgs)


# ---------------------------------------------------------------------------
# Explicit-topology sparse exchange (VERDICT r2 item 5)
#
# The complete-graph kernel above stratifies the partner draw BY
# CONSTRUCTION (round-robin groups -> permuted shards), which is only
# possible because every row is a legal partner.  With an explicit
# neighbor table the partner of a slot is dictated by the graph
# (``nbrs[i, j]`` for a uniform j < deg[i] — the batched analog of the
# reference's per-neighbor RPC, /root/reference/main.go:81), so
# per-(src,dst) counts are data-dependent.  Static shapes come instead
# from CAPACITY-CAPPED buckets: each shard packs its requests into a
# ``[P, cap]`` buffer by destination shard (owner of the partner row
# under the equal row-block partition), in local slot order.  The
# bucket rank is deterministic, so the rare slot that overflows its
# bucket (cap defaults to the TABLE-DERIVED expected max load plus a
# 4-sigma tail — auto_topo_cap) is DROPPED deterministically —
# reproduced bit-for-bit by the single-device reference twin, counted
# per round, and reported as the ``overflow`` output.  An overflowing
# slot is a lost pull request for that round only — at-least-once
# delivery comes from re-sampling every round, exactly like a dropped
# link in FaultConfig.drop_prob.
#
# Traffic: per device per round ``P*cap*(4 + 4W)`` bytes vs the dense
# packed all_gather's ``n_pad*4W`` (parallel/sharded_packed.py).  On
# shard-uniform graphs (ER, shuffled power-law) cap ~ nl*k/P and the
# drop is ~P*4W/(k*(4+4W)) — ~3.6x at P=8, W=1, k=1, linear in mesh
# size and rumor words.  On banded graphs (WS rings) cap honestly grows
# toward nl*k and the meta shows no win — halo exchange territory.


def auto_topo_cap(nbrs, deg, nl: int, k: int, p: int,
                  slack_sigma: float = 4.0, floor: int = 4) -> int:
    """Static per-(src,dst) bucket capacity derived FROM THE TABLE.

    The expected request load on bucket (s, d) is fixed by the graph:
    ``E[s,d] = k * sum_{rows i in s} |nbrs(i) in d| / deg(i)``.  A
    uniform balanced-load cap (2*nl*k/p) is catastrophically wrong for
    banded graphs — on a Watts-Strogatz ring ~80% of every shard's
    requests target the shard's OWN row block, overflowing a uniform
    bucket ~4x over.  Instead the cap is ``max_{s,d} E + slack_sigma *
    sqrt(maxE) + floor`` (the load is a sum of independent per-slot
    Bernoulli draws, so sqrt(E) bounds its std): overflow stays rare on
    ANY topology, and a banded graph honestly drives cap toward the slot
    count ``nl*k`` — where SparseMeta reports no byte win over dense and
    the halo exchange (parallel/halo.py) is the right tool instead.

    ``nbrs``/``deg`` are the REAL (unpadded) host rows — padding rows
    have degree 0 and contribute no load.  One O(N*D) numpy pass at
    build time; no device round-trip of a padded copy."""
    import numpy as np
    nbrs = np.asarray(nbrs)
    deg = np.asarray(deg)
    n_rows, d_max = nbrs.shape
    src = np.repeat(np.arange(n_rows) // nl, d_max)
    valid = np.arange(d_max)[None, :] < deg[:, None]
    dst = np.where(valid, nbrs // nl, 0).reshape(-1)
    wts = np.where(valid, k / np.maximum(deg, 1)[:, None], 0.0).reshape(-1)
    E = np.zeros((p, p))
    np.add.at(E, (src, dst), wts)
    max_e = float(E.max())
    cap = math.ceil(max_e + slack_sigma * math.sqrt(max(max_e, 1.0))
                    + floor)
    return min(nl * k, max(1, cap))


def resolve_topo_cap(topo, p: int, k: int,
                     cap: Optional[int] = None) -> int:
    """The capacity actually used by the topo-sparse kernels: an explicit
    ``cap`` wins; otherwise :func:`auto_topo_cap` on the raw table."""
    if cap is not None:
        return cap
    n_pad = math.ceil(topo.n / p) * p
    return auto_topo_cap(topo.nbrs, topo.deg, n_pad // p, k, p)


def sparse_topo_meta(n_pad: int, p: int, k: int, w: int, cap: int,
                     bidirectional: bool = False) -> SparseMeta:
    """Traffic accounting for the explicit-topology sparse pull (dense
    equivalent: the packed all_gather of parallel/sharded_packed.py).
    ``bidirectional``: anti-entropy's piggybacked requester digest, one
    extra [p, cap, W] all_to_all on exchange rounds."""
    return SparseMeta(p=p, cap=cap,
                      request_bytes=p * cap * 4,
                      response_bytes=p * cap * 4 * w,
                      dense_bytes=n_pad * 4 * w,
                      reverse_bytes=p * cap * 4 * w if bidirectional else 0)


def _slot_nbr_choice(rkey: jax.Array, slot_gids: jax.Array,
                     deg_slot: jax.Array) -> jax.Array:
    """Uniform neighbor INDEX j in [0, deg) per slot, keyed by global
    slot id (mesh-shape invariant).  deg==0 yields j=0; such slots are
    masked invalid by the caller."""
    base = jax.random.fold_in(rkey, TOPO_NBR_TAG)
    keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(base, slot_gids)
    u = jax.vmap(lambda kk: jax.random.uniform(kk))(keys)
    return jnp.minimum((u * deg_slot).astype(jnp.int32),
                       jnp.maximum(deg_slot - 1, 0))


def _bucket_rank(dst_eff: jax.Array, p: int) -> jax.Array:
    """Rank of each slot within its destination bucket, in slot order.
    ``dst_eff == p`` marks an invalid slot (consumes no capacity)."""
    occ = dst_eff[:, None] == jnp.arange(p, dtype=jnp.int32)    # [S, p]
    pos = jnp.cumsum(occ.astype(jnp.int32), axis=0) - 1
    return jnp.take_along_axis(
        pos, jnp.clip(dst_eff, 0, p - 1)[:, None], axis=1)[:, 0]


def make_sparse_topo_pull_round(
        proto: ProtocolConfig, topo, mesh: Mesh,
        fault: Optional[FaultConfig] = None, origin: int = 0,
        axis_name: str = "nodes", cap: Optional[int] = None,
        tabled: bool = False):
    """Sharded packed pull / anti-entropy round over an EXPLICIT
    topology with capacity-capped all_to_all request/response exchange
    (see the block comment above).  State is rumor-packed
    ``uint32[n_pad, W]``.

    Anti-entropy piggybacks the requester's digest on the request (one
    extra [p, cap, W] all_to_all, SparseMeta.reverse_bytes) and the
    responder scatter-merges it — the capacity cap bounds the reverse
    side for free, since an overflow-dropped request carries no digest
    either.  ``period > 1`` cond-skips the reverse collective and masks
    the forward merge on quiescent rounds (complete-graph twin,
    :func:`make_sparse_pull_round`).

    Returns ``step(state, overflow, nbrs, deg) -> (state, overflow)``
    plus the padded tables when ``tabled=True`` (the overflow operand is
    a replicated float32 running count of capacity-dropped requests).
    """
    from gossip_tpu.models.state import SimState as _SimState
    if proto.mode not in (C.PULL, C.ANTI_ENTROPY):
        raise ValueError("sparse topology exchange covers pull and "
                         f"anti-entropy (got mode {proto.mode!r}); push/"
                         "flood ride the dense kernels")
    if topo.implicit:
        raise ValueError("implicit complete topology routes to "
                         "make_sparse_pull_round (stratified draw)")
    from gossip_tpu.ops import nemesis as NE
    NE.check_supported(fault, engine="topo-sparse", events=False,
                       partitions=False, ramp=False)
    p = mesh.shape[axis_name]
    k = proto.fanout
    n = topo.n
    n_pad = pad_to_mesh(n, mesh, axis_name)
    nl = n_pad // p
    S = nl * k
    w = n_words(proto.rumors)
    cap = resolve_topo_cap(topo, p, k, cap)
    drop_prob = 0.0 if fault is None else fault.drop_prob
    nbrs_pad = _pad_rows(topo.nbrs, n_pad, n)     # sentinel n; deg 0 rows
    deg_pad = _pad_rows(topo.deg, n_pad, 0)

    def local_round(seen_l, round_, base_key, msgs, ovf, nbrs_l, deg_l):
        shard = jax.lax.axis_index(axis_name)
        rkey = jax.random.fold_in(base_key, round_)
        row_gids = shard * nl + jnp.arange(nl, dtype=jnp.int32)
        alive_l = sharded_alive(fault, n, n_pad, origin)[row_gids]
        visible = jnp.where(alive_l[:, None], seen_l, jnp.uint32(0))

        def exchange(_):
            """The whole round's sampling + collectives.  period > 1
            cond-skips this ENTIRELY on quiescent rounds — no forward
            bytes move either (draws are keyed by (round, slot id), so
            skipped rounds never perturb later ones; the reference twin
            computes-and-zeroes to the identical state)."""
            slot_gids = shard * S + jnp.arange(S, dtype=jnp.int32)
            deg_slot = jnp.repeat(deg_l, k)
            j = _slot_nbr_choice(rkey, slot_gids, deg_slot)
            row_of_slot = jnp.arange(S, dtype=jnp.int32) // k
            gid = nbrs_l[row_of_slot, j]                      # [S] global
            valid = (_slot_valid(rkey, slot_gids, drop_prob, alive_l, k)
                     & (deg_slot > 0))
            dst_eff = jnp.where(valid, gid // nl, jnp.int32(p))
            pos = _bucket_rank(dst_eff, p)
            sent = valid & (pos < cap)

            # out-of-range (dst_eff == p: invalid; pos >= cap: overflow)
            # indices are dropped by the scatter, leaving the -1 sentinel
            send_rows = jnp.full((p, cap), -1, jnp.int32
                                 ).at[dst_eff, pos].set(gid % nl,
                                                        mode="drop")
            recv = jax.lax.all_to_all(send_rows, axis_name, 0, 0,
                                      tiled=False)
            ok = recv >= 0
            resp = visible[jnp.clip(recv, 0, nl - 1)]         # [p, cap, W]
            resp = jnp.where(ok[:, :, None], resp, jnp.uint32(0))
            back = jax.lax.all_to_all(resp, axis_name, 0, 0, tiled=False)

            got = back[jnp.clip(dst_eff, 0, p - 1),
                       jnp.clip(pos, 0, cap - 1)]             # [S, W]
            got = jnp.where(sent[:, None], got, jnp.uint32(0))
            pulled = _or_reduce_k(got, nl, k)

            if proto.mode == C.ANTI_ENTROPY:
                # requester digest rides WITH the request in the same
                # (dst, pos) bucket slot; the responder scatter-merges
                # into the requested rows (complete-graph twin layout)
                req_digest = visible[row_of_slot]             # [S, W]
                req_digest = jnp.where(sent[:, None], req_digest,
                                       jnp.uint32(0))
                send_d = jnp.zeros((p, cap, w), jnp.uint32
                                   ).at[dst_eff, pos].set(req_digest,
                                                          mode="drop")
                recv_d = jax.lax.all_to_all(send_d, axis_name, 0, 0,
                                            tiled=False)
                pulled = pulled | _scatter_merge_digests(
                    ok, recv, recv_d, nl, proto.rumors, w)
            return (pulled,
                    jnp.sum(sent).astype(jnp.float32),
                    jnp.sum(valid & ~sent).astype(jnp.float32))

        if proto.mode == C.ANTI_ENTROPY and proto.period > 1:
            on = (round_ % proto.period) == 0
            # the quiescent branch's constants must carry the same
            # varying-manual-axes type as the exchange outputs
            zf = pvary(jnp.float32(0.0), (axis_name,))
            quiet = (jnp.zeros_like(seen_l), zf, zf)
            pulled, n_sent, n_over = jax.lax.cond(on, exchange,
                                                  lambda _: quiet, None)
        else:
            pulled, n_sent, n_over = exchange(None)
        mfac = 3.0 if proto.mode == C.ANTI_ENTROPY else 2.0
        pulled = jnp.where(alive_l[:, None], pulled, jnp.uint32(0))
        msgs_new = msgs + jax.lax.psum(mfac * n_sent, axis_name)
        ovf_new = ovf + jax.lax.psum(n_over, axis_name)
        return seen_l | pulled, msgs_new, ovf_new

    sh, sh2, rep = P(axis_name), P(axis_name, None), P()
    mapped = shard_map(local_round, mesh=mesh,
                           in_specs=(sh2, rep, rep, rep, rep, sh2, sh),
                           out_specs=(sh2, rep, rep))

    def step_tabled(state, overflow, nbrs, deg):
        seen, msgs, ovf = mapped(state.seen, state.round, state.base_key,
                                 state.msgs, overflow, nbrs, deg)
        return (_SimState(seen=seen, round=state.round + 1,
                          base_key=state.base_key, msgs=msgs), ovf)

    if tabled:
        return step_tabled, (nbrs_pad, deg_pad)

    def step(state, overflow):
        return step_tabled(state, overflow, nbrs_pad, deg_pad)

    return step


def sparse_topo_pull_round_reference(
        proto: ProtocolConfig, topo, p: int,
        fault: Optional[FaultConfig] = None, origin: int = 0,
        cap: Optional[int] = None):
    """Single-device twin of :func:`make_sparse_topo_pull_round` —
    identical trajectory INCLUDING the deterministic capacity drops
    (bucket ranks recomputed per source-shard block in the same slot
    order) and the anti-entropy reverse merge.  The parity oracle;
    collectives only move data."""
    if proto.mode not in (C.PULL, C.ANTI_ENTROPY):
        raise ValueError("sparse topology exchange covers pull and "
                         f"anti-entropy (got mode {proto.mode!r})")
    from gossip_tpu.ops import nemesis as NE
    NE.check_supported(fault, engine="topo-sparse", events=False,
                       partitions=False, ramp=False)
    k = proto.fanout
    n = topo.n
    n_pad = math.ceil(n / p) * p
    nl = n_pad // p
    S = nl * k
    cap = resolve_topo_cap(topo, p, k, cap)
    drop_prob = 0.0 if fault is None else fault.drop_prob
    nbrs_pad = _pad_rows(topo.nbrs, n_pad, n)
    deg_pad = _pad_rows(topo.deg, n_pad, 0)
    alive_pad = sharded_alive(fault, n, n_pad, origin)

    def step(state, overflow):
        seen, round_ = state.seen, state.round
        rkey = jax.random.fold_in(state.base_key, round_)
        slot_gids = jnp.arange(n_pad * k, dtype=jnp.int32)
        deg_slot = jnp.repeat(deg_pad, k)
        j = _slot_nbr_choice(rkey, slot_gids, deg_slot)
        row_of_slot = slot_gids // k
        gid = nbrs_pad[row_of_slot, j]
        valid = (_slot_valid(rkey, slot_gids, drop_prob, alive_pad, k)
                 & (deg_slot > 0))
        dst_eff = jnp.where(valid, gid // nl, jnp.int32(p))
        pos = jax.vmap(_bucket_rank, in_axes=(0, None))(
            dst_eff.reshape(p, S), p).reshape(-1)
        sent = valid & (pos < cap)

        visible = jnp.where(alive_pad[:, None], seen, jnp.uint32(0))
        got = visible[jnp.clip(gid, 0, n_pad - 1)]
        got = jnp.where(sent[:, None], got, jnp.uint32(0))
        pulled = _or_reduce_k(got, n_pad, k)

        n_sent = jnp.sum(sent).astype(jnp.float32)
        n_over = jnp.sum(valid & ~sent).astype(jnp.float32)
        if proto.mode == C.ANTI_ENTROPY:
            # reverse delta: the requester's digest merges into the
            # partner (mesh kernel's piggybacked digest)
            req_digest = visible[row_of_slot]
            req_digest = jnp.where(sent[:, None], req_digest,
                                   jnp.uint32(0))
            tgt = jnp.where(sent, gid, n_pad)
            cnt = jnp.zeros((n_pad, proto.rumors), jnp.int32
                            ).at[tgt].add(
                unpack(req_digest, proto.rumors).astype(jnp.int32),
                mode="drop")
            back = pack(cnt > 0)
            if proto.period > 1:
                on = (round_ % proto.period) == 0
                pulled = jnp.where(on, pulled, jnp.uint32(0))
                back = jnp.where(on, back, jnp.uint32(0))
                n_sent = jnp.where(on, n_sent, 0.0)
                n_over = jnp.where(on, n_over, 0.0)
            pulled = pulled | back
        mfac = 3.0 if proto.mode == C.ANTI_ENTROPY else 2.0
        pulled = jnp.where(alive_pad[:, None], pulled, jnp.uint32(0))

        from gossip_tpu.models.state import SimState as _SimState
        return (_SimState(seen=seen | pulled, round=round_ + 1,
                          base_key=state.base_key,
                          msgs=state.msgs + mfac * n_sent),
                overflow + n_over)

    return step


def _sparse_recorder(proto: ProtocolConfig, n_shards: int,
                     meta: SparseMeta):
    """In-loop metrics row for the sparse exchange drivers
    (ops/round_metrics).  ``bytes`` comes straight from the driver's own
    :class:`SparseMeta` traffic accounting — per device per EXCHANGE
    round — gated in-trace on quiescent anti-entropy rounds exactly as
    the kernels cond-skip the collectives (plus the 4-byte msgs
    psum, which moves every round).  The previous round's entry count
    rides the carry as one scalar (the parallel/sharded._dense_recorder
    liveness rationale)."""
    from gossip_tpu.ops import round_metrics as RM
    offered_per_msg = proto.rumors * RM.payload_factor(proto.mode)
    exchange_b = float(meta.sparse_bytes) + 4.0

    def rec(m, prev_count, round0, msgs0, s1, alive_pad, nem=None):
        count = RM.count_packed(s1.seen, alive_pad)
        newly = count - prev_count
        msgs = s1.msgs - msgs0
        b = jnp.float32(exchange_b)
        if proto.mode == C.ANTI_ENTROPY:
            b = RM.gate_on_exchange_rounds(exchange_b, proto.period,
                                           round0, off=4.0)
        kw = ({} if nem is None
              else dict(alive=nem[0], cut_pairs=nem[1], dropped=nem[2]))
        return RM.record(
            m, newly=newly, msgs=msgs,
            dup=RM.dup_estimate(offered_per_msg * msgs, newly),
            bytes=b,
            front=RM.front_packed(s1.seen, alive_pad, n_shards),
            **kw), count

    return rec


def simulate_curve_topo_sparse(proto: ProtocolConfig, topo, run: RunConfig,
                               mesh: Mesh,
                               fault: Optional[FaultConfig] = None,
                               axis_name: str = "nodes",
                               cap: Optional[int] = None, timing=None):
    """lax.scan over rounds on the explicit-topology sparse pull path.
    Returns (coverage[T], msgs[T], final, SparseMeta, overflow[T]).
    ``timing``: optional compile/steady AOT-split dict
    (parallel/sharded.simulate_curve_sharded contract).  With an active
    run ledger the scan carries a round-metrics buffer stack, flushed
    once by the chokepoint (ops/round_metrics)."""
    import numpy as np

    from gossip_tpu.ops import round_metrics as RM
    from gossip_tpu.utils.trace import maybe_aot_timed
    p = mesh.shape[axis_name]
    cap_used = resolve_topo_cap(topo, p, proto.fanout, cap)
    step, tables = make_sparse_topo_pull_round(proto, topo, mesh, fault,
                                               run.origin, axis_name,
                                               cap_used, tabled=True)
    n_pad = pad_to_mesh(topo.n, mesh, axis_name)
    init = init_sparse_state(run, proto, topo.n, mesh, axis_name)
    r = proto.rumors
    meta = sparse_topo_meta(n_pad, p, proto.fanout, n_words(proto.rumors),
                            cap_used,
                            bidirectional=proto.mode == C.ANTI_ENTROPY)
    rec = _sparse_recorder(proto, p, meta) if RM.wanted() else None

    @jax.jit
    def scan(state, *tbl):
        alive_pad = sharded_alive(fault, topo.n, n_pad, run.origin)
        m0 = (RM.init(run.max_rounds, p, "simulate_curve_topo_sparse")
              if rec else None)
        c0 = RM.count_packed(state.seen, alive_pad) if rec else None
        def body(carry, _):
            s0, ovf0, m, cnt = carry
            round0, msgs0 = s0.round, s0.msgs
            s, ovf = step(s0, ovf0, *tbl)
            if m is not None:
                m, cnt = rec(m, cnt, round0, msgs0, s, alive_pad)
            return ((s, ovf, m, cnt),
                    (coverage_packed(s.seen, r, alive_pad), s.msgs, ovf))
        return jax.lax.scan(body, (state, jnp.float32(0.0), m0, c0),
                            None, length=run.max_rounds)

    ((final, _, _, _),
     (covs, msgs, ovfs)) = maybe_aot_timed(scan, timing, init, *tables,
                                           label="sparse")
    return (np.asarray(covs), np.asarray(msgs), final, meta,
            np.asarray(ovfs))


def simulate_until_topo_sparse(proto: ProtocolConfig, topo, run: RunConfig,
                               mesh: Mesh,
                               fault: Optional[FaultConfig] = None,
                               axis_name: str = "nodes",
                               cap: Optional[int] = None, timing=None):
    """while_loop to target coverage on the explicit-topology sparse pull
    path.  Returns (rounds, coverage, msgs, final, SparseMeta, overflow).
    ``timing``: optional compile/steady AOT-split dict.  With an active
    run ledger the loop carries a round-metrics buffer stack
    (ops/round_metrics)."""
    from gossip_tpu.ops import round_metrics as RM
    from gossip_tpu.utils.trace import maybe_aot_timed
    p = mesh.shape[axis_name]
    cap_used = resolve_topo_cap(topo, p, proto.fanout, cap)
    step, tables = make_sparse_topo_pull_round(proto, topo, mesh, fault,
                                               run.origin, axis_name,
                                               cap_used, tabled=True)
    n_pad = pad_to_mesh(topo.n, mesh, axis_name)
    alive_pad = sharded_alive(fault, topo.n, n_pad, run.origin)
    init = init_sparse_state(run, proto, topo.n, mesh, axis_name)
    target = jnp.float32(run.target_coverage)
    r = proto.rumors
    meta = sparse_topo_meta(n_pad, p, proto.fanout, n_words(proto.rumors),
                            cap_used,
                            bidirectional=proto.mode == C.ANTI_ENTROPY)
    rec = _sparse_recorder(proto, p, meta) if RM.wanted() else None

    @jax.jit
    def loop(state, *tbl):
        # liveness in-trace: no O(N) closed-over constant in the compile
        # request (bind_tables doc)
        alive_t = sharded_alive(fault, topo.n, n_pad, run.origin)
        m0 = (RM.init(run.max_rounds, p, "simulate_until_topo_sparse")
              if rec else None)
        c0 = RM.count_packed(state.seen, alive_t) if rec else None
        def cond(carry):
            s, _, _, _ = carry
            return ((coverage_packed(s.seen, r, alive_t) < target)
                    & (s.round < run.max_rounds))
        def body(carry):
            s0, ovf0, m, cnt = carry
            round0, msgs0 = s0.round, s0.msgs
            s, ovf = step(s0, ovf0, *tbl)
            if m is not None:
                m, cnt = rec(m, cnt, round0, msgs0, s, alive_t)
            return s, ovf, m, cnt
        return jax.lax.while_loop(cond, body,
                                  (state, jnp.float32(0.0), m0, c0))

    final, ovf, _, _ = maybe_aot_timed(loop, timing, init, *tables,
                                       label="sparse")
    return (int(final.round),
            float(coverage_packed(final.seen, r, alive_pad)),
            float(final.msgs), final, meta, float(ovf))


def simulate_curve_sparse(proto: ProtocolConfig, n: int, run: RunConfig,
                          mesh: Mesh, fault: Optional[FaultConfig] = None,
                          axis_name: str = "nodes", timing=None):
    """lax.scan over rounds recording (coverage, msgs) on the sparse
    exchange path.  Returns (coverage[T], msgs[T], final, SparseMeta).
    ``timing``: optional compile/steady AOT-split dict.  With an active
    run ledger the scan carries a round-metrics buffer stack
    (ops/round_metrics)."""
    import numpy as np

    from gossip_tpu.ops import round_metrics as RM
    from gossip_tpu.utils.trace import maybe_aot_timed
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.parallel.sharded import _churn_observables
    step, tables = make_sparse_pull_round(proto, n, mesh, fault,
                                          run.origin, axis_name,
                                          tabled=True)
    p = mesh.shape[axis_name]
    n_pad = pad_to_mesh(n, mesh, axis_name)
    init = init_sparse_state(run, proto, n, mesh, axis_name)
    r = proto.rumors
    meta = sparse_meta(n_pad, p, proto.fanout, n_words(proto.rumors),
                       bidirectional=proto.mode == C.ANTI_ENTROPY)
    rec = _sparse_recorder(proto, p, meta) if RM.wanted() else None
    ch = NE.get(fault)
    obs = _churn_observables(fault, n, n_pad, run.origin)

    @jax.jit
    def scan(state, *tbl):
        alive_pad = (NE.eventual_alive_pad(fault, n, n_pad, run.origin)
                     if ch is not None
                     else sharded_alive(fault, n, n_pad, run.origin))
        m0 = (RM.init(run.max_rounds, p, "simulate_curve_sparse",
                      nemesis=ch is not None) if rec else None)
        c0 = RM.count_packed(state.seen, alive_pad) if rec else None
        def body(carry, _):
            s0, m, cnt = carry
            round0, msgs0 = s0.round, s0.msgs
            if ch is not None:
                s, lost = step(s0, *tbl)
            else:
                s, lost = step(s0, *tbl), None
            if m is not None:
                m, cnt = rec(m, cnt, round0, msgs0, s, alive_pad,
                             nem=(obs(round0, lost,
                                      NE.sched_of_tables(tbl))
                                  if obs else None))
            return (s, m, cnt), (coverage_packed(s.seen, r, alive_pad),
                                 s.msgs)
        return jax.lax.scan(body, (state, m0, c0), None,
                            length=run.max_rounds)

    (final, _, _), (covs, msgs) = maybe_aot_timed(scan, timing, init,
                                                  *tables, label="sparse")
    return np.asarray(covs), np.asarray(msgs), final, meta


def simulate_until_sparse(proto: ProtocolConfig, n: int, run: RunConfig,
                          mesh: Mesh, fault: Optional[FaultConfig] = None,
                          axis_name: str = "nodes", timing=None):
    """while_loop to target coverage on the sparse exchange path.
    Returns (rounds, coverage, msgs, final_state, SparseMeta).
    ``timing``: optional compile/steady AOT-split dict.  With an active
    run ledger the loop carries a round-metrics buffer stack
    (ops/round_metrics)."""
    from gossip_tpu.ops import round_metrics as RM
    from gossip_tpu.utils.trace import maybe_aot_timed
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.parallel.sharded import _churn_observables
    step, tables = make_sparse_pull_round(proto, n, mesh, fault,
                                          run.origin, axis_name,
                                          tabled=True)
    p = mesh.shape[axis_name]
    n_pad = pad_to_mesh(n, mesh, axis_name)
    ch = NE.get(fault)
    alive_pad = (NE.eventual_alive_pad(fault, n, n_pad, run.origin)
                 if ch is not None
                 else sharded_alive(fault, n, n_pad, run.origin))
    init = init_sparse_state(run, proto, n, mesh, axis_name)
    target = jnp.float32(run.target_coverage)
    r = proto.rumors
    meta = sparse_meta(n_pad, p, proto.fanout, n_words(proto.rumors),
                       bidirectional=proto.mode == C.ANTI_ENTROPY)
    rec = _sparse_recorder(proto, p, meta) if RM.wanted() else None
    obs = _churn_observables(fault, n, n_pad, run.origin)

    @jax.jit
    def loop(state, *tbl):
        # liveness in-trace: no O(N) closed-over constant (bind_tables
        # doc) — same hardening as simulate_until_topo_sparse
        alive_t = (NE.eventual_alive_pad(fault, n, n_pad, run.origin)
                   if ch is not None
                   else sharded_alive(fault, n, n_pad, run.origin))
        m0 = (RM.init(run.max_rounds, p, "simulate_until_sparse",
                      nemesis=ch is not None) if rec else None)
        c0 = RM.count_packed(state.seen, alive_t) if rec else None
        def cond(carry):
            s, _, _ = carry
            return ((coverage_packed(s.seen, r, alive_t) < target)
                    & (s.round < run.max_rounds))
        def body(carry):
            s0, m, cnt = carry
            round0, msgs0 = s0.round, s0.msgs
            if ch is not None:
                s, lost = step(s0, *tbl)
            else:
                s, lost = step(s0, *tbl), None
            if m is not None:
                m, cnt = rec(m, cnt, round0, msgs0, s, alive_t,
                             nem=(obs(round0, lost,
                                      NE.sched_of_tables(tbl))
                                  if obs else None))
            return s, m, cnt
        return jax.lax.while_loop(cond, body, (state, m0, c0))

    final, _, _ = maybe_aot_timed(loop, timing, init, *tables, label="sparse")
    return (int(final.round),
            float(coverage_packed(final.seen, r, alive_pad)),
            float(final.msgs), final, meta)
