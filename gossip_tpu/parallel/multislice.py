"""Multi-slice (DCN) meshes: the multi-host scaling story.

The reference scales across machines through the Maelstrom harness — one
OS process per node, JSON over pipes, no awareness of network locality
(reference main.go:72-88 contacts neighbors one at a time over whatever
transport the harness provides).  The TPU-native equivalent is a
**hybrid 2-D mesh**: a fast intra-slice axis (chips connected by ICI)
and a slow cross-slice axis (hosts/slices connected by DCN).  The layout
rule — the scaling-book recipe — is to put the communication-HEAVY
dimension on ICI and the communication-FREE (or -light) dimension on
DCN:

* the **node axis** (O(N) digest collectives every round:
  ``psum_scatter`` / ``all_gather`` / ``all_to_all`` in
  parallel/sharded*.py) rides ICI, inside a slice;
* the **sweep axis** (independent configs, parallel/sweep.py) or the
  **rumor-plane axis** (zero-ICI by construction,
  parallel/sharded_fused.py) rides DCN, across slices — those axes
  exchange at most a scalar per round.

``make_hybrid_mesh`` builds that mesh by grouping devices by their
reported ``slice_index`` — each mesh row is one slice (devices within a
row id-ordered, the platform's enumeration order), sub-pod meshes
allowed — and falls back to a plain reshape on single-slice or CPU
virtual devices.  The same program compiles either way, which is what
lets the 8-device CPU mesh (tests, dryrun) validate the layout without
a pod.

Real multi-host execution additionally needs one ``jax.distributed.
initialize()`` call per host before any jax API; ``maybe_init_distributed``
wraps it behind the standard env vars so single-host runs stay untouched.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

# (dcn_axis, ici_axis) default names match the 2-D pod sweep
# (cli.cmd_grid / parallel/sweep.config_sweep_curves_2d).
DEFAULT_AXES = ("sweep", "nodes")


def device_slice_index(dev) -> int:
    """The DCN slice a device belongs to (0 when the platform does not
    report one — CPU, single-slice TPU)."""
    idx = getattr(dev, "slice_index", None)
    return 0 if idx is None else int(idx)


def detect_slices(devices: Optional[Sequence] = None) -> int:
    """Number of distinct DCN slices among ``devices``."""
    devs = jax.devices() if devices is None else list(devices)
    return len({device_slice_index(d) for d in devs})


def make_hybrid_mesh(dcn_slices: int, per_slice: int,
                     axis_names: Tuple[str, str] = DEFAULT_AXES) -> Mesh:
    """A 2-D ``Mesh`` of shape (dcn_slices, per_slice) whose OUTER axis
    crosses DCN slices and INNER axis stays inside a slice.

    On hardware that reports multiple slices, each mesh row is one slice:
    devices are grouped by ``slice_index`` and ``per_slice`` devices are
    taken from each of the first ``dcn_slices`` groups — so sub-pod
    meshes (fewer slices, fewer chips per slice) are valid, and the
    inner axis never crosses DCN.  On single-slice or CPU virtual
    devices it is a plain row-major reshape — the hybrid layout's
    degenerate case, which is what lets the 8-device CPU mesh validate
    the same shard_map programs without a pod.
    """
    grid = _hybrid_device_grid(jax.devices(), dcn_slices, per_slice)
    return Mesh(grid, axis_names)


def _hybrid_device_grid(devs: Sequence, dcn_slices: int,
                        per_slice: int) -> np.ndarray:
    """The (dcn_slices, per_slice) device grid behind make_hybrid_mesh —
    split out so the slice-grouping logic is testable without real
    multi-slice hardware."""
    if dcn_slices < 1 or per_slice < 1:
        raise ValueError("mesh axes must be >= 1")
    want = dcn_slices * per_slice
    if len(devs) < want:
        raise ValueError(f"hybrid mesh {dcn_slices}x{per_slice} needs "
                         f"{want} devices; only {len(devs)} available")
    groups: dict = {}
    for d in devs:
        groups.setdefault(device_slice_index(d), []).append(d)
    if len(groups) > 1:
        slice_ids = sorted(groups)
        if dcn_slices > len(slice_ids):
            raise ValueError(
                f"hybrid mesh wants {dcn_slices} DCN slices; platform "
                f"reports {len(slice_ids)}")
        rows = []
        for sid in slice_ids[:dcn_slices]:
            members = sorted(groups[sid], key=lambda d: d.id)
            if len(members) < per_slice:
                raise ValueError(
                    f"slice {sid} has {len(members)} devices; the inner "
                    f"mesh axis wants {per_slice} and must not cross DCN")
            rows.append(members[:per_slice])
        grid = np.empty((dcn_slices, per_slice), dtype=object)
        for i, row in enumerate(rows):
            for j, d in enumerate(row):
                grid[i, j] = d
        return grid
    return np.asarray(list(devs[:want])).reshape(dcn_slices, per_slice)


def maybe_init_distributed() -> bool:
    """Initialize jax.distributed for a multi-host run.  Opt-in: fires
    when ``JAX_COORDINATOR_ADDRESS`` is set (explicit coordinator) or
    ``GOSSIP_TPU_MULTIHOST=1`` is set (let ``jax.distributed.
    initialize()`` auto-detect the coordinator from the cluster
    environment — Cloud TPU metadata, GKE, Slurm).  Returns True when
    initialization ran.  Without either variable this is a no-op:
    unconditionally initializing on a single host would hang waiting for
    peers in partially-configured environments."""
    import os
    explicit = os.environ.get("JAX_COORDINATOR_ADDRESS") is not None
    opted_in = os.environ.get("GOSSIP_TPU_MULTIHOST") == "1"
    if not (explicit or opted_in):
        return False
    jax.distributed.initialize()
    return True
