"""Sharded replicated-log pull rounds: ordered per-key offset payloads
on the node-mesh exchange fabric.

Twin of models/log.make_log_round over the node mesh — structurally
parallel/sharded_crdt.make_sharded_crdt_round with the log payload's
max join in place of the counter merge and the send/commit program
applied locally per shard.  The only collective is the all_gather of
the masked state table — ``N x K*(C+1)`` int32 per round — plus the
msgs/lost psums.  Bitwise parity with the single-device round is
pinned in tests/test_logs.py: every random draw is keyed by
(base_key, round, *global* node id), so mesh shape never changes the
trajectory.

Nemesis schedules AND injection programs are runtime operands on the
step's ``tables`` tail (ops/nemesis + ops/logs); convergence is judged
on the eventual-alive set with an integer-exact converged-node count
divided ONCE on the host, and with an active run ledger the drivers
carry a RoundMetrics stack with the ``log_conv`` column.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gossip_tpu.compat import shard_map
from gossip_tpu import config as C
from gossip_tpu.config import (FaultConfig, LogConfig, ProtocolConfig,
                               RunConfig)
from gossip_tpu.models import si as si_mod
from gossip_tpu.models.log import (LogState, _conv_target_count,
                                   check_injections_reachable,
                                   check_log_mode, init_log_state)
from gossip_tpu.models.state import bind_tables
from gossip_tpu.ops import logs as LG
from gossip_tpu.ops.sampling import apply_drop, sample_peers
from gossip_tpu.parallel.sharded import (_churn_observables, _pad_rows,
                                         pad_to_mesh, sharded_alive)
from gossip_tpu.topology.generators import Topology


def make_sharded_log_round(
        cfg: LogConfig, proto: ProtocolConfig, topo: Topology,
        mesh: Mesh, fault: Optional[FaultConfig] = None, origin: int = 0,
        axis_name: str = "nodes", tabled: bool = False):
    """``tabled=True`` returns ``(step, tables)`` with padded topology
    + injection (+ schedule) arrays as step ARGUMENTS (no O(N) jit
    closure constants — models/swim.py doc)."""
    check_log_mode(proto)
    n, k = topo.n, proto.fanout
    n_pad = pad_to_mesh(n, mesh, axis_name)
    nl = n_pad // mesh.shape[axis_name]
    drop_prob = 0.0 if fault is None else fault.drop_prob
    from gossip_tpu.ops import nemesis as NE
    ch = NE.get(fault)
    # capability row: full schedule feature set on the log fabric
    NE.check_supported(fault, engine="log-pull")

    have_table = not topo.implicit
    if have_table:
        nbrs_pad = _pad_rows(topo.nbrs, n_pad, n)
        deg_pad = _pad_rows(topo.deg, n_pad, 0)
    zero = jnp.zeros((), jnp.int32)

    def local_round(val_l, round_, base_key, msgs, *table):
        table, sched = NE.split_tables(ch, table)
        table, inj = LG.split_inject(cfg, table)
        shard = jax.lax.axis_index(axis_name)
        gids = shard * nl + jnp.arange(nl, dtype=jnp.int32)
        rkey = jax.random.fold_in(base_key, round_)
        if ch is not None:
            base_pad = _pad_rows(
                NE.base_alive_or_ones(fault, n, origin), n_pad, False)
            alive_l = NE.alive_rows(sched, base_pad, round_)[gids]
            dp = NE.drop_at(sched, round_)
            cut = NE.cut_at(sched, round_)
        else:
            alive_l = sharded_alive(fault, n, n_pad, origin)[gids]
            dp, cut = drop_prob, None
        lost = jnp.float32(0.0)
        # local appends/commits first (models/log.py twin); padding
        # rows (gids >= n) own no send/commit, so inject_rows is zero
        # there by construction
        inj_rows = LG.inject_rows(cfg, inj, gids, round_, n, origin,
                                  fault)
        val_l = LG.merge_max(val_l, inj_rows)
        visible = jnp.where(alive_l[:, None], val_l, zero)
        rows_all = jax.lax.all_gather(visible, axis_name, tiled=True)
        nbrs_l, deg_l = table if have_table else (None, None)

        qkey = jax.random.fold_in(rkey, si_mod.PULL_TAG)
        partners0 = sample_peers(qkey, gids, topo, k, proto.exclude_self,
                                 local_nbrs=nbrs_l, local_deg=deg_l)
        partners = apply_drop(rkey, si_mod.PULL_DROP_TAG, gids,
                              partners0, dp, n, force=ch is not None)
        if ch is not None:
            partners = NE.partition_targets(cut, gids, partners, n)
        pulled = LG.pull_merge_log(rows_all, partners, n)
        partners = jnp.where(alive_l[:, None], partners, n)
        n_req = jnp.sum(partners < n).astype(jnp.float32)
        if ch is not None:
            lost = lost + NE.lost_count(partners0, partners, alive_l, n)
        pulled = jnp.where(alive_l[:, None], pulled, zero)
        out_val = LG.merge_max(val_l, pulled)
        msgs_new = msgs + jax.lax.psum(2.0 * n_req, axis_name)
        if ch is not None:
            return out_val, msgs_new, jax.lax.psum(lost, axis_name)
        return out_val, msgs_new

    sh2 = P(axis_name, None)
    rep = P()
    in_specs = [sh2, rep, rep, rep]
    tables = ()
    if have_table:
        in_specs += [sh2, P(axis_name)]
        tables = (nbrs_pad, deg_pad)
    # injection operands replicated (tiny padded lists; the per-shard
    # ownership slice happens via gids inside local_round)
    inj_ops = LG.inject_args(cfg, n)
    in_specs += [rep] * len(inj_ops)
    tables = tables + inj_ops
    if ch is not None:
        in_specs += [rep] * NE.N_SCHED_OPERANDS
        tables = tables + NE.sched_args(NE.build(fault, n, n_pad))

    out_specs = (sh2, rep, rep) if ch is not None else (sh2, rep)
    mapped = shard_map(local_round, mesh=mesh, in_specs=tuple(in_specs),
                       out_specs=out_specs)

    def step_tabled(state: LogState, *tbl):
        out = mapped(state.val, state.round, state.base_key,
                     state.msgs, *tbl)
        new = LogState(val=out[0], round=state.round + 1,
                       base_key=state.base_key, msgs=out[1])
        return (new, out[2]) if ch is not None else new

    return bind_tables(step_tabled, tables, tabled)


def init_sharded_log_state(run: RunConfig, cfg: LogConfig,
                           topo: Topology, mesh: Mesh,
                           axis_name: str = "nodes") -> LogState:
    n_pad = pad_to_mesh(topo.n, mesh, axis_name)
    st = init_log_state(run, cfg, topo.n)
    val = _pad_rows(st.val, n_pad, 0)
    val = jax.device_put(val, NamedSharding(mesh, P(axis_name, None)))
    return st._replace(val=val)


def _log_recorder(cfg: LogConfig, proto: ProtocolConfig, n: int,
                  n_pad: int, n_shards: int, truth, eventual_pad):
    """In-loop metrics row for the log pull kernels — the
    parallel/sharded_crdt._crdt_recorder twin.  ``newly`` is the
    per-round delta of the merged payload mass (filled slots +
    committed counts — monotone under max, so the delta is exact);
    ``log_conv`` is the converged fraction on the eventual-alive set;
    per-device egress is the state all_gather plus the msgs psum."""
    from gossip_tpu.ops import round_metrics as RM
    s = LG.state_width(cfg)
    nl = n_pad // n_shards
    base = 4.0 + 4.0 * nl * s
    offered_per_msg = s * RM.payload_factor(C.PULL)

    def rec(m, prev_count, round0, msgs0, s1, alive_pad, nem=None):
        count = LG.payload_count(cfg, s1.val, alive_pad)
        newly = count - prev_count
        msgs = s1.msgs - msgs0
        kw = ({} if nem is None
              else dict(alive=nem[0], cut_pairs=nem[1], dropped=nem[2]))
        covered = jnp.any(s1.val != 0, axis=1) & alive_pad
        per = jnp.sum(covered.reshape(n_shards, -1), axis=1,
                      dtype=jnp.float32)
        tot = jnp.sum(alive_pad.reshape(n_shards, -1), axis=1,
                      dtype=jnp.float32)
        return RM.record(
            m, newly=newly, msgs=msgs,
            dup=RM.dup_estimate(offered_per_msg * msgs, newly),
            bytes=jnp.float32(base),
            front=per / jnp.maximum(tot, 1.0),
            log_conv=LG.value_conv_frac(s1.val, truth, eventual_pad),
            **kw), count

    return rec


def _sharded_truth_and_alive(cfg: LogConfig, tbl, ch, fault, n: int,
                             n_pad: int, origin: int):
    """(truth row, eventual-alive over padded rows) — truth from the
    TRACED injection operands on the step's table tail, shared by both
    sharded drivers so the metric and the readout agree."""
    from gossip_tpu.ops import nemesis as NE
    head, _ = NE.split_tables(ch, tbl)
    _, inj = LG.split_inject(cfg, head)
    truth = LG.ground_truth(cfg, inj, fault, n, origin)
    eventual = _pad_rows(LG.eventual_alive_crdt(fault, n, origin),
                         n_pad, False)
    return truth, eventual


def simulate_curve_log_sharded(cfg: LogConfig, proto: ProtocolConfig,
                               topo: Topology, run: RunConfig,
                               mesh: Mesh,
                               fault: Optional[FaultConfig] = None,
                               axis_name: str = "nodes", timing=None):
    """Sharded scan driver: returns ``(log_conv f64[T], msgs f32[T],
    final_state, truth_summary)`` — log_conv from the integer
    converged count divided once on the host (models/log.py
    contract)."""
    import numpy as np

    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.ops import round_metrics as RM
    from gossip_tpu.utils.trace import maybe_aot_timed
    check_injections_reachable(cfg, run)
    step, tables = make_sharded_log_round(cfg, proto, topo, mesh, fault,
                                          run.origin, axis_name,
                                          tabled=True)
    ch = NE.get(fault)
    n = topo.n
    n_pad = pad_to_mesh(n, mesh, axis_name)
    n_shards = mesh.shape[axis_name]
    init = init_sharded_log_state(run, cfg, topo, mesh, axis_name)
    obs = _churn_observables(fault, n, n_pad, run.origin)

    @jax.jit
    def scan(state, *tbl):
        truth, eventual = _sharded_truth_and_alive(cfg, tbl, ch, fault,
                                                   n, n_pad, run.origin)
        rec = (_log_recorder(cfg, proto, n, n_pad, n_shards, truth,
                             eventual) if RM.wanted() else None)
        m0 = (RM.init(run.max_rounds, n_shards,
                      "simulate_curve_log_sharded",
                      nemesis=ch is not None, log=True)
              if rec else None)
        c0 = LG.payload_count(cfg, state.val, eventual) if rec else None

        def body(carry, _):
            s0, m, cnt = carry
            round0, msgs0 = s0.round, s0.msgs
            if ch is not None:
                s, lo = step(s0, *tbl)
            else:
                s, lo = step(s0, *tbl), None
            if m is not None:
                m, cnt = rec(m, cnt, round0, msgs0, s, eventual,
                             nem=(obs(round0, lo,
                                      NE.sched_of_tables(tbl))
                                  if obs else None))
            return (s, m, cnt), (
                LG.converged_count(s.val, truth, eventual), s.msgs)

        (final, m, _), ys = jax.lax.scan(body, (state, m0, c0), None,
                                         length=run.max_rounds)
        return (final, m), ys, truth

    # truth comes back from the jitted scan — recomputing it here
    # would re-lower the injection operands un-jitted per call (the
    # sharded_crdt review lesson)
    (final, _), (convs, msgs), truth = maybe_aot_timed(scan, timing,
                                                       init, *tables,
                                                       label="log")
    eventual_np = np.asarray(LG.eventual_alive_crdt(fault, n,
                                                    run.origin))
    denom = max(1, int(eventual_np.sum()))
    return (np.asarray(convs, np.int64) / denom, np.asarray(msgs),
            final, LG.truth_summary(cfg, truth))


def simulate_until_log_sharded(cfg: LogConfig, proto: ProtocolConfig,
                               topo: Topology, run: RunConfig,
                               mesh: Mesh,
                               fault: Optional[FaultConfig] = None,
                               axis_name: str = "nodes", timing=None):
    """Sharded while_loop driver: ``(rounds, log_conv, msgs,
    final_state, truth_summary)`` — the loop cond is the exact integer
    converged-count compare."""
    import numpy as np

    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.ops import round_metrics as RM
    from gossip_tpu.utils.trace import maybe_aot_timed
    check_injections_reachable(cfg, run)
    step, tables = make_sharded_log_round(cfg, proto, topo, mesh, fault,
                                          run.origin, axis_name,
                                          tabled=True)
    ch = NE.get(fault)
    n = topo.n
    n_pad = pad_to_mesh(n, mesh, axis_name)
    n_shards = mesh.shape[axis_name]
    init = init_sharded_log_state(run, cfg, topo, mesh, axis_name)
    obs = _churn_observables(fault, n, n_pad, run.origin)
    eventual_np = np.asarray(LG.eventual_alive_crdt(fault, n,
                                                    run.origin))
    denom = max(1, int(eventual_np.sum()))
    target = _conv_target_count(run, denom)

    @jax.jit
    def loop(state, *tbl):
        truth, eventual = _sharded_truth_and_alive(cfg, tbl, ch, fault,
                                                   n, n_pad, run.origin)
        rec = (_log_recorder(cfg, proto, n, n_pad, n_shards, truth,
                             eventual) if RM.wanted() else None)
        m0 = (RM.init(run.max_rounds, n_shards,
                      "simulate_until_log_sharded",
                      nemesis=ch is not None, log=True)
              if rec else None)
        c0 = LG.payload_count(cfg, state.val, eventual) if rec else None

        def cond(carry):
            s, _, _ = carry
            return ((LG.converged_count(s.val, truth, eventual)
                     < target) & (s.round < run.max_rounds))

        def body(carry):
            s0, m, cnt = carry
            round0, msgs0 = s0.round, s0.msgs
            if ch is not None:
                s, lo = step(s0, *tbl)
            else:
                s, lo = step(s0, *tbl), None
            if m is not None:
                m, cnt = rec(m, cnt, round0, msgs0, s, eventual,
                             nem=(obs(round0, lo,
                                      NE.sched_of_tables(tbl))
                                  if obs else None))
            return s, m, cnt

        final, m, _ = jax.lax.while_loop(cond, body, (state, m0, c0))
        return (final, m), truth

    (final, _), truth = maybe_aot_timed(loop, timing, init, *tables,
                                        label="log")
    eventual = _pad_rows(LG.eventual_alive_crdt(fault, n, run.origin),
                         n_pad, False)
    conv = int(LG.converged_count(final.val, truth, eventual)) / denom
    return (int(final.round), conv, float(final.msgs), final,
            LG.truth_summary(cfg, truth))
